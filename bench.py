"""Benchmark: PQL query throughput on TPU vs CPU-numpy reference.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures the BASELINE.md config-2 shape (Intersect of 8 rows + Count over a
1M-column fragment) as batched query throughput.  Because the reference repo
publishes no numbers (BASELINE.md), the baseline denominator is the same
workload executed by a numpy CPU oracle on this host — the stand-in for
stock pilosa's CPU roaring path until a Go toolchain measurement exists.

The axon tunnel has a ~100 ms per-call dispatch floor, so queries are batched
into one XLA computation (B independent 8-row intersect+counts per call) and
throughput is reported per query.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.core import SHARD_WORDS, SHARD_WIDTH
    from pilosa_tpu.ops import bitset

    rng = np.random.default_rng(0)
    n_rows = 64
    bits_per_row = 200_000
    frag_np = bitset.pack_fragment(
        np.repeat(np.arange(n_rows), bits_per_row),
        rng.integers(0, SHARD_WIDTH, size=n_rows * bits_per_row),
        n_rows=n_rows,
    )

    B = 128  # queries per XLA call; each picks 8 distinct rows

    # Distinct query sets per call: the axon relay memoizes identical
    # (executable, args) calls, so reusing one arg set measures the cache,
    # not the chip (verified empirically; see .claude/skills/verify/SKILL.md).
    iters = 20
    qsets_np = [
        rng.permuted(np.tile(np.arange(n_rows), (B, 1)), axis=1)[:, :8]
        .astype(np.int32)
        for _ in range(iters)
    ]

    @jax.jit
    def batch_intersect_count(frag, qrows):
        sel = frag[qrows]          # [B, 8, W]
        seg = sel[:, 0]
        for i in range(1, 8):
            seg = seg & sel[:, i]
        return jnp.sum(jax.lax.population_count(seg).astype(jnp.int32), axis=-1)

    frag = jax.device_put(frag_np)
    qsets = [jax.device_put(q) for q in qsets_np]
    warmup = rng.permuted(
        np.tile(np.arange(n_rows), (B, 1)), axis=1)[:, :8].astype(np.int32)
    batch_intersect_count(frag, jax.device_put(warmup)).block_until_ready()

    t0 = time.perf_counter()
    outs = [batch_intersect_count(frag, q) for q in qsets]
    jax.block_until_ready(outs)
    t1 = time.perf_counter()
    out = outs[0]
    tpu_qps = (B * iters) / (t1 - t0)

    # CPU numpy reference for the same queries
    qrows0 = qsets_np[0]
    t0 = time.perf_counter()
    cpu_iters = 2
    for _ in range(cpu_iters):
        for q in range(B):
            seg = frag_np[qrows0[q, 0]]
            for i in range(1, 8):
                seg = seg & frag_np[qrows0[q, i]]
            int(np.bitwise_count(seg).sum())
    t1 = time.perf_counter()
    cpu_qps = (B * cpu_iters) / (t1 - t0)

    # sanity: results agree with oracle on one query
    seg = frag_np[qrows0[0, 0]]
    for i in range(1, 8):
        seg = seg & frag_np[qrows0[0, i]]
    assert int(np.asarray(out)[0]) == int(np.bitwise_count(seg).sum())

    print(json.dumps({
        "metric": "intersect8_count_qps_1M_cols",
        "value": round(tpu_qps, 1),
        "unit": "queries/sec",
        "vs_baseline": round(tpu_qps / cpu_qps, 2),
    }))


if __name__ == "__main__":
    main()
