"""Benchmark: ENGINE-path PQL throughput on the BASELINE.md configs.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
"configs": {...}}.

Every number drives ``Executor.execute`` — fingerprint -> prepared plan ->
compiled XLA -> mesh dispatch -> reduce — i.e. the same path the server's
/query serves (api.py builds ``Executor(holder, use_mesh=True)``).  One
config additionally goes through the real HTTP server.

Configs (BASELINE.md):
  1. Count(Row(stargazer=r))              — single-shard Star-Trace
  2. Count(Intersect(8 rows))             — container op matrix, 1M columns
  3. TopN(language, Row(stars=r), n=50)   — ranked TopN over 10M columns
  4. Sum(Row(v > X), field=v) + GroupBy   — BSI scans over 64 shards
  5. TopN+Intersect over ~1B columns (954 shards) under a DeviceBudget
     limit sized so LRU eviction fires (BASELINE.md:30; the budget is the
     HBM analog of the reference's mmap paging).

Methodology notes (load-bearing, see .claude/skills/verify/SKILL.md):
* The axon tunnel memoizes identical (executable, args) calls, so every
  query uses DISTINCT literal values; plans are parametrized
  (executor/plan.py Slot) so distinct values share one compiled executable.
* The tunnel has a ~110 ms blocking round-trip floor per batch, so queries
  are issued as multi-call PQL batches AND multiple batches run in flight
  from concurrent client threads (the tunnel pipelines: measured ~9
  round-trips/s serial, 330/s at 32 threads).  This is throughput under
  concurrent load — how the reference's own worker pool is exercised
  (executor.go:80-110); batch latency is reported separately.
* vs_cpu is the same workload on a single-thread numpy oracle doing the
  reference's algorithm (dense word-wise ops / bit-sliced scans) on this
  host — the stand-in for stock pilosa's CPU roaring path (BASELINE.md:
  the reference publishes no numbers).  This host has ONE core, so the
  single-thread oracle is the machine's full CPU capability.
* Engine and oracle timings are best-of-REPEATS with the relative spread
  ((max-min)/max qps across repeats) reported per config — single-shot
  numbers through a shared tunnel wobbled 2x between r4 runs.
* Config 5 data is DENSE (seg rows ~25%, metric rows ~12.5% fill, like
  SSB lineorder flag/discount rows): every 65536-column container is far
  above the 4096-bit array/bitmap threshold, so stock pilosa would hold
  bitmap containers and the word-wise AND+popcount oracle is exactly the
  reference's hot loop (roaring.go:1712 intersectionCountBitmapBitmap).
  At the sparse densities of r4's config 5 the honest roaring oracle is
  sorted-array intersection, which CPUs do faster than any dense scan —
  dense data is where a bitmap engine (and the TPU) is supposed to live.
"""

import json
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

SEED = 7
HBM_PEAK_GBS = 819.0  # v5e HBM bandwidth, for the achieved-fraction column
REPEATS = 3  # best-of-N for engine and oracle timings (spread reported)


def best_of(fn, n=REPEATS):
    """Run ``fn`` n times; returns (best_result, spread) where ``fn``
    returns (qps, *rest) tuples, best = max qps, and spread is
    (max-min)/max across repeats."""
    runs = [fn() for _ in range(n)]
    qs = [r[0] for r in runs]
    best = max(runs, key=lambda r: r[0])
    spread = (max(qs) - min(qs)) / max(qs) if max(qs) > 0 else 0.0
    return best, round(spread, 3)


def _rand_rows(rng, n_rows, k):
    return rng.permuted(np.tile(np.arange(n_rows), (k, 1)), axis=1)[:, :8]


def _device_telemetry() -> dict:
    """Cumulative device-runtime counters (pilosa_tpu/utils/devobs.py):
    legs bracket their work with these so every BENCH_*.json row carries
    compile/retrace counts and the padding-waste ratio — the trajectory
    can then distinguish "got slower" from "started recompiling".
    Opening a bracket also RESTARTS the decode-workspace high-watermark,
    so each leg's "device" row reports its own peak, not a
    predecessor's."""
    from pilosa_tpu.utils import devobs
    c = devobs.COMPILES
    led = devobs.LEDGER
    out = {"compiles": c.compiles_total, "retraces": c.retraces_total,
           "compile_s": c.compile_seconds_total,
           "launches": led.launches_total,
           "rows": led.rows_actual_total,
           "padded": led.rows_padded_total,
           "decode_bytes": led.decode_bytes_total,
           "kernel_launches": led.kernel_launches_total}
    led.reset_decode_peak()
    return out


def _device_delta(before: dict) -> dict:
    from pilosa_tpu.utils import devobs
    # read the leg-local peak BEFORE _device_telemetry restarts it
    peak = devobs.LEDGER.decode_peak_bytes
    after = _device_telemetry()
    rows = after["rows"] - before["rows"]
    padded = after["padded"] - before["padded"]
    total = rows + padded
    return {"compiles": after["compiles"] - before["compiles"],
            "retraces": after["retraces"] - before["retraces"],
            "compile_s": round(after["compile_s"] - before["compile_s"],
                               3),
            "launches": after["launches"] - before["launches"],
            "padding_waste_ratio": round(padded / total, 4) if total
            else 0.0,
            "decode_mb": round(
                (after["decode_bytes"] - before["decode_bytes"]) / 2**20,
                2),
            "decode_peak_mb": round(peak / 2**20, 2),
            "kernel_launches": after["kernel_launches"]
            - before["kernel_launches"],
            # resolved container-kernels backend this leg ran under
            # (ops/kernels.py) — the per-leg BENCH_*.json provenance of
            # whether decode went through the Pallas kernels or jnp
            "kernel_backend": _kernel_backend()}


def _kernel_backend() -> str:
    from pilosa_tpu.ops import kernels
    return kernels.resolve()


def build_indexes():
    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.storage import FieldOptions, Holder

    rng = np.random.default_rng(SEED)
    h = Holder(None)

    # configs 1+2: single-shard, 64 rows x 200k bits (Star-Trace shaped)
    star = h.create_index("startrace", track_existence=False)
    stargazer = star.create_field("stargazer")
    n_rows, per_row = 64, 200_000
    stargazer.import_bits(
        np.repeat(np.arange(n_rows), per_row),
        rng.integers(0, SHARD_WIDTH, size=n_rows * per_row))

    # config 3: 10M columns (10 shards), 50 languages + 16-row filter field
    lang = h.create_index("lang10m", track_existence=False)
    language = lang.create_field("language")
    stars = lang.create_field("stars")
    n_bits = 2_000_000
    cols3 = rng.integers(0, 10 * SHARD_WIDTH, size=n_bits)
    language.import_bits(rng.integers(0, 50, size=n_bits), cols3)
    stars.import_bits(rng.integers(0, 16, size=n_bits), cols3)

    # GroupBy grid ride-along: two 128-row fields over 4 shards — the
    # 128x128 combo grid must run as ONE async dispatch wave (r4 verdict
    # #8; executor.GROUP_GRID_PREFIX_MAX)
    grid = h.create_index("grid4", track_existence=False)
    ga = grid.create_field("a")
    gb = grid.create_field("b")
    n_g = 400_000
    gcols = rng.integers(0, 4 * SHARD_WIDTH, size=n_g)
    ga.import_bits(rng.integers(0, 128, size=n_g), gcols)
    gb.import_bits(rng.integers(0, 128, size=n_g), gcols)

    # config 4: 64 shards, BSI int field (depth 20) + 8-row set field
    bsi_idx = h.create_index("bsi64", track_existence=False)
    v = bsi_idx.create_field("v", FieldOptions(type="int", min=0,
                                               max=1_000_000))
    seg = bsi_idx.create_field("seg")
    n_vals = 1_000_000
    cols4 = np.unique(rng.integers(0, 64 * SHARD_WIDTH, size=n_vals))
    vals4 = rng.integers(0, 1_000_000, size=cols4.size)
    v.import_values(cols4, vals4)
    seg.import_bits(rng.integers(0, 8, size=cols4.size), cols4)

    return h, {"star_rows": n_rows, "cols4": cols4, "vals4": vals4}


N_SHARDS5 = 954  # ~1B columns (954 * 2^20)


def build_config5(rng, n_shards=N_SHARDS5, sparse=False):
    """~1B-column index: 954 shards, an 8-row metric field (~12.5% fill)
    and a 4-row segment field (~25% fill) — SSB lineorder flag/discount
    shaped.  At these densities every 65536-column container is a roaring
    BITMAP container, so the CPU oracle's word-wise loop is the
    reference's own algorithm (roaring.go:1712).

    ``sparse=True`` builds the compressed-residency variant instead
    (docs/memory-budget.md): ~1.5% of words non-zero (scattered) plus one
    contiguous fully-set word range per row — the clustered + scattered
    mix of real user-id index data, where roaring would hold array/run
    containers and the packed device form compresses ~25-30x.  Same
    query/oracle surface either way.

    Rows are written densely via the Store/setRow surface
    (fragment.set_row; fragment.go setRow) — the word-level analog of
    pre-loading the benchmark index from a snapshot, sidestepping ~1e9
    single-bit import pairs on this 1-core host.  Returns (holder,
    oracle_words): oracle_words[shard] is the [12, SHARD_WORDS] uint32
    block (seg rows 0-3, then metric rows 0-7) shared by the numpy
    oracle, so engine and oracle read identical data."""
    from pilosa_tpu.core import SHARD_WORDS, VIEW_STANDARD
    from pilosa_tpu.storage import Holder

    h5 = Holder(None)
    idx = h5.create_index("ssb1b", track_existence=False)
    seg = idx.create_field("seg")
    metric = idx.create_field("metric")
    seg_view = seg._create_view_if_not_exists(VIEW_STANDARD)
    met_view = metric._create_view_if_not_exists(VIEW_STANDARD)
    oracle_words: dict[int, np.ndarray] = {}
    for shard in range(n_shards):
        a = rng.integers(0, 1 << 32, size=(12, SHARD_WORDS), dtype=np.uint32)
        b = rng.integers(0, 1 << 32, size=(12, SHARD_WORDS), dtype=np.uint32)
        words = a & b                      # ~25% fill
        words[4:] &= np.roll(b[4:], 7, axis=1)  # metric rows ~12.5%
        if sparse:
            keep = rng.random((12, SHARD_WORDS)) < 0.015
            words *= keep
            starts = rng.integers(0, SHARD_WORDS - 256, size=12)
            for r in range(12):
                words[r, starts[r]: starts[r] + 256] = 0xFFFFFFFF
        sf = seg_view.create_fragment_if_not_exists(shard)
        mf = met_view.create_fragment_if_not_exists(shard)
        for r in range(4):
            sf.set_row(r, words[r])
        for r in range(8):
            mf.set_row(r, words[4 + r])
        oracle_words[shard] = words
    return h5, oracle_words


def cpu_config5(oracle_words, shards, rng, n=2):
    """Single-thread word-wise Intersect+TopN — the roaring bitmap-
    container hot loop (roaring.go:1712 intersectionCountBitmapBitmap,
    fragment.go:1570 top) over the same words the engine reads."""
    pairs = [(int(a), int((a + 1 + rng.integers(0, 3)) % 4))
             for a in rng.integers(0, 4, size=n)]
    t0 = time.perf_counter()
    for a, b in pairs:
        counts = np.zeros(8, dtype=np.int64)
        for s in shards:
            w = oracle_words[s]
            mask = w[a] & w[b]
            for m in range(8):
                counts[m] += int(np.bitwise_count(w[4 + m] & mask).sum())
        sorted(((int(counts[m]), -m) for m in range(8)), reverse=True)[:5]
    return n / (time.perf_counter() - t0)


def oracle_topn5(oracle_words, shards, a, b, n=5):
    """Exact TopN answer for one config-5 query (for the engine
    answer-equality check)."""
    counts = np.zeros(8, dtype=np.int64)
    for s in shards:
        w = oracle_words[s]
        mask = w[a] & w[b]
        for m in range(8):
            counts[m] += int(np.bitwise_count(w[4 + m] & mask).sum())
    order = sorted(range(8), key=lambda m: (-counts[m], m))
    return [(m, int(counts[m])) for m in order[:n] if counts[m] > 0]


def _frag_bytes(executor, index, field, view="standard", rows=None):
    """Bytes one device pass reads over a field's fragments, from the LIVE
    stacked shapes (sum over shards of rows_touched * words * 4) — derived
    from holder state rather than hand-modeled constants."""
    from pilosa_tpu.core import SHARD_WORDS

    h = executor.holder
    f = h.field(index, field)
    v = f.view(view)
    total = 0
    for fr in v.fragments.values():
        total += (rows if rows is not None else fr.n_rows) * SHARD_WORDS * 4
    return total


def _run_batches(executor, index, batches, n_threads, shards_of=None):
    """Execute pre-built batch strings from ``n_threads`` concurrent client
    threads (round-robin).  Returns (qps, mean_batch_latency_s,
    p50_batch_latency_s) — BASELINE.json's metric of record is qps + p50
    latency, so the median rides along with the mean."""
    lat = []

    def run_one(i):
        t0 = time.perf_counter()
        out = executor.execute(index, batches[i],
                               shards=None if shards_of is None
                               else shards_of[i])
        lat.append(time.perf_counter() - t0)
        return len(out)

    t0 = time.perf_counter()
    with ThreadPoolExecutor(n_threads) as pool:
        counts = list(pool.map(run_one, range(len(batches))))
    dt = time.perf_counter() - t0
    return (sum(counts) / dt, sum(lat) / len(lat),
            float(np.median(lat)))


def bench_config1(executor, meta, rng):
    # B=32768 amortizes per-batch host+tunnel cost over enough queries
    # that the native fingerprint scan (+ one fetch RTT) stays under the
    # per-query budget (A/B on-chip: 32768/16/8 beat 16384/8/8 by 1.7x);
    # in-flight batches pipeline the tunnel
    B, n_batches, T = 32768, 16, 8

    def batch():
        rows = rng.integers(0, meta["star_rows"], size=B)
        return " ".join(f"Count(Row(stargazer={r}))" for r in rows)

    executor.execute("startrace", batch())  # warm compile + stacks

    def run():
        batches = [batch() for _ in range(n_batches)]
        return _run_batches(executor, "startrace", batches, T)

    (qps, bat_s, p50_s), spread = best_of(run)
    # one row segment read per query
    bytes_per_q = _frag_bytes(executor, "startrace", "stargazer", rows=1)
    return qps, bat_s, p50_s, bytes_per_q, spread


def bench_config2(executor, meta, rng):
    B, n_batches, T = 4096, 32, 32
    n_rows = meta["star_rows"]

    def batch():
        sets = _rand_rows(rng, n_rows, B)
        return " ".join(
            "Count(Intersect(" + ", ".join(
                f"Row(stargazer={r})" for r in q) + "))"
            for q in sets)

    executor.execute("startrace", batch())

    def run():
        batches = [batch() for _ in range(n_batches)]
        return _run_batches(executor, "startrace", batches, T)

    (qps, bat_s, p50_s), spread = best_of(run)
    # 8 row segments streamed per query
    bytes_per_q = _frag_bytes(executor, "startrace", "stargazer", rows=8)
    return qps, bat_s, p50_s, bytes_per_q, spread


def bench_config3(executor, meta, rng):
    B, n_batches, T = 128, 32, 16

    def batch():
        rs = rng.integers(0, 16, size=B)
        return " ".join(f"TopN(language, Row(stars={r}), n=50)" for r in rs)

    executor.execute("lang10m", batch())

    def run():
        batches = [batch() for _ in range(n_batches)]
        return _run_batches(executor, "lang10m", batches, T)

    (qps, bat_s, p50_s), spread = best_of(run)
    # per query: full language fragment pass + one stars row per shard
    bytes_per_q = _frag_bytes(executor, "lang10m", "language") + \
        _frag_bytes(executor, "lang10m", "stars", rows=1)
    return qps, bat_s, p50_s, bytes_per_q, spread


def bench_config4(executor, meta, rng):
    B, n_batches, T = 64, 24, 12

    def batch():
        xs = rng.integers(0, 1_000_000, size=B)
        return " ".join(f"Sum(Row(v > {int(x)}), field=v)" for x in xs)

    executor.execute("bsi64", batch())

    def run():
        batches = [batch() for _ in range(n_batches)]
        return _run_batches(executor, "bsi64", batches, T)

    (qps, bat_s, p50_s), spread = best_of(run)
    # per query: ONE fused pass over the BSI fragment (XLA fuses the range
    # scan and the masked slice popcounts into a single read of the
    # stacked block)
    bytes_per_q = _frag_bytes(executor, "bsi64", "v", view="bsig_v")
    # GroupBy ride-along: 8x8 combo grid + BSI filter in ONE executable
    # invocation; the timed run uses a DISTINCT filter literal so the
    # remote-device memoization cannot serve a cached answer
    executor.execute("bsi64", "GroupBy(Rows(seg), Rows(seg), Row(v > 1))")
    t0 = time.perf_counter()
    executor.execute("bsi64",
                     "GroupBy(Rows(seg), Rows(seg), Row(v > 500000))")
    gb_s = time.perf_counter() - t0
    # 128x128 two-field grid in one dispatch wave (grid4 index); the
    # timed run varies a parametrized filter literal so the tunnel's
    # (executable, args) memoization cannot serve a cached answer while
    # the executable stays compiled
    executor.execute("grid4", "GroupBy(Rows(a), Rows(b), Row(b=1))")
    t0 = time.perf_counter()
    executor.execute("grid4", "GroupBy(Rows(a), Rows(b), Row(b=7))")
    gb_grid_s = time.perf_counter() - t0
    return qps, bat_s, p50_s, bytes_per_q, gb_s, gb_grid_s, spread


def _cfg5_batch(rng, B):
    """B distinct Intersect+TopN calls (SSB flagship query shape,
    executor.go:2414-2552)."""
    aa = rng.integers(0, 4, size=B)
    bb = (aa + 1 + rng.integers(0, 3, size=B)) % 4
    return " ".join(
        f"TopN(metric, Intersect(Row(seg={a}), Row(seg={b})), n=5)"
        for a, b in zip(aa, bb))


def bench_config5(ex5, oracle_words, rng, budget_mb, resident):
    """Intersect+TopN over ~1B columns (954 shards, 4 rotating shard
    subsets).

    ``resident=True``: budget sized so all 4 subset stacks stay
    HBM-resident — the realistic v5e operating point, with vs_cpu against
    the word-wise roaring oracle.  ``resident=False``: budget deliberately
    below one rotation's working set so LRU eviction must fire — the
    HBM-pressure stress variant (the reference's mmap-paging analog)."""
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET

    budget = budget_mb << 20
    old_limit = DEFAULT_BUDGET.limit_bytes
    DEFAULT_BUDGET.limit_bytes = budget
    DEFAULT_BUDGET.shrink_to_limit()
    DEFAULT_BUDGET.reset_peak()
    ev0 = DEFAULT_BUDGET.evictions
    try:
        subsets = np.array_split(np.arange(N_SHARDS5), 4)
        subsets = [list(map(int, s)) for s in subsets]
        if resident:
            B, nb, T, reps = 64, 24, 8, REPEATS
            order = [subsets[i % 4] for i in range(nb)]
        else:
            # hot subset alternating with rotating cold subsets: cache-
            # working-set pattern that forces eviction under the budget
            B, nb, T, reps = 32, 12, 1, 1
            order = [subsets[0] if i % 2 == 0
                     else subsets[1 + (i // 2) % 3] for i in range(nb)]
        # warm: compile once + stage each subset's stacks
        for sub in subsets:
            ex5.execute("ssb1b", _cfg5_batch(rng, B), shards=sub)

        def run():
            batches = [_cfg5_batch(rng, B) for _ in range(nb)]
            return _run_batches(ex5, "ssb1b", batches, T, shards_of=order)

        (qps, bat_s, p50_s), spread = best_of(run, n=reps)
        stats = DEFAULT_BUDGET.stats()
        # per query: one pass over the subset's metric+seg stacked rows
        rows_touched = 8 + 4
        bytes_per_q = len(subsets[0]) * rows_touched * 32768 * 4
        out = {
            "qps": round(qps, 1),
            "batch_ms": round(bat_s * 1e3, 1),
            "batch_p50_ms": round(p50_s * 1e3, 1),
            "spread": spread,
            "gbps": round(qps * bytes_per_q / 1e9, 1),
            "columns": N_SHARDS5 << 20,
            "budget_mb": budget_mb,
            "peak_mb": stats["peakBytes"] >> 20,
            "resident_mb": stats["residentBytes"] >> 20,
            "evictions": DEFAULT_BUDGET.evictions - ev0,
            "budget_held": stats["peakBytes"] <= budget,
        }
        if resident:
            out["hbm_frac"] = round(qps * bytes_per_q / 1e9 / HBM_PEAK_GBS,
                                    3)
        # oracle over one rotation subset (same shards the engine hits)
        (oracle_qps,), o_spread = best_of(
            lambda: (cpu_config5(oracle_words, subsets[0], rng),),
            n=min(reps, 2))
        out["vs_cpu"] = round(qps / oracle_qps, 2)
        out["cpu_qps"] = round(oracle_qps, 2)
        out["cpu_spread"] = o_spread
        return out
    finally:
        DEFAULT_BUDGET.limit_bytes = old_limit


def bench_config5_compressed(rng, n_shards=N_SHARDS5, budget_mb=768,
                             B=32, nb=12, reps=1):
    """The over-budget cliff, compressed vs dense (docs/memory-budget.md
    "Compressed residency"): the SPARSE ~1B-col corpus (the data shape
    compressed residency exists for) queried over rotating shard subsets
    under a budget deliberately below one rotation's dense working set.

    Three sub-legs on identical data and identical queries:
      * ``resident``   — dense form, unlimited budget: the qps anchor.
      * ``dense``      — dense form, over-budget: today's cliff (stream +
                         evict every rotation).
      * ``compressed`` — packed container streams under the same budget:
                         the working set fits, rotation is free.
    Reports compressed_mb, the effective-capacity ratio (dense bytes per
    compressed byte actually staged), and each leg's cliff vs the
    resident anchor."""
    from pilosa_tpu.executor import Executor as _Ex
    from pilosa_tpu.storage import fragment as _frag
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET

    h5, oracle_words = build_config5(rng, n_shards=n_shards, sparse=True)
    ex = _Ex(h5, use_mesh=True)
    old_limit = DEFAULT_BUDGET.limit_bytes
    old_form = _frag.COMPRESSED_RESIDENT
    subsets = [list(map(int, s))
               for s in np.array_split(np.arange(n_shards), 4)]
    dense_set_mb = (n_shards * 12 * 32768 * 4) >> 20
    out = {"columns": n_shards << 20, "budget_mb": budget_mb,
           "dense_working_set_mb": dense_set_mb, "sparse": True}

    def leg(compressed, limit_mb):
        _frag.COMPRESSED_RESIDENT = compressed
        # flush residency from the previous leg so each leg's
        # resident/compressed gauges describe only its own staging
        DEFAULT_BUDGET.limit_bytes = 1
        DEFAULT_BUDGET.shrink_to_limit()
        DEFAULT_BUDGET.limit_bytes = \
            None if limit_mb is None else limit_mb << 20
        DEFAULT_BUDGET.reset_peak()
        ev0 = DEFAULT_BUDGET.evictions
        # hot subset alternating with rotating cold subsets — the
        # working-set pattern that makes an over-budget dense form
        # evict + re-stage every other batch
        order = [subsets[0] if i % 2 == 0
                 else subsets[1 + (i // 2) % 3] for i in range(nb)]
        for sub in subsets:  # warm: compile + stage
            ex.execute("ssb1b", _cfg5_batch(rng, B), shards=sub)

        def run():
            batches = [_cfg5_batch(rng, B) for _ in range(nb)]
            return _run_batches(ex, "ssb1b", batches, 1, shards_of=order)

        (qps, _bat_s, p50_s), spread = best_of(run, n=reps)
        stats = DEFAULT_BUDGET.stats()
        return {
            "qps": round(qps, 1),
            "batch_p50_ms": round(p50_s * 1e3, 1),
            "spread": spread,
            "evictions": DEFAULT_BUDGET.evictions - ev0,
            "resident_mb": stats["residentBytes"] >> 20,
            "compressed_mb": round(stats["compressedBytes"] / 2**20, 1),
            "peak_mb": stats["peakBytes"] >> 20,
            "budget_held": limit_mb is None or
            stats["peakBytes"] <= (limit_mb << 20),
        }

    try:
        # answer-equality in BOTH forms before any timing
        q = "TopN(metric, Intersect(Row(seg=0), Row(seg=2)), n=5)"
        want = oracle_topn5(oracle_words, range(n_shards), 0, 2)
        for form in (False, True):
            _frag.COMPRESSED_RESIDENT = form
            DEFAULT_BUDGET.limit_bytes = budget_mb << 20
            DEFAULT_BUDGET.shrink_to_limit()
            got = ex.execute("ssb1b", q)
            assert [(p.id, p.count) for p in got[0]] == want, \
                f"compressed={form} answer diverged from the oracle"

        out["resident"] = leg(False, None)
        out["dense"] = leg(False, budget_mb)
        out["compressed"] = leg(True, budget_mb)
        anchor = out["resident"]["qps"]
        if anchor > 0:
            out["dense"]["cliff_vs_resident"] = round(
                anchor / max(out["dense"]["qps"], 1e-9), 1)
            out["compressed"]["cliff_vs_resident"] = round(
                anchor / max(out["compressed"]["qps"], 1e-9), 1)
        comp_mb = out["compressed"]["compressed_mb"]
        if comp_mb > 0:
            out["effective_capacity_ratio"] = round(
                dense_set_mb / comp_mb, 1)
        return out
    finally:
        _frag.COMPRESSED_RESIDENT = old_form
        DEFAULT_BUDGET.limit_bytes = old_limit
        ex.close()


# -- SSB star-schema workload (docs/architecture.md "On native code and
# Pallas"; the r10 on-TPU round's main leg) ---------------------------------

N_SHARDS_SSB = 256  # ~268M fact rows at the 2^20-shard geometry

# (field, rows): the denormalized dimension columns of an SSB lineorder
# fact table, bitmap-encoded — each field partitions every fact column
# into one selective row (d_year buckets, region/category codes) — plus
# an 8-bucket revenue measure for the TopN/GroupBy legs.
SSB_FIELDS = (("year", 7), ("region", 5), ("category", 12), ("rev", 8))


def build_ssb(rng, n_shards=N_SHARDS_SSB, sparse=True):
    """Wide denormalized star-schema fact index, SSB-shaped: one row of
    ``ssb`` per fact, every dimension attribute denormalized onto it as
    a selective Row (the reference's canonical star-join modeling —
    dimension filters become Row intersects, no join machinery).  Every
    column belongs to exactly one row per field, assigned in 32-column
    blocks so the word-wise numpy oracle is exact.

    ``sparse=True`` (default) keeps only ~1.5% of fact columns plus one
    contiguous fully-populated region per shard — the scattered +
    clustered mix the compressed container forms exist for, giving the
    compressed-over-budget sub-leg array AND run containers to decode.
    Returns (holder, ssb_words): ssb_words[shard] maps field ->
    [rows, SHARD_WORDS] uint32 oracle block."""
    from pilosa_tpu.core import SHARD_WORDS, VIEW_STANDARD
    from pilosa_tpu.storage import Holder

    h = Holder(None)
    idx = h.create_index("ssb", track_existence=False)
    views = {}
    for name, _rows in SSB_FIELDS:
        f = idx.create_field(name)
        views[name] = f._create_view_if_not_exists(VIEW_STANDARD)
    ssb_words: dict[int, dict[str, np.ndarray]] = {}
    for shard in range(n_shards):
        if sparse:
            live = (rng.random(SHARD_WORDS) < 0.015).astype(np.uint32)
            live *= np.uint32(0xFFFFFFFF)
            start = int(rng.integers(0, SHARD_WORDS - 512))
            live[start: start + 512] = 0xFFFFFFFF
        else:
            live = np.full(SHARD_WORDS, 0xFFFFFFFF, dtype=np.uint32)
        per_field = {}
        for name, n_rows in SSB_FIELDS:
            assign = rng.integers(0, n_rows, size=SHARD_WORDS)
            words = np.zeros((n_rows, SHARD_WORDS), dtype=np.uint32)
            for r in range(n_rows):
                words[r, assign == r] = 0xFFFFFFFF
            words &= live[None, :]
            fr = views[name].create_fragment_if_not_exists(shard)
            for r in range(n_rows):
                fr.set_row(r, words[r])
            per_field[name] = words
        ssb_words[shard] = per_field
    return h, ssb_words


def _ssb_batch(rng, B):
    """B calls cycling the three SSB query shapes: Q1-style restricted
    Count (Intersect of two dimension rows), Q2-style TopN of the
    revenue measure under a dimension filter, Q3-style two-dimension
    GroupBy under a region filter."""
    out = []
    for kind in rng.integers(0, 3, size=B):
        y = rng.integers(0, 7)
        rg = rng.integers(0, 5)
        c = rng.integers(0, 12)
        if kind == 0:
            out.append(f"Count(Intersect(Row(year={y}), "
                       f"Row(region={rg})))")
        elif kind == 1:
            out.append(f"TopN(rev, Intersect(Row(region={rg}), "
                       f"Row(category={c})), n=5)")
        else:
            out.append(f"GroupBy(Rows(year), Rows(region), "
                       f"Row(category={c}))")
    return " ".join(out)


def _ssb_norm(results):
    """Mixed SSB results (Count ints, TopN Pairs, GroupBy GroupCounts)
    -> comparable plain values; _smoke_norm is TopN-only."""
    return [[p.to_dict() for p in r] if isinstance(r, list) else r
            for r in results]


def oracle_ssb_topn(ssb_words, shards, rg, c, n=5):
    """Exact word-wise answer for the Q2-style TopN (the SSB
    answer-equality gate, like oracle_topn5 for config 5)."""
    counts = np.zeros(8, dtype=np.int64)
    for s in shards:
        w = ssb_words[s]
        mask = w["region"][rg] & w["category"][c]
        for m in range(8):
            counts[m] += int(np.bitwise_count(w["rev"][m] & mask).sum())
    order = sorted(range(8), key=lambda m: (-counts[m], m))
    return [(m, int(counts[m])) for m in order[:n] if counts[m] > 0]


def bench_ssb(rng, n_shards=N_SHARDS_SSB, budget_mb=96, B=24, nb=8,
              reps=1):
    """SSB star-schema main leg: the sparse fact corpus queried with the
    three SSB shapes, as two sub-legs on identical data/queries —
    ``resident`` (dense form, unlimited budget: the anchor) vs
    ``compressed`` (packed container streams under a budget below the
    dense working set, decoding per launch through whatever
    container-kernels backend the process resolved — recorded per leg in
    ``device.kernel_backend``).  Runnable unchanged on real TPU, where
    the compressed sub-leg exercises the fused Pallas kernels."""
    from pilosa_tpu.executor import Executor as _Ex
    from pilosa_tpu.storage import fragment as _frag
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET

    h, ssb_words = build_ssb(rng, n_shards=n_shards, sparse=True)
    ex = _Ex(h, use_mesh=True)
    old_limit = DEFAULT_BUDGET.limit_bytes
    old_form = _frag.COMPRESSED_RESIDENT
    n_rows_total = sum(r for _, r in SSB_FIELDS)
    dense_set_mb = (n_shards * n_rows_total * 32768 * 4) >> 20
    out = {"columns": n_shards << 20, "budget_mb": budget_mb,
           "dense_working_set_mb": dense_set_mb,
           "fields": dict(SSB_FIELDS)}
    subsets = [list(map(int, s))
               for s in np.array_split(np.arange(n_shards), 4)]

    def leg(compressed, limit_mb):
        _frag.COMPRESSED_RESIDENT = compressed
        DEFAULT_BUDGET.limit_bytes = 1
        DEFAULT_BUDGET.shrink_to_limit()
        DEFAULT_BUDGET.limit_bytes = \
            None if limit_mb is None else limit_mb << 20
        DEFAULT_BUDGET.reset_peak()
        for sub in subsets:  # warm: compile + stage
            ex.execute("ssb", _ssb_batch(rng, B), shards=sub)
        dev0 = _device_telemetry()

        def run():
            batches = [_ssb_batch(rng, B) for _ in range(nb)]
            order = [subsets[i % 4] for i in range(nb)]
            return _run_batches(ex, "ssb", batches, 1, shards_of=order)

        (qps, _bat_s, p50_s), spread = best_of(run, n=reps)
        stats = DEFAULT_BUDGET.stats()
        return {
            "qps": round(qps, 1),
            "batch_p50_ms": round(p50_s * 1e3, 1),
            "spread": spread,
            "resident_mb": stats["residentBytes"] >> 20,
            "compressed_mb": round(stats["compressedBytes"] / 2**20, 1),
            "budget_held": limit_mb is None or
            stats["peakBytes"] <= (limit_mb << 20),
            "device": _device_delta(dev0),
        }

    try:
        # answer-equality in both forms before any timing
        q = "TopN(rev, Intersect(Row(region=1), Row(category=3)), n=5)"
        want = oracle_ssb_topn(ssb_words, range(n_shards), 1, 3)
        for form in (False, True):
            _frag.COMPRESSED_RESIDENT = form
            DEFAULT_BUDGET.limit_bytes = budget_mb << 20
            DEFAULT_BUDGET.shrink_to_limit()
            got = ex.execute("ssb", q)
            assert [(p.id, p.count) for p in got[0]] == want, \
                f"ssb compressed={form} answer diverged from the oracle"

        out["resident"] = leg(False, None)
        out["compressed"] = leg(True, budget_mb)
        anchor = out["resident"]["qps"]
        if anchor > 0:
            out["compressed"]["cliff_vs_resident"] = round(
                anchor / max(out["compressed"]["qps"], 1e-9), 1)
        comp_mb = out["compressed"]["compressed_mb"]
        if comp_mb > 0:
            out["effective_capacity_ratio"] = round(
                dense_set_mb / comp_mb, 1)
        return out
    finally:
        _frag.COMPRESSED_RESIDENT = old_form
        DEFAULT_BUDGET.limit_bytes = old_limit
        ex.close()


def run_ssb_smoke(rng) -> dict:
    """SSB leg of --smoke: the star-schema corpus at 8 shards run
    dense-resident (reference), compressed-jnp, and compressed-PALLAS
    (interpreted on CPU — the same kernels a TPU compiles), asserting
    all three byte-identical, at least one container-kernel launch in
    the pallas leg's ledger bracket, and none in the jnp kill-switch
    leg."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops import kernels
    from pilosa_tpu.storage import fragment as _frag
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET

    n_shards = 8
    h, ssb_words = build_ssb(rng, n_shards=n_shards, sparse=True)
    ex = Executor(h, use_mesh=True)
    old_limit = DEFAULT_BUDGET.limit_bytes
    old_form = _frag.COMPRESSED_RESIDENT
    old_backend = kernels.CONTAINER_KERNELS
    batches = [_ssb_batch(rng, 6) for _ in range(3)]
    full_q = "TopN(rev, Intersect(Row(region=1), Row(category=3)), n=5)"
    out = {}
    try:
        _frag.COMPRESSED_RESIDENT = False
        DEFAULT_BUDGET.limit_bytes = None
        want = [_ssb_norm(ex.execute("ssb", b)) for b in batches]
        assert _smoke_norm(ex.execute("ssb", full_q))[0] == \
            oracle_ssb_topn(ssb_words, range(n_shards), 1, 3), \
            "ssb dense answer diverged from the oracle"

        _frag.COMPRESSED_RESIDENT = True
        DEFAULT_BUDGET.limit_bytes = 16 << 20
        for backend in ("jnp", "pallas"):
            kernels.CONTAINER_KERNELS = backend
            DEFAULT_BUDGET.shrink_to_limit()
            dev0 = _device_telemetry()
            t0 = time.perf_counter()
            got = [_ssb_norm(ex.execute("ssb", b)) for b in batches]
            leg_s = time.perf_counter() - t0
            dev = _device_delta(dev0)
            assert got == want, \
                f"ssb compressed-{backend} results diverged from the " \
                f"dense run"
            assert dev["kernel_backend"] == backend
            if backend == "pallas":
                assert dev["kernel_launches"] > 0, \
                    "pallas leg never launched a container kernel"
            else:
                assert dev["kernel_launches"] == 0, \
                    "jnp kill-switch leg launched container kernels"
            out[backend] = {"leg_s": round(leg_s, 2), "device": dev}
        st = DEFAULT_BUDGET.stats()
        assert st["compressedBytes"] > 0, \
            "ssb smoke never staged a packed stream"
        out["compressed_mb"] = round(st["compressedBytes"] / 2**20, 2)
        return out
    finally:
        kernels.CONTAINER_KERNELS = old_backend
        _frag.COMPRESSED_RESIDENT = old_form
        DEFAULT_BUDGET.limit_bytes = old_limit
        ex.close()


N_SHARDS5D = 256  # ~268M columns over 4 nodes


def bench_config5_distributed(rng):
    """BASELINE config 5's cluster half: 4 real server nodes in-process
    (sharing the one local accelerator), dense SSB-shaped data loaded
    through the binary roaring import surface, queries fanned out as
    pinned multi-call batches and reduced over real HTTP
    (executor.go:2414-2552 scatter/gather).  The measured load is a
    RECORDED mixed-workload replay: a varied workload (TopN batches,
    Count(Intersect), Row fetches) runs once with the slow-query
    threshold dropped to ~0 so the PR 5 slow-log ring records every
    query, and the recorded texts are then replayed as the measured
    corpus — traffic shaped like what the cluster actually served, not
    synthetic-uniform batches.  Publishes vs_cpu against the same
    word-wise oracle as config 5 plus the coordinator's
    device/wire/reduce latency breakdown from /debug/vars."""
    import http.client
    import socket
    import tempfile

    from pilosa_tpu.core import SHARD_WIDTH, SHARD_WORDS
    from pilosa_tpu.server import Config, Server
    from pilosa_tpu.storage.roaring_io import pack_roaring_words

    socks = []
    for _ in range(4):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    hosts = [f"localhost:{p}" for p in ports]
    servers = []

    def req(port, method, path, body: bytes | None = None, timeout=300):
        conn = http.client.HTTPConnection("localhost", port,
                                          timeout=timeout)
        conn.request(method, path, body=body)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{path}: {resp.status} {data[:200]!r}")
        return data

    def post(port, path, body: bytes, timeout=300):
        return req(port, "POST", path, body, timeout=timeout)

    try:
        for i, p in enumerate(ports):
            srv = Server(Config(
                data_dir=tempfile.mkdtemp(prefix=f"ptpu_b5d_{i}_"),
                bind=hosts[i], node_id=f"node{i}", cluster_hosts=hosts,
                replica_n=1, anti_entropy_interval=0,
                slow_log_size=2048))
            servers.append(srv)  # before open: finally closes partials
            srv.open()
        p0 = ports[0]
        post(p0, "/index/dist", b"{}")
        post(p0, "/index/dist/field/seg", b"{}")
        post(p0, "/index/dist/field/metric", b"{}")
        # dense data, same shape/density as config 5 (seg rows ~25%,
        # metric rows ~12.5%): bitmap-container regime where the CPU
        # oracle is the reference's word-wise hot loop.  Loaded per shard
        # through the binary roaring import endpoint (the reference's
        # /import-roaring surface), which forwards to the shard's owner.
        oracle_words: dict[int, np.ndarray] = {}
        for shard in range(N_SHARDS5D):
            a = rng.integers(0, 1 << 32, size=(12, SHARD_WORDS),
                             dtype=np.uint32)
            b = rng.integers(0, 1 << 32, size=(12, SHARD_WORDS),
                             dtype=np.uint32)
            words = a & b
            words[4:] &= np.roll(b[4:], 7, axis=1)
            oracle_words[shard] = words
            post(p0, f"/index/dist/field/seg/import-roaring/{shard}",
                 pack_roaring_words(words[:4]))
            post(p0, f"/index/dist/field/metric/import-roaring/{shard}",
                 pack_roaring_words(words[4:]))

        B, n_batches, T = 64, 16, 8

        def batch():
            return _cfg5_batch(rng, B)

        # warm every node's compile + stacks FIRST: the initial queries
        # pay each node's XLA compile (~11-40s over the tunnel) plus
        # ~100MB/node of stack staging, so they get a generous timeout;
        # heavy imports can also make health probes time out and mark
        # peers DOWN transiently
        for attempt in range(6):
            try:
                for p in ports:
                    post(p, "/index/dist/query", batch().encode(),
                         timeout=1800)
                break
            except (RuntimeError, OSError):
                if attempt == 5:
                    raise
                time.sleep(4)

        # answer-equality: cluster TopN == word-wise oracle over all
        # shards (r4 weak #3: the distributed config had no oracle)
        got = json.loads(post(
            p0, "/index/dist/query",
            b"TopN(metric, Intersect(Row(seg=1), Row(seg=3)), n=5)",
            timeout=1800))
        want = oracle_topn5(oracle_words, range(N_SHARDS5D), 1, 3)
        got_pairs = [(p["id"], p["count"]) for p in got["results"][0]]
        assert got_pairs == want, f"5d mismatch: {got_pairs} != {want}"

        # -- record phase (docs/cluster.md; the PR 5 slow-log corpus):
        # drop every node's slow threshold to ~0 so the ring records the
        # whole mixed workload — TopN batches plus Count(Intersect) and
        # Row singles — then harvest the recorded query texts as the
        # replay corpus and restore the threshold before measuring
        for srv in servers:
            srv.slowlog.threshold_s = 1e-9
        # the slow log marks over-ceiling entries textTruncated
        # (slow-log-text-max knob): the harvester skips those BY FLAG —
        # a truncated batch replays as a parse error, and the old
        # length-heuristic filter silently depended on the exact
        # ceiling value
        mixed = [_cfg5_batch(rng, 4) for _ in range(12)]
        for i in range(16):
            a = int(rng.integers(0, 4))
            b = (a + 1 + int(rng.integers(0, 3))) % 4
            mixed.append(
                f"Count(Intersect(Row(seg={a}), Row(seg={b})))"
                if i % 2 else f"Row(seg={a})")
        for i, m in enumerate(mixed):
            post(ports[i % 4], "/index/dist/query", m.encode(),
                 timeout=1800)
        corpus = []
        for p in ports:
            slow = json.loads(req(p, "GET", "/debug/slow"))
            corpus.extend(
                e["query"] for e in slow.get("entries", [])
                if e.get("index") == "dist" and e.get("query")
                and not e.get("textTruncated"))
        assert len(corpus) >= len(mixed), \
            f"slow-log recorded only {len(corpus)} of {len(mixed)}"
        for srv in servers:
            srv.slowlog.threshold_s = 1.0
        calls_per_replay = sum(max(q.count("TopN("), 1) for q in corpus)

        # baseline the timing counters AFTER warm-up: the warm waves pay
        # each node's XLA compile (seconds), which must not pollute the
        # per-wave averages published below
        snap0 = json.loads(req(p0, "GET", "/debug/vars"))
        t0s = snap0.get("timings", {})

        def run():
            batches = [(ports[i % 4], corpus[i % len(corpus)].encode())
                       for i in range(len(corpus))]
            lats = []

            def post_one(pb):
                t1 = time.perf_counter()
                post(pb[0], "/index/dist/query", pb[1])
                lats.append(time.perf_counter() - t1)

            t0 = time.perf_counter()
            with ThreadPoolExecutor(T) as pool:
                list(pool.map(post_one, batches))
            return (calls_per_replay / (time.perf_counter() - t0),
                    float(np.median(lats)))

        (qps, p50_s), spread = best_of(run)
        (oracle_qps,), _ = best_of(
            lambda: (cpu_config5(oracle_words, range(N_SHARDS5D), rng),),
            n=2)
        # coordinator-side breakdown (avg ms per fan-out wave, timed
        # waves only: post-warm delta of the cumulative counters)
        snap = json.loads(req(p0, "GET", "/debug/vars"))
        timings = snap.get("timings", {})

        def avg_ms(name):
            t = timings.get(name)
            if not t or not t.get("count"):
                return None
            base = t0s.get(name, {"count": 0, "sum": 0.0})
            cnt = t["count"] - base.get("count", 0)
            tot = t["sum"] - base.get("sum", 0.0)
            return round(1e3 * tot / cnt, 2) if cnt > 0 else None

        return {
            "qps": round(qps, 1),
            "batch_p50_ms": round(p50_s * 1e3, 1),
            "spread": spread,
            "nodes": 4,
            "workload": "recorded_replay",
            "corpus_queries": len(corpus),
            "columns": N_SHARDS5D * SHARD_WIDTH,
            "vs_cpu": round(qps / oracle_qps, 2),
            "cpu_qps": round(oracle_qps, 2),
            "breakdown_avg_ms": {
                "peer_exec": avg_ms("cluster.multi.peer_exec"),
                "wire_overhead": avg_ms("cluster.multi.wire_overhead"),
                "local_exec": avg_ms("cluster.multi.local_exec"),
                "reduce": avg_ms("cluster.multi.reduce"),
            },
        }
    finally:
        for s in servers:
            try:
                s.close()
            # lint: allow(swallowed-exception) — bench teardown; the
            # server may already be down and the leg's numbers are in
            except Exception:
                pass


def _routing_leg(rng, *, n_cold_shards=6, waves=4, wave_q=64, threads=8,
                 hot_bits=6000, cold_bits=4000):
    """Elastic-serving leg (docs/cluster.md "Read routing &
    rebalancing"): 3 real server nodes in-process, replica_n=2, and a
    SKEWED workload — ~80% of queries hit a hot 2-shard index, the rest
    spread over a cold index — replayed under read-routing=primary
    (reads pinned to the jump-hash primary, the pre-PR-13 behavior) and
    then read-routing=loaded.  Asserts the two runs answer byte-
    identically and reports qps for both plus the per-shard replica
    spread (how many nodes served each hot shard under loaded — the
    idle-replica signal this subsystem exists to fix)."""
    import http.client
    import socket
    import tempfile
    import threading

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server

    socks = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    hosts = [f"localhost:{p}" for p in ports]
    servers = []

    def post(port, path, body: bytes, timeout=600):
        conn = http.client.HTTPConnection("localhost", port,
                                          timeout=timeout)
        conn.request("POST", path, body=body)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{path}: {resp.status} {data[:200]!r}")
        return json.loads(data)

    try:
        for i, p in enumerate(ports):
            srv = Server(Config(
                data_dir=tempfile.mkdtemp(prefix=f"ptpu_rt_{i}_"),
                bind=hosts[i], node_id=f"node{i}", cluster_hosts=hosts,
                replica_n=2, anti_entropy_interval=0))
            servers.append(srv)
            srv.open()
        p0 = ports[0]
        for name, n_shards, n_bits in (("hotidx", 2, hot_bits),
                                       ("coldidx", n_cold_shards,
                                        cold_bits)):
            post(p0, f"/index/{name}", b"{}")
            post(p0, f"/index/{name}/field/a", b"{}")
            cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH,
                                          size=n_bits))
            rows = rng.integers(0, 8, size=cols.size)
            post(p0, f"/index/{name}/field/a/import", json.dumps({
                "rowIDs": rows.tolist(),
                "columnIDs": cols.tolist()}).encode())

        def gen_q():
            a = int(rng.integers(0, 8))
            b = (a + 1 + int(rng.integers(0, 6))) % 8
            hot = rng.random() < 0.8
            idx = "hotidx" if hot else "coldidx"
            kind = int(rng.integers(0, 4))
            if kind == 0:
                q = f"Count(Intersect(Row(a={a}), Row(a={b})))"
            elif kind == 1:
                q = f"Count(Row(a={a}))"
            elif kind == 2:
                q = f"Row(a={a})"
            else:
                q = "TopN(a, n=0)"  # exact cluster reduce
            return idx, q

        corpus = [gen_q() for _ in range(wave_q)]
        # warm every node's compiles before timing
        for p in ports:
            for idx, q in corpus[:6]:
                post(p, f"/index/{idx}/query", q.encode(), timeout=1800)
        coord = servers[0].cluster

        def run(policy):
            for srv in servers:
                srv.cluster.router.policy = policy
            coord.load_tracker.rotate()
            coord.load_tracker.rotate()
            answers = {}
            lock = threading.Lock()

            def post_one(item):
                i, (idx, q) = item
                out = post(p0, f"/index/{idx}/query", q.encode())
                with lock:
                    answers[i % wave_q] = out["results"]

            items = [(i, corpus[i % wave_q])
                     for i in range(waves * wave_q)]
            t0 = time.perf_counter()
            with ThreadPoolExecutor(threads) as pool:
                list(pool.map(post_one, items))
            qps = len(items) / (time.perf_counter() - t0)
            return qps, answers

        qps_primary, ans_primary = run("primary")
        qps_loaded, ans_loaded = run("loaded")
        assert ans_loaded == ans_primary, \
            "loaded routing diverged from primary-pinned answers"
        # per-shard replica spread on the hot index under loaded
        snap = coord.load_tracker.snapshot(top=32)
        spread = {e["shard"]: len(e["nodes"]) for e in snap["hottest"]
                  if e["index"] == "hotidx"}
        return {
            "answers_identical": True,
            "qps_primary": round(qps_primary, 1),
            "qps_loaded": round(qps_loaded, 1),
            "loaded_vs_primary": round(qps_loaded / qps_primary, 3)
            if qps_primary else None,
            "hot_shard_nodes": max(spread.values(), default=0),
            "hot_shard_spread": spread,
            "fallbacks": servers[0].cluster.router.snapshot()["fallbacks"],
        }
    finally:
        for s in servers:
            try:
                s.close()
            # lint: allow(swallowed-exception) — bench teardown; the
            # server may already be down and the leg's numbers are in
            except Exception:
                pass


def bench_routing(rng):
    """Main-bench elastic-serving leg: the skewed-hot-index corpus at
    full wave counts (see _routing_leg)."""
    return _routing_leg(rng, waves=6, wave_q=64, threads=8)


def run_routing_smoke(rng) -> dict:
    """Routing leg of --smoke (docs/cluster.md): the skew corpus small —
    routing-on (loaded) vs primary-pinned qps, answers asserted
    identical, and the hot shards served by more than one node."""
    out = _routing_leg(rng, waves=3, wave_q=24, threads=8,
                       hot_bits=2500, cold_bits=1500, n_cold_shards=4)
    assert out["hot_shard_nodes"] > 1, \
        f"hot shards never spread: {out['hot_shard_spread']}"
    return out


def _chaos_leg(rng, *, n_shards=8, n_base=30, n_fault=12,
               min_delay_s=0.3):
    """Tail-tolerance leg (docs/robustness.md "Tail-tolerant fan-out"):
    3 real server nodes with the two replicas dialed through
    ChaosProxies (utils/netchaos.py — REAL sockets, not failpoints),
    read-routing pinned to primary so the straggler keeps being
    targeted, hedge-delay-ms fixed at 40.  Measures intersect/TopN
    latency three ways on identical data: no fault (baseline), one
    replica's responses delayed >= 5x the baseline p99 with hedging ON,
    and the same straggler with hedging OFF.  Asserts all three runs
    answer byte-identically; the hedged-vs-baseline p99 ratio is the
    headline number."""
    import http.client
    import socket
    import tempfile

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server
    from pilosa_tpu.utils.netchaos import ChaosProxy

    socks = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    binds = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    proxies = {}
    hosts = [f"localhost:{binds[0]}"]
    for i in (1, 2):
        proxies[f"node{i}"] = ChaosProxy("localhost", binds[i])
        hosts.append(proxies[f"node{i}"].address)
    servers = []

    def post(port, path, body: bytes, timeout=600):
        conn = http.client.HTTPConnection("localhost", port,
                                          timeout=timeout)
        conn.request("POST", path, body=body)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{path}: {resp.status} {data[:200]!r}")
        return json.loads(data)

    try:
        for i, p in enumerate(binds):
            srv = Server(Config(
                data_dir=tempfile.mkdtemp(prefix=f"ptpu_chaos_{i}_"),
                bind=f"localhost:{p}", node_id=f"node{i}",
                cluster_hosts=hosts, replica_n=2,
                anti_entropy_interval=0,
                read_routing="primary", hedge_delay_ms=40.0))
            servers.append(srv)
            srv.open()
        coord = servers[0].cluster
        # an index whose placement gives node0 some — but not all —
        # replica sets, so a remote straggler actually owns primaries
        def remote_owned(name):
            return [s for s in range(n_shards)
                    if "node0" not in
                    coord.placement.shard_nodes(name, s)]
        index = next(name for name in (f"chaos{i}" for i in range(64))
                     if 0 < len(remote_owned(name)) < n_shards)
        p0 = binds[0]
        post(p0, f"/index/{index}", b"{}")
        post(p0, f"/index/{index}/field/a", b"{}")
        cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH,
                                      size=5000))
        rows = rng.integers(0, 8, size=cols.size)
        post(p0, f"/index/{index}/field/a/import", json.dumps({
            "rowIDs": rows.tolist(), "columnIDs": cols.tolist()}).encode())
        corpus = ["Count(Intersect(Row(a=1), Row(a=2)))",
                  "TopN(a, n=0)", "Count(Row(a=3))", "Row(a=4)"]
        for q in corpus:  # compile warm-up
            post(p0, f"/index/{index}/query", q.encode(), timeout=1800)
        # primary-policy target of a node0-less shard = first owner in
        # placement order (every node is READY here)
        straggler = coord.placement.shard_nodes(
            index, remote_owned(index)[0])[0]

        def run(n):
            lats, answers = [], []
            for i in range(n):
                q = corpus[i % len(corpus)]
                t0 = time.perf_counter()
                out = post(p0, f"/index/{index}/query", q.encode())
                lats.append(time.perf_counter() - t0)
                if i < len(corpus):
                    answers.append(out["results"])
            lats.sort()
            return lats[max(int(len(lats) * 0.99) - 1, 0)], answers

        p99_base, ans_base = run(n_base)
        delay = max(min_delay_s, 5 * p99_base)
        counts0 = servers[0].api.stats.snapshot()["counts"]
        hedges0 = counts0.get("cluster.hedges", 0)
        proxies[straggler].configure(f"down=latency:{delay}")
        p99_hedged, ans_hedged = run(n_fault)
        coord.hedge_reads = False
        p99_unhedged, ans_unhedged = run(n_fault)
        coord.hedge_reads = True
        proxies[straggler].heal()
        counts1 = servers[0].api.stats.snapshot()["counts"]
        assert ans_hedged == ans_base and ans_unhedged == ans_base, \
            "chaos leg answers diverged from the no-fault baseline"
        return {
            "answers_identical": True,
            "injected_delay_ms": round(delay * 1e3, 1),
            "p99_base_ms": round(p99_base * 1e3, 1),
            "p99_hedged_ms": round(p99_hedged * 1e3, 1),
            "p99_unhedged_ms": round(p99_unhedged * 1e3, 1),
            "hedged_vs_base": round(p99_hedged / p99_base, 2)
            if p99_base else None,
            "unhedged_vs_base": round(p99_unhedged / p99_base, 2)
            if p99_base else None,
            "hedges": counts1.get("cluster.hedges", 0) - hedges0,
            "hedge_wins": counts1.get("cluster.hedge_wins", 0)
            - counts0.get("cluster.hedge_wins", 0),
        }
    finally:
        for s in servers:
            try:
                s.close()
            # lint: allow(swallowed-exception) — bench teardown; the
            # server may already be down and the leg's numbers are in
            except Exception:
                pass
        for proxy in proxies.values():
            proxy.close()


def bench_chaos(rng):
    """Main-bench tail-tolerance leg: straggler p99 with hedging on vs
    off at full query counts (see _chaos_leg)."""
    return _chaos_leg(rng, n_base=40, n_fault=16)


def run_chaos_smoke(rng) -> dict:
    """Chaos leg of --smoke (docs/robustness.md): small query counts;
    asserts hedging actually fired and rescued the tail — hedged p99
    under the injected delay, unhedged p99 bound BY it — with answers
    byte-identical across all three runs (asserted in _chaos_leg)."""
    out = _chaos_leg(rng, n_base=20, n_fault=8, min_delay_s=0.3)
    assert out["hedges"] > 0, "straggler never triggered a hedge"
    assert out["p99_hedged_ms"] < out["injected_delay_ms"], out
    assert out["p99_unhedged_ms"] >= 0.8 * out["injected_delay_ms"], out
    assert out["p99_hedged_ms"] < out["p99_unhedged_ms"], out
    return out


def _slo_leg(rng, *, n_shards=6, fault_delay_s=0.5, overhead_q=100,
             overhead_runs=2):
    """SLO/alerting leg (docs/observability.md "SLOs & alerting"), two
    stories on real sockets.  (1) Alerting: a 3-node cluster with the
    replica nodes dialed through ChaosProxies; delaying every remote
    read past the 250 ms latency objective must fire slo-latency-burn
    within 2 evaluation passes of the first faulted sample, the on-fire
    hook must land a readable flight-recorder bundle inside the disk
    budget, and healing the proxies must resolve the alert.  The
    monitor cadence is parked at 60 s and the leg drives force-samples
    + evaluations itself, so "evaluation interval" is deterministic
    wall-clock-free.  (2) Overhead: the same workload against an
    evaluation-on vs evaluation-off single node (alert-rules=all vs
    off; the time-series sampler runs in BOTH, isolating evaluation
    cost) — evaluation rides the monitor thread, never a query, so
    serving qps must be noise-identical (the >=0.95x acceptance,
    best-of-N) with byte-identical answers."""
    import http.client
    import socket
    import tempfile

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server
    from pilosa_tpu.utils.netchaos import ChaosProxy

    def free_ports(n):
        socks = []
        for _ in range(n):
            s = socket.socket()
            s.bind(("localhost", 0))
            socks.append(s)
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        return ports

    def post(port, path, body: bytes, timeout=600):
        conn = http.client.HTTPConnection("localhost", port,
                                          timeout=timeout)
        conn.request("POST", path, body=body)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{path}: {resp.status} {data[:200]!r}")
        return json.loads(data)

    out = {}

    # -- story 1: straggler -> fire -> bundle -> heal -> resolve ---------
    binds = free_ports(3)
    proxies = {}
    hosts = [f"localhost:{binds[0]}"]
    for i in (1, 2):
        proxies[f"node{i}"] = ChaosProxy("localhost", binds[i])
        hosts.append(proxies[f"node{i}"].address)
    servers = []
    try:
        for i, p in enumerate(binds):
            srv = Server(Config(
                data_dir=tempfile.mkdtemp(prefix=f"ptpu_slo_{i}_"),
                bind=f"localhost:{p}", node_id=f"node{i}",
                cluster_hosts=hosts, replica_n=1,
                anti_entropy_interval=0, read_routing="primary",
                hedge_reads=False,
                slo_latency_ms=250.0, slo_target=0.999,
                flight_recorder_mb=4,
                timeseries_interval=60, timeseries_window=1200,
                trace_sample_rate=0.0))
            servers.append(srv)
            srv.open()
        srv0 = servers[0]
        p0 = binds[0]
        coord = srv0.cluster
        # an index whose placement leaves node0 short of some shards, so
        # the proxy delay sits on the query path
        index = next(
            name for name in (f"slo{i}" for i in range(64))
            if any("node0" not in coord.placement.shard_nodes(name, s)
                   for s in range(n_shards)))
        post(p0, f"/index/{index}", b"{}")
        post(p0, f"/index/{index}/field/a", b"{}")
        cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH,
                                      size=3000))
        rows = rng.integers(0, 4, size=cols.size)
        post(p0, f"/index/{index}/field/a/import", json.dumps({
            "rowIDs": rows.tolist(), "columnIDs": cols.tolist()}).encode())
        q = "Count(Row(a=1))"
        baseline = post(p0, f"/index/{index}/query", q.encode(),
                        timeout=1800)["results"]
        eng = srv0.slo
        assert eng is not None and eng.enabled, "SLO engine absent"

        def pulse():
            for _ in range(3):
                assert post(p0, f"/index/{index}/query",
                            q.encode())["results"] == baseline, \
                    "answers diverged under the straggler"
            assert srv0.sample_timeseries(force=True)
            eng.evaluate()

        # prime one healthy sample so deltas span single intervals
        srv0.sample_timeseries(force=True)
        eng.evaluate()
        evals_before = eng.evaluations
        for proxy in proxies.values():
            proxy.configure(f"down=latency:{fault_delay_s}")
        for _ in range(3):
            pulse()
            if "slo-latency-burn" in eng.active:
                break
        fired = "slo-latency-burn" in eng.active
        evals_to_fire = (
            eng.active["slo-latency-burn"]["firedAtEvaluation"]
            - evals_before) if fired else None
        rec = srv0.flightrec
        bundle_ok, bundle_bytes = False, 0
        if rec is not None and rec.last is not None:
            with open(rec.last["path"]) as f:
                bundle = json.load(f)
            bundle_ok = "slo-latency-burn" in \
                (bundle.get("alerts") or {}).get("active", {})
            bundle_bytes = rec.last["bytes"]
        for proxy in proxies.values():
            proxy.heal()
        resolved = False
        for _ in range(10):
            pulse()
            if "slo-latency-burn" not in eng.active:
                resolved = True
                break
        out["alert"] = {
            "fired": fired,
            "evals_to_fire": evals_to_fire,
            "resolved": resolved,
            "bundle_ok": bundle_ok,
            "bundle_kb": round(bundle_bytes / 1024, 1),
            "budget_held": rec is not None
            and rec.disk_bytes() <= rec.budget_mb << 20,
            "fired_total": eng.fired_total,
            "resolved_total": eng.resolved_total,
        }
    finally:
        for s in servers:
            try:
                s.close()
            # lint: allow(swallowed-exception) — bench teardown; the
            # server may already be down and the leg's numbers are in
            except Exception:
                pass
        for proxy in proxies.values():
            proxy.close()

    # -- story 2: evaluation overhead on the serving path ----------------
    cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, size=4000))
    rows = rng.integers(0, 4, size=cols.size)
    corpus = ["Count(Row(a=1))", "Row(a=2)", "TopN(a, n=3)",
              "Count(Intersect(Row(a=0), Row(a=3)))"]
    qps, answers = {}, {}
    for mode in ("on", "off"):
        srv = Server(Config(
            data_dir=tempfile.mkdtemp(prefix=f"ptpu_slo_{mode}_"),
            bind="localhost:0",
            alert_rules="all" if mode == "on" else "off",
            timeseries_interval=0.05, timeseries_window=30,
            trace_sample_rate=0.0))
        srv.open()
        try:
            p = srv.port
            post(p, "/index/ov", b"{}")
            post(p, "/index/ov/field/a", b"{}")
            post(p, "/index/ov/field/a/import", json.dumps({
                "rowIDs": rows.tolist(),
                "columnIDs": cols.tolist()}).encode())
            for qq in corpus:  # compile warm-up
                post(p, "/index/ov/query", qq.encode(), timeout=1800)
            best, got = 0.0, []
            for _ in range(overhead_runs):  # best-of-N: absorb CI noise
                t0 = time.perf_counter()
                got = []
                for i in range(overhead_q):
                    r = post(p, "/index/ov/query",
                             corpus[i % len(corpus)].encode())
                    if i < len(corpus):
                        got.append(r["results"])
                best = max(best,
                           overhead_q / (time.perf_counter() - t0))
            qps[mode] = best
            answers[mode] = got
            if mode == "on":
                assert srv.slo is not None \
                    and srv.slo.evaluations > 0, \
                    "evaluation-on leg never evaluated"
                out["evaluations_on"] = srv.slo.evaluations
            else:
                assert srv.slo is None, "alert-rules=off still built"
        finally:
            srv.close()
    out["answers_identical"] = answers["on"] == answers["off"]
    out["qps_on"] = round(qps["on"], 1)
    out["qps_off"] = round(qps["off"], 1)
    out["qps_ratio"] = round(qps["on"] / max(qps["off"], 1e-9), 3)
    return out


def bench_slo(rng):
    """Main-bench SLO/alerting leg: the same two stories at a larger
    overhead sample (see _slo_leg)."""
    return _slo_leg(rng, overhead_q=240, overhead_runs=3)


def run_slo_smoke(rng) -> dict:
    """SLO leg of --smoke (docs/observability.md "SLOs & alerting"):
    the straggler must page within 2 evaluation passes, the flight
    recorder must land a readable bundle inside its disk budget, the
    heal must resolve the alert, and burn-rate evaluation must be free
    on the serving path (>=0.95x qps, best-of-2) with byte-identical
    answers."""
    out = _slo_leg(rng)
    a = out["alert"]
    assert a["fired"] is True, a
    assert a["evals_to_fire"] <= 2, a
    assert a["bundle_ok"] is True and a["bundle_kb"] > 0, a
    assert a["budget_held"] is True, a
    assert a["resolved"] is True, a
    assert out["answers_identical"] is True, out
    assert out["qps_ratio"] >= 0.95, out
    return out


def _wire_leg(rng, *, waves=4, wave_q=48, threads=8, n_shards=4,
              dense_rows=6, dense_bits=320000, sparse_rows=6,
              sparse_run=3000, fallback_check=False):
    """Internal-wire leg (docs/cluster.md "Internal query wire"): 2 real
    server nodes where the coordinator (node0) owns NO shard of either
    bench index — "w1" and "qx" jump-hash every shard onto node1 — so
    every query is a pure remote fan-out and the internal wire carries
    all result traffic.  The SAME recorded corpus replays once over the
    PTPUQRY1 binary wire and once with every node pinned
    internal-wire=json (the PR 16 knob, flipped in-process between
    passes); answers are asserted byte-identical, and qps, wire
    bytes/query, and the per-wave wire-vs-reduce time split come off the
    cluster counters (cluster.wire_bytes_*, cluster.multi.wire_overhead
    / cluster.multi.reduce — same series both wires).

    Two corpora, matching the wire's two size regimes: a DENSE Row-heavy
    one ("w1": scattered random bits, segments ride raw or
    bitmap-packed; the JSON wire pays zlib+base64 of every 128 KiB
    segment either way, so this is the qps headline) and a SPARSE
    clustered one ("qx": short runs, roaring-packs to a few hundred
    bytes; this is the bytes/query headline)."""
    import http.client
    import socket
    import tempfile
    import threading

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server

    socks = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    hosts = [f"localhost:{p}" for p in ports]
    servers = []

    def post(port, path, body: bytes, timeout=600):
        conn = http.client.HTTPConnection("localhost", port,
                                          timeout=timeout)
        conn.request("POST", path, body=body)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{path}: {resp.status} {data[:200]!r}")
        return json.loads(data)

    def set_wire(mode):
        # flip the knob in-process between passes: the serving branch
        # keys off cluster.internal_wire, the dispatch side off
        # client.wire_mode; clear the per-peer latches so the new mode
        # starts from a clean negotiation state
        for srv in servers:
            srv.cluster.internal_wire = mode
            srv.cluster.client.wire_mode = mode
            srv.cluster.client._wire_down.clear()
            srv.cluster.client._peer_wire.clear()

    try:
        for i, p in enumerate(ports):
            srv = Server(Config(
                data_dir=tempfile.mkdtemp(prefix=f"ptpu_wire_{i}_"),
                bind=hosts[i], node_id=f"node{i}", cluster_hosts=hosts,
                replica_n=1, anti_entropy_interval=0,
                internal_wire="bin1"))
            servers.append(srv)
            srv.open()
        p0 = ports[0]
        span = n_shards * SHARD_WIDTH
        for name in ("w1", "qx"):
            post(p0, f"/index/{name}", b"{}")
            post(p0, f"/index/{name}/field/a", b"{}")
        # seed through the coordinator's api IN-PROCESS (the public
        # import JSON adds nothing here); the cluster import fan-out
        # still routes each shard batch to its owner.  Dense rows are
        # scattered at ~dense_bits/n_shards bits per segment — dense
        # enough that the JSON wire's per-segment zlib actually costs
        # what it costs in production, which is the regime the binary
        # wire exists for.
        for r in range(dense_rows):
            cols = np.unique(rng.integers(0, span, size=dense_bits))
            servers[0].api.import_bits(
                "w1", "a", [r] * cols.size, cols.tolist())
        for r in range(sparse_rows):
            # short runs near the base of each shard: roaring run/array
            # containers, a few hundred wire bytes per packed segment
            cols = np.concatenate([
                np.arange(s * SHARD_WIDTH + r * sparse_run,
                          s * SHARD_WIDTH + (r + 1) * sparse_run)
                for s in range(n_shards)])
            servers[0].api.import_bits(
                "qx", "a", [r] * cols.size, cols.tolist())

        def gen_dense():
            a = int(rng.integers(0, dense_rows))
            b = (a + 1 + int(rng.integers(0, dense_rows - 1))) \
                % dense_rows
            kind = int(rng.integers(0, 3))
            if kind == 0:
                q = f"Row(a={a})Row(a={b})"
            elif kind == 1:
                q = f"Union(Row(a={a}), Row(a={b}))Count(Row(a={a}))"
            else:
                q = f"Row(a={a})Intersect(Row(a={a}), Row(a={b}))"
            return "w1", q

        def gen_sparse():
            a = int(rng.integers(0, sparse_rows))
            b = (a + 1) % sparse_rows
            return "qx", f"Row(a={a})Row(a={b})"

        dense_corpus = [gen_dense() for _ in range(wave_q)]
        sparse_corpus = [gen_sparse() for _ in range(wave_q)]
        stats = servers[0].stats

        def counters():
            return {
                "bytes": stats.count_value("cluster.wire_bytes_tx")
                + stats.count_value("cluster.wire_bytes_rx"),
                "frames": stats.count_value("cluster.wire_frames"),
                "fallback": stats.count_value("cluster.wire_fallback"),
                "wire_s": stats.timing_totals(
                    "cluster.multi.wire_overhead")[1],
                "reduce_s": stats.timing_totals(
                    "cluster.multi.reduce")[1],
            }

        # replay: recorded corpus, threaded like production fan-in, but
        # dispatched through the coordinator's api.query IN-PROCESS —
        # the public HTTP+JSON surface is identical in both modes and
        # would dilute the internal-wire signal this leg exists to
        # measure.  Two passes: an UNTIMED identity pass that captures
        # every answer in public wire form (result_to_wire — exactly
        # what a client would see, for the byte-identity assert), then
        # the timed pass, pure dispatch with results consumed but not
        # re-serialized.  Returns qps + answers + the counter deltas of
        # the timed window.
        from pilosa_tpu.parallel.cluster import result_to_wire

        def replay(corpus, n):
            answers = {}
            for i, (idx, q) in enumerate(corpus):
                res = servers[0].api.query(idx, q)
                answers[i] = json.dumps(
                    [result_to_wire(r) for r in res], sort_keys=True)

            def post_one(item):
                _i, (idx, q) = item
                servers[0].api.query(idx, q)

            items = [(i, corpus[i % len(corpus)]) for i in range(n)]
            c0 = counters()
            t0 = time.perf_counter()
            with ThreadPoolExecutor(threads) as pool:
                list(pool.map(post_one, items))
            dt = time.perf_counter() - t0
            c1 = counters()
            d = {k: c1[k] - c0[k] for k in c0}
            return {
                "qps": n / dt,
                "answers": answers,
                "bytes_per_q": d["bytes"] / n,
                "frames_per_q": d["frames"] / n,
                "fallback": d["fallback"],
                "wire_ms_per_q": d["wire_s"] / n * 1e3,
                "reduce_ms_per_q": d["reduce_s"] / n * 1e3,
            }

        runs = {}
        for mode in ("bin1", "json"):
            set_wire(mode)
            for idx, q in dense_corpus[:4] + sparse_corpus[:4]:
                servers[0].api.query(idx, q)  # warm compiles + wire
            runs[mode] = {
                "dense": replay(dense_corpus, waves * wave_q),
                "sparse": replay(sparse_corpus, wave_q),
            }
        for leg in ("dense", "sparse"):
            assert runs["bin1"][leg]["answers"] == \
                runs["json"][leg]["answers"], \
                f"binary wire diverged from JSON answers ({leg})"

        out = {
            "answers_identical": True,
            "qps_bin1": round(runs["bin1"]["dense"]["qps"], 1),
            "qps_json": round(runs["json"]["dense"]["qps"], 1),
            "bin1_vs_json": round(runs["bin1"]["dense"]["qps"]
                                  / runs["json"]["dense"]["qps"], 2),
            "dense_wire_bytes_per_q": {
                m: int(runs[m]["dense"]["bytes_per_q"])
                for m in runs},
            "sparse_wire_bytes_per_q": {
                m: int(runs[m]["sparse"]["bytes_per_q"])
                for m in runs},
            "sparse_bytes_ratio": round(
                runs["json"]["sparse"]["bytes_per_q"]
                / runs["bin1"]["sparse"]["bytes_per_q"], 2),
            "wire_ms_per_q": {
                m: round(runs[m]["dense"]["wire_ms_per_q"], 3)
                for m in runs},
            "reduce_ms_per_q": {
                m: round(runs[m]["dense"]["reduce_ms_per_q"], 3)
                for m in runs},
            "frames_per_q_bin1": round(
                runs["bin1"]["dense"]["frames_per_q"], 1),
        }
        if fallback_check:
            # mixed-version exercise: node1 pinned json, node0 still
            # binary and force-marked optimistic — the first POST must
            # 415, downgrade-latch, retry as JSON, and answer
            # identically
            servers[1].cluster.internal_wire = "json"
            cl0 = servers[0].cluster
            cl0.internal_wire = "bin1"
            cl0.client.wire_mode = "bin1"
            cl0.client._wire_down.clear()
            host1 = cl0.nodes[1].host
            cl0.client._peer_wire[host1] = "bin1"
            fb0 = stats.count_value("cluster.wire_fallback")
            idx, q = sparse_corpus[0]
            res = servers[0].api.query(idx, q)
            got = json.dumps([result_to_wire(r) for r in res],
                             sort_keys=True)
            fb = stats.count_value("cluster.wire_fallback") - fb0
            assert fb >= 1, "415 downgrade never fired"
            assert got == runs["bin1"]["sparse"]["answers"][0], \
                "downgraded answer diverged"
            out["fallback"] = {"count": int(fb),
                               "answers_identical": True}
        return out
    finally:
        for s in servers:
            try:
                s.close()
            # lint: allow(swallowed-exception) — bench teardown; the
            # server may already be down and the leg's numbers are in
            except Exception:
                pass


def bench_wire(rng):
    """Main-bench internal-wire leg: binary vs JSON at full wave counts
    on the recorded dense + sparse corpora (see _wire_leg)."""
    return _wire_leg(rng, waves=5, wave_q=48, threads=8)


def run_wire_smoke(rng) -> dict:
    """Wire leg of --smoke (docs/cluster.md "Internal query wire"):
    small corpus; asserts answers byte-identical across wires, sparse
    wire bytes/query actually reduced by the roaring framing, and the
    mixed-version 415 downgrade exercised end-to-end."""
    out = _wire_leg(rng, waves=2, wave_q=16, threads=6,
                    dense_rows=4, dense_bits=240000, sparse_run=1500,
                    fallback_check=True)
    assert out["sparse_bytes_ratio"] > 1.5, \
        f"binary wire did not shrink sparse results: {out}"
    assert out["fallback"]["count"] >= 1, out
    return out


def _tenant_leg(rng, *, n_polite=20, flood_threads=8, flood_iters=2000,
                n_shards=4):
    """Two-tenant flood leg (docs/robustness.md "Tenant isolation"): a
    hostile tenant hammers the query gate from ``flood_threads`` threads
    that never honor Retry-After, while a polite tenant runs its fixed
    corpus sequentially with bounded, Retry-After-honoring retries.
    Three passes on identical data: polite alone (idle baseline), the
    flood with isolation ON (weighted-fair DRR, polite:4 hostile:1),
    and the flood with isolation OFF (the legacy single FIFO).  Records
    polite p99 per pass, per-tenant shed counts + attribution from the
    tenant registry, and hedge-budget denials; asserts the polite
    corpus answers byte-identically across all three passes — the
    isolation plane must never change WHAT an admitted query returns,
    only WHEN it runs."""
    import http.client
    import tempfile
    import threading

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server
    from pilosa_tpu.utils import tenant as qtenant

    cols = np.unique(rng.integers(0, n_shards * SHARD_WIDTH, size=8000))
    rows = rng.integers(0, 8, size=cols.size)
    corpus = ["Count(Intersect(Row(f=1), Row(f=2)))",
              "TopN(f, n=0)", "Count(Row(f=3))", "Row(f=4)"]

    def post(port, path, body, tenant=None, timeout=600):
        conn = http.client.HTTPConnection("localhost", port,
                                          timeout=timeout)
        headers = {qtenant.TENANT_HEADER: tenant} if tenant else {}
        conn.request("POST", path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        ra = resp.getheader("Retry-After")
        conn.close()
        return resp.status, (float(ra) if ra else None), data

    def run_pass(isolation):
        srv = Server(Config(
            data_dir=tempfile.mkdtemp(prefix="ptpu_tenant_"),
            bind="localhost:0", anti_entropy_interval=0,
            max_queries=2, queue_timeout=0.2,
            tenant_isolation=isolation,
            tenant_weights="polite:4,hostile:1"))
        srv.open()
        qtenant.REGISTRY.clear()
        try:
            p = srv.port
            st, _, _ = post(p, "/index/t", b"{}")
            assert st == 200
            st, _, _ = post(p, "/index/t/field/f", b"{}")
            assert st == 200
            st, _, _ = post(p, "/index/t/field/f/import", json.dumps({
                "rowIDs": rows.tolist(),
                "columnIDs": cols.tolist()}).encode())
            assert st == 200
            for q in corpus:  # compile warm-up
                st, _, _ = post(p, "/index/t/query", q.encode(),
                                tenant="polite", timeout=1800)
                assert st == 200

            def polite_run(n):
                lats, answers, sheds = [], [], 0
                for i in range(n):
                    q = corpus[i % len(corpus)]
                    t0 = time.perf_counter()
                    for _ in range(40):
                        st, ra, data = post(p, "/index/t/query",
                                            q.encode(), tenant="polite")
                        if st == 200:
                            break
                        assert st == 503, (st, data[:200])
                        sheds += 1
                        time.sleep(min(ra or 0.05, 0.25))
                    else:
                        raise RuntimeError(
                            "polite query never admitted in 40 tries")
                    # per-query wall time INCLUDES any shed+retry waits:
                    # the polite tenant's experienced latency, not the
                    # admitted attempt's
                    lats.append(time.perf_counter() - t0)
                    if i < len(corpus):
                        answers.append(json.loads(data)["results"])
                lats.sort()
                return (lats[max(int(len(lats) * 0.99) - 1, 0)],
                        answers, sheds)

            p99_idle, ans_idle, idle_sheds = polite_run(n_polite)
            assert idle_sheds == 0, "idle polite pass was shed?"

            stop = threading.Event()

            def flood():
                for _ in range(flood_iters):
                    if stop.is_set():
                        return
                    # rude by design: a 503's Retry-After is ignored
                    post(p, "/index/t/query", corpus[0].encode(),
                         tenant="hostile")

            threads = [threading.Thread(target=flood, daemon=True)
                       for _ in range(flood_threads)]
            for t in threads:
                t.start()
            time.sleep(0.2)  # let the flood fill the slots + queue
            try:
                p99_flood, ans_flood, polite_sheds = polite_run(n_polite)
            finally:
                stop.set()
                for t in threads:
                    t.join()
            assert ans_flood == ans_idle, \
                "admitted answers diverged under the flood"
            reg = qtenant.REGISTRY.snapshot()
            hostile_shed = reg.get("hostile", {}).get("shed", 0)
            total_shed = hostile_shed + \
                reg.get("polite", {}).get("shed", 0)
            return {
                "fair": srv.admission.snapshot()["fair"],
                "p99_idle_ms": round(p99_idle * 1e3, 1),
                "p99_flood_ms": round(p99_flood * 1e3, 1),
                "polite_vs_idle": round(p99_flood / p99_idle, 2)
                if p99_idle else None,
                "polite_sheds": polite_sheds,
                "hostile_sheds": hostile_shed,
                "total_sheds": total_shed,
                "shed_attribution": round(hostile_shed / total_shed, 3)
                if total_shed else None,
                "hedge_denied": reg.get("polite", {}).get(
                    "hedgeDenied", 0) + reg.get("hostile", {}).get(
                    "hedgeDenied", 0),
            }, ans_idle
        finally:
            qtenant.REGISTRY.clear()
            try:
                srv.close()
            # lint: allow(swallowed-exception) — bench teardown; the
            # pass's numbers are already collected
            except Exception:
                pass

    on, ans_on = run_pass(True)
    off, ans_off = run_pass(False)
    return {
        # the isolation plane changes scheduling, never answers
        "answers_identical": ans_on == ans_off,
        "isolation_on": on,
        "isolation_off": off,
    }


def bench_tenant(rng):
    """Main-bench tenant-isolation leg: polite-tenant p99 under a
    hostile flood, weighted-fair admission on vs off (see _tenant_leg).
    The acceptance read on real hardware: isolation ON holds polite p99
    within ~1.5x its idle baseline while isolation OFF degrades with
    the flood."""
    return _tenant_leg(rng, n_polite=40, flood_threads=8)


def run_tenant_smoke(rng) -> dict:
    """Tenant leg of --smoke (docs/robustness.md "Tenant isolation"):
    small counts; asserts the flood's sheds land on the hostile tenant
    (>=95% attribution), the polite tenant is never shed under
    isolation, and admitted answers are byte-identical across idle /
    isolation-on / isolation-off passes (asserted in _tenant_leg).  The
    1.5x polite-p99 bound is recorded, not asserted — CPU-smoke timing
    is too noisy to judge it; the bench on real hardware does."""
    out = _tenant_leg(rng, n_polite=12, flood_threads=6,
                      flood_iters=1000)
    on = out["isolation_on"]
    assert out["answers_identical"] is True, out
    assert on["fair"] is True and out["isolation_off"]["fair"] is False
    assert on["total_sheds"] > 0, f"flood never shed: {out}"
    assert on["shed_attribution"] >= 0.95, out
    assert on["polite_sheds"] == 0, out
    return out


# -- numpy oracle baselines (single-thread reference-algorithm stand-in) ----

def _np_frag(holder, index, field, view=None):
    f = holder.field(index, field)
    v = f.view(view or "standard")
    return {s: fr.words for s, fr in v.fragments.items()}


def cpu_config1(holder, meta, rng, n=64):
    frag = _np_frag(holder, "startrace", "stargazer")[0]
    rows = rng.integers(0, meta["star_rows"], size=n)
    t0 = time.perf_counter()
    for r in rows:
        int(np.bitwise_count(frag[r]).sum())
    return n / (time.perf_counter() - t0)


def cpu_config2(holder, meta, rng, n=64):
    frag = _np_frag(holder, "startrace", "stargazer")[0]
    sets = _rand_rows(rng, meta["star_rows"], n)
    t0 = time.perf_counter()
    for q in sets:
        seg = frag[q[0]]
        for i in range(1, 8):
            seg = seg & frag[q[i]]
        int(np.bitwise_count(seg).sum())
    return n / (time.perf_counter() - t0)


def cpu_config3(holder, meta, rng, n=2):
    lang = _np_frag(holder, "lang10m", "language")
    stars = _np_frag(holder, "lang10m", "stars")
    rs = rng.integers(0, 16, size=n)
    t0 = time.perf_counter()
    for r in rs:
        counts = np.zeros(64, dtype=np.int64)
        for s, frag in lang.items():
            filt = stars[s][r]
            masked = frag & filt[None, :]
            c = np.bitwise_count(masked).sum(axis=1).astype(np.int64)
            counts[: c.size] += c
        nz = np.nonzero(counts)[0]
        sorted(((int(counts[i]), -int(i)) for i in nz), reverse=True)[:50]
    return n / (time.perf_counter() - t0)


def cpu_config4(holder, meta, rng, n=2):
    """Bit-sliced range+sum scan with numpy words — the reference's BSI
    algorithm (fragment.go:1111 sum, :1436 rangeGT) on dense words."""
    frags = _np_frag(holder, "bsi64", "v", "bsig_v")
    xs = rng.integers(0, 1_000_000, size=n)
    t0 = time.perf_counter()
    for x in xs:
        total = 0
        for s, w in frags.items():
            depth = w.shape[0] - 2
            exists = w[0]
            # rangeGT via MSB-first magnitude compare
            eq = exists.copy()
            gt = np.zeros_like(exists)
            for i in range(depth - 1, -1, -1):
                bit = w[2 + i]
                if (int(x) >> i) & 1:
                    eq &= bit
                else:
                    gt |= eq & bit
                    eq &= ~bit
            filt = gt
            for i in range(depth):
                total += int(np.bitwise_count(w[2 + i] & filt).sum()) << i
    return n / (time.perf_counter() - t0)


def bench_http(server_port, rng, n_rows):
    """Config 2 through the real HTTP surface: concurrent POSTs over
    per-thread keep-alive connections (the ThreadingHTTPServer overlaps
    request threads the same way the engine bench overlaps client
    threads)."""
    import http.client
    import threading

    B, n_batches, T = 256, 24, 8
    local = threading.local()

    def post(body):
        conn = getattr(local, "conn", None)
        if conn is None:
            conn = local.conn = http.client.HTTPConnection(
                "localhost", server_port, timeout=120)
        try:
            conn.request("POST", "/index/startrace/query",
                         body=body.encode())
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException):
            conn.close()
            local.conn = None
            raise
        assert resp.status == 200, data
        return data

    def batch():
        sets = _rand_rows(rng, n_rows, B)
        return " ".join("Count(Intersect(" + ", ".join(
            f"Row(stargazer={r})" for r in q) + "))" for q in sets)

    post(batch())  # warm
    batches = [batch() for _ in range(n_batches)]
    t0 = time.perf_counter()
    with ThreadPoolExecutor(T) as pool:
        list(pool.map(post, batches))
    return (B * n_batches) / (time.perf_counter() - t0)


def _http_count_load(port, index, field, n_rows, rng, threads,
                     per_thread=120):
    """Drive ``threads`` keep-alive clients of SINGLE small Count queries
    (one query per POST — the serving shape cross-query dynamic batching
    exists for; distinct literals defeat the tunnel's (executable, args)
    memoization).  Returns (qps, p50_s)."""
    import http.client
    import threading

    local = threading.local()

    def post(body: bytes):
        conn = getattr(local, "conn", None)
        if conn is None:
            conn = local.conn = http.client.HTTPConnection(
                "localhost", port, timeout=120)
        try:
            conn.request("POST", f"/index/{index}/query", body=body)
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException):
            conn.close()
            local.conn = None
            raise
        assert resp.status == 200, data
        return data

    rows = rng.integers(0, n_rows, size=threads * per_thread)
    lats: list[float] = []
    lock = threading.Lock()

    def worker(k):
        mine = []
        for i in range(k * per_thread, (k + 1) * per_thread):
            t1 = time.perf_counter()
            post(f"Count(Row({field}={rows[i]}))".encode())
            mine.append(time.perf_counter() - t1)
        with lock:
            lats.extend(mine)

    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker, args=(k,))
          for k in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.perf_counter() - t0
    return threads * per_thread / dt, float(np.median(lats))


def bench_http_dynamic_batching(holder, executor, meta, rng):
    """Concurrent-HTTP dynamic-batching config (docs/batching.md): 16
    client threads of small single-Count queries through the REAL server,
    ``dispatch-batch`` on vs off, plus single-client p50 both ways (the
    acceptance criteria: >=4x qps at 16 threads, solo p50 within 10%).
    Reports the on-server's batch-size histogram and window-wait
    percentiles from /debug/vars."""
    import tempfile
    import urllib.request

    from pilosa_tpu.executor import Executor as _Ex
    from pilosa_tpu.server import Config, Server

    n_rows = meta["star_rows"]
    out = {}
    for mode, ex in (("on", executor),
                     ("off", _Ex(holder, use_mesh=True,
                                 dispatch_batch=False))):
        srv = Server(Config(
            data_dir=tempfile.mkdtemp(prefix=f"ptpu_dynb_{mode}_"),
            bind="localhost:0", anti_entropy_interval=0,
            dispatch_batch=(mode == "on")))
        try:
            srv.holder.indexes = holder.indexes
            srv.api.holder = holder
            srv.api.executor = ex
            srv.open()
            # warm: compile the padded fused query-axis shapes before
            # the timed window so XLA compiles don't pollute it
            _http_count_load(srv.port, "startrace", "stargazer", n_rows,
                             rng, 16, per_thread=20)
            (qps, _), spread = best_of(lambda: _http_count_load(
                srv.port, "startrace", "stargazer", n_rows, rng, 16))
            (solo_qps, solo_p50), _ = best_of(lambda: _http_count_load(
                srv.port, "startrace", "stargazer", n_rows, rng, 1,
                per_thread=64))
            out[f"qps_{mode}"] = round(qps, 1)
            out[f"spread_{mode}"] = spread
            out[f"solo_p50_ms_{mode}"] = round(solo_p50 * 1e3, 3)
            if mode == "on":
                with urllib.request.urlopen(
                        f"http://localhost:{srv.port}/debug/vars",
                        timeout=30) as resp:
                    snap = json.loads(resp.read())
                b = snap.get("dispatchBatcher", {})
                out["batch_size_hist"] = b.get("batchSize")
                out["window_wait"] = b.get("windowWaitS")
                out["fused_launches"] = b.get("fusedLaunches")
        finally:
            srv.httpd.shutdown()
            if mode == "off":
                ex.close()
    out["speedup"] = round(out["qps_on"] / out["qps_off"], 2) \
        if out.get("qps_off") else None
    return out


def run_http_batch_smoke(rng) -> dict:
    """Dynamic-batching leg of --smoke (docs/batching.md): 16 concurrent
    HTTP clients of small single-Count queries against a real server with
    ``dispatch-batch`` on, then off — asserting the on-mode actually
    fused launches and both modes agree on a sample answer.  The >=4x
    qps acceptance floor is a device-dispatch-floor effect and is judged
    by the full bench on real hardware, not this CPU smoke."""
    import tempfile
    import urllib.request

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server.server import Config, Server

    out = {}
    want = None
    # one dataset for BOTH modes (the rng advances per draw — sampling
    # inside the loop would hand each server different bits and void the
    # answer comparison)
    cols = rng.integers(0, SHARD_WIDTH, size=20_000)
    rws = rng.integers(0, 64, size=20_000)
    for mode in ("on", "off"):
        srv = Server(Config(
            data_dir=tempfile.mkdtemp(prefix=f"ptpu_smkb_{mode}_"),
            bind="localhost:0", anti_entropy_interval=0,
            dispatch_batch=(mode == "on"),
            dispatch_batch_window_us=1000))
        try:
            srv.open()

            def post(path, body):
                req = urllib.request.Request(
                    f"http://localhost:{srv.port}{path}", method="POST",
                    data=body.encode())
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.read()

            post("/index/dynb", "{}")
            post("/index/dynb/field/f", "{}")
            post("/index/dynb/field/f/import", json.dumps(
                {"rowIDs": rws.tolist(), "columnIDs": cols.tolist()}))
            got = json.loads(post("/index/dynb/query",
                                  "Count(Row(f=7))"))["results"]
            if want is None:
                want = got
            assert got == want, f"batched answer diverged: {got} != {want}"
            _http_count_load(srv.port, "dynb", "f", 64, rng, 16,
                             per_thread=8)  # warm compiles
            qps, p50 = _http_count_load(srv.port, "dynb", "f", 64, rng,
                                        16, per_thread=32)
            out[f"qps_{mode}"] = round(qps, 1)
            out[f"p50_ms_{mode}"] = round(p50 * 1e3, 2)
            if mode == "on":
                with urllib.request.urlopen(
                        f"http://localhost:{srv.port}/debug/vars",
                        timeout=30) as resp:
                    snap = json.loads(resp.read())
                b = snap["dispatchBatcher"]
                assert b["fusedLaunches"] > 0, \
                    "16 concurrent clients never produced a fused launch"
                out["fused_launches"] = b["fusedLaunches"]
                out["batch_size_hist"] = b["batchSize"]
                out["window_wait"] = b["windowWaitS"]
                out["client_aborts"] = snap["counts"].get(
                    "http.client_abort", 0)
        finally:
            srv.close()
    out["speedup"] = round(out["qps_on"] / out["qps_off"], 2)
    return out


def run_observability_smoke(rng, baseline_qps=None) -> dict:
    """Observability leg of --smoke (docs/observability.md): with
    tracing, latency histograms, and the slow-query log all armed, the
    profile-OFF serving path must stay within noise of the PR 4 batching
    leg (< 5%: collection is a contextvar read and a histogram bucket
    increment per stage), and ``?profile=true`` must return a populated
    stage tree whose trace id resolves at /debug/traces."""
    import tempfile
    import urllib.request

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server.server import Config, Server

    out = {}
    srv = Server(Config(
        data_dir=tempfile.mkdtemp(prefix="ptpu_smko_"),
        bind="localhost:0", anti_entropy_interval=0,
        dispatch_batch_window_us=1000,
        slow_query_threshold=0.5, trace_sample_rate=1.0,
        # fast time-series cadence so the leg can assert a full window
        # of samples in seconds instead of minutes
        timeseries_interval=0.05, timeseries_window=1.0))
    try:
        srv.open()

        def post(path, body):
            req = urllib.request.Request(
                f"http://localhost:{srv.port}{path}", method="POST",
                data=body.encode())
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()

        def get(path):
            with urllib.request.urlopen(
                    f"http://localhost:{srv.port}{path}",
                    timeout=30) as resp:
                return resp.read()

        cols = rng.integers(0, SHARD_WIDTH, size=20_000)
        rws = rng.integers(0, 64, size=20_000)
        post("/index/obs", "{}")
        post("/index/obs/field/f", "{}")
        post("/index/obs/field/f/import", json.dumps(
            {"rowIDs": rws.tolist(), "columnIDs": cols.tolist()}))
        # same load shape as the batching leg; best-of-2 after a warm
        # pass so a stray scheduler hiccup can't fail the 5% bound
        _http_count_load(srv.port, "obs", "f", 64, rng, 16, per_thread=8)
        qps = max(_http_count_load(srv.port, "obs", "f", 64, rng, 16,
                                   per_thread=32)[0]
                  for _ in range(2))
        out["qps"] = round(qps, 1)
        if baseline_qps:
            out["overhead_pct"] = round(
                100.0 * (1.0 - qps / baseline_qps), 1)
            assert qps >= 0.95 * baseline_qps, \
                (f"profile-off observability overhead over 5%: "
                 f"{qps:.0f} qps vs batching leg {baseline_qps:.0f}")
        # profile-on: a populated stage tree, inline with the response
        prof = json.loads(post("/index/obs/query?profile=true",
                               "Count(Row(f=7))"))
        assert prof.get("profile", {}).get("children"), \
            "?profile=true returned an empty stage tree"
        out["profile_stages"] = len(prof["profile"]["children"])
        tid = prof["traceID"]
        spans = json.loads(get(f"/debug/traces?trace={tid}"))["spans"]
        assert spans, "profile trace id unknown to /debug/traces"
        # slow-query log: drop the threshold and capture one.  The log
        # entry lands in the handler's post-response accounting, so poll
        # briefly instead of racing the microseconds after the reply.
        srv.slowlog.threshold_s = 1e-9
        post("/index/obs/query", "Count(Row(f=9))")
        slow_deadline = time.perf_counter() + 5
        while True:
            slow = json.loads(get("/debug/slow"))
            if slow["entries"] or time.perf_counter() >= slow_deadline:
                break
            time.sleep(0.02)
        assert slow["entries"], "slow-query log captured nothing"
        out["slow_recorded"] = slow["recorded"]
        # histograms: p99 derivable from the exposition
        text = get("/metrics").decode()
        assert "pilosa_tpu_http_query_seconds_bucket" in text, \
            "/metrics lacks the http.query latency histogram"
        # device runtime (docs/observability.md "Device runtime"): after
        # the load above the time-series ring must hold >= its window of
        # samples (wrapped at least once), and the compile registry must
        # have seen the leg's executables compile
        deadline = time.perf_counter() + 10
        while True:
            ts = json.loads(get("/debug/timeseries"))
            if (ts["coveredS"] >= ts["windowS"]
                    and ts["samplesTotal"] > ts["capacity"]) \
                    or time.perf_counter() >= deadline:
                break
            time.sleep(0.05)
        assert ts["coveredS"] >= ts["windowS"], \
            (f"time-series ring covers {ts['coveredS']}s of its "
             f"{ts['windowS']}s window after the load")
        assert ts["samplesTotal"] > ts["capacity"], \
            "time-series ring never wrapped"
        out["timeseries_samples"] = len(ts["samples"])
        dev = json.loads(get("/debug/vars"))["device"]
        assert dev["compiles"]["compiles"] > 0, \
            "compile registry saw no executable compile"
        assert "pilosa_tpu_device_compiles_total" in text and \
            "pilosa_tpu_device_padding_waste_ratio" in text and \
            "pilosa_tpu_device_decode_workspace_peak_bytes" in text, \
            "/metrics lacks the device-runtime families"
        out["device"] = {
            "compiles": dev["compiles"]["compiles"],
            "retraces": dev["compiles"]["retraces"],
            "compile_s": dev["compiles"]["compileSecondsTotal"],
            "padding_waste_ratio":
                dev["launches"]["paddingWasteRatio"],
        }
    finally:
        srv.close()
    return out


def _ingest_stream_load(port, index, field, rng, n_records,
                        n_rows=64, col_span=None, batch_records=50_000,
                        stop_evt=None):
    """Stream framed record batches at the binary ingest endpoint
    (docs/ingest.md) until ``n_records`` are acked (or until
    ``stop_evt`` is set, looping forever).  503s honor Retry-After and
    resend the batch.  Returns {records, bytes, seconds, retries}."""
    import http.client
    import threading
    import urllib.error
    import urllib.request

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.ingest import wire

    span = col_span if col_span is not None else SHARD_WIDTH
    sent = sent_bytes = retries = 0
    t0 = time.perf_counter()
    while (stop_evt is not None and not stop_evt.is_set()) \
            or (stop_evt is None and sent < n_records):
        n = min(batch_records, max(n_records - sent, 1)) \
            if stop_evt is None else batch_records
        rows = rng.integers(0, n_rows, size=n)
        cols = rng.integers(0, span, size=n)
        body = wire.encode_records(rows, cols)
        while True:
            req = urllib.request.Request(
                f"http://localhost:{port}/index/{index}/field/{field}"
                f"/ingest", data=body, method="POST")
            req.add_header("Content-Type", "application/octet-stream")
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    resp.read()
                break
            except urllib.error.HTTPError as e:
                e.read()
                if e.code != 503:
                    raise
                retries += 1
                time.sleep(0.05)
            except (OSError, http.client.HTTPException):
                if stop_evt is not None and stop_evt.is_set():
                    break  # server shutting down under us
                raise
        sent += n
        sent_bytes += len(body)
    return {"records": sent, "bytes": sent_bytes,
            "seconds": time.perf_counter() - t0, "retries": retries}


def bench_ingest(holder, executor, meta, rng):
    """Streaming-ingest config (docs/ingest.md): sustained binary-frame
    ingest alone, then ingest CONCURRENT with the intersect8 read leg —
    the read-qps retention ratio is the read/write interference
    headline (ROADMAP item 4: reads should hold >=80% of idle qps)."""
    import tempfile
    import threading

    from pilosa_tpu.server import Config, Server

    B, n_batches, T = 4096, 8, 8
    n_rows = meta["star_rows"]

    def read_batch():
        sets = _rand_rows(rng, n_rows, B)
        return " ".join(
            "Count(Intersect(" + ", ".join(
                f"Row(stargazer={r})" for r in q) + "))"
            for q in sets)

    def read_run():
        batches = [read_batch() for _ in range(n_batches)]
        return _run_batches(executor, "startrace", batches, T)

    srv = Server(Config(data_dir=tempfile.mkdtemp(prefix="ptpu_bing_"),
                        bind="localhost:0", anti_entropy_interval=0))
    srv.holder.indexes = holder.indexes  # serve the bench data
    srv.api.holder = holder
    srv.committer.holder = holder
    srv.open()
    try:
        idx = holder.index("startrace")
        idx.create_field_if_not_exists("ingested")
        executor.execute("startrace", read_batch())  # warm
        (qps_idle, _b, _p), _sp = best_of(read_run, n=2)
        # sustained ingest alone
        alone = _ingest_stream_load(srv.port, "startrace", "ingested",
                                    rng, 2_000_000)
        # ingest concurrent with the read leg
        stop = threading.Event()
        conc: dict = {}
        t = threading.Thread(
            target=lambda: conc.update(_ingest_stream_load(
                srv.port, "startrace", "ingested", rng, 0,
                stop_evt=stop)))
        t.start()
        try:
            (qps_load, _b2, _p2), _sp2 = best_of(read_run, n=2)
        finally:
            stop.set()
            t.join(timeout=120)
        ing = srv.committer.snapshot()
        return {
            "ingest_records_per_s": round(
                alone["records"] / alone["seconds"], 1),
            "ingest_mb_per_s": round(
                alone["bytes"] / alone["seconds"] / 1e6, 2),
            "ingest_retries": alone["retries"] + conc.get("retries", 0),
            "concurrent_ingest_records_per_s": round(
                conc["records"] / conc["seconds"], 1)
            if conc.get("seconds") else 0.0,
            "read_qps_idle": round(qps_idle, 1),
            "read_qps_under_ingest": round(qps_load, 1),
            "read_qps_retention": round(qps_load / qps_idle, 3),
            "flushes": ing["flushes"],
            "delta_folds": ing["folds"],
        }
    finally:
        # NOT srv.close(): that would close the SHARED bench holder (the
        # same reason bench_http only shuts the listener down)
        srv.httpd.shutdown()
        if hasattr(srv.httpd, "close_connections"):
            srv.httpd.close_connections()
        srv.httpd.server_close()
        srv.committer.close()


def run_ingest_smoke(rng) -> dict:
    """Ingest leg of --smoke (docs/ingest.md): the same corpus through
    the binary streaming endpoint and through the JSON bulk import must
    answer identically — while the deltas are overlay-resident AND
    after the merge folds them — plus a small read-under-ingest
    retention measurement (the acceptance floor is judged on real
    hardware by the full bench, not this CPU smoke)."""
    import tempfile
    import threading
    import urllib.request

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server

    srv = Server(Config(data_dir=tempfile.mkdtemp(prefix="ptpu_smki_"),
                        bind="localhost:0", anti_entropy_interval=0,
                        ingest_flush_ms=20.0))
    srv.open()
    try:
        def post(path, body, ctype="application/json"):
            req = urllib.request.Request(
                f"http://localhost:{srv.port}{path}", method="POST",
                data=body if isinstance(body, bytes) else body.encode())
            req.add_header("Content-Type", ctype)
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.read()

        post("/index/ings", "{}")
        for f in ("fb", "fi", "readf"):
            post(f"/index/ings/field/{f}", "{}")
        n = 120_000
        rows = rng.integers(0, 64, size=n)
        cols = rng.integers(0, 2 * SHARD_WIDTH, size=n)
        # read working set + its baseline qps
        post("/index/ings/field/readf/import", json.dumps(
            {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()}))
        _http_count_load(srv.port, "ings", "readf", 64, rng, 8,
                         per_thread=8)  # warm compiles
        qps_idle, _ = _http_count_load(srv.port, "ings", "readf", 64,
                                       rng, 8, per_thread=24)
        # bulk twin
        post("/index/ings/field/fb/import", json.dumps(
            {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()}))

        # streamed twin, concurrent with read load.  Two POSTs: the
        # first establishes the fragments' row capacity (that flush
        # folds — capacity growth changes device shapes), so the second
        # exercises the delta-overlay journal.
        half = n // 2
        from pilosa_tpu.ingest import wire
        post("/index/ings/field/fi/ingest",
             wire.encode_records(rows[:half], cols[:half],
                                 frame_records=10_000),
             "application/octet-stream")
        stop = threading.Event()
        conc: dict = {}

        def stream():
            body = wire.encode_records(rows[half:], cols[half:],
                                       frame_records=10_000)
            t0 = time.perf_counter()
            post("/index/ings/field/fi/ingest", body,
                 "application/octet-stream")
            conc["seconds"] = time.perf_counter() - t0
            conc["bytes"] = len(body)
            conc["records"] = n - half
            stop.set()

        t = threading.Thread(target=stream)
        t.start()
        qps_load, _ = _http_count_load(srv.port, "ings", "readf", 64,
                                       rng, 8, per_thread=24)
        t.join(timeout=300)
        assert stop.is_set(), "ingest stream never completed"

        def answers(field):
            out = []
            for r in (3, 17, 42):
                out.append(json.loads(post(
                    "/index/ings/query",
                    f"Count(Row({field}={r}))"))["results"])
            out.append(json.loads(post(
                "/index/ings/query", f"TopN({field}, n=5)"))["results"])
            return out

        live_journal = sum(fr.delta_bytes()
                           for *_x, fr in srv.holder.iter_fragments("ings"))
        assert live_journal > 0, \
            "second ingest stream never journaled a delta overlay"
        got_live = answers("fi")
        want = answers("fb")
        assert got_live == want, \
            "overlay-resident ingest answers diverged from bulk import"
        srv.committer.merge_all()  # fold the overlays
        assert answers("fi") == want, \
            "post-merge ingest answers diverged from bulk import"
        ing = srv.committer.snapshot()
        return {
            "records": n,
            "records_per_s": round(conc["records"] / conc["seconds"], 1),
            "ingest_mb_per_s": round(
                conc["bytes"] / conc["seconds"] / 1e6, 2),
            "read_qps_idle": round(qps_idle, 1),
            "read_qps_under_ingest": round(qps_load, 1),
            "read_qps_retention": round(qps_load / qps_idle, 3),
            "overlay_journal_bytes": live_journal,
            "flushes": ing["flushes"],
            "answers_identical": True,
        }
    finally:
        srv.close()


def bench_wholequery(holder, executor, meta, rng):
    """Whole-query legs (docs/whole-query.md): intersect8 (config-2
    corpus), bsi_sum (config-4), and filtered TopN (config-3) with the
    program path on (the serving default — ``executor``) vs a
    whole-query-off twin, plus the single-launch ledger check.  The
    on-path intersect8/bsi_sum qps are the numbers the r05 anchors
    judge; ratio is on/off on identical data and queries."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.utils import devobs

    off = Executor(holder, use_mesh=True, whole_query=False)
    out = {}
    try:
        # Batch/thread sizes are deliberately smaller than the config
        # 2/3/4 legs: a filtered row_counts launch materialises a
        # [B, rows, W] masked temp per stacked shard row, and deep
        # ticket fusion multiplies it — identically on BOTH paths (the
        # FUSED_ROWS_MAX cap predates this leg and does not scale by
        # fragment rows), so the on/off ratio is measured at sizes
        # every host can hold.
        legs = {
            "intersect8": ("startrace", lambda: " ".join(
                "Count(Intersect(" + ", ".join(
                    f"Row(stargazer={r})" for r in q) + "))"
                for q in _rand_rows(rng, meta["star_rows"], 1024)),
                16, 8),
            "bsi_sum": ("bsi64", lambda: " ".join(
                f"Sum(Row(v > {int(x)}), field=v)"
                for x in rng.integers(0, 1_000_000, size=32)), 8, 4),
            "topn": ("lang10m", lambda: " ".join(
                f"TopN(language, Row(stars={r}), n=50)"
                for r in rng.integers(0, 16, size=32)), 8, 4),
        }
        for name, (index, mk, nb, T) in legs.items():
            row = {}
            for label, ex in (("on", executor), ("off", off)):
                ex.execute(index, mk())  # warm compile + stacks

                def run(ex=ex, index=index, mk=mk, nb=nb, T=T):
                    return _run_batches(ex, index,
                                        [mk() for _ in range(nb)], T)

                d0 = _device_telemetry()
                (qps, _bat, _p50), spread = best_of(run)
                dev = _device_delta(d0)
                row[f"qps_{label}"] = round(qps, 1)
                row[f"spread_{label}"] = spread
                if label == "on":
                    row["device_on"] = dev
            row["ratio"] = round(row["qps_on"] / row["qps_off"], 3) \
                if row["qps_off"] else None
            out[name] = row
        # acceptance: a Count(Intersect)-class request is ONE ledger
        # entry of kind wholequery
        executor.execute(
            "startrace",
            "Count(Intersect(Row(stargazer=1), Row(stargazer=2)))")
        before = devobs.LEDGER.launches_total
        executor.execute(
            "startrace",
            "Count(Intersect(Row(stargazer=3), Row(stargazer=4)))")
        single = devobs.LEDGER.launches_total - before == 1
        entry = devobs.LEDGER.snapshot()["entries"][-1]
        out["single_launch"] = bool(single
                                    and entry["kind"] == "wholequery")
        out["wq_requests"] = executor.wq_requests
        out["wq_fallbacks"] = executor.wq_fallbacks
    finally:
        off.close()
    return out


def run_wholequery_smoke(rng) -> dict:
    """Whole-query leg of --smoke (docs/whole-query.md): a small corpus
    served with the program path on vs off — answers must be identical,
    a Count(Intersect)-class request must be exactly ONE launch on the
    ledger (kind wholequery), and on/off qps ride along (the
    r05-anchor floor is judged on real hardware by the full bench, not
    this CPU smoke)."""
    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage import FieldOptions, Holder
    from pilosa_tpu.utils import devobs

    h = Holder(None)
    idx = h.create_index("wq", track_existence=False)
    seg = idx.create_field("seg")
    metric = idx.create_field("metric")
    v = idx.create_field("v", FieldOptions(type="int", min=0,
                                           max=100_000))
    n = 200_000
    cols = rng.integers(0, 4 * SHARD_WIDTH, size=n)
    seg.import_bits(rng.integers(0, 8, size=n), cols)
    metric.import_bits(rng.integers(0, 8, size=n), cols)
    ucols = np.unique(cols)
    v.import_values(ucols, rng.integers(0, 100_000, size=ucols.size))

    on = Executor(h, use_mesh=True)
    off = Executor(h, use_mesh=True, whole_query=False)
    out = {}
    try:
        def batch(B=64):
            sets = _rand_rows(rng, 8, B)
            return " ".join(
                "Count(Intersect(" + ", ".join(
                    f"Row(seg={r})" for r in q[:4]) + "))"
                for q in sets)

        qs = [batch() for _ in range(8)]
        extra = [
            "Sum(Row(v > 5000), field=v)",
            "TopN(metric, Intersect(Row(seg=0), Row(seg=2)), n=5)",
            "Count(Intersect(Row(seg=1), Row(seg=3))) Sum(field=v) "
            "TopN(metric, n=3)",
        ]

        def norm(results):  # mixed kinds, unlike the TopN-only _smoke_norm
            return [[(p.id, p.count) for p in r] if isinstance(r, list)
                    else r for r in results]

        want = [norm(off.execute("wq", q)) for q in qs + extra]
        got = [norm(on.execute("wq", q)) for q in qs + extra]
        out["answers_identical"] = want == got
        assert out["answers_identical"], \
            "whole-query answers diverged from the legacy path"
        # single-launch-per-request, ledger-verified
        on.execute("wq", "Count(Intersect(Row(seg=2), Row(seg=5)))")
        before = devobs.LEDGER.launches_total
        on.execute("wq", "Count(Intersect(Row(seg=0), Row(seg=6)))")
        launches = devobs.LEDGER.launches_total - before
        entry = devobs.LEDGER.snapshot()["entries"][-1]
        out["single_launch"] = bool(launches == 1
                                    and entry["kind"] == "wholequery")
        assert out["single_launch"], \
            f"expected 1 wholequery launch, saw {launches}"
        out["wq_requests"] = on.wq_requests
        out["fallbacks"] = on.wq_fallbacks

        d0 = _device_telemetry()

        def timed(ex):
            t0 = time.perf_counter()
            served = 0
            for q in qs:
                served += len(ex.execute("wq", q))
            return served / (time.perf_counter() - t0)

        out["qps_off"] = round(timed(off), 1)
        out["qps_on"] = round(timed(on), 1)
        out["device"] = _device_delta(d0)
    finally:
        on.close()
        off.close()
    return out


def _smoke_norm(results):
    """TopN results -> comparable (id, count) lists."""
    return [[(p.id, p.count) for p in r] for r in results]


def run_overload_smoke() -> dict:
    """Overload-armor leg of --smoke (docs/robustness.md): drive the
    REAL server's admission and deadline paths so a regression in either
    shows in the bench trajectory.  A burst of 4x max-queries against a
    slot pool of 2 must yield only 200s/503s with both present, and a
    failpoint-delayed query under a 50 ms budget must 504 — asserted,
    then reported."""
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from pilosa_tpu.server.server import Config, Server
    from pilosa_tpu.utils.faults import FAULTS

    srv = Server(Config(data_dir=tempfile.mkdtemp(prefix="ptpu_smoke_"),
                        bind="localhost:0", anti_entropy_interval=0,
                        max_queries=2, queue_timeout=0.05))
    try:
        def post(path, body):
            req = urllib.request.Request(
                f"http://localhost:{srv.port}{path}", method="POST",
                data=body.encode())
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                    return resp.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code

        srv.open()
        post("/index/sm", "{}")
        post("/index/sm/field/f", "{}")
        post("/index/sm/query", "Set(1, f=1) Set(1048579, f=1)")
        FAULTS.arm("mesh.slice", mode="delay", arg=0.15, match="sm")
        try:
            codes = []
            lock = threading.Lock()

            def one():
                c = post("/index/sm/query", "Count(Row(f=1))")
                with lock:
                    codes.append(c)

            threads = [threading.Thread(target=one) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert set(codes) <= {200, 503}, f"burst statuses {set(codes)}"
            assert codes.count(200) >= 1 and codes.count(503) >= 1, codes
            t0 = time.perf_counter()
            code_504 = post("/index/sm/query?timeout=0.05",
                            "Count(Row(f=1))")
            deadline_s = time.perf_counter() - t0
            assert code_504 == 504, f"expected 504, got {code_504}"
        finally:
            FAULTS.disarm()
        return {"burst_200": codes.count(200),
                "burst_503": codes.count(503),
                "deadline_504_s": round(deadline_s, 3)}
    finally:
        srv.close()


def _clear_query_caches(ex):
    """Flush both cache layers (the /internal/cache/clear admin route's
    in-process analog) so a 'cold' measurement is genuinely cold."""
    from pilosa_tpu.cache.rank import iter_rank_caches

    ex.result_cache.clear()
    for _frag, cache in iter_rank_caches(ex.holder):
        cache.invalidate()


def run_cache_smoke(rng) -> dict:
    """Cache leg of --smoke (docs/caching.md): repeated unfiltered
    TopN/Count on unchanged data, cold (both cache layers flushed before
    every run) vs warm (result-cache hits).  Asserts the acceptance
    floor — warm >= 5x faster than cold — and reports the hit ratio."""
    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage import Holder

    h = Holder(None)
    idx = h.create_index("cachesmoke", track_existence=False)
    f = idx.create_field("f")
    n_bits = 200_000
    f.import_bits(rng.integers(0, 64, size=n_bits),
                  rng.integers(0, 4 * SHARD_WIDTH, size=n_bits))
    ex = Executor(h, use_mesh=True)
    ex.result_cache.limit_bytes = 64 << 20
    queries = ["TopN(f, n=10)", "Count(Row(f=7))",
               "Count(Intersect(Row(f=1), Row(f=2)))"]
    try:
        # compile warm-up with DISTINCT literals: the cold timings below
        # must measure execution + cache builds, not XLA compilation
        ex.execute("cachesmoke", "TopN(f, n=9) Count(Row(f=6)) "
                                 "Count(Intersect(Row(f=3), Row(f=4)))")

        def once():
            t0 = time.perf_counter()
            for q in queries:
                ex.execute("cachesmoke", q)
            return time.perf_counter() - t0

        colds = []
        for _ in range(3):
            _clear_query_caches(ex)
            colds.append(once())
        cold_s = float(np.median(colds))
        _clear_query_caches(ex)
        once()  # fill
        h0, m0 = ex.result_cache.hits, ex.result_cache.misses
        warms = [once() for _ in range(15)]
        warm_s = float(np.median(warms))
        hits = ex.result_cache.hits - h0
        misses = ex.result_cache.misses - m0
        speedup = cold_s / warm_s if warm_s > 0 else float("inf")
        assert hits == 15 * len(queries) and misses == 0, \
            f"warm repeats were not served from the cache " \
            f"({hits} hits, {misses} misses)"
        assert speedup >= 5, \
            f"warm repeats only {speedup:.1f}x faster than cold " \
            f"(acceptance floor is 5x)"
        return {
            "cold_ms": round(cold_s * 1e3, 2),
            "warm_ms": round(warm_s * 1e3, 3),
            "speedup": round(speedup, 1),
            "hit_ratio": round(hits / (hits + misses), 3),
            "resident_bytes": ex.result_cache.resident_bytes,
        }
    finally:
        ex.close()


def run_compressed_smoke(rng) -> dict:
    """Compressed-residency leg of --smoke (docs/memory-budget.md
    "Compressed residency"): the sparse corpus variant queried under a
    budget well below its dense working set must (a) hold the budget,
    (b) stage a compressed footprint smaller than the dense-resident
    one, and (c) return results identical to the dense-resident run —
    the three acceptance gates of the compressed path, end-to-end."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage import fragment as _frag
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET

    n_shards = 16
    h, oracle_words = build_config5(rng, n_shards=n_shards, sparse=True)
    ex = Executor(h, use_mesh=True)
    old_limit = DEFAULT_BUDGET.limit_bytes
    old_form = _frag.COMPRESSED_RESIDENT
    batches = [_cfg5_batch(rng, 8) for _ in range(4)]
    full_q = "TopN(metric, Intersect(Row(seg=1), Row(seg=3)), n=5)"
    try:
        # dense-resident reference (unlimited budget: compression is
        # off by design there — the heuristic requires a limit)
        _frag.COMPRESSED_RESIDENT = False
        DEFAULT_BUDGET.limit_bytes = None
        want = [_smoke_norm(ex.execute("ssb1b", b)) for b in batches]
        assert _smoke_norm(ex.execute("ssb1b", full_q))[0] == \
            oracle_topn5(oracle_words, range(n_shards), 1, 3), \
            "dense answer diverged from the oracle"
        dense_resident_mb = DEFAULT_BUDGET.stats()["residentBytes"] >> 20

        # compressed under a budget below the dense working set
        _frag.COMPRESSED_RESIDENT = True
        budget = 8 << 20
        DEFAULT_BUDGET.limit_bytes = budget
        DEFAULT_BUDGET.shrink_to_limit()
        DEFAULT_BUDGET.reset_peak()
        dev0 = _device_telemetry()
        t0 = time.perf_counter()
        got = [_smoke_norm(ex.execute("ssb1b", b)) for b in batches]
        compressed_s = time.perf_counter() - t0
        dev = _device_delta(dev0)
        assert got == want, \
            "compressed-resident results diverged from the dense run"
        stats = DEFAULT_BUDGET.stats()
        assert stats["peakBytes"] <= budget, \
            f"budget not held: peak {stats['peakBytes']} > {budget}"
        assert stats["compressedBytes"] > 0, \
            "no packed stream ever staged: the leg exercised nothing"
        compressed_mb = stats["compressedBytes"] / 2**20
        assert compressed_mb < dense_resident_mb, \
            (f"compressed footprint {compressed_mb:.1f}MB not below the "
             f"dense resident {dense_resident_mb}MB")
        # device-runtime telemetry (docs/observability.md "Device
        # runtime"): compressed launches must have decoded dense tiles
        # (the workspace high-watermark is the knob's feedback loop) and
        # the mixed-signature groups must have paid measurable bucket
        # padding — both exported at /metrics, asserted non-zero here
        assert dev["decode_mb"] > 0 and dev["decode_peak_mb"] > 0, \
            "compressed leg decoded nothing: workspace telemetry dead"
        assert dev["padding_waste_ratio"] > 0, \
            "compressed leg padded nothing: padding telemetry dead"
        return {
            "budget_held": True,
            "compressed_mb": round(compressed_mb, 2),
            "dense_resident_mb": dense_resident_mb,
            "effective_capacity_ratio": round(
                n_shards * 12 * 32768 * 4 / stats["compressedBytes"], 1),
            "compressed_s": round(compressed_s, 2),
            "device": dev,
        }
    finally:
        _frag.COMPRESSED_RESIDENT = old_form
        DEFAULT_BUDGET.limit_bytes = old_limit
        ex.close()


# Restart-leg worker (docs/warmup.md).  Inline rather than
# tests/crash_worker.py because the crash harness pins its Config — the
# restart leg needs the warm-start knobs and its own traffic shape.
# "seed" serves steady traffic, flushes the corpus, then parks until the
# parent kill -9s it mid-serving; "restart" boots on the same data dir,
# waits out the warming phase, and times the first query end-to-end.
_RESTART_WORKER = r'''
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
mode, data_dir = sys.argv[1], sys.argv[2]
from pilosa_tpu.server.server import Server, Config
s = Server(Config(data_dir=data_dir, bind="localhost:0",
                  timeseries_interval=0, metric_poll_interval=0,
                  anti_entropy_interval=0))
s.open()
if mode == "seed":
    s.api.create_index("ri")
    s.api.create_field("ri", "f")
    s.api.query("ri", "".join(f"Set({c}, f={r})"
                              for r in range(4) for c in range(60)))
    for _ in range(3):
        s.api.query("ri", "Count(Row(f=1))")
        s.api.query("ri", "Row(f=2)")
        s.api.query("ri", "TopN(f, n=3)")
    s.warmup.recorder.flush(s.warmup.corpus)
    print("SEEDED", flush=True)
    time.sleep(600)  # the parent kill -9s us here: no clean close
else:
    t0 = time.monotonic()
    while s.warmup.warming() and time.monotonic() - t0 < 120:
        time.sleep(0.01)
    st = s.warmup.status()
    t1 = time.perf_counter()
    first = s.api.query("ri", "Count(Row(f=1))")
    first_ms = (time.perf_counter() - t1) * 1e3
    assert first == [60], first
    steady = []
    for _ in range(5):
        t2 = time.perf_counter()
        s.api.query("ri", "Count(Row(f=1))")
        steady.append((time.perf_counter() - t2) * 1e3)
    s.close()
    print(json.dumps({"warmup": st, "first_ms": round(first_ms, 2),
                      "steady_ms": round(min(steady), 2)}), flush=True)
'''


def run_restart_smoke(rng) -> dict:
    """Restart leg of --smoke (docs/warmup.md): seed a server with
    steady traffic, kill -9 it mid-serving, restart on the same data
    dir (warm: durable corpus + persistent compile cache survive), then
    restart again with both wiped (cold baseline).  The CPU smoke
    asserts the qualitative invariants — the warm restart replayed the
    corpus with ZERO retraces and its first query beats the cold
    restart's; the acceptance ratios (warm first-query p99 within ~2x
    steady state and >=5x better than cold) are judged on real
    hardware."""
    import os
    import shutil
    import subprocess
    import tempfile

    tmp = tempfile.mkdtemp(prefix="ptpu-restart-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def worker(mode):
        return subprocess.Popen(
            [sys.executable, "-c", _RESTART_WORKER, mode, tmp],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)

    try:
        seed = worker("seed")
        line = seed.stdout.readline().strip()
        if line != "SEEDED":
            _, err = seed.communicate(timeout=30)
            raise AssertionError(f"seed worker failed: {err[-2000:]}")
        seed.kill()  # SIGKILL mid-serving: the crash-harness discipline
        seed.wait(timeout=30)
        assert os.path.exists(os.path.join(tmp, "signatures.log")), \
            "kill -9 lost the corpus: periodic flush never landed"

        warm_raw, warm_err = worker("restart").communicate(timeout=300)
        assert warm_raw.strip(), f"warm restart died: {warm_err[-2000:]}"
        warm = json.loads(warm_raw.strip().splitlines()[-1])
        wst = warm["warmup"]
        assert wst["replayed"] >= 1, \
            f"warm restart replayed nothing: {wst}"
        assert wst["errors"] == 0, f"warm replay errored: {wst}"
        assert wst["retracesDuringWarm"] == 0, \
            f"retraces during warm replay: {wst}"

        # cold baseline: no corpus, no compiled bytes
        os.unlink(os.path.join(tmp, "signatures.log"))
        shutil.rmtree(os.path.join(tmp, ".compile-cache"),
                      ignore_errors=True)
        cold_raw, cold_err = worker("restart").communicate(timeout=300)
        assert cold_raw.strip(), f"cold restart died: {cold_err[-2000:]}"
        cold = json.loads(cold_raw.strip().splitlines()[-1])
        assert cold["warmup"]["replayed"] == 0, cold["warmup"]
        assert warm["first_ms"] < cold["first_ms"], \
            (f"warm first query ({warm['first_ms']} ms) not faster than "
             f"cold ({cold['first_ms']} ms)")
        return {
            "replayed": wst["replayed"],
            "planned": wst["planned"],
            "retraces_during_warm": wst["retracesDuringWarm"],
            "saved_compile_s": wst["savedCompileS"],
            "warm_first_ms": warm["first_ms"],
            "cold_first_ms": cold["first_ms"],
            "steady_ms": warm["steady_ms"],
            "warm_vs_cold": round(cold["first_ms"]
                                  / max(warm["first_ms"], 1e-9), 1),
            "warm_vs_steady": round(warm["first_ms"]
                                    / max(warm["steady_ms"], 1e-9), 1),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_smoke():
    """--smoke: seconds-scale end-to-end exercise of the resident AND the
    budgeted/streaming query paths on tiny shard counts — wired as a
    slow-marked pytest (tests/test_bench_smoke.py) so the streaming
    pipeline is covered without bloating tier-1.  Asserts budgeted
    results are identical to the resident run and that eviction,
    streaming, and prefetch actually engaged; also drives the admission/
    deadline overload path (run_overload_smoke); prints one JSON line."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET

    rng = np.random.default_rng(SEED + 2)
    n_shards = 24
    h5, oracle_words = build_config5(rng, n_shards=n_shards)
    ex5 = Executor(h5, use_mesh=True)
    old_limit = DEFAULT_BUDGET.limit_bytes
    out = {"smoke": True, "shards": n_shards}
    t_start = time.perf_counter()
    try:
        subsets = [list(map(int, s))
                   for s in np.array_split(np.arange(n_shards), 4)]
        batches = [_cfg5_batch(rng, 8) for _ in range(6)]
        full_q = "TopN(metric, Intersect(Row(seg=0), Row(seg=2)), n=5)"

        # resident pass: no limit, everything stays staged
        DEFAULT_BUDGET.limit_bytes = None
        want = [ex5.execute("ssb1b", b, shards=subsets[i % 4])
                for i, b in enumerate(batches)]
        want_full = ex5.execute("ssb1b", full_q)
        assert _smoke_norm(want_full)[0] == \
            oracle_topn5(oracle_words, range(n_shards), 0, 2), \
            "resident answer diverged from the oracle"

        # budgeted pass: limit sized so two subset stacks cannot both
        # stay resident (per-subset ~12 MB stacked) and a full-shard
        # pass (~38 MB) must stream in slices with prefetch
        DEFAULT_BUDGET.limit_bytes = 20 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        ev0 = DEFAULT_BUDGET.evictions
        pf0 = DEFAULT_BUDGET.prefetch_hits + DEFAULT_BUDGET.prefetch_misses
        t0 = time.perf_counter()
        got = [ex5.execute("ssb1b", b, shards=subsets[i % 4])
               for i, b in enumerate(batches)]
        got_full = ex5.execute("ssb1b", full_q)
        budgeted_s = time.perf_counter() - t0
        for w, g in zip(want, got):
            assert _smoke_norm(w) == _smoke_norm(g), \
                "budgeted subset results diverged from the resident run"
        assert _smoke_norm(want_full) == _smoke_norm(got_full), \
            "streamed full-pass result diverged from the resident run"
        stats = DEFAULT_BUDGET.stats()
        assert DEFAULT_BUDGET.evictions > ev0, \
            "budget never evicted under the smoke limit"
        assert stats["prefetchHits"] + stats["prefetchMisses"] > pf0, \
            "streaming prefetch never engaged on the over-budget pass"
        out.update({
            "budgeted_s": round(budgeted_s, 2),
            "evictions": DEFAULT_BUDGET.evictions - ev0,
            "prefetch_hits": stats["prefetchHits"],
            "prefetch_misses": stats["prefetchMisses"],
            "upload_mb": stats["uploadBytes"] >> 20,
            "pinned_bytes": stats["pinnedBytes"],
        })
    finally:
        DEFAULT_BUDGET.limit_bytes = old_limit
        ex5.close()
    out["wholequery"] = run_wholequery_smoke(
        np.random.default_rng(SEED + 9))
    out["routing"] = run_routing_smoke(np.random.default_rng(SEED + 10))
    out["chaos"] = run_chaos_smoke(np.random.default_rng(SEED + 11))
    out["slo"] = run_slo_smoke(np.random.default_rng(SEED + 16))
    out["wire"] = run_wire_smoke(np.random.default_rng(SEED + 12))
    out["tenant"] = run_tenant_smoke(np.random.default_rng(SEED + 13))
    out["compressed"] = run_compressed_smoke(np.random.default_rng(SEED + 6))
    out["ssb"] = run_ssb_smoke(np.random.default_rng(SEED + 15))
    out["ingest"] = run_ingest_smoke(np.random.default_rng(SEED + 8))
    out["cache"] = run_cache_smoke(np.random.default_rng(SEED + 3))
    out["overload"] = run_overload_smoke()
    out["http_batch"] = run_http_batch_smoke(np.random.default_rng(SEED + 4))
    out["observability"] = run_observability_smoke(
        np.random.default_rng(SEED + 5),
        baseline_qps=out["http_batch"]["qps_on"])
    out["restart"] = run_restart_smoke(np.random.default_rng(SEED + 14))
    out["total_s"] = round(time.perf_counter() - t_start, 2)
    print(json.dumps(out))


def main():
    from pilosa_tpu.executor import Executor

    holder, meta = build_indexes()
    executor = Executor(holder, use_mesh=True)
    rng = np.random.default_rng(SEED + 1)

    d0 = _device_telemetry()
    q1, l1, p1, b1, s1 = bench_config1(executor, meta, rng)
    dev1, d0 = _device_delta(d0), _device_telemetry()
    q2, l2, p2, b2, s2 = bench_config2(executor, meta, rng)
    dev2, d0 = _device_delta(d0), _device_telemetry()
    q3, l3, p3, b3, s3 = bench_config3(executor, meta, rng)
    dev3, d0 = _device_delta(d0), _device_telemetry()
    q4, l4, p4, b4, gb_s, gb_grid_s, s4 = bench_config4(executor, meta,
                                                        rng)
    dev4 = _device_delta(d0)

    (c1,), _ = best_of(lambda: (cpu_config1(holder, meta, rng),))
    (c2,), _ = best_of(lambda: (cpu_config2(holder, meta, rng),))
    (c3,), _ = best_of(lambda: (cpu_config3(holder, meta, rng),))
    (c4,), _ = best_of(lambda: (cpu_config4(holder, meta, rng),))

    # sanity: engine answers match the numpy oracle on one query per config
    frag = _np_frag(holder, "startrace", "stargazer")[0]
    got = executor.execute("startrace", "Count(Row(stargazer=14))")[0]
    assert got == int(np.bitwise_count(frag[14]).sum()), "config1 mismatch"

    from pilosa_tpu.executor import Executor as _Ex
    h5, oracle_words = build_config5(rng)
    ex5 = _Ex(h5, use_mesh=True)
    try:
        # answer-equality: engine TopN == word-wise oracle on a full pass
        got5 = ex5.execute(
            "ssb1b", "TopN(metric, Intersect(Row(seg=0), Row(seg=2)), n=5)")
        want5 = oracle_topn5(oracle_words, range(N_SHARDS5), 0, 2)
        assert [(p.id, p.count) for p in got5[0]] == want5, \
            f"config5 mismatch: {got5[0]} != {want5}"
        # resident variant: all 4 subset stacks fit (954 shards x 12 rows
        # x 128KB  stacked ~1.6GB; 6GB leaves staging headroom)
        d5 = _device_telemetry()
        cfg5r = bench_config5(ex5, oracle_words, rng, 6144, resident=True)
        cfg5r["device"], d5 = _device_delta(d5), _device_telemetry()
        cfg5 = bench_config5(ex5, oracle_words, rng, 768, resident=False)
        cfg5["device"] = _device_delta(d5)
    finally:
        ex5.close()
    # compressed-residency leg (docs/memory-budget.md): the over-budget
    # cliff on the sparse corpus, compressed vs dense vs resident anchor
    try:
        d5c = _device_telemetry()
        cfg5c = bench_config5_compressed(np.random.default_rng(SEED + 7))
        cfg5c["device"] = _device_delta(d5c)
    except Exception as e:
        import traceback
        print(f"config 5 compressed leg failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        cfg5c = None

    # SSB star-schema config (the r10 on-TPU round's main leg):
    # resident vs compressed-over-budget, per-leg kernel backend
    try:
        ssb_leg = bench_ssb(np.random.default_rng(SEED + 15))
    except Exception as e:
        import traceback
        print(f"ssb config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        ssb_leg = None

    try:
        cfg5d = bench_config5_distributed(rng)
    except Exception as e:
        import traceback
        print(f"config 5d failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        cfg5d = None

    # elastic-serving config (docs/cluster.md): skewed-hot-index corpus,
    # loaded routing vs primary-pinned on a replica_n=2 cluster
    try:
        routing_leg = bench_routing(np.random.default_rng(SEED + 10))
    except Exception as e:
        import traceback
        print(f"routing config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        routing_leg = None

    # tail-tolerance config (docs/robustness.md "Tail-tolerant
    # fan-out"): ChaosProxy straggler p99 with hedging on vs off
    try:
        chaos_leg = bench_chaos(np.random.default_rng(SEED + 11))
    except Exception as e:
        import traceback
        print(f"chaos config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        chaos_leg = None

    # SLO/alerting config (docs/observability.md "SLOs & alerting"):
    # straggler fire -> bundle -> resolve + evaluation-overhead pair
    try:
        slo_leg = bench_slo(np.random.default_rng(SEED + 16))
    except Exception as e:
        import traceback
        print(f"slo config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        slo_leg = None

    # internal-wire config (docs/cluster.md "Internal query wire"):
    # binary PTPUQRY1 vs JSON envelope on the same recorded fan-out
    # corpus, answers asserted byte-identical
    try:
        wire_leg = bench_wire(np.random.default_rng(SEED + 12))
    except Exception as e:
        import traceback
        print(f"wire config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        wire_leg = None

    # tenant-isolation config (docs/robustness.md "Tenant isolation"):
    # polite-tenant p99 under a hostile flood, fair admission on vs off
    try:
        tenant_leg = bench_tenant(np.random.default_rng(SEED + 13))
    except Exception as e:
        import traceback
        print(f"tenant config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        tenant_leg = None

    # concurrent-HTTP dynamic-batching config (docs/batching.md): the
    # served single-query path, dispatch-batch on vs off
    try:
        http_batch = bench_http_dynamic_batching(holder, executor, meta,
                                                 rng)
    except Exception as e:
        import traceback
        print(f"http dynamic-batching config failed: {e!r}",
              file=sys.stderr)
        traceback.print_exc()
        http_batch = None

    # streaming-ingest config (docs/ingest.md): sustained write rate and
    # the read-qps retention under concurrent ingest
    try:
        ingest_leg = bench_ingest(holder, executor, meta,
                                  np.random.default_rng(SEED + 8))
    except Exception as e:
        import traceback
        print(f"ingest config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        ingest_leg = None

    # whole-query config (docs/whole-query.md): program path on vs off
    # on the config-2/3/4 corpora + the single-launch ledger check
    try:
        wq_leg = bench_wholequery(holder, executor, meta,
                                  np.random.default_rng(SEED + 9))
    except Exception as e:
        import traceback
        print(f"whole-query config failed: {e!r}", file=sys.stderr)
        traceback.print_exc()
        wq_leg = None

    # HTTP variant (engine behind the real server)
    http_qps = None
    try:
        import tempfile
        from pilosa_tpu.server import Config, Server
        srv = Server(Config(data_dir=tempfile.mkdtemp(prefix="ptpu_bench_"),
                            bind="localhost:0", anti_entropy_interval=0))
        srv.holder.indexes = holder.indexes  # serve the bench data
        srv.api.holder = holder
        srv.api.executor = executor
        srv.open()
        http_qps = bench_http(srv.port, rng, meta["star_rows"])
        srv.httpd.shutdown()
    # lint: allow(swallowed-exception) — the HTTP leg is optional; a
    # null qps in the emitted report IS the failure signal
    except Exception:
        http_qps = None

    configs = {
        "1_count_row_1shard": {
            "qps": round(q1, 1), "batch_ms": round(l1 * 1e3, 1),
            "batch_p50_ms": round(p1 * 1e3, 1),
            "spread": s1, "vs_cpu": round(q1 / c1, 2),
            "cpu_qps": round(c1, 1),
            "gbps": round(q1 * b1 / 1e9, 1),
            "device": dev1},
        "2_intersect8_1M_cols": {
            "qps": round(q2, 1), "batch_ms": round(l2 * 1e3, 1),
            "batch_p50_ms": round(p2 * 1e3, 1),
            "spread": s2, "vs_cpu": round(q2 / c2, 2),
            "cpu_qps": round(c2, 1),
            "gbps": round(q2 * b2 / 1e9, 1),
            "device": dev2},
        "3_topn_filtered_10M_cols": {
            "qps": round(q3, 1), "batch_ms": round(l3 * 1e3, 1),
            "batch_p50_ms": round(p3 * 1e3, 1),
            "spread": s3, "vs_cpu": round(q3 / c3, 2),
            "cpu_qps": round(c3, 2),
            "gbps": round(q3 * b3 / 1e9, 1),
            "hbm_frac": round(q3 * b3 / 1e9 / HBM_PEAK_GBS, 3),
            "device": dev3},
        "4_bsi_sum_gt_64shards": {
            "qps": round(q4, 1), "batch_ms": round(l4 * 1e3, 1),
            "batch_p50_ms": round(p4 * 1e3, 1),
            "spread": s4, "vs_cpu": round(q4 / c4, 2),
            "cpu_qps": round(c4, 2),
            "gbps": round(q4 * b4 / 1e9, 1),
            "hbm_frac": round(q4 * b4 / 1e9 / HBM_PEAK_GBS, 3),
            "groupby_s": round(gb_s, 3),
            "groupby_128x128_s": round(gb_grid_s, 3),
            "device": dev4},
        "5_topn_1B_cols_resident": cfg5r,
        "5_topn_1B_cols_budgeted": cfg5,
    }
    if cfg5c:
        configs["7_topn_1B_cols_sparse_compressed"] = cfg5c
    if cfg5d:
        configs["5d_intersect_topn_4node_cluster"] = cfg5d
    if http_qps:
        configs["2_http_path"] = {"qps": round(http_qps, 1)}
    if http_batch:
        configs["6_http_dynamic_batching"] = http_batch
    if ingest_leg:
        configs["8_streaming_ingest"] = ingest_leg
    if wq_leg:
        configs["9_whole_query"] = wq_leg
    if routing_leg:
        configs["10_elastic_routing"] = routing_leg
    if chaos_leg:
        configs["11_tail_tolerance_chaos"] = chaos_leg
    if slo_leg:
        configs["20_slo_alerting"] = slo_leg
    if wire_leg:
        configs["12_internal_wire"] = wire_leg
    if tenant_leg:
        configs["13_tenant_isolation"] = tenant_leg
    if ssb_leg:
        configs["14_ssb_star_schema"] = ssb_leg

    print(json.dumps({
        "metric": "engine_intersect8_count_qps_1M_cols",
        "value": round(q2, 1),
        "unit": "queries/sec",
        "vs_baseline": round(q2 / c2, 2),
        "configs": configs,
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        run_smoke()
    else:
        main()
