"""Child process for the kill -9 crash harness (tests/test_crash.py).

Runs one single-node server with a failpoint spec armed BEFORE anything
touches disk, so kill-mode failpoints (utils/faults.py) can SIGKILL the
process inside exact storage windows: mid WAL append, mid snapshot
write, between the snapshot fsync and its rename, and inside the
startup torn-tail truncation.  The parent drives write load over HTTP
and records which writes were acknowledged; this process just serves
until it is killed.

Usage: crash_worker.py DATA_DIR BIND MAX_OP_N [FAILPOINT_SPEC]
"""

import sys
import threading


def main():
    data_dir, bind, max_op_n = sys.argv[1:4]
    spec = sys.argv[4] if len(sys.argv) > 4 else ""

    # Arm BEFORE constructing the server: Server.open() arms config
    # failpoints before holder.open(), but the spec must also cover any
    # earlier import-time disk touches a future refactor might add.
    from pilosa_tpu.utils.faults import FAULTS
    if spec:
        FAULTS.configure(spec)

    from pilosa_tpu.server.server import Config, Server
    cfg = Config(data_dir=data_dir, bind=bind, max_op_n=int(max_op_n),
                 anti_entropy_interval=0, repair_interval=0,
                 failpoints=spec)
    srv = Server(cfg)
    srv.open()
    print(f"CRASH WORKER READY port={srv.port}", flush=True)
    threading.Event().wait()  # serve until SIGKILL


if __name__ == "__main__":
    main()
