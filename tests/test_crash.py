"""Process-level kill -9 crash harness (docs/robustness.md
"Durability & recovery").

A child server (tests/crash_worker.py) runs under single-bit write
load; each cycle it is SIGKILLed — either by a kill-mode failpoint
(utils/faults.py) armed inside an exact storage window (mid WAL
append, mid snapshot write, between snapshot fsync and rename, inside
the startup torn-tail truncation) or by a manual kill -9 at a random
write index — then restarted.  After every restart the harness asserts:

* zero acknowledged-write loss: every Set that returned HTTP 200
  before the kill is present after replay;
* no invented data: anything extra is exactly the (at most one)
  in-flight write the kill interrupted;
* clean startup: the server reaches serving state and reports
  storage.degraded == false — a pure process kill must never quarantine
  (torn tails recover; CRCs only fail on real corruption).

The byte-level truncation/bit-flip fuzz lives in tests/test_durability.py.
The short 2-cycle run rides tier-1 and scripts/check.sh; the 20-cycle
randomized soak is marked slow.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "crash_worker.py")
MAX_OP_N = 12   # snapshot every ~12 ops so the snapshot windows see traffic
N_ROWS = 6
INDEX = "ci"


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _req(port, method, path, data=None, timeout=15):
    body = None
    if data is not None:
        body = data.encode() if isinstance(data, str) \
            else json.dumps(data).encode()
    r = urllib.request.Request(
        f"http://localhost:{port}{path}", method=method, data=body)
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read())


def _pick_spec(rng) -> str:
    """One cycle's failpoint spec.  Empty = manual mid-load SIGKILL."""
    roll = int(rng.integers(0, 5))
    if roll == 0:
        return f"fragment.wal=kill:{int(rng.integers(0, 60))}"
    if roll == 1:
        return f"fragment.snapshot=kill:{int(rng.integers(0, 4))}"
    if roll == 2:
        return f"fragment.snapshot.rename=kill:{int(rng.integers(0, 4))}"
    if roll == 3:
        # fires only when startup actually finds a torn tail to
        # truncate; otherwise the manual fallback kill ends the cycle
        return "fragment.wal.truncate=kill:0"
    return ""


class _Harness:
    def __init__(self, tmp_path):
        self.data_dir = str(tmp_path / "node")
        self.proc = None
        self.port = None
        # acknowledged (row -> cols) and possibly-landed in-flight writes
        self.acked = {r: set() for r in range(N_ROWS)}
        self.maybe = set()
        self.next_col = 0

    # -- child lifecycle ---------------------------------------------------

    def _spawn(self, spec: str) -> bool:
        """Start the worker; True once serving, False if it was SIGKILLed
        during startup (a legitimate outcome for startup-window
        failpoints like fragment.wal.truncate)."""
        self.port = _free_port()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + [p for p in
                           env.get("PYTHONPATH", "").split(os.pathsep) if p])
        self.proc = subprocess.Popen(
            [sys.executable, WORKER, self.data_dir,
             f"localhost:{self.port}", str(MAX_OP_N), spec],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True)
        deadline = time.time() + 120
        while time.time() < deadline:
            ret = self.proc.poll()
            if ret is not None:
                out = self.proc.stdout.read()
                assert ret == -signal.SIGKILL, \
                    f"worker died rc={ret} (not SIGKILL):\n{out[-4000:]}"
                return False
            try:
                _req(self.port, "GET", "/status", timeout=5)
                return True
            except Exception:
                time.sleep(0.1)
        raise AssertionError("worker did not reach serving state in 120s")

    def start(self, spec: str = ""):
        """Start the worker with ``spec`` armed; if a startup-window
        failpoint kills it during replay/recovery, restart bare — the
        recovery itself must be crash-safe (truncation re-runs
        idempotently)."""
        if not self._spawn(spec):
            assert self._spawn(""), "recovery-of-recovery died"

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self):
        try:
            self.kill()
        except Exception:
            pass

    # -- load + verification -----------------------------------------------

    def ensure_schema(self):
        for path in (f"/index/{INDEX}", f"/index/{INDEX}/field/f"):
            try:
                _req(self.port, "POST", path, {})
            except urllib.error.HTTPError as e:
                if e.code not in (400, 409):  # already exists
                    raise

    def write_until_death(self, rng, max_writes=250) -> None:
        """Single-bit write load until the child dies at its failpoint;
        if it survives ``max_writes`` (or the cycle is a manual one),
        kill -9 at a random write index."""
        manual_at = int(rng.integers(20, max_writes))
        for i in range(max_writes):
            row = int(rng.integers(0, N_ROWS))
            col = self.next_col
            self.next_col += 1
            self.maybe.add((row, col))
            try:
                _req(self.port, "POST", f"/index/{INDEX}/query",
                     f"Set({col}, f={row})", timeout=15)
            except Exception:
                # the in-flight write died with the child: confirm the
                # death was the SIGKILL we engineered, not a crash
                ret = self.proc.wait(timeout=30)
                assert ret == -signal.SIGKILL, \
                    f"worker died rc={ret} under write load"
                return
            self.acked[row].add(col)
            self.maybe.discard((row, col))
            if i >= manual_at:
                self.kill()
                return
        self.kill()

    def verify(self):
        """The durability contract, checked after every restart."""
        st = _req(self.port, "GET", "/status")
        # a pure process kill never loses/corrupts synced state: torn
        # tails recover, nothing quarantines
        assert st["storage"]["degraded"] is False, st["storage"]
        for row in range(N_ROWS):
            [res] = _req(self.port, "POST", f"/index/{INDEX}/query",
                         f"Row(f={row})")["results"]
            got = set(res["columns"])
            may = {c for (r, c) in self.maybe if r == row}
            lost = self.acked[row] - got
            assert not lost, \
                f"row {row}: {len(lost)} acknowledged writes lost " \
                f"(e.g. {sorted(lost)[:5]})"
            extra = got - self.acked[row] - may
            assert not extra, \
                f"row {row}: invented columns {sorted(extra)[:5]}"


def _run_cycles(tmp_path, n_cycles: int, seed: int,
                forced_specs: list[str] | None = None):
    """Each cycle: (re)start with that cycle's failpoint spec armed —
    the restart itself replays the previous kill's WAL — verify the
    whole durability contract, then write until the armed window (or
    the manual fallback) SIGKILLs the child.  One final bare restart
    verifies the last kill."""
    rng = np.random.default_rng(seed)
    h = _Harness(tmp_path)
    try:
        for cycle in range(n_cycles):
            spec = forced_specs[cycle] if forced_specs is not None \
                else _pick_spec(rng)
            h.start(spec)
            h.ensure_schema()
            h.verify()
            h.write_until_death(rng)
        h.start()
        h.verify()
    finally:
        h.stop()


def test_crash_harness_short(tmp_path):
    """Two deterministic cycles covering the two highest-value windows
    (WAL append, snapshot rename) — fast enough for tier-1 and the
    scripts/check.sh subset."""
    _run_cycles(tmp_path, 2, seed=7, forced_specs=[
        "fragment.wal=kill:25",
        "fragment.snapshot.rename=kill:0",
    ])


@pytest.mark.slow
def test_crash_harness_soak(tmp_path):
    """The acceptance soak: >= 20 randomized kill -9 cycles across all
    storage failpoint windows, zero acknowledged-write loss."""
    _run_cycles(tmp_path, 20, seed=1234)
