"""Executor tests — mirrors executor_test.go coverage: bitmap algebra across
shards, Count/Sum/Min/Max, BSI range conditions (incl. out-of-range and
full-encompass fast paths), TopN two-phase, Rows pagination, GroupBy,
Set/Clear/ClearRow/Store writes, Not, Shift, time-range Row, Options."""

import numpy as np
import pytest
from datetime import datetime

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor, RowResult, ValCount
from pilosa_tpu.storage import FieldOptions, Holder


@pytest.fixture
def holder():
    h = Holder(None)
    return h


@pytest.fixture
def ex(holder):
    return Executor(holder)


def setup_set_field(holder, bits, index="i", field="f", **opts):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_field_if_not_exists(field,
                                       FieldOptions(**opts) if opts else None)
    rows = np.array([b[0] for b in bits])
    cols = np.array([b[1] for b in bits])
    f.import_bits(rows, cols)
    idx.add_existence(cols)
    return f


def cols(res: RowResult):
    return res.columns().tolist()


# -- bitmap calls -----------------------------------------------------------

def test_row(ex, holder):
    setup_set_field(holder, [(10, 1), (10, SHARD_WIDTH + 2), (11, 3)])
    res = ex.execute("i", "Row(f=10)")[0]
    assert cols(res) == [1, SHARD_WIDTH + 2]
    assert res.count() == 2


def test_row_missing_field_errors(ex, holder):
    holder.create_index("i")
    with pytest.raises(Exception):
        ex.execute("i", "Row(nope=1)")


def test_intersect_union_difference_xor(ex, holder):
    setup_set_field(holder, [
        (1, 100), (1, 200), (1, SHARD_WIDTH + 7),
        (2, 100), (2, SHARD_WIDTH + 7), (2, 300),
    ])
    assert cols(ex.execute("i", "Intersect(Row(f=1), Row(f=2))")[0]) == \
        [100, SHARD_WIDTH + 7]
    assert cols(ex.execute("i", "Union(Row(f=1), Row(f=2))")[0]) == \
        [100, 200, 300, SHARD_WIDTH + 7]
    assert cols(ex.execute("i", "Difference(Row(f=1), Row(f=2))")[0]) == [200]
    assert cols(ex.execute("i", "Xor(Row(f=1), Row(f=2))")[0]) == [200, 300]


def test_count(ex, holder):
    setup_set_field(holder, [(1, c) for c in range(50)] +
                    [(1, SHARD_WIDTH + c) for c in range(30)])
    assert ex.execute("i", "Count(Row(f=1))")[0] == 80


def test_not(ex, holder):
    setup_set_field(holder, [(1, 10), (1, 20), (2, 30)])
    res = ex.execute("i", "Not(Row(f=1))")[0]
    assert cols(res) == [30]


def test_shift(ex, holder):
    setup_set_field(holder, [(1, 10), (1, 20)])
    assert cols(ex.execute("i", "Shift(Row(f=1), n=5)")[0]) == [15, 25]


def test_row_time_range(ex, holder):
    idx = holder.create_index("i")
    f = idx.create_field("f", FieldOptions(type="time", time_quantum="YMD"))
    f.set_bit(1, 10, ts=datetime(2017, 1, 5))
    f.set_bit(1, 20, ts=datetime(2017, 3, 5))
    f.set_bit(1, 30, ts=datetime(2018, 1, 5))
    res = ex.execute(
        "i", "Row(f=1, from=2017-01-01T00:00, to=2017-12-31T00:00)")[0]
    assert cols(res) == [10, 20]
    # no time bounds -> standard view (all)
    assert cols(ex.execute("i", "Row(f=1)")[0]) == [10, 20, 30]
    # legacy Range call form
    res = ex.execute(
        "i", "Range(f=1, 2017-01-01T00:00, 2017-02-01T00:00)")[0]
    assert cols(res) == [10]


# -- BSI --------------------------------------------------------------------

@pytest.fixture
def bsi_holder(holder):
    idx = holder.create_index("i")
    f = idx.create_field("v", FieldOptions(type="int", min=-1000, max=1000))
    cols_ = np.array([1, 2, 3, SHARD_WIDTH + 4, SHARD_WIDTH + 5])
    vals = np.array([-500, 0, 250, 600, -1000])
    f.import_values(cols_, vals)
    idx.add_existence(cols_)
    return holder


def test_bsi_range_ops(ex, bsi_holder):
    assert cols(ex.execute("i", "Row(v < 0)")[0]) == [1, SHARD_WIDTH + 5]
    assert cols(ex.execute("i", "Row(v <= 0)")[0]) == [1, 2, SHARD_WIDTH + 5]
    assert cols(ex.execute("i", "Row(v > 250)")[0]) == [SHARD_WIDTH + 4]
    assert cols(ex.execute("i", "Row(v >= 250)")[0]) == [3, SHARD_WIDTH + 4]
    assert cols(ex.execute("i", "Row(v == 250)")[0]) == [3]
    assert cols(ex.execute("i", "Row(v != 250)")[0]) == \
        [1, 2, SHARD_WIDTH + 4, SHARD_WIDTH + 5]
    assert cols(ex.execute("i", "Row(v != null)")[0]) == \
        [1, 2, 3, SHARD_WIDTH + 4, SHARD_WIDTH + 5]
    assert cols(ex.execute("i", "Row(-600 < v < 300)")[0]) == [1, 2, 3]


def test_bsi_out_of_range_semantics(ex, bsi_holder):
    # LT above max -> everything not-null (executor.go:1650)
    assert len(cols(ex.execute("i", "Row(v < 99999)")[0])) == 5
    # GT above representable range -> empty
    assert cols(ex.execute("i", "Row(v > 99999)")[0]) == []
    # EQ out of range -> empty
    assert cols(ex.execute("i", "Row(v == 99999)")[0]) == []
    # NEQ out of range -> all not-null
    assert len(cols(ex.execute("i", "Row(v != 99999)")[0])) == 5
    # BETWEEN fully covering -> not-null
    assert len(cols(ex.execute("i", "Row(-1000 <= v <= 1000)")[0])) == 5


def test_sum_min_max(ex, bsi_holder):
    got = ex.execute("i", "Sum(field=v)")[0]
    assert got == ValCount(-650, 5)
    assert ex.execute("i", "Min(field=v)")[0] == ValCount(-1000, 1)
    assert ex.execute("i", "Max(field=v)")[0] == ValCount(600, 1)
    # with filter child
    got = ex.execute("i", "Sum(Row(v > 0), field=v)")[0]
    assert got == ValCount(850, 2)
    assert ex.execute("i", "Min(Row(v > -1000), field=v)")[0] == \
        ValCount(-500, 1)


# -- TopN -------------------------------------------------------------------

def test_topn(ex, holder):
    bits = []
    for row, n in [(0, 5), (1, 3), (2, 10), (3, 1)]:
        bits += [(row, 1000 + row * SHARD_WIDTH // 2 + i) for i in range(n)]
    setup_set_field(holder, bits)
    pairs = ex.execute("i", "TopN(f, n=2)")[0]
    assert [(p.id, p.count) for p in pairs] == [(2, 10), (0, 5)]
    # all rows
    pairs = ex.execute("i", "TopN(f)")[0]
    assert [(p.id, p.count) for p in pairs] == \
        [(2, 10), (0, 5), (1, 3), (3, 1)]


def test_topn_with_filter_and_ids(ex, holder):
    setup_set_field(holder, [
        (0, 10), (0, 20), (0, 30),
        (1, 10), (1, 20),
        (2, 99),
    ])
    pairs = ex.execute("i", "TopN(f, Row(f=0), n=5)")[0]
    assert [(p.id, p.count) for p in pairs] == [(0, 3), (1, 2)]
    pairs = ex.execute("i", "TopN(f, ids=[1,2])")[0]
    assert [(p.id, p.count) for p in pairs] == [(1, 2), (2, 1)]


@pytest.mark.parametrize("use_mesh", [False, True])
def test_topn_tanimoto(holder, use_mesh):
    """fragment.go:1704 topBitmapPairs: keep rows whose tanimoto
    coefficient vs the source row clears the threshold."""
    # src = row 0 with cols {0..9}; row 1 = same 10 cols (tan=100);
    # row 2 = 5 of them + 5 others (tan = 5/15 = 33%); row 3 disjoint
    bits = [(0, c) for c in range(10)]
    bits += [(1, c) for c in range(10)]
    bits += [(2, c) for c in range(5, 15)]
    bits += [(3, c) for c in range(100, 110)]
    setup_set_field(holder, bits)
    e = Executor(holder, use_mesh=use_mesh)
    pairs = e.execute("i", "TopN(f, Row(f=0), tanimotoThreshold=50)")[0]
    assert [(p.id, p.count) for p in pairs] == [(0, 10), (1, 10)]
    pairs = e.execute("i", "TopN(f, Row(f=0), tanimotoThreshold=30)")[0]
    assert [(p.id, p.count) for p in pairs] == [(0, 10), (1, 10), (2, 5)]
    with pytest.raises(Exception, match="source row"):
        e.execute("i", "TopN(f, tanimotoThreshold=50)")
    with pytest.raises(Exception, match="tanimotoThreshold"):
        e.execute("i", "TopN(f, Row(f=0), tanimotoThreshold=0)")


@pytest.mark.parametrize("use_mesh", [False, True])
def test_topn_attr_filter(holder, use_mesh):
    """executor.go:942-995: attrName/attrValues restrict TopN to rows whose
    row attribute matches."""
    f = setup_set_field(holder, [
        (0, 1), (0, 2), (1, 3), (2, 4), (2, 5), (2, 6)])
    f.row_attrs.set_attrs(0, {"category": "tool"})
    f.row_attrs.set_attrs(2, {"category": "lib"})
    e = Executor(holder, use_mesh=use_mesh)
    pairs = e.execute(
        "i", 'TopN(f, attrName="category", attrValues=["tool", "lib"])')[0]
    assert [(p.id, p.count) for p in pairs] == [(2, 3), (0, 2)]
    pairs = e.execute(
        "i", 'TopN(f, attrName="category", attrValues=["tool"])')[0]
    assert [(p.id, p.count) for p in pairs] == [(0, 2)]
    with pytest.raises(Exception, match="attrValues"):
        e.execute("i", 'TopN(f, attrName="category")')


# -- Rows -------------------------------------------------------------------

def test_rows(ex, holder):
    setup_set_field(holder, [(5, 1), (7, 2), (9, SHARD_WIDTH + 3)])
    assert ex.execute("i", "Rows(f)")[0].rows == [5, 7, 9]
    assert ex.execute("i", "Rows(f, previous=5)")[0].rows == [7, 9]
    assert ex.execute("i", "Rows(f, limit=2)")[0].rows == [5, 7]
    assert ex.execute("i", "Rows(f, column=2)")[0].rows == [7]


# -- GroupBy ----------------------------------------------------------------

def test_group_by(ex, holder):
    idx = holder.create_index("i")
    fa = idx.create_field("a")
    fb = idx.create_field("b")
    # a=0: cols {1,2,3}; a=1: cols {2,3}
    fa.import_bits(np.array([0, 0, 0, 1, 1]), np.array([1, 2, 3, 2, 3]))
    # b=0: cols {2}; b=1: cols {3, S+1}
    fb.import_bits(np.array([0, 1, 1]), np.array([2, 3, SHARD_WIDTH + 1]))
    got = ex.execute("i", "GroupBy(Rows(a), Rows(b))")[0]
    as_tuples = [tuple((fr.field, fr.row_id) for fr in g.group) + (g.count,)
                 for g in got]
    assert as_tuples == [
        (("a", 0), ("b", 0), 1),   # {2}
        (("a", 0), ("b", 1), 1),   # {3}
        (("a", 1), ("b", 0), 1),   # {2}
        (("a", 1), ("b", 1), 1),   # {3}
    ]


def _grid_single_wave_case(holder, rows, n):
    """Two-field GroupBy over a rows x rows grid must take the row-id
    grid path (async dispatch waves) — never fall back to per-child
    blocking Rows round trips.  Verified against an exact pair-count
    oracle on deduplicated (row, col) bits."""
    idx = holder.create_index("i")
    fa = idx.create_field("a")
    fb = idx.create_field("b")
    rng = np.random.default_rng(9)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=n)
    ra = rng.integers(0, rows, size=n)
    rb = rng.integers(0, rows, size=n)
    fa.import_bits(ra, cols)
    fb.import_bits(rb, cols)

    e = Executor(holder, use_mesh=True)
    # the grid path must never execute the Rows children
    def boom(*a, **k):
        raise AssertionError("grid path fell back to Rows execution")
    e._execute_rows = boom

    got = e.execute("i", "GroupBy(Rows(a), Rows(b))")[0]

    import collections
    a_cols = collections.defaultdict(set)
    b_cols = collections.defaultdict(set)
    for r, c_ in zip(ra.tolist(), cols.tolist()):
        a_cols[r].add(c_)
    for r, c_ in zip(rb.tolist(), cols.tolist()):
        b_cols[r].add(c_)
    want = {}
    for i_ in range(rows):
        for j in range(rows):
            cnt = len(a_cols[i_] & b_cols[j])
            if cnt:
                want[(i_, j)] = cnt
    got_map = {(g.group[0].row_id, g.group[1].row_id): g.count
               for g in got}
    assert got_map == want


def test_group_by_grid_single_wave(holder):
    """Small grid (24x24, one 32-combo pad bucket) through the full
    dispatch path: grid taken, Rows never executed, oracle-exact."""
    _grid_single_wave_case(holder, rows=24, n=2000)


def test_group_by_grid_bounds_128x128(holder):
    """16384 total combos stay within the grid bounds (r4 verdict #8:
    the old cap was 4096 TOTAL combos and fell back to blocking Rows
    round trips for 128x128).  Checks _group_by_grid directly — the
    bound decision — without paying the 128-wide grid compile; the
    slow-marked test below covers the full dispatch."""
    idx = holder.create_index("i")
    fa = idx.create_field("a")
    fb = idx.create_field("b")
    fa.import_bits(np.array([127]), np.array([1]))
    fb.import_bits(np.array([127]), np.array([2]))
    e = Executor(holder, use_mesh=True)
    from pilosa_tpu.pql import parse
    names, rows_calls, _, _ = e._group_by_parse(
        "i", parse("GroupBy(Rows(a), Rows(b))").calls[0])
    grid = e._group_by_grid("i", names, rows_calls)
    assert grid is not None, "128x128 fell out of the grid bounds"
    assert [len(rows) for _, rows in grid] == [128, 128]


@pytest.mark.slow
def test_group_by_128x128_grid_single_wave(holder):
    """Full-size 128x128 grid (16384 combos) — the original r4 case.
    Slow: the grid compile dominates tier-1 wall clock; the fast 24x24
    variant above covers the full dispatch path and the bounds check
    covers the retired 4096-combo cap without the compile."""
    _grid_single_wave_case(holder, rows=128, n=20000)


def test_group_by_with_filter_and_limit(ex, holder):
    idx = holder.create_index("i")
    fa = idx.create_field("a")
    fa.import_bits(np.array([0, 0, 1]), np.array([1, 2, 2]))
    got = ex.execute("i", "GroupBy(Rows(a), limit=1)")[0]
    assert len(got) == 1
    assert got[0].count == 2
    got = ex.execute("i", "GroupBy(Rows(a), Row(a=1))")[0]
    # filter = col {2}
    as_tuples = [(g.group[0].row_id, g.count) for g in got]
    assert as_tuples == [(0, 1), (1, 1)]


@pytest.mark.parametrize("use_mesh", [False, True])
def test_group_by_previous_pagination(holder, use_mesh):
    """executor.go:1403: previous=[...] resumes strictly after that group;
    with limit it pages through the full result set."""
    idx = holder.create_index("i")
    fa = idx.create_field("a")
    fb = idx.create_field("b")
    fa.import_bits(np.array([0, 0, 1, 1]), np.array([1, 2, 1, 2]))
    fb.import_bits(np.array([0, 1]), np.array([1, 2]))
    e = Executor(holder, use_mesh=use_mesh)
    full = e.execute("i", "GroupBy(Rows(a), Rows(b))")[0]
    tuples = [tuple(fr.row_id for fr in g.group) for g in full]
    assert tuples == [(0, 0), (0, 1), (1, 0), (1, 1)]
    # page with limit=2 then resume from the last group of page 1
    page1 = e.execute("i", "GroupBy(Rows(a), Rows(b), limit=2)")[0]
    assert [tuple(fr.row_id for fr in g.group) for g in page1] == \
        [(0, 0), (0, 1)]
    page2 = e.execute(
        "i", "GroupBy(Rows(a), Rows(b), limit=2, previous=[0, 1])")[0]
    assert [tuple(fr.row_id for fr in g.group) for g in page2] == \
        [(1, 0), (1, 1)]
    assert [g.count for g in page2] == [g.count for g in full[2:]]
    with pytest.raises(Exception, match="previous"):
        e.execute("i", "GroupBy(Rows(a), Rows(b), previous=[1])")


# -- Options (executor.go:340-403 executeOptionsCall) -----------------------

@pytest.mark.parametrize("use_mesh", [False, True])
def test_options_attrs_and_exclusions(holder, use_mesh):
    f = setup_set_field(holder, [(1, 10), (1, 20), (2, 10)])
    f.row_attrs.set_attrs(1, {"name": "alpha"})
    idx = holder.index("i")
    idx.column_attrs.set_attrs(10, {"city": "x"})
    e = Executor(holder, use_mesh=use_mesh)
    # plain Row carries its row attrs
    row = e.execute("i", "Row(f=1)")[0]
    assert row.attrs == {"name": "alpha"}
    # columnAttrs attaches sets for columns that have attrs
    row = e.execute("i", "Options(Row(f=1), columnAttrs=true)")[0]
    assert row.column_attrs == [{"id": 10, "attrs": {"city": "x"}}]
    # excludeRowAttrs strips row attrs; excludeColumns strips columns
    row = e.execute("i", "Options(Row(f=1), excludeRowAttrs=true)")[0]
    assert row.attrs == {}
    row = e.execute("i", "Options(Row(f=1), excludeColumns=true)")[0]
    assert row.columns().size == 0
    with pytest.raises(Exception, match="bool"):
        e.execute("i", "Options(Row(f=1), columnAttrs=3)")


# -- writes -----------------------------------------------------------------

def test_set_clear(ex, holder):
    holder.create_index("i").create_field("f")
    assert ex.execute("i", "Set(100, f=1)") == [True]
    assert ex.execute("i", "Set(100, f=1)") == [False]
    assert cols(ex.execute("i", "Row(f=1)")[0]) == [100]
    # existence tracked
    assert cols(ex.execute("i", "Not(Row(f=9))")[0]) == [100]
    assert ex.execute("i", "Clear(100, f=1)") == [True]
    assert ex.execute("i", "Clear(100, f=1)") == [False]
    assert cols(ex.execute("i", "Row(f=1)")[0]) == []


def test_set_int_field(ex, holder):
    holder.create_index("i").create_field(
        "v", FieldOptions(type="int", min=0, max=100))
    ex.execute("i", "Set(5, v=42)")
    assert ex.execute("i", "Sum(field=v)")[0] == ValCount(42, 1)


def test_set_with_timestamp(ex, holder):
    holder.create_index("i").create_field(
        "t", FieldOptions(type="time", time_quantum="YMD"))
    ex.execute("i", "Set(7, t=3, 2017-05-05T00:00)")
    res = ex.execute(
        "i", "Row(t=3, from=2017-05-01T00:00, to=2017-06-01T00:00)")[0]
    assert cols(res) == [7]


def test_clear_row_and_store(ex, holder):
    setup_set_field(holder, [(1, 10), (1, 20), (2, 20)])
    assert ex.execute("i", "ClearRow(f=1)") == [True]
    assert cols(ex.execute("i", "Row(f=1)")[0]) == []
    assert cols(ex.execute("i", "Row(f=2)")[0]) == [20]
    # Store: copy row 2 into row 9
    assert ex.execute("i", "Store(Row(f=2), f=9)") == [True]
    assert cols(ex.execute("i", "Row(f=9)")[0]) == [20]


def test_set_attrs(ex, holder):
    idx = holder.create_index("i")
    idx.create_field("f")
    ex.execute("i", "SetRowAttrs(f, 3, color=blue, weight=2)")
    assert idx.field("f").row_attrs.attrs(3) == \
        {"color": "blue", "weight": 2}
    ex.execute("i", "SetColumnAttrs(9, active=true)")
    assert idx.column_attrs.attrs(9) == {"active": True}


def test_options_shards(ex, holder):
    setup_set_field(holder, [(1, 5), (1, SHARD_WIDTH + 5),
                             (1, 3 * SHARD_WIDTH + 5)])
    res = ex.execute("i", "Options(Row(f=1), shards=[0, 3])")[0]
    assert cols(res) == [5, 3 * SHARD_WIDTH + 5]


# -- plan cache -------------------------------------------------------------

def test_plan_cache_reuse(ex, holder):
    setup_set_field(holder, [(1, 5), (2, 5), (1, 6)])
    ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")
    n1 = len(ex.compiler._cache)
    ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")
    assert len(ex.compiler._cache) == n1  # cache hit, no recompile


def test_multiple_calls_in_one_query(ex, holder):
    setup_set_field(holder, [(1, 5)])
    out = ex.execute("i", "Set(6, f=1)Count(Row(f=1))")
    assert out == [True, 2]


def test_shift_zero_is_identity(ex, holder):
    setup_set_field(holder, [(1, 10)])
    assert cols(ex.execute("i", "Shift(Row(f=1), n=0)")[0]) == [10]
    assert cols(ex.execute("i", "Shift(Row(f=1))")[0]) == [10]


def test_set_attrs_bool_id_rejected(ex, holder):
    holder.create_index("i").create_field("f")
    with pytest.raises(Exception):
        ex.execute("i", "SetColumnAttrs(true, active=true)")
