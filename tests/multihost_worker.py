"""Worker process for test_multihost.py: one of N jax.distributed CPU
processes forming ONE multi-host engine (multihost mode 2 —
parallel/multihost.py; the role the reference's gossip+HTTP data plane
plays across nodes, http/client.go:268 QueryNode).

Every process runs this same script in SPMD lockstep: it imports only its
own shard slice host-side (import_process_slice), joins the global mesh,
and executes an identical query sequence whose collectives (psum,
all_gather) cross process boundaries over the distributed runtime.
Answers are asserted against a full-data numpy oracle; the parent test
checks every process printed MULTIHOST OK.

Usage: multihost_worker.py <coordinator_port> <process_id> <n_processes>
"""

import os
import sys


def main():
    port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # the axon TPU-tunnel plugin registers a PJRT backend that breaks the
    # CPU distributed runtime; this worker is CPU-only by design
    sys.path[:] = [p for p in sys.path if "axon" not in p]
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from pilosa_tpu.parallel.multihost import (
        global_mesh, import_process_slice, init_distributed,
    )
    init_distributed(f"localhost:{port}", nproc, pid)

    import jax
    import numpy as np

    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 4 * nproc

    # Environment probe (ROADMAP item 3): some jaxlib builds accept
    # distributed init on CPU but implement NO cross-process collectives
    # — the first psum dies with "Multiprocess computations aren't
    # implemented on the CPU backend".  Probe with a trivial collective
    # BEFORE the heavy import machinery so unsupported environments fail
    # fast with a distinctive marker the parent test turns into a skip
    # (every process runs the same probe in lockstep, so none is left
    # hanging in a half-started collective).
    try:
        jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            np.ones((jax.local_device_count(),), dtype=np.float32))
    except jax.errors.JAXTypeError:
        raise
    except Exception as e:  # XlaRuntimeError lives in jaxlib; match wide
        msg = str(e).replace("\n", " ")
        if "implemented on the CPU backend" in msg or \
                "Multiprocess" in msg:
            print(f"MULTIHOST UNSUPPORTED proc={pid}: {msg[:300]}",
                  flush=True)
            sys.exit(42)
        raise

    from pilosa_tpu.core import SHARD_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops import bsi
    from pilosa_tpu.storage import FieldOptions, Holder

    n_shards = 8
    rng = np.random.default_rng(21)  # same stream on every process
    h = Holder(None)
    idx = h.create_index("mh", track_existence=False)
    f = idx.create_field("f")
    n = 20000
    cols = rng.integers(0, n_shards * SHARD_WIDTH, size=n)
    rows = rng.integers(0, 6, size=n)
    lo, hi = import_process_slice(f, rows, cols, n_shards, max_row_id=5)
    assert (hi - lo) == n_shards // nproc

    # BSI field: same per-slice import; remote shards get shape-matched
    # empty fragments at the GLOBAL bit depth (part of the executable's
    # shape signature, so it must agree on every process)
    v = idx.create_field("v", FieldOptions(type="int", min=0, max=1000))
    vcols = np.unique(cols)[::3]
    vvals = rng.integers(1, 1000, size=vcols.size)
    sel = (vcols >= lo * SHARD_WIDTH) & (vcols < hi * SHARD_WIDTH)
    v.import_values(vcols[sel], vvals[sel])
    depth = int(vvals.max()).bit_length()
    bview = v._create_view_if_not_exists(v.bsi_view_name())
    for s in range(n_shards):
        fr = bview.create_fragment_if_not_exists(s)
        if fr.n_rows <= bsi.OFFSET_ROW + depth - 1:
            fr.set_row(bsi.OFFSET_ROW + depth - 1, None)

    ex = Executor(h, mesh=global_mesh())

    # oracle over the FULL data (each process imported only a slice)
    by_row = {r: set(cols[rows == r].tolist()) for r in range(6)}
    val_of = dict(zip(vcols.tolist(), vvals.tolist()))

    # 1: Count (psum across processes)
    [cnt] = ex.execute("mh", "Count(Row(f=3))")
    assert cnt == len(by_row[3]), (cnt, len(by_row[3]))
    # 2: Intersect+Count
    [cnt] = ex.execute("mh", "Count(Intersect(Row(f=1), Row(f=2)))")
    assert cnt == len(by_row[1] & by_row[2])
    # 3: Row segments (all_gather across processes)
    [row] = ex.execute("mh", "Row(f=1)")
    assert set(row.columns()) == by_row[1]
    # 4: TopN
    [topn] = ex.execute("mh", "TopN(f, n=3)")
    exact = sorted(((len(v_), -r) for r, v_ in by_row.items()),
                   reverse=True)
    assert [p.count for p in topn] == [c for c, _ in exact[:3]]
    # 5: Sum with filter
    [s_] = ex.execute("mh", "Sum(Row(f=2), field=v)")
    want = sum(val_of.get(c, 0) for c in by_row[2])
    assert s_.val == want, (s_.val, want)
    # 6: Min/Max (per-shard extrema gathered across processes)
    [mn] = ex.execute("mh", "Min(field=v)")
    [mx] = ex.execute("mh", "Max(field=v)")
    assert mn.val == int(vvals.min()) and mx.val == int(vvals.max())
    # 7: GroupBy + Rows
    [rws] = ex.execute("mh", "Rows(f)")
    assert rws.rows == sorted(by_row)
    [gb] = ex.execute("mh", "GroupBy(Rows(f), Rows(f))")
    gb_map = {(g.group[0].row_id, g.group[1].row_id): g.count
              for g in gb}
    for a in range(6):
        for b in range(6):
            want = len(by_row[a] & by_row[b])
            assert gb_map.get((a, b), 0) == want, (a, b)

    print(f"MULTIHOST OK proc={pid}", flush=True)


if __name__ == "__main__":
    main()
