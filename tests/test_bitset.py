"""Differential tests for the dense bitset kernels against a naive set-based
oracle — same strategy as the reference's roaring/naive.go + naive_test.go
(every container op checked against a []uint64 reimplementation)."""

import numpy as np
import pytest

from pilosa_tpu.ops import bitset

WORDS = 256  # 8192-column mini-shard: fast on CPU, shape-polymorphic kernels
NBITS = WORDS * 32


def rand_cols(rng, density=0.1):
    n = int(NBITS * density)
    return np.unique(rng.integers(0, NBITS, size=n))


def seg_of(cols):
    return bitset.pack_columns(cols, words=WORDS)


def cols_of(seg):
    return set(bitset.unpack_columns(np.asarray(seg)).tolist())


@pytest.fixture
def ab(rng):
    a = rand_cols(rng, 0.1)
    b = rand_cols(rng, 0.05)
    return a, b, seg_of(a), seg_of(b)


def test_pack_unpack_roundtrip(rng):
    cols = rand_cols(rng)
    assert cols_of(seg_of(cols)) == set(cols.tolist())


def test_intersect(ab):
    a, b, sa, sb = ab
    assert cols_of(bitset.intersect(sa, sb)) == set(a) & set(b)


def test_union(ab):
    a, b, sa, sb = ab
    assert cols_of(bitset.union(sa, sb)) == set(a) | set(b)


def test_difference(ab):
    a, b, sa, sb = ab
    assert cols_of(bitset.difference(sa, sb)) == set(a) - set(b)


def test_xor(ab):
    a, b, sa, sb = ab
    assert cols_of(bitset.xor(sa, sb)) == set(a) ^ set(b)


def test_union_many(rng):
    sets = [rand_cols(rng, 0.02) for _ in range(5)]
    stacked = np.stack([seg_of(c) for c in sets])
    expect = set()
    for c in sets:
        expect |= set(c.tolist())
    assert cols_of(bitset.union_many(stacked)) == expect


def test_count(ab):
    a, _, sa, _ = ab
    assert int(bitset.count(sa)) == len(a)


def test_intersection_count(ab):
    a, b, sa, sb = ab
    assert int(bitset.intersection_count(sa, sb)) == len(set(a) & set(b))


def test_count_range(rng):
    cols = rand_cols(rng)
    seg = seg_of(cols)
    for start, end in [(0, NBITS), (100, 200), (31, 33), (32, 64), (5, 5),
                       (0, 1), (NBITS - 1, NBITS), (1000, 4097)]:
        expect = len([c for c in cols if start <= c < end])
        assert int(bitset.count_range(seg, start, end)) == expect, (start, end)


def test_flip(rng):
    cols = rand_cols(rng)
    seg = seg_of(cols)
    start, end = 50, 7000
    got = cols_of(bitset.flip(seg, start, end))
    expect = set(cols.tolist()) ^ set(range(start, end))
    assert got == expect


def test_keep_range(rng):
    cols = rand_cols(rng)
    got = cols_of(bitset.keep_range(seg_of(cols), 33, 5000))
    assert got == {c for c in cols if 33 <= c < 5000}


@pytest.mark.parametrize("n", [1, 7, 32, 33, 100])
def test_shift(rng, n):
    cols = rand_cols(rng)
    got = cols_of(bitset.shift(seg_of(cols), n))
    expect = {c + n for c in cols if c + n < NBITS}
    assert got == expect


def test_row_counts(rng):
    frag = np.stack([seg_of(rand_cols(rng, d)) for d in (0.1, 0.01, 0.0)])
    counts = np.asarray(bitset.row_counts(frag))
    for i in range(3):
        assert counts[i] == len(cols_of(frag[i]))


def test_intersection_counts_matrix(rng):
    aset = [rand_cols(rng, 0.05) for _ in range(3)]
    bset = [rand_cols(rng, 0.05) for _ in range(4)]
    a = np.stack([seg_of(c) for c in aset])
    b = np.stack([seg_of(c) for c in bset])
    got = np.asarray(bitset.intersection_counts_matrix(a, b))
    for i in range(3):
        for j in range(4):
            assert got[i, j] == len(set(aset[i]) & set(bset[j]))


def test_set_clear_bits(rng):
    import jax.numpy as jnp

    frag = jnp.zeros((4, WORDS), dtype=jnp.uint32)
    rows = np.array([0, 1, 3, 3, -1], dtype=np.int32)
    cols = np.array([5, 8191, 0, 77, 123], dtype=np.int32)
    frag = bitset.set_bits(frag, jnp.asarray(rows), jnp.asarray(cols))
    r, c = bitset.unpack_fragment(np.asarray(frag))
    assert set(zip(r.tolist(), c.tolist())) == {(0, 5), (1, 8191), (3, 0), (3, 77)}

    frag = bitset.clear_bits(
        frag, jnp.asarray(np.array([3, -1], np.int32)),
        jnp.asarray(np.array([77, 5], np.int32)))
    r, c = bitset.unpack_fragment(np.asarray(frag))
    assert set(zip(r.tolist(), c.tolist())) == {(0, 5), (1, 8191), (3, 0)}


def test_pack_fragment(rng):
    rows = np.array([0, 0, 2, 5])
    cols = np.array([1, 100, 1, 8000])
    frag = bitset.pack_fragment(rows, cols, n_rows=6, words=WORDS)
    r, c = bitset.unpack_fragment(frag)
    assert set(zip(r.tolist(), c.tolist())) == set(zip(rows.tolist(), cols.tolist()))


def test_set_bits_same_word_collision():
    # Regression: two positions in the same 32-bit word must both land.
    import jax.numpy as jnp

    frag = jnp.zeros((2, WORDS), dtype=jnp.uint32)
    rows = jnp.asarray(np.array([0, 0, 0, 1, 1], np.int32))
    cols = jnp.asarray(np.array([0, 1, 1, 31, 30], np.int32))
    frag = bitset.set_bits(frag, rows, cols)
    r, c = bitset.unpack_fragment(np.asarray(frag))
    assert set(zip(r.tolist(), c.tolist())) == {(0, 0), (0, 1), (1, 31), (1, 30)}


def test_clear_bits_same_word_collision():
    import jax.numpy as jnp

    frag = jnp.asarray(bitset.pack_fragment(
        np.array([0, 0, 0]), np.array([0, 1, 2]), n_rows=1, words=WORDS))
    frag = bitset.clear_bits(
        frag, jnp.asarray(np.array([0, 0], np.int32)),
        jnp.asarray(np.array([0, 1], np.int32)))
    r, c = bitset.unpack_fragment(np.asarray(frag))
    assert set(zip(r.tolist(), c.tolist())) == {(0, 2)}


def test_set_bits_padding_does_not_clobber():
    # Regression: a row==-1 padding entry must not race a real write to word 0.
    import jax.numpy as jnp

    frag = jnp.zeros((1, WORDS), dtype=jnp.uint32)
    rows = jnp.asarray(np.array([-1, 0], np.int32))
    cols = jnp.asarray(np.array([0, 0], np.int32))
    frag = bitset.set_bits(frag, rows, cols)
    r, c = bitset.unpack_fragment(np.asarray(frag))
    assert set(zip(r.tolist(), c.tolist())) == {(0, 0)}


def test_set_bits_random_vs_oracle(rng):
    import jax.numpy as jnp

    n_rows = 8
    frag = jnp.zeros((n_rows, WORDS), dtype=jnp.uint32)
    rows = rng.integers(0, n_rows, size=2000).astype(np.int32)
    cols = rng.integers(0, NBITS, size=2000).astype(np.int32)
    frag = bitset.set_bits(frag, jnp.asarray(rows), jnp.asarray(cols))
    expect = bitset.pack_fragment(rows, cols, n_rows=n_rows, words=WORDS)
    assert np.array_equal(np.asarray(frag), expect)
