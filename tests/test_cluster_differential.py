"""Cluster-level differential fuzzing with fault injection (r4 verdict
item 6; the reference's clustertests role,
internal/clustertests/cluster_test.go:29-101).

A seeded query grammar (shared with tests/test_differential.py) runs
against a 3-node cluster over real HTTP and a single-node oracle holding
identical data.  Mid-workload a node is killed (replica retry must keep
answers exact), restarted (schema catch-up + anti-entropy), and writes
resume — answers must equal the oracle's at every step, for every seed.

TopN is generated with n=0 (exact cluster reduce): the bounded two-phase
protocol is deliberately approximate like the reference's
(executor.go:879), so it has its own tests rather than a place in an
exact-equality differential.
"""

import json

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.handler import serialize_result
from pilosa_tpu.server.server import Server
from pilosa_tpu.storage import FieldOptions, Holder

from test_cluster import _req, make_cluster
from test_differential import gen_bitmap

N_QUERIES = 30


def gen_cluster_query(rng):
    kind = rng.integers(0, 8)
    bm = gen_bitmap(rng)
    if kind == 0:
        return bm
    if kind == 1:
        return f"Count({bm})"
    if kind == 2:
        return f"Sum({bm}, field=v)"
    if kind in (3, 4):
        which = "Min" if kind == 3 else "Max"
        return f"{which}({bm}, field=v)"
    if kind == 5:
        return f"TopN(a, {bm}, n=0)"  # exact cluster reduce
    if kind == 6:
        return f"Rows(a, limit={rng.integers(1, 12)})"
    return "GroupBy(Rows(b), Rows(a), " + bm + ")"


def _oracle_results(oracle_ex, pql):
    return [json.loads(json.dumps(serialize_result(r)))
            for r in oracle_ex.execute("d", pql)]


def _seed_data(seed):
    rng = np.random.default_rng(seed)
    n = 4000
    cols = rng.integers(0, 5 * SHARD_WIDTH, size=n)
    arows = rng.integers(0, 10, size=n)
    brows = rng.integers(0, 6, size=n)
    vcols = np.unique(cols[: n // 2])
    vvals = rng.integers(-500, 500, size=vcols.size)
    return cols, arows, brows, vcols, vvals


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_cluster_matches_oracle_through_kill_restart(tmp_path, seed):
    servers = make_cluster(tmp_path, n=3, replica_n=2)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/d", {})
        _req(p0, "POST", "/index/d/field/a", {})
        _req(p0, "POST", "/index/d/field/b", {})
        _req(p0, "POST", "/index/d/field/v", {"options": {
            "type": "int", "min": -500, "max": 500}})

        cols, arows, brows, vcols, vvals = _seed_data(seed)
        _req(p0, "POST", "/index/d/field/a/import",
             {"rowIDs": arows.tolist(), "columnIDs": cols.tolist()})
        _req(p0, "POST", "/index/d/field/b/import",
             {"rowIDs": brows.tolist(), "columnIDs": cols.tolist()})
        _req(p0, "POST", "/index/d/field/v/import",
             {"columnIDs": vcols.tolist(), "values": vvals.tolist()})

        # single-node oracle with identical data
        oh = Holder(None)
        idx = oh.create_index("d")
        idx.create_field("a").import_bits(arows, cols)
        idx.create_field("b").import_bits(brows, cols)
        idx.create_field("v", FieldOptions(
            type="int", min=-500, max=500)).import_values(vcols, vvals)
        idx.add_existence(cols)
        oracle = Executor(oh, use_mesh=True)

        rng = np.random.default_rng(seed + 1)
        queries = [gen_cluster_query(rng) for _ in range(N_QUERIES)]

        def check(pql, port):
            got = _req(port, "POST", "/index/d/query", pql)["results"]
            want = _oracle_results(oracle, pql)
            assert got == want, (pql, got, want)

        def run_span(span, port):
            i = 0
            while i < len(span):
                take = int(rng.integers(1, 4))  # mix single + multi-call
                check(" ".join(span[i: i + take]), port)
                i += take

        # phase 1: whole cluster, reads + a write applied to both sides
        run_span(queries[:10], p0)
        wcol = int(rng.integers(0, 5 * SHARD_WIDTH))
        write = f"Set({wcol}, a=3) Set({wcol}, b=1)"
        _req(p0, "POST", "/index/d/query", write)
        oracle.execute("d", write)
        idx.add_existence(np.array([wcol]))
        run_span(queries[10:15], p0)

        # phase 2: kill node2 mid-workload; replica retry keeps answers
        # exact from any surviving node
        dead_cfg = servers[2].config
        servers[2].close()
        for srv in servers[:2]:
            srv.cluster.probe_peers()
        run_span(queries[15:22], p0)
        run_span(queries[22:25], servers[1].port)

        # phase 3: restart + anti-entropy, then writes resume
        servers[2] = Server(dead_cfg)
        servers[2].open()
        for srv in servers:
            srv.cluster.probe_peers()
        servers[2].cluster.sync_holder()
        wcol2 = int(rng.integers(0, 5 * SHARD_WIDTH))
        write2 = f"Set({wcol2}, a=7) Clear({wcol}, a=3)"
        _req(p0, "POST", "/index/d/query", write2)
        oracle.execute("d", write2)
        idx.add_existence(np.array([wcol2]))
        run_span(queries[25:], p0)
        # and the restarted node answers identically too
        run_span(queries[:6], servers[2].port)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
