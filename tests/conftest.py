"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths (mesh placement, shard_map execution, collectives) are
exercised without TPU hardware.  Must run before jax initialises."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
