"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths (mesh placement, shard_map execution, collectives) are
exercised without TPU hardware.  Must run before jax initialises."""

import os

# Force CPU regardless of the ambient platform.  The dev environment's
# sitecustomize imports jax at interpreter startup with JAX_PLATFORMS=axon
# (the TPU tunnel) already latched into jax's config, so the env var alone
# is too late — override the config directly before any backend
# initialises (backends init lazily on first device use).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: seconds-to-minutes end-to-end exercises (bench smoke, "
        "multihost) excluded from tier-1 via -m 'not slow'")


@pytest.fixture
def rng():
    return np.random.default_rng(42)
