"""Key translation tests (reference translate.go, executor.go:2610-2907,
executor_test.go keyed-query cases)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.api import API
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.translate import TranslateStore


# -- store ------------------------------------------------------------------

def test_store_roundtrip(tmp_path):
    s = TranslateStore(str(tmp_path / "keys"))
    a = s.translate_key("alpha")
    b = s.translate_key("beta")
    assert (a, b) == (1, 2)
    assert s.translate_key("alpha") == a  # stable
    assert s.translate_id(a) == "alpha"
    assert s.translate_id(99) is None
    assert s.find_key("beta") == b
    assert s.find_key("nope") is None
    s.close()

    # replay from the append-only log
    s2 = TranslateStore(str(tmp_path / "keys"))
    assert s2.translate_id(1) == "alpha"
    assert s2.translate_key("beta") == 2
    assert s2.translate_key("gamma") == 3
    s2.close()


def test_store_entries_from(tmp_path):
    s = TranslateStore(None)
    for k in ("a", "b", "c"):
        s.translate_key(k)
    assert s.entries_from(1) == [(2, "b"), (3, "c")]
    assert s.entries_from(3) == []


# -- single-node keyed queries ---------------------------------------------

@pytest.fixture
def keyed_api():
    h = Holder(None)
    api = API(h)
    api.create_index("ki", keys=True)
    api.create_field("ki", "f", {"keys": True})
    api.create_field("ki", "plain", {})
    api.create_field("ki", "b", {"type": "bool"})
    return api


def test_keyed_set_and_row(keyed_api):
    api = keyed_api
    [changed] = api.query("ki", 'Set("user123", f="admin")')
    assert changed is True
    [row] = api.query("ki", 'Row(f="admin")')
    assert row.keys == ["user123"]
    [count] = api.query("ki", 'Count(Row(f="admin"))')
    assert count == 1
    # same keys translate to the same ids on re-use
    api.query("ki", 'Set("user456", f="admin")')
    [row] = api.query("ki", 'Row(f="admin")')
    assert sorted(row.keys) == ["user123", "user456"]


def test_keyed_topn_and_rows(keyed_api):
    api = keyed_api
    for user, role in [("u1", "admin"), ("u2", "admin"), ("u3", "dev"),
                       ("u4", "admin"), ("u5", "dev"), ("u6", "ops")]:
        api.query("ki", f'Set("{user}", f="{role}")')
    [topn] = api.query("ki", "TopN(f, n=2)")
    assert [(p.key, p.count) for p in topn] == [("admin", 3), ("dev", 2)]
    [rows] = api.query("ki", "Rows(f)")
    assert sorted(rows.keys) == ["admin", "dev", "ops"]


def test_keyed_groupby(keyed_api):
    api = keyed_api
    api.query("ki", 'Set("u1", f="admin") Set("u2", f="admin")')
    [groups] = api.query("ki", "GroupBy(Rows(f))")
    assert groups[0].group[0].row_key == "admin"
    assert groups[0].count == 2


def test_unknown_read_key_is_empty(keyed_api):
    [count] = keyed_api.query("ki", 'Count(Row(f="nobody"))')
    assert count == 0


def test_bool_row_translation(keyed_api):
    api = keyed_api
    api.query("ki", 'Set("u1", b=true) Set("u2", b=false)')
    [row_t] = api.query("ki", "Row(b=true)")
    assert row_t.keys == ["u1"]
    [row_f] = api.query("ki", "Row(b=false)")
    assert row_f.keys == ["u2"]


def test_string_keys_rejected_when_disabled():
    h = Holder(None)
    api = API(h)
    api.create_index("plain")
    api.create_field("plain", "f", {})
    with pytest.raises(ValueError, match="keys"):
        api.query("plain", 'Set("user", f=1)')
    with pytest.raises(ValueError, match="keys"):
        api.query("plain", 'Row(f="admin")')


def test_non_string_rejected_when_keys_enabled(keyed_api):
    with pytest.raises(ValueError, match="must be a string"):
        keyed_api.query("ki", "Set(5, f=1)")


def test_clear_keyed(keyed_api):
    api = keyed_api
    api.query("ki", 'Set("u1", f="admin")')
    [changed] = api.query("ki", 'Clear("u1", f="admin")')
    assert changed is True
    [count] = api.query("ki", 'Count(Row(f="admin"))')
    assert count == 0


def test_keyed_import(keyed_api):
    api = keyed_api
    api.import_bits("ki", "f", row_keys=["r1", "r1", "r2"],
                    column_keys=["c1", "c2", "c3"])
    [row] = api.query("ki", 'Row(f="r1")')
    assert sorted(row.keys) == ["c1", "c2"]


def test_keys_persist_across_restart(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    api = API(h)
    api.create_index("ki", keys=True)
    api.create_field("ki", "f", {"keys": True})
    api.query("ki", 'Set("user123", f="admin")')
    h.close()

    h2 = Holder(str(tmp_path / "d"))
    h2.open()
    api2 = API(h2)
    [row] = api2.query("ki", 'Row(f="admin")')
    assert row.keys == ["user123"]
    # new keys continue the sequence, not restart it
    assert h2.index("ki").translate_store().translate_key("userX") > 1
    h2.close()


# -- cluster round-trip over HTTP ------------------------------------------

def test_keyed_cluster_roundtrip(tmp_path):
    from tests.test_cluster import make_cluster, _req, query

    servers = make_cluster(tmp_path, n=3, replica_n=2)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/ki", {"options": {"keys": True}})
        _req(p0, "POST", "/index/ki/field/f",
             {"options": {"keys": True}})
        # write via a NON-coordinator node: translation routes to node0
        p1 = servers[1].port
        [changed] = query(p1, "ki", 'Set("user123", f="admin")')
        assert changed is True
        query(p1, "ki", 'Set("user456", f="admin") Set("user789", f="dev")')
        # read back via every node
        for srv in servers:
            [row] = query(srv.port, "ki", 'Row(f="admin")')
            assert sorted(row["keys"]) == ["user123", "user456"]
            [topn] = query(srv.port, "ki", "TopN(f, n=2)")
            assert [(p["key"], p["count"]) for p in topn] == \
                [("admin", 2), ("dev", 1)]
        # keyed import over HTTP through a replica
        _req(p1, "POST", "/index/ki/field/f/import",
             {"rowKeys": ["ops", "ops"], "columnKeys": ["userA", "userB"]})
        [cnt] = query(servers[2].port, "ki", 'Count(Row(f="ops"))')
        assert cnt == 2
    finally:
        for s in servers:
            s.close()


def test_replica_translate_streaming_catchup(tmp_path):
    """Anti-entropy pulls new key entries to replicas in one stream
    (holder.go:812 holderTranslateStoreReplicator): after a sync, reads of
    coordinator-written keys need no per-key round trips."""
    from tests.test_cluster import make_cluster, _req, query
    from pilosa_tpu.parallel.cluster import RemoteTranslateStore

    servers = make_cluster(tmp_path, n=2, replica_n=2)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/ki", {"options": {"keys": True}})
        _req(p0, "POST", "/index/ki/field/f", {"options": {"keys": True}})
        query(p0, "ki", 'Set("u1", f="admin") Set("u2", f="dev")')
        # replica's stores are remote and empty-cached before the sync
        idx1 = servers[1].holder.index("ki")
        col_ts = idx1.translate_store()
        row_ts = idx1.field("f").translate_store()
        assert isinstance(col_ts, RemoteTranslateStore)
        assert col_ts.find_key("u1") is None
        servers[1].cluster.sync_holder()
        assert col_ts.find_key("u1") is not None
        assert col_ts.find_key("u2") is not None
        assert row_ts.find_key("admin") is not None
        # incremental: only NEW entries stream on the next pass
        query(p0, "ki", 'Set("u3", f="admin")')
        assert col_ts.sync_entries() == 1
        assert col_ts.sync_entries() == 0
    finally:
        for s in servers:
            s.close()


def test_remote_translate_batches_requests(tmp_path):
    """N uncached keys/ids must translate in ONE coordinator POST, not N
    (r2 advisor's last open finding)."""
    from pilosa_tpu.parallel.cluster import RemoteTranslateStore

    calls = []

    class FakeClient:
        def _json(self, host, method, path, body):
            calls.append(body)
            if "keys" in body:
                return {"ids": [100 + i for i, _ in
                                enumerate(body["keys"])]}
            return {"keys": [f"k{i}" for i in body["ids"]]}

    st = RemoteTranslateStore(FakeClient(), "h", "i", None)
    ids = st.translate_keys(["a", "b", "c", "a"])
    assert len(calls) == 1 and calls[0] == {"keys": ["a", "b", "c"]}
    assert ids[0] == ids[3]
    # cached now: no further requests
    st.translate_keys(["a", "c"])
    assert len(calls) == 1
    # id -> key batches the uncached subset only
    st.translate_ids([7, 8, ids[0]])
    assert len(calls) == 2 and calls[1] == {"ids": [7, 8]}
    st.translate_ids([7, 8])
    assert len(calls) == 2
