"""Device-runtime observability (ISSUE 8, docs/observability.md "Device
runtime"): the compile registry's retrace red flag on a forced re-trace
of a cached executable (the PR 7 regression corpus), launch-ledger ring
bounds + padding-ratio math, the time-series ring's sampling/wrap/
interval math under a fake clock, the new /debug surfaces (served,
probe-excluded), and the /metrics round-trip of the new families through
the PR 5 Prometheus parser."""

import json
import urllib.request

import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.storage.membudget import DEFAULT_BUDGET
from pilosa_tpu.utils import devobs
from pilosa_tpu.utils.devobs import CompileRegistry, LaunchLedger
from pilosa_tpu.utils.timeseries import TimeSeriesRing

from test_containers import corpus  # noqa: F401 — PR 7 regression corpus
from test_observability import _parse_prometheus, _req, make_server


class _EventLogger:
    """Collects Logger.event calls (the structured retrace lines)."""

    def __init__(self):
        self.events = []

    def event(self, name, **fields):
        self.events.append((name, fields))


# -- compile registry -------------------------------------------------------

def test_compile_registry_unit():
    reg = CompileRegistry()
    log = _EventLogger()
    reg.logger = log
    # first compile of a signature: counted, not a retrace
    reg.begin_call()
    assert not reg.traced()
    reg.mark_traced()
    assert reg.traced()
    assert reg.note_call("count:abc", "count", 0.5, "8x4:int32") is False
    t = reg.totals()
    assert t["compiles"] == 1 and t["retraces"] == 0
    # an un-traced call records nothing (the caller gates on traced())
    reg.begin_call()
    assert not reg.traced()
    # second compile of the SAME signature: retrace — log event carries
    # the fingerprint diff
    reg.begin_call()
    reg.mark_traced()
    assert reg.note_call("count:abc", "count", 0.25, "16x4:int32") is True
    t = reg.totals()
    assert t["compiles"] == 2 and t["retraces"] == 1
    assert t["compileSecondsTotal"] == pytest.approx(0.75)
    assert log.events == [("device.retrace", {
        "sig": "count:abc", "kind": "count", "compiles": 2,
        "compileS": 0.25, "prevShapes": "8x4:int32",
        "shapes": "16x4:int32"})]
    (entry,) = reg.snapshot()["entries"]
    assert entry["compiles"] == 2
    assert entry["lastFingerprint"] == "16x4:int32"
    assert entry["lastCompileWall"] > 0


def test_compile_registry_entry_bound():
    reg = CompileRegistry()
    reg.MAX_ENTRIES = 4
    for i in range(10):
        reg.begin_call()
        reg.mark_traced()
        reg.note_call(f"sig{i}", "count", 0.01, "fp")
    snap = reg.snapshot()
    assert len(snap["entries"]) == 4          # LRU-bounded
    assert snap["compiles"] == 10             # totals keep counting
    assert [e["sig"] for e in snap["entries"]] == \
        ["sig6", "sig7", "sig8", "sig9"]


def test_forced_retrace_fires_counter_and_event(corpus):  # noqa: F811
    """The acceptance gate: re-running the PR 7 retrace corpus (growing
    then shrinking shard subsets re-trace cached executables at new
    stacked group sizes) increments device retraces, emits the
    structured log event with the signature diff, and lands in the
    registry with compiles > 1."""
    ex = Executor(corpus, use_mesh=True)
    old_limit = DEFAULT_BUDGET.limit_bytes
    log = _EventLogger()
    old_logger = devobs.COMPILES.logger
    devobs.COMPILES.logger = log
    before = devobs.COMPILES.totals()
    q = "Count(Intersect(Row(a=11), Row(a=2)))"
    try:
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        want = {}
        for size in (16, 2, 9, 16, 1):
            sl = list(range(size))
            got = ex.execute("c", q, shards=sl)[0]
            if size in want:
                assert got == want[size]
            want[size] = got
    finally:
        DEFAULT_BUDGET.limit_bytes = old_limit
        devobs.COMPILES.logger = old_logger
        ex.close()
    after = devobs.COMPILES.totals()
    assert after["retraces"] > before["retraces"], \
        "forced re-trace never reached the retrace counter"
    retraces = [f for n, f in log.events if n == "device.retrace"]
    assert retraces, "no structured device.retrace event emitted"
    # the signature diff IS the red flag: the re-trace changed shapes
    assert all(f["prevShapes"] != f["shapes"] for f in retraces)
    assert any(e["compiles"] > 1
               for e in devobs.COMPILES.snapshot()["entries"])


# -- launch ledger ----------------------------------------------------------

def test_launch_ledger_ring_bound_and_padding_math():
    led = LaunchLedger(size=4)
    for i in range(10):
        # 3 real shard rows padded to a 4-bucket, single query row:
        # 3 actual units, 1 padded unit per launch
        led.record(sig=f"s{i}", kind="count", shards=3, shards_padded=4,
                   batch_rows=1, batch_rows_padded=1, queue_s=0.001,
                   dispatch_s=0.002, decode_bytes=100, compiled=(i == 0))
    snap = led.snapshot()
    assert snap["launches"] == 10
    assert len(snap["entries"]) == 4          # ring bound
    assert [e["sig"] for e in snap["entries"]] == ["s6", "s7", "s8", "s9"]
    # golden padding math: 10 x (3 actual, 1 padded) -> 25% waste
    assert snap["rowsActual"] == 30 and snap["rowsPadded"] == 10
    assert snap["paddingWasteRatio"] == pytest.approx(0.25)
    assert led.padding_waste_ratio() == pytest.approx(0.25)
    assert snap["decodePeakBytes"] == 100
    assert snap["decodeBytesTotal"] == 1000
    assert snap["launchS"]["count"] == 10

    # query-axis padding counts too: 2 tickets fused to 3 rows padded
    # to 4 over an exact 8-shard bucket -> 8 padded units of 32
    led2 = LaunchLedger(size=4)
    led2.record(sig="f", kind="count", shards=8, shards_padded=8,
                batch_rows=3, batch_rows_padded=4, queue_s=0.0,
                dispatch_s=0.001, decode_bytes=0, compiled=False,
                tickets=2)
    assert led2.aggregates()["rowsActual"] == 24
    assert led2.aggregates()["rowsPadded"] == 8
    assert led2.aggregates()["paddingWasteRatio"] == pytest.approx(0.25)

    # resize keeps the newest entries
    led.resize(2)
    assert [e["sig"] for e in led.snapshot()["entries"]] == ["s8", "s9"]


def test_launch_ledger_populates_on_query(corpus):  # noqa: F811
    before = devobs.LEDGER.launches_total
    ex = Executor(corpus, use_mesh=True)
    try:
        ex.execute("c", "Count(Row(a=2))", shards=list(range(3)))
    finally:
        ex.close()
    assert devobs.LEDGER.launches_total > before
    entry = devobs.LEDGER.snapshot()["entries"][-1]
    assert entry["kind"] in ("count", "countB", "wholequery")
    assert entry["shards"] == 3
    # 3 shards bucket-pad to the 8-device mesh width
    assert entry["shardsPadded"] == 8
    assert entry["dispatchS"] > 0


# -- time-series ring -------------------------------------------------------

def test_timeseries_ring_fake_clock():
    clock = [100.0]
    ring = TimeSeriesRing(interval_s=5.0, window_s=20.0,
                          now_fn=lambda: clock[0])
    assert ring.capacity == 5                  # ceil(20/5) + 1
    assert ring.sample({"v": 1}) is True       # first sample always lands
    assert ring.sample({"v": 2}) is False      # same instant: gated
    clock[0] += 2.0
    assert ring.sample({"v": 3}) is False      # under the interval: gated
    clock[0] += 2.6                            # 4.6 >= 0.9 * 5: slack
    assert ring.sample({"v": 4}) is True
    for i in range(10):                        # wrap the ring
        clock[0] += 5.0
        assert ring.sample({"v": 10 + i}) is True
    snap = ring.snapshot()
    assert snap["samplesTotal"] == 12
    assert len(snap["samples"]) == 5           # bounded
    assert [s["v"] for s in snap["samples"]] == [15, 16, 17, 18, 19]
    # inter-sample math is monotonic-clock based and covers the window
    assert snap["coveredS"] == pytest.approx(20.0)
    assert snap["samples"][-1]["uptimeS"] == pytest.approx(54.6)
    # force bypasses the cadence gate (epoch marks)
    assert ring.sample({"v": 99}, force=True) is True


# -- served surfaces --------------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=30) as resp:
        return resp.read(), dict(resp.headers)


def test_debug_surfaces_served_and_probe_excluded(tmp_path):
    srv = make_server(tmp_path, timeseries_interval=0.05,
                      timeseries_window=0.5)
    p = srv.port
    try:
        _req(p, "POST", "/index/i", {})
        _req(p, "POST", "/index/i/field/f", {})
        _req(p, "POST", "/index/i/query", "Count(Row(f=1))")
        # post-request accounting runs AFTER the response is sent
        # (handler._observe in the finally block); poll until all three
        # requests above have landed or the late increment would read
        # as a probe-exclusion leak below
        import time
        deadline = time.perf_counter() + 5
        while time.perf_counter() < deadline:
            hist0 = srv.stats.snapshot()["timings"]["http.request"]["count"]
            if hist0 >= 3:
                break
            time.sleep(0.01)
        body, _ = _get(p, "/debug/compiles")
        comp = json.loads(body)
        assert comp["compiles"] > 0 and "entries" in comp
        body, _ = _get(p, "/debug/launches")
        lau = json.loads(body)
        assert lau["launches"] > 0 and lau["entries"]
        assert 0.0 <= lau["paddingWasteRatio"] <= 1.0
        # sampler thread fills the ring on its own cadence
        import time
        deadline = time.perf_counter() + 5
        while time.perf_counter() < deadline:
            ts = json.loads(_get(p, "/debug/timeseries")[0])
            if len(ts["samples"]) >= 3:
                break
            time.sleep(0.02)
        assert ts["intervalS"] == 0.05
        assert len(ts["samples"]) >= 3
        sample = ts["samples"][-1]
        for field in ("hbmResidentBytes", "hbmCompressedBytes",
                      "admissionInUse", "batcherQueued", "compilesDelta",
                      "retracesDelta", "evictionsDelta",
                      "httpQueriesDelta"):
            assert field in sample, f"time-series sample lacks {field}"
        body, headers = _get(p, "/debug/dashboard")
        assert headers["Content-Type"].startswith("text/html")
        assert b"/debug/timeseries" in body
        # /debug/vars carries the summary sections the cli top reads
        v, _ = _req(p, "GET", "/debug/vars")
        assert v["device"]["compiles"]["compiles"] > 0
        assert v["timeseries"]["samplesTotal"] >= 3
        # all of the above is background traffic: the edge histograms
        # must not have moved (probe/debug exclusion, PR 5 discipline)
        hist1 = srv.stats.snapshot()["timings"]["http.request"]["count"]
        assert hist1 == hist0, "debug traffic leaked into http.request"
    finally:
        srv.close()


def test_retrace_visible_at_debug_compiles(tmp_path):
    """Server-side acceptance: two queries whose shard subsets bucket to
    different stacked shapes re-trace one cached executable, and the
    retrace shows at /debug/compiles and as device_retraces_total at
    /metrics."""
    srv = make_server(tmp_path)
    p = srv.port
    try:
        _req(p, "POST", "/index/rt", {})
        _req(p, "POST", "/index/rt/field/f", {})
        # one bit in each of 16 shards: subsets of <= 8 shards bucket to
        # the 8-device mesh width, the full set to 16
        _req(p, "POST", "/index/rt/field/f/import",
             {"rowIDs": [1] * 16,
              "columnIDs": [s * SHARD_WIDTH for s in range(16)]})
        before = json.loads(_get(p, "/debug/compiles")[0])
        shards = ",".join(str(s) for s in range(16))
        _req(p, "POST", f"/index/rt/query?shards={shards}",
             "Count(Row(f=1))")
        _req(p, "POST", "/index/rt/query?shards=0", "Count(Row(f=1))")
        after = json.loads(_get(p, "/debug/compiles")[0])
        assert after["retraces"] > before["retraces"]
        assert any(e["compiles"] > 1 for e in after["entries"])
        text = _get(p, "/metrics")[0].decode()
        _, samples = _parse_prometheus(text)
        assert samples[("pilosa_tpu_device_retraces_total",
                        frozenset())] >= 1
    finally:
        srv.close()


def test_metrics_device_families_round_trip(tmp_path):
    srv = make_server(tmp_path)
    p = srv.port
    try:
        _req(p, "POST", "/index/i", {})
        _req(p, "POST", "/index/i/field/f", {})
        for _ in range(2):
            _req(p, "POST", "/index/i/query", "Count(Row(f=1))")
        text = _get(p, "/metrics")[0].decode()
        types, samples = _parse_prometheus(text)
        flat = {n: v for (n, ls), v in samples.items() if not ls}
        assert flat["pilosa_tpu_device_compiles_total"] >= 1
        assert flat["pilosa_tpu_device_retraces_total"] >= 0
        assert flat["pilosa_tpu_device_launches_total"] >= 1
        assert 0.0 <= flat["pilosa_tpu_device_padding_waste_ratio"] <= 1.0
        assert "pilosa_tpu_device_decode_workspace_peak_bytes" in flat
        assert flat["pilosa_tpu_device_decode_workspace_limit_bytes"] > 0
        # the launch ledger's own histogram families parse as proper
        # cumulative Prometheus histograms
        fam = "pilosa_tpu_device_launch_seconds"
        assert types[fam] == "histogram"
        buckets = [v for (n, ls), v in samples.items()
                   if n == f"{fam}_bucket"]
        assert max(buckets) == samples[(f"{fam}_count", frozenset())]
        assert samples[(f"{fam}_count", frozenset())] >= 1
    finally:
        srv.close()


# -- cli top ----------------------------------------------------------------

def test_cli_top_renders_summary(tmp_path, capsys):
    from pilosa_tpu import cli
    srv = make_server(tmp_path, timeseries_interval=0.05)
    p = srv.port
    try:
        _req(p, "POST", "/index/i", {})
        _req(p, "POST", "/index/i/field/f", {})
        _req(p, "POST", "/index/i/query", "Count(Row(f=1))")
        rc = cli.main(["top", "-host", f"localhost:{p}",
                       "--count", "2", "--interval", "0.1"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "qps" in out and "hbm" in out and "retraces" in out
        assert out.count("pilosa-tpu top @") == 2
    finally:
        srv.close()
