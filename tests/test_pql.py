"""PQL parser golden tests — mirrors reference pql/pqlpeg_test.go cases
(happy path ncalls vectors + structure assertions + error cases)."""

import pytest

from pilosa_tpu import pql
from pilosa_tpu.pql import BETWEEN, Condition


# (input, expected number of top-level calls) — from pqlpeg_test.go:79-303
HAPPY = [
    ("", 0),
    ("Set(2, f=10)", 1),
    ("Set('foo', f=10)", 1),
    ('Set("foo", f=10)', 1),
    ("Set(2, f=1, 1999-12-31T00:00)", 1),
    ("Set(1, a=4)Set(2, a=4)", 2),
    ("Set(1, a=4) Set(2, a=4)", 2),
    ("Set(1, a=4) \n Set(2, a=4)", 2),
    ("Set(1, a=4)Blerg(z=ha)", 2),
    ("Set(1, a=4)Blerg(z=ha)Set(2, z=99)", 3),
    ("Arb(q=1, a=4)Set(1, z=9)Arb(z=99)", 3),
    ("Set(1, a=zoom)", 1),
    ("Set(1, a=4, b=5)", 1),
    ("Set(1, a=4, bsd=haha)", 1),
    ("Set(1, a=4, 2017-04-03T19:34)", 1),
    ("Union()", 1),
    ("Union(Row(a=1))", 1),
    ("Union(Row(a=1), Row(z=44))", 1),
    ("Union(Intersect(Row(), Union(Row(), Row())), Row())", 1),
    ("TopN(boondoggle)", 1),
    ("TopN(boon, doggle=9)", 1),
    ('B(a="zm\'\'e")', 1),
    ("B(a='zm\"\"e')", 1),
    ("SetRowAttrs(blah, 9, a=47)", 1),
    ("SetRowAttrs(blah, 9, a=47, b=bval)", 1),
    ("SetRowAttrs(blah, 'rowKey', a=47)", 1),
    ('SetRowAttrs(blah, "rowKey", a=47)', 1),
    ("SetColumnAttrs(9, a=47)", 1),
    ("SetColumnAttrs(9, a=47, b=bval)", 1),
    ("SetColumnAttrs('colKey', a=47)", 1),
    ('SetColumnAttrs("colKey", a=47)', 1),
    ("Clear(1, a=53)", 1),
    ("Clear(1, a=53, b=33)", 1),
    ("TopN(myfield, n=44)", 1),
    ("TopN(myfield, Row(a=47), n=10)", 1),
    ("Row(a < 4)", 1),
    ("Row(a > 4)", 1),
    ("Row(a <= 4)", 1),
    ("Row(a >= 4)", 1),
    ("Row(a == 4)", 1),
    ("Row(a != null)", 1),
    ("Row(4 < a < 9)", 1),
    ("Row(4 < a <= 9)", 1),
    ("Row(4 <= a < 9)", 1),
    ("Row(4 <= a <= 9)", 1),
    ("Row(a=4, from=2010-07-04T00:00, to=2010-08-04T00:00)", 1),
    ("Row(a=4, from='2010-07-04T00:00', to=\"2010-08-04T00:00\")", 1),
    ("Row(a=4, from='2010-07-04T00:00')", 1),
    ("Row(a=4, to=\"2010-08-04T00:00\")", 1),
    ("Set(1, my-frame=9)", 1),
    ("Set(\n1,\nmy-frame\n=9)", 1),
    ("Range(blah=1, 2019-04-07T00:00, 2019-08-07T00:00)", 1),
]


@pytest.mark.parametrize("text,ncalls", HAPPY)
def test_parse_happy(text, ncalls):
    q = pql.parse(text)
    assert len(q.calls) == ncalls, repr(q)


# error cases (pqlpeg_test.go:304-341 TestPEGErrors) + extras
BAD = [
    "Set",
    "Set(1, a=4, 2017-94-03T19:34)",
    "Set(1, 2017-04-03T19:34)",
    "Set(, 1, a=4)",
    "Zeeb(, a=4)",
    "SetRowAttrs(blah, 9)",
    "Clear(9)",
    "Row(a>4, 2010-07-04T00:00, 2010-08-04T00:00)",
    "Row(a=4, 2010-07-04T00:00)",
    "Row(a=9223372036854775808)",
    "Row(a=-9223372036854775809)",
    "Set()haha",
    "Set(1, a=4)'",
    "Set(a=4)",
    "Set(1, b=5",
    ", Blerg()",
    "SetRowAttrs(blah)",
    "Clear()",
]


@pytest.mark.parametrize("text", BAD)
def test_parse_errors(text):
    with pytest.raises(pql.ParseError):
        pql.parse(text)


# -- structural assertions --------------------------------------------------

def test_set_structure():
    q = pql.parse("Set(2, f=10, 1999-12-31T00:00)")
    c = q.calls[0]
    assert c.name == "Set"
    assert c.args["_col"] == 2
    assert c.args["f"] == 10
    assert c.args["_timestamp"] == "1999-12-31T00:00"


def test_nested_structure():
    q = pql.parse("Intersect(Row(a=1), Union(Row(b=2), Row(c=3)), x=7)")
    c = q.calls[0]
    assert c.name == "Intersect"
    assert [ch.name for ch in c.children] == ["Row", "Union"]
    assert c.children[1].children[0].args["b"] == 2
    assert c.args["x"] == 7


def test_condition_structure():
    q = pql.parse("Row(a <= 4)")
    cond = q.calls[0].args["a"]
    assert isinstance(cond, Condition)
    assert cond.op == "<="
    assert cond.value == 4


def test_between_adjusts_strict_bounds():
    q = pql.parse("Row(4 < a <= 9)")
    cond = q.calls[0].args["a"]
    assert cond.op == BETWEEN
    assert cond.value == [5, 9]
    q = pql.parse("Row(4 <= a < 9)")
    assert q.calls[0].args["a"].value == [4, 8]


def test_topn_posfield():
    q = pql.parse("TopN(myfield, Row(a=47), n=10)")
    c = q.calls[0]
    assert c.args["_field"] == "myfield"
    assert c.children[0].name == "Row"
    assert c.args["n"] == 10


def test_store_structure():
    q = pql.parse("Store(Row(a=1), b=2)")
    c = q.calls[0]
    assert c.name == "Store"
    assert c.children[0].name == "Row"
    assert c.args["b"] == 2


def test_rows_args():
    q = pql.parse("Rows(f, previous=10, limit=5, column=3)")
    c = q.calls[0]
    assert c.args["_field"] == "f"
    assert c.args["previous"] == 10
    assert c.args["limit"] == 5


def test_value_forms():
    q = pql.parse(
        'F(a=null, b=true, c=false, d=-5, e=1.25, f=word, g="q s", '
        "h=[1,2,3], i=a:b-c_d)")
    a = q.calls[0].args
    assert a["a"] is None
    assert a["b"] is True
    assert a["c"] is False
    assert a["d"] == -5
    assert a["e"] == 1.25
    assert a["f"] == "word"
    assert a["g"] == "q s"
    assert a["h"] == [1, 2, 3]
    assert a["i"] == "a:b-c_d"


def test_quoted_string_escapes():
    q = pql.parse(r'F(a="x\"y", b=\'p\\\'q\')'.replace(r"\'", "'")
                  if False else 'F(a="x\\"y")')
    assert q.calls[0].args["a"] == 'x"y'


def test_clearrow_and_range_call():
    q = pql.parse("ClearRow(f=5)")
    assert q.calls[0].args["f"] == 5
    q = pql.parse("Range(blah=1, 2019-04-07T00:00, 2019-08-07T00:00)")
    c = q.calls[0]
    assert c.args["blah"] == 1
    assert c.args["from"] == "2019-04-07T00:00"
    assert c.args["to"] == "2019-08-07T00:00"


def test_write_calls_detection():
    q = pql.parse("Set(1, a=2)Count(Row(a=2))")
    assert [c.name for c in q.write_calls()] == ["Set"]


def test_duplicate_arg_rejected():
    with pytest.raises(pql.ParseError):
        pql.parse("Row(a=1, a=2)")
