"""Multi-host mode 2 across REAL processes (r4 verdict item 5).

Launches two jax.distributed CPU processes (4 virtual devices each) that
form one 8-device engine: each imports only its own shard slice, and the
full distributed query set — Count/Intersect/Row/TopN/Sum/Min/Max/Rows/
GroupBy — executes in SPMD lockstep with psum/all_gather collectives
crossing the process boundary.  See tests/multihost_worker.py for the
worker body (reference role: gossip/gossip.go + http/client.go node-to-
node engine)."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_engine():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    # the axon TPU-tunnel site hooks the interpreter via a .pth at
    # startup (before any in-process scrubbing can run), so it must be
    # dropped from PYTHONPATH in the parent
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(i), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    # Environment sandbox (ROADMAP item 3): jaxlib builds whose CPU
    # backend implements no cross-process collectives make this test
    # un-runnable, not failing — the worker probes with a trivial psum
    # right after distributed init and exits 42 with an UNSUPPORTED
    # marker.  Skip with the real error so the reason is visible.
    for out in outs:
        for line in out.splitlines():
            if "MULTIHOST UNSUPPORTED" in line:
                pytest.skip(
                    "XLA CPU multiprocess collectives unsupported by "
                    f"this jaxlib: {line.split(':', 1)[-1].strip()}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert f"MULTIHOST OK proc={i}" in out, out[-2000:]
