"""Elastic serving: load-aware replica read routing, residency tiers,
and hot-shard rebalancing (docs/cluster.md "Read routing & rebalancing";
parallel/routing.py, parallel/balancer.py).

Covers: policy selection semantics against a real (unopened) Cluster —
primary byte-for-byte vs the legacy grouping, loaded scoring with the
no-data fallback, round-robin spread, residency preference with one
replica budget-constrained, breaker pre-skip (and its all-open waiver);
the 3-node differential (loaded answers byte-identical to primary under
interleaved writes); skew-corpus replica spread over real HTTP;
piggybacked load/residency folding; and the balancer: handoff
convergence with oracle-identical answers, overlay-aware writes,
epoch-gated overlay application on a restarted (state-wiped) node, and
balancer=off restoring static jump-hash exactly.
"""

import json
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel.cluster import Cluster
from pilosa_tpu.server.handler import serialize_result
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.storage import Holder

from test_cluster import _free_ports, _req, query


def make_routing_cluster(tmp_path, n=3, replica_n=2, **overrides):
    ports = _free_ports(n)
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        cfg = Config(
            data_dir=str(tmp_path / f"node{i}"),
            bind=f"localhost:{p}",
            node_id=f"node{i}",
            cluster_hosts=hosts,
            replica_n=replica_n,
            anti_entropy_interval=0,  # driven manually in tests
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        srv = Server(cfg)
        srv.open()
        servers.append(srv)
    return servers


def close_all(servers):
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


# -- router selection semantics (no servers: a Cluster is enough) -----------


@pytest.fixture
def bare_cluster():
    """Unopened 3-node cluster over a memory holder: placement, router,
    and breaker state are all live without any sockets."""
    cl = Cluster("node0", ["localhost:1", "localhost:2", "localhost:3"],
                 replica_n=2, holder=Holder(None))
    yield cl
    cl.close()


def legacy_group(cl, index, shards):
    """The pre-routing grouping, reimplemented verbatim: self if an
    owner, else the first READY owner (executor.go:2435)."""
    groups = {}
    for s in shards:
        owners = cl.placement.shard_nodes(index, s)
        ready = [o for o in owners if cl.by_id[o].state == "READY"]
        order = ready or owners
        target = cl.node_id if cl.node_id in order else order[0]
        groups.setdefault(target, []).append(s)
    return groups


def test_primary_policy_matches_legacy_grouping(bare_cluster):
    cl = bare_cluster
    cl.router.policy = "primary"
    shards = list(range(24))
    assert cl.router.group_shards("i", shards) == \
        legacy_group(cl, "i", shards)
    # balancer off + empty overlay: owner sets are EXACTLY static
    # jump-hash
    for s in shards:
        assert cl.shard_owner_nodes("i", s) == \
            cl.placement.shard_nodes("i", s)


def test_loaded_with_no_history_falls_back_to_primary(bare_cluster):
    cl = bare_cluster
    cl.router.policy = "loaded"
    shards = list(range(16))
    assert cl.router.group_shards("i", shards) == \
        legacy_group(cl, "i", shards)
    assert cl.router.fallbacks >= 1
    assert cl.router.snapshot()["fallbacks"] >= 1


def test_loaded_prefers_low_load_replica(bare_cluster):
    cl = bare_cluster
    cl.router.policy = "loaded"
    cl.router.residency_routing = False
    # find a shard with two distinct remote owners so the score decides
    shard = next(s for s in range(64)
                 if "node0" not in cl.placement.shard_nodes("i", s))
    a, b = cl.placement.shard_nodes("i", shard)
    # equal RTT history; b is drowning in queued work
    cl.router.note_dispatch(a, 1)
    cl.router.note_done(a, 0.01)
    cl.router.note_dispatch(b, 1)
    cl.router.note_done(b, 0.01)
    cl.router.note_query_load(b, {"inFlight": 50, "queued": 10})
    groups = cl.router.group_shards("i", [shard])
    assert groups == {a: [shard]}
    # flip: now a is overloaded and b idle
    cl.router.note_query_load(a, {"inFlight": 50, "queued": 10})
    cl.router.note_query_load(b, {"inFlight": 0, "queued": 0})
    assert cl.router.group_shards("i", [shard]) == {b: [shard]}


def test_round_robin_spreads_owners(bare_cluster):
    cl = bare_cluster
    cl.router.policy = "round-robin"
    shard = next(s for s in range(64)
                 if "node0" not in cl.placement.shard_nodes("i", s))
    seen = set()
    for _ in range(6):
        ((nid, _),) = cl.router.group_shards("i", [shard]).items()
        seen.add(nid)
    assert seen == set(cl.placement.shard_nodes("i", shard))


def test_residency_preference_with_budget_constrained_replica(bare_cluster):
    """One replica advertises the shard HBM-resident, the other is
    budget-constrained (nothing resident): equal load must route to the
    resident one; with residency-routing off the tie reverts to
    placement order."""
    cl = bare_cluster
    cl.router.policy = "loaded"
    cl.router.residency_routing = True
    shard = next(s for s in range(64)
                 if "node0" not in cl.placement.shard_nodes("i", s))
    a, b = cl.placement.shard_nodes("i", shard)
    for nid in (a, b):
        cl.router.note_dispatch(nid, 1)
        cl.router.note_done(nid, 0.01)
    # b holds the shard resident; a (budget-constrained) holds nothing
    cl.router.note_status(b, {"residency": {"i": {"hbm": [shard],
                                                  "host": []}}})
    cl.router.note_status(a, {"residency": {}})
    assert cl.router.group_shards("i", [shard]) == {b: [shard]}
    snap = cl.router.snapshot()["peers"][b]
    assert snap["residencyAgeS"] is not None
    assert snap["residentShards"]["i"]["hbm"] == 1
    # host-staged beats disk-only too
    cl.router.note_status(b, {"residency": {"i": {"hbm": [],
                                                  "host": [shard]}}})
    assert cl.router.group_shards("i", [shard]) == {b: [shard]}
    # pure-load mode ignores residency: equal scores, placement order
    cl.router.residency_routing = False
    assert cl.router.group_shards("i", [shard]) == {a: [shard]}


def test_breaker_skip_before_dispatch_and_all_open_waiver(bare_cluster):
    cl = bare_cluster
    cl.router.policy = "primary"
    shard = next(s for s in range(64)
                 if "node0" not in cl.placement.shard_nodes("i", s))
    a, b = cl.placement.shard_nodes("i", shard)
    # open a's breaker directly
    ba = cl.client._breaker(cl.by_id[a].host)
    ba.state = "open"
    skips0 = cl.router.breaker_skips
    assert cl.router.group_shards("i", [shard]) == {b: [shard]}
    assert cl.router.breaker_skips == skips0 + 1
    assert cl.by_id[a].state == "DOWN"  # skip converges with NODE_DOWN
    # ALL candidates open: the skip is waived so the fan-out still
    # dispatches (and surfaces the fail-fast error loudly)
    cl.by_id[a].state = "READY"
    bb = cl.client._breaker(cl.by_id[b].host)
    bb.state = "open"
    groups = cl.router.group_shards("i", [shard])
    assert sum(groups.values(), []) == [shard]
    assert cl.router.breaker_skips == skips0 + 1  # no new skip counted


def test_overlay_epoch_gating_and_owner_extension(bare_cluster):
    cl = bare_cluster
    owners = cl.placement.shard_nodes("i", 0)
    extra = next(n.id for n in cl.nodes if n.id not in owners)
    cl._apply_overlay({"epoch": 3, "overlay": [["i", 0, [extra]]]})
    assert cl.overlay_epoch == 3
    assert cl.shard_owner_nodes("i", 0) == owners + [extra]
    assert cl.owned_shards(extra, "i", [0, 1]) \
        == [0] + ([1] if extra in cl.placement.shard_nodes("i", 1) else [])
    # older or duplicate epochs are idempotent no-ops
    cl._apply_overlay({"epoch": 2, "overlay": []})
    assert cl.overlay_epoch == 3
    assert cl.shard_owner_nodes("i", 0) == owners + [extra]
    # a newer empty table clears it
    cl._apply_overlay({"epoch": 4, "overlay": []})
    assert cl.shard_owner_nodes("i", 0) == owners


def test_shard_load_tracker_hot_and_spread():
    from pilosa_tpu.parallel.balancer import ShardLoadTracker
    tr = ShardLoadTracker(window_s=1000)
    for _ in range(40):
        tr.note("i", [7], "node1")
    for _ in range(8):
        tr.note("i", [7], "node2")
    for s in range(4):
        tr.note("i", [s], "node0")
    hot = tr.hot_shards(threshold=2.0)
    assert hot and hot[0][:2] == ("i", 7) and hot[0][2] == 48
    snap = tr.snapshot()
    top = snap["hottest"][0]
    assert top["shard"] == 7 and set(top["nodes"]) == {"node1", "node2"}
    assert tr.node_counts()["node1"] == 40
    # rotation keeps the previous window visible, then ages it out
    tr.rotate()
    assert tr.hot_shards(threshold=2.0)[0][2] == 48
    tr.rotate()
    assert tr.hot_shards(threshold=2.0) == []


# -- 3-node end-to-end suite -------------------------------------------------


@pytest.fixture(scope="module")
def rcluster(tmp_path_factory):
    """3-node replica_n=2 cluster with the ``sk`` corpus loaded (shared
    read-only by the skew/piggyback/residency tests, so each test does
    not pay 3 server startups)."""
    servers = make_routing_cluster(
        tmp_path_factory.mktemp("routing"), n=3, replica_n=2,
        read_routing="loaded")
    p0 = _setup(servers, "sk")
    cols = list(range(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 8))
    _req(p0, "POST", "/index/sk/field/a/import",
         {"rowIDs": [1] * len(cols), "columnIDs": cols})
    yield servers
    close_all(servers)


def _setup(servers, name):
    p0 = servers[0].port
    _req(p0, "POST", f"/index/{name}", {})
    _req(p0, "POST", f"/index/{name}/field/a", {})
    _req(p0, "POST", f"/index/{name}/field/v",
         {"options": {"type": "int", "min": -500, "max": 500}})
    return p0


def test_differential_loaded_vs_primary_interleaved_writes(rcluster):
    """Byte-identity: the same queries answer identically under
    read-routing=primary and loaded, across interleaved writes, and
    match a single-node oracle holding identical data."""
    from pilosa_tpu.storage import FieldOptions

    servers = rcluster
    p0 = _setup(servers, "dr")
    rng = np.random.default_rng(17)
    n = 2500
    cols = rng.integers(0, 4 * SHARD_WIDTH, size=n)
    rows = rng.integers(0, 8, size=n)
    vcols = np.unique(cols[: n // 2])
    vvals = rng.integers(-500, 500, size=vcols.size)
    _req(p0, "POST", "/index/dr/field/a/import",
         {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    _req(p0, "POST", "/index/dr/field/v/import",
         {"columnIDs": vcols.tolist(), "values": vvals.tolist()})

    oh = Holder(None)
    idx = oh.create_index("dr")
    idx.create_field("a").import_bits(rows, cols)
    idx.create_field("v", FieldOptions(
        type="int", min=-500, max=500)).import_values(vcols, vvals)
    idx.add_existence(cols)
    oracle = Executor(oh, use_mesh=True)

    queries = ["Count(Row(a=3))", "Row(a=1)",
               "Count(Intersect(Row(a=1), Row(a=2)))",
               "Sum(Row(a=4), field=v)", "Min(field=v)", "Max(field=v)",
               "TopN(a, n=0)", "Rows(a)",
               "GroupBy(Rows(a), limit=6)"]

    def run_policy(policy):
        for s in servers:
            s.cluster.router.policy = policy
        return [query(p0, "dr", q) for q in queries]

    try:
        for phase in range(2):
            want = [
                [json.loads(json.dumps(serialize_result(r)))
                 for r in oracle.execute("dr", q)] for q in queries]
            got_primary = run_policy("primary")
            got_loaded = run_policy("loaded")
            assert got_loaded == got_primary == want, f"phase {phase}"
            # interleaved writes (fan to every replica synchronously)
            wcol = int(rng.integers(0, 4 * SHARD_WIDTH))
            w = f"Set({wcol}, a=2) Clear({int(cols[phase])}, a={int(rows[phase])})"
            _req(p0, "POST", "/index/dr/query", w)
            oracle.execute("dr", w)
            idx.add_existence(np.array([wcol]))
    finally:
        for s in servers:
            s.cluster.router.policy = "loaded"
        oracle.close()


def test_skew_corpus_spreads_hot_shard(rcluster):
    """Skewed load on one shard with replica_n=2: loaded routing must
    serve the hot shard from MORE than one node (the idle-replica
    problem this subsystem exists to fix)."""
    servers = rcluster
    p0 = servers[0].port
    cols = list(range(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 8))
    coord = servers[0].cluster
    # pick a hot shard with two REMOTE owners so spread is observable
    # regardless of the local bias
    hot = next(s for s in range(4)
               if "node0" not in coord.placement.shard_nodes("sk", s))
    hot_q = "Count(Row(a=1))"
    # seed RTT history (first waves fall back to primary and pay XLA
    # compiles; they must not count toward the spread assertion)
    for _ in range(4):
        query(p0, "sk", hot_q)
    tracker = coord.load_tracker
    tracker.rotate()
    tracker.rotate()

    served = set()
    for _round in range(3):
        threads = [threading.Thread(
            target=query, args=(p0, "sk", hot_q)) for _ in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        snap = tracker.snapshot(top=16)
        for entry in snap["hottest"]:
            if entry["index"] == "sk" and entry["shard"] == hot:
                served |= set(entry["nodes"])
        if len(served) > 1:
            break
    assert len(served) > 1, \
        f"hot shard {hot} only ever served by {served}"
    # answers stayed correct throughout
    [cnt] = query(p0, "sk", hot_q)
    assert cnt == len(cols)


def test_piggybacked_load_and_residency_fold(rcluster):
    """/internal/query responses and /status probes feed the router:
    after traffic + one probe pass the coordinator holds per-peer load
    and residency summaries, and every surface exposes them."""
    servers = rcluster
    p0 = servers[0].port
    coord = servers[0].cluster
    query(p0, "sk", "Count(Row(a=1))")
    coord.probe_peers()
    snap = coord.router.snapshot()
    peers = snap["peers"]
    assert peers, "router never saw a peer"
    remotes = {nid: st for nid, st in peers.items() if nid != "node0"}
    assert remotes, "router never saw a remote peer"
    for nid, st in remotes.items():
        assert st["reportedInFlight"] >= 0
        assert st["residencyAgeS"] is not None, \
            f"{nid} never advertised residency"
    # the peers ran queries, so their summaries list resident shards
    assert any(st["residentShards"] for st in remotes.values())
    # /status carries the piggybacks
    st = _req(servers[1].port, "GET", "/status")
    assert "load" in st and "residency" in st and "overlayEpoch" in st
    # /debug/vars cluster.routing + /metrics cluster_peer_* gauges
    dv = _req(p0, "GET", "/debug/vars")
    assert dv["cluster"]["routing"]["policy"] == "loaded"
    assert set(dv["cluster"]["routing"]["peers"]) >= {"node1", "node2"}
    import urllib.request
    with urllib.request.urlopen(
            f"http://localhost:{p0}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    assert "pilosa_tpu_cluster_peer_node1_ewma_rtt_ms" in text
    assert "pilosa_tpu_cluster_peer_node2_inflight" in text
    assert "pilosa_tpu_cluster_overlay_epoch" in text


def test_local_residency_summary_tiers(rcluster):
    """A node that just served a mesh query reports the shards
    HBM-resident (stacked blocks count as resident)."""
    servers = rcluster
    query(servers[0].port, "sk", "Count(Row(a=1))")
    summaries = [s.cluster.residency_summary() for s in servers]
    assert any("sk" in s and s["sk"]["hbm"] for s in summaries), \
        f"no node reports sk resident: {summaries}"


# -- hot-shard balancer ------------------------------------------------------


def test_balancer_handoff_converges_with_oracle_answers(tmp_path):
    """End-to-end handoff: a hot shard with replica_n=1 gains an overlay
    owner (fragments copied via the resize-fetch machinery), every node
    adopts the overlay epoch, answers stay oracle-identical, writes fan
    to the overlay owner, and a restarted state-wiped node is
    reconciled by the probe's overlay-epoch re-push."""
    servers = make_routing_cluster(tmp_path, n=3, replica_n=1,
                                   hot_shard_threshold=2.0)
    try:
        p0 = _setup(servers, "hb2")
        rng = np.random.default_rng(5)
        cols = np.unique(rng.integers(0, 6 * SHARD_WIDTH, size=1200))
        rows = rng.integers(0, 6, size=cols.size)
        _req(p0, "POST", "/index/hb2/field/a/import",
             {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
        coord = servers[0].cluster
        # a shard whose single owner is REMOTE, so the overlay owner and
        # the restart victim below are both non-coordinator nodes
        hot = next(s for s in range(6)
                   if coord.placement.primary("hb2", s) != "node0")
        hot_q = "Count(Row(a=2))"
        want = query(p0, "hb2", hot_q)
        # skewed load: the tracker must rank shard `hot` hot
        for _ in range(40):
            coord.load_tracker.note("hb2", [hot],
                                    coord.placement.primary("hb2", hot))
        for s in range(6):
            coord.load_tracker.note("hb2", [s], "node0")

        owners0 = coord.shard_owner_nodes("hb2", hot)
        assert len(owners0) == 1
        assert coord.balancer.tick() == 1, coord.balancer.snapshot()
        owners1 = coord.shard_owner_nodes("hb2", hot)
        assert len(owners1) == 2 and owners1[:1] == owners0
        extra = owners1[1]
        # every node adopted the same overlay epoch + table
        for s in servers:
            assert s.cluster.overlay_epoch == coord.overlay_epoch
            assert s.cluster.shard_owner_nodes("hb2", hot) == owners1
        # the overlay owner holds a real copy
        extra_srv = next(s for s in servers
                         if s.cluster.node_id == extra)
        frag = extra_srv.holder.fragment("hb2", "a", "standard", hot)
        assert frag is not None and frag.n_rows > 0
        # answers unchanged, from any node
        for s in servers:
            assert query(s.port, "hb2", hot_q) == want
        # writes now fan to the overlay owner too
        wcol = hot * SHARD_WIDTH + 123
        query(p0, "hb2", f"Set({wcol}, a=2)")
        assert extra_srv.holder.fragment(
            "hb2", "a", "standard", hot).row(2)[123 // 32] >> (123 % 32) & 1
        [cnt] = query(p0, "hb2", hot_q)
        assert cnt == want[0] + 1
        # bounded: a second tick can widen by at most one more owner,
        # and a third finds no non-owner left — never loops
        coord.balancer.tick()
        assert len(coord.shard_owner_nodes("hb2", hot)) <= 3

        # restart the OVERLAY owner with WIPED cluster state (.topology
        # removed): the probe pass must re-push the overlay, epoch-gated
        victim = extra_srv
        vid, vcfg = victim.cluster.node_id, victim.config
        servers.remove(victim)
        victim.close()
        import os
        topo = os.path.join(os.path.expanduser(vcfg.data_dir),
                            ".topology")
        if os.path.exists(topo):
            os.remove(topo)
        restarted = Server(vcfg)
        restarted.open()
        servers.append(restarted)
        assert restarted.cluster.overlay_epoch == 0  # wiped
        coord.probe_peers()

        def wait_for(cond, timeout=10.0):
            t0 = time.monotonic()
            while time.monotonic() - t0 < timeout:
                if cond():
                    return True
                time.sleep(0.05)
            return False

        assert wait_for(lambda: restarted.cluster.overlay_epoch
                        == coord.overlay_epoch)
        assert restarted.cluster.shard_owner_nodes("hb2", hot) \
            == coord.shard_owner_nodes("hb2", hot)
        assert query(restarted.port, "hb2", hot_q) == [want[0] + 1]
        # balancer counters surfaced
        dv = _req(p0, "GET", "/debug/vars")
        assert dv["cluster"]["balancer"]["handoffs"] >= 1
        assert dv["cluster"]["overlay"]["epoch"] >= 1
    finally:
        close_all(servers)


def test_balancer_off_is_static_jump_hash(tmp_path):
    """balancer=off (the default): no balancer thread, empty overlay,
    and the primary policy reproduces the static grouping exactly."""
    servers = make_routing_cluster(tmp_path, n=2, replica_n=2,
                                   read_routing="primary")
    try:
        p0 = _setup(servers, "st")
        _req(p0, "POST", "/index/st/field/a/import",
             {"rowIDs": [1, 1, 1],
              "columnIDs": [5, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 5]})
        coord = servers[0].cluster
        assert not coord.balancer_on
        assert coord.overlay_snapshot() == {"epoch": 0, "entries": []}
        shards = [0, 1, 2]
        assert coord.router.group_shards("st", shards) == \
            legacy_group(coord, "st", shards)
        [cnt] = query(p0, "st", "Count(Row(a=1))")
        assert cnt == 3
    finally:
        close_all(servers)
