"""Streaming ingest subsystem (docs/ingest.md): wire codec fuzz,
ingest-vs-bulk_import differential (overlay-live AND merged), group
commit counting, backpressure 503s, 2-node forwarded-shard ingest, the
CLI client, and the kill -9 crash window inside the committer flush."""

import http.client
import io
import json
import os
import signal
import socket
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.ingest import wire
from pilosa_tpu.ingest.committer import GroupCommitter
from pilosa_tpu.storage import Holder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- helpers ---------------------------------------------------------------


def _req(port, method, path, body=None, ctype="application/json",
         timeout=120):
    r = urllib.request.Request(f"http://localhost:{port}{path}",
                               method=method, data=body)
    if body is not None:
        r.add_header("Content-Type", ctype)
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _mk_server(tmp_path, **overrides):
    from pilosa_tpu.server.server import Config, Server
    overrides.setdefault("ingest_flush_ms", 20.0)
    cfg = Config(data_dir=str(tmp_path / "ing_node"), bind="localhost:0",
                 anti_entropy_interval=0, **overrides)
    srv = Server(cfg)
    srv.open()
    return srv


@pytest.fixture
def srv(tmp_path):
    s = _mk_server(tmp_path)
    yield s
    s.close()


def _wal_frames(frag) -> int:
    """Count CRC frames in a fragment's WAL file."""
    from pilosa_tpu.storage.fragment import _WAL_FRAME, _WAL_MAGIC
    with open(frag._wal_path(), "rb") as f:
        buf = f.read()
    assert buf.startswith(_WAL_MAGIC)
    off, n = len(_WAL_MAGIC), 0
    while off < len(buf):
        plen, _crc = _WAL_FRAME.unpack_from(buf, off)
        off += _WAL_FRAME.size + plen
        n += 1
    assert off == len(buf)
    return n


# -- wire codec ------------------------------------------------------------


def test_wire_round_trip(rng):
    rows = rng.integers(0, 50, 1000)
    cols = rng.integers(0, 5 * SHARD_WIDTH, 1000)
    ts = rng.integers(0, 2 ** 31, 1000)
    for t in (None, ts):
        body = wire.encode_records(rows, cols, ts=t, frame_records=300)
        reader = wire.FrameReader(io.BytesIO(body).read, len(body))
        out_r, out_c, out_t = [], [], []
        frames = 0
        while True:
            item = reader.next_frame()
            if item is None:
                break
            rectype, recs, _n = item
            frames += 1
            assert rectype == (wire.REC_BITS if t is None
                               else wire.REC_BITS_TS)
            out_r.append(recs["row"])
            out_c.append(recs["col"])
            if t is not None:
                out_t.append(recs["ts"])
        assert frames == 4  # 1000 records / 300 per frame
        assert np.array_equal(np.concatenate(out_r), rows)
        assert np.array_equal(np.concatenate(out_c), cols)
        if t is not None:
            assert np.array_equal(np.concatenate(out_t), ts)
    # values records
    vals = rng.integers(-1000, 1000, 64)
    body = wire.encode_records(None, cols[:64], values=vals)
    reader = wire.FrameReader(io.BytesIO(body).read, len(body))
    rectype, recs, _n = reader.next_frame()
    assert rectype == wire.REC_VALS
    assert np.array_equal(recs["value"], vals)
    assert reader.next_frame() is None


def _drain(body: bytes):
    reader = wire.FrameReader(io.BytesIO(body).read, len(body))
    out = []
    while True:
        item = reader.next_frame()
        if item is None:
            return out
        out.append((item[0], item[1].tobytes()))


def test_wire_every_byte_corruption_rejected(rng):
    """Flip one bit at EVERY byte offset of a two-frame stream: the
    reader must reject the stream (magic check, frame bounds, CRC) —
    never silently import different records."""
    rows = rng.integers(0, 8, 40)
    cols = rng.integers(0, SHARD_WIDTH, 40)
    body = wire.encode_records(rows, cols, frame_records=25)
    want = _drain(body)
    for off in range(len(body)):
        bad = bytearray(body)
        bad[off] ^= 0x10
        try:
            got = _drain(bytes(bad))
        except wire.FrameError:
            continue
        assert got != want, f"corruption at byte {off} went undetected"
    # truncation at every length is detected too
    for cut in range(len(body)):
        try:
            got = _drain(body[:cut])
        except wire.FrameError:
            continue
        assert got != want, f"truncation to {cut} bytes went undetected"


def test_wire_frame_ceiling():
    payload = wire.pack_bits([1], [2])
    body = wire.MAGIC + wire.encode_frame(payload)
    reader = wire.FrameReader(io.BytesIO(body).read, len(body),
                              max_frame_bytes=4)
    with pytest.raises(wire.FrameError, match="ingest-max-frame-mb"):
        reader.next_frame()


# -- differential: ingest vs bulk_import -----------------------------------


def test_ingest_bulk_differential(rng):
    """The same corpus through the committer and through bulk_import
    yields byte-identical fragments; queries agree while deltas are
    overlay-resident AND after the merge folds them."""
    from pilosa_tpu.executor import Executor

    n_shards = 4
    batches = []
    for _ in range(6):
        n = int(rng.integers(200, 2000))
        batches.append((rng.integers(0, 24, n),
                        rng.integers(0, n_shards * SHARD_WIDTH, n)))

    h_bulk = Holder(None)
    idx_b = h_bulk.create_index("d")
    f_b = idx_b.create_field("f")
    for rows, cols in batches:
        f_b.import_bits(rows, cols)
        idx_b.add_existence(np.unique(cols))

    h_ing = Holder(None)
    idx_i = h_ing.create_index("d")
    idx_i.create_field("f")
    com = GroupCommitter(h_ing, flush_ms=0)  # inline flush per wait
    ex = Executor(h_ing, use_mesh=True)
    ex_b = Executor(h_bulk, use_mesh=True)
    queries = ["Count(Row(f=3))", "TopN(f, n=5)",
               "Count(Intersect(Row(f=1), Row(f=2)))"]
    try:
        # prime the mesh stacks so later flushes exercise the overlay
        seq = com.submit("d", "f", rows=batches[0][0], cols=batches[0][1])
        com.wait_flushed(seq)
        for q in queries:
            ex.execute("d", q)
        for rows, cols in batches[1:]:
            seq = com.submit("d", "f", rows=rows, cols=cols)
            com.wait_flushed(seq)
        live_journal = sum(
            fr.delta_bytes() for *_x, fr in h_ing.iter_fragments("d"))
        assert live_journal > 0, "overlay journal never engaged"
        for q in queries:  # overlay-resident reads
            assert repr(ex.execute("d", q)) == repr(ex_b.execute("d", q))
        com.merge_all()  # fold = the background merge
        assert sum(fr.delta_bytes()
                   for *_x, fr in h_ing.iter_fragments("d")) == 0
        for q in queries:  # merged reads
            assert repr(ex.execute("d", q)) == repr(ex_b.execute("d", q))
        # byte-identical fragments (snapshot codec over the host store)
        frs_b = {(f_, v, s): fr for _i, f_, v, s, fr
                 in h_bulk.iter_fragments("d")}
        frs_i = {(f_, v, s): fr for _i, f_, v, s, fr
                 in h_ing.iter_fragments("d")}
        assert set(frs_b) == set(frs_i)
        for key, fr in frs_b.items():
            assert fr.snapshot_bytes() == frs_i[key].snapshot_bytes(), key
    finally:
        ex.close()
        ex_b.close()
        com.close()


def test_ingest_int_values(srv, rng):
    p = srv.port
    _req(p, "POST", "/index/i", b"{}")
    _req(p, "POST", "/index/i/field/v",
         json.dumps({"options": {"type": "int", "min": -500,
                                 "max": 500}}).encode())
    cols = np.arange(300) * 17 % (2 * SHARD_WIDTH)
    vals = rng.integers(-500, 500, 300)
    body = wire.encode_records(None, cols, values=vals)
    out = _req(p, "POST", "/index/i/field/v/ingest", body,
               "application/octet-stream")
    assert out["records"] == 300
    res = _req(p, "POST", "/index/i/query", b"Sum(field=v)")
    last = {}
    for c, v in zip(cols, vals):
        last[int(c)] = int(v)
    assert res["results"][0]["value"] == sum(last.values())


def test_ingest_rejects_bad_records(srv, rng):
    """Record validation happens AT THE SOCKET (400), never as a
    poisoned shared flush: negative ids and rectype/field-type
    mismatches are refused before submission."""
    p = srv.port
    _req(p, "POST", "/index/val", b"{}")
    _req(p, "POST", "/index/val/field/f", b"{}")
    _req(p, "POST", "/index/val/field/v",
         json.dumps({"options": {"type": "int", "min": 0,
                                 "max": 100}}).encode())
    cases = [
        # negative row into a set field
        ("f", wire.encode_records([-1], [5])),
        # negative column
        ("f", wire.encode_records([1], [-5])),
        # values frame at a set field
        ("f", wire.encode_records(None, [5], values=[7])),
        # bits frame at an int field
        ("v", wire.encode_records([1], [5])),
    ]
    for field, body in cases:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(p, "POST", f"/index/val/field/{field}/ingest", body,
                 "application/octet-stream")
        ei.value.read()
        assert ei.value.code == 400
    # the server stayed consistent: a valid stream still lands
    out = _req(p, "POST", "/index/val/field/f/ingest",
               wire.encode_records([1], [5]),
               "application/octet-stream")
    assert out["records"] == 1


def test_inline_flush_concurrent_ack_serialized(rng):
    """flush_ms <= 0 (inline) mode under concurrent producers: every
    acked wait_flushed means the records are actually applied — the
    flush lock keeps a second caller from advancing the covering
    sequence past an in-flight apply."""
    import threading

    h = Holder(None)
    idx = h.create_index("inl", track_existence=False)
    f = idx.create_field("f")
    com = GroupCommitter(h, flush_ms=0)
    errs = []

    def producer(k):
        try:
            for i in range(20):
                rows = np.full(50, k, dtype=np.int64)
                cols = (np.arange(50) + i * 50) % SHARD_WIDTH
                seq = com.submit("inl", "f", rows=rows, cols=cols)
                assert com.wait_flushed(seq)
                # acked => visible in the host store immediately
                got = set(f.view("standard").fragment(0)
                          .rows_with_bit(int(cols[0])))
                assert k in got, f"acked write for row {k} not applied"
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=producer, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    com.close()
    assert not errs, errs


# -- group commit ----------------------------------------------------------


def test_group_commit_one_frame_one_gen(tmp_path, rng):
    """5000 records over many wire frames in one request: each touched
    fragment gets ONE WAL frame and ONE generation bump.  The flush
    window is set wide so the whole request coalesces into one flush
    deterministically (the acker's wait nudges it at end-of-stream)."""
    s = _mk_server(tmp_path, ingest_flush_ms=1000.0)
    try:
        p = s.port
        _req(p, "POST", "/index/g", b"{}")
        _req(p, "POST", "/index/g/field/f", b"{}")
        # first stream creates the fragments
        rows = rng.integers(0, 8, 500)
        cols = rng.integers(0, SHARD_WIDTH // 2, 500)
        _req(p, "POST", "/index/g/field/f/ingest",
             wire.encode_records(rows, cols), "application/octet-stream")
        frag = s.holder.fragment("g", "f", "standard", 0)
        gen0 = frag.gen
        epoch0 = frag.ingest_epoch
        frames0 = _wal_frames(frag)
        # 5000 records, 10 wire frames, one request -> one flush
        rows = rng.integers(0, 8, 5000)
        cols = rng.integers(SHARD_WIDTH // 2, SHARD_WIDTH, 5000)
        out = _req(p, "POST", "/index/g/field/f/ingest",
                   wire.encode_records(rows, cols, frame_records=500),
                   "application/octet-stream")
        assert out["frames"] == 10 and out["records"] == 5000
        # gen moved (readers/result caches must invalidate) and it moved
        # ONCE for this fragment: exactly one journal chunk / one epoch
        # (Fragment._GEN is process-global, so gen0+1 would race other
        # fragments — the per-fragment epoch is the bump counter)
        assert frag.gen != gen0
        assert frag.ingest_epoch == epoch0 + 1, \
            "expected exactly one gen bump / journal chunk per flush"
        assert _wal_frames(frag) == frames0 + 1, \
            "expected exactly one WAL frame per flush"
        assert s.committer.snapshot()["flushes"] == 2
    finally:
        s.close()


def test_idempotent_reingest_no_wal_growth(srv, rng):
    p = srv.port
    _req(p, "POST", "/index/r", b"{}")
    _req(p, "POST", "/index/r/field/f", b"{}")
    rows = rng.integers(0, 8, 400)
    cols = rng.integers(0, SHARD_WIDTH, 400)
    body = wire.encode_records(rows, cols)
    _req(p, "POST", "/index/r/field/f/ingest", body,
         "application/octet-stream")
    frag = srv.holder.fragment("r", "f", "standard", 0)
    gen0, frames0 = frag.gen, _wal_frames(frag)
    # exact resend (the retry-after-503 story): no change, no WAL frame
    _req(p, "POST", "/index/r/field/f/ingest", body,
         "application/octet-stream")
    assert frag.gen == gen0
    assert _wal_frames(frag) == frames0


# -- backpressure ----------------------------------------------------------


def test_backpressure_503_burst(tmp_path, rng):
    """A stalled flush (failpoint delay) with a tiny high-water mark
    turns sustained ingest into 503 + Retry-After; after the stall
    clears, the idempotent resend succeeds and the data is complete."""
    from pilosa_tpu.utils.faults import FAULTS

    s = _mk_server(tmp_path, ingest_flush_ms=30.0)
    try:
        p = s.port
        _req(p, "POST", "/index/b", b"{}")
        _req(p, "POST", "/index/b/field/f", b"{}")
        s.committer.HIGH_WATER_BYTES = 2048
        FAULTS.arm("ingest.flush", mode="delay", arg=1.5)
        rows = rng.integers(0, 8, 3000)
        cols = rng.integers(0, SHARD_WIDTH, 3000)
        body = wire.encode_records(rows, cols, frame_records=200)
        got_503 = False
        try:
            _req(p, "POST", "/index/b/field/f/ingest", body,
                 "application/octet-stream")
        except urllib.error.HTTPError as e:
            got_503 = e.code == 503
            assert e.headers.get("Retry-After") is not None
            e.read()
        assert got_503, "backlog over high-water never produced a 503"
        FAULTS.disarm("ingest.flush")
        s.committer.HIGH_WATER_BYTES = GroupCommitter.HIGH_WATER_BYTES
        out = _req(p, "POST", "/index/b/field/f/ingest", body,
                   "application/octet-stream")
        assert out["records"] == 3000
        res = _req(p, "POST", "/index/b/query", b"Count(Row(f=3))")
        want = len({int(c) for r, c in zip(rows, cols) if r == 3})
        assert res["results"][0] == want
    finally:
        FAULTS.disarm()
        s.close()


# -- cluster: forwarded-shard ingest ---------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_node_forwarded_ingest(tmp_path, rng):
    from pilosa_tpu.server.server import Config, Server

    ports = [_free_port(), _free_port()]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i in range(2):
        cfg = Config(data_dir=str(tmp_path / f"n{i}"), bind=hosts[i],
                     node_id=f"node{i}", cluster_hosts=hosts,
                     replica_n=1, anti_entropy_interval=0,
                     ingest_flush_ms=20.0)
        s = Server(cfg)
        s.open()
        servers.append(s)
    try:
        p = ports[0]
        _req(p, "POST", "/index/c", b"{}")
        _req(p, "POST", "/index/c/field/f", b"{}")
        n_shards = 6
        rows = rng.integers(0, 16, 4000)
        cols = rng.integers(0, n_shards * SHARD_WIDTH, 4000)
        out = _req(p, "POST", "/index/c/field/f/ingest",
                   wire.encode_records(rows, cols, frame_records=700),
                   "application/octet-stream")
        assert out["forwarded"] > 0, \
            "no shard landed on the remote node (placement fluke?)"
        # every shard's bits live on its OWNER, nowhere else
        for shard in np.unique(cols // SHARD_WIDTH):
            owner = servers[0].cluster.placement.primary("c", int(shard))
            for s in servers:
                frag = s.holder.fragment("c", "f", "standard", int(shard))
                if s.cluster.node_id == owner:
                    assert frag is not None and frag.host_bytes() > 0
                elif frag is not None:
                    assert frag.host_bytes() == 0
        # coordinator-side query agrees with a host oracle
        for row in (3, 7):
            want = len({int(c) for r, c in zip(rows, cols) if r == row})
            res = _req(p, "POST", "/index/c/query",
                       f"Count(Row(f={row}))".encode())
            assert res["results"][0] == want
    finally:
        for s in servers:
            s.close()


# -- CLI client ------------------------------------------------------------


def test_cli_ingest_csv(srv, tmp_path, rng):
    from pilosa_tpu.cli import main

    rows = rng.integers(0, 8, 1500)
    cols = rng.integers(0, SHARD_WIDTH, 1500)
    csv = tmp_path / "in.csv"
    csv.write_text("".join(f"{r},{c}\n" for r, c in zip(rows, cols)))
    assert main(["ingest", "-host", f"localhost:{srv.port}",
                 "-i", "cli", "-f", "f", "--create",
                 "--batch-size", "400", str(csv)]) == 0
    res = _req(srv.port, "POST", "/index/cli/query", b"Count(Row(f=5))")
    want = len({int(c) for r, c in zip(rows, cols) if r == 5})
    assert res["results"][0] == want


# -- roaring octet-stream satellite ----------------------------------------


def test_import_roaring_binary_and_sniff(srv, rng):
    from pilosa_tpu.storage.roaring_io import pack_roaring

    p = srv.port
    _req(p, "POST", "/index/ro", b"{}")
    _req(p, "POST", "/index/ro/field/f", b"{}")
    rows = np.sort(rng.integers(0, 8, 300))
    cols = rng.integers(0, SHARD_WIDTH, 300)
    blob = pack_roaring(rows, cols)
    # raw octet-stream body
    _req(p, "POST", "/index/ro/field/f/import-roaring/0", blob,
         "application/octet-stream")
    want = len({int(c) for r, c in zip(rows, cols) if r == 2})
    res = _req(p, "POST", "/index/ro/query", b"Count(Row(f=2))")
    assert res["results"][0] == want
    # lying JSON Content-Type over raw bytes: sniffed as binary
    _req(p, "POST", "/index/ro/field/f/import-roaring/1", blob,
         "application/json")
    res = _req(p, "POST", "/index/ro/query",
               b"Count(Row(f=2))")
    assert res["results"][0] == 2 * want


# -- kill -9 inside the committer flush ------------------------------------


def _start_worker(data_dir, spec=""):
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "crash_worker.py"),
         str(data_dir), f"localhost:{port}", "100000", spec],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=REPO, env=env)
    line = proc.stdout.readline().decode()
    assert "READY" in line, line
    return proc, port


def test_kill9_in_commit_window_zero_acked_loss(tmp_path, rng):
    """SIGKILL the server inside the committer flush (after the WAL
    appends, before ackers release — the worst window for an acker):
    every ACKED ingest batch must survive the restart byte-for-byte."""
    data_dir = tmp_path / "crash"
    # skip 2 flushes, die on the 3rd flush's ack window
    proc, port = _start_worker(data_dir, "ingest.flush.ack=kill:2")
    acked: list[tuple[np.ndarray, np.ndarray]] = []
    try:
        _req(port, "POST", "/index/k", b"{}")
        _req(port, "POST", "/index/k/field/f", b"{}")
        for i in range(40):
            rows = rng.integers(0, 6, 150)
            cols = rng.integers(0, SHARD_WIDTH, 150)
            body = wire.encode_records(rows, cols)
            try:
                _req(port, "POST", "/index/k/field/f/ingest", body,
                     "application/octet-stream", timeout=20)
            except (urllib.error.URLError, ConnectionError, OSError,
                    http.client.HTTPException):
                break  # the kill landed
            acked.append((rows, cols))
        else:
            pytest.fail("worker never died at the armed kill window")
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)
    assert acked, "no batch was acked before the kill"
    # restart clean and verify every acked bit
    proc, port = _start_worker(data_dir, "")
    try:
        want_rows: dict[int, set] = {}
        for rows, cols in acked:
            for r, c in zip(rows, cols):
                want_rows.setdefault(int(r), set()).add(int(c))
        for row, want_cols in want_rows.items():
            res = _req(port, "POST", "/index/k/query",
                       f"Row(f={row})".encode())
            got = set(res["results"][0]["columns"])
            missing = want_cols - got
            assert not missing, \
                f"row {row}: {len(missing)} acked bits lost"
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
