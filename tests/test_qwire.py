"""Binary internal query wire (parallel/qwire.py, docs/cluster.md
"Internal query wire").

Three layers: codec round-trips (every result shape, packed-vs-raw
segment choice, the endianness tag), frame robustness (the PR 6/PR 9
fuzz pattern — one flipped bit at EVERY byte offset and truncation at
EVERY length must be rejected, never mis-merged, on request AND response
streams), and cluster negotiation (binary steady-state with counters, a
mixed-version fan-out where a JSON-pinned peer triggers the 415
downgrade path with byte-identical merged answers, and the
internal-wire=json knob restoring the JSON envelope)."""

import json
import socket
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH, SHARD_WORDS
from pilosa_tpu.executor.results import (
    FieldRow, GroupCount, Pair, RowIdentifiers, RowResult, ValCount,
)
from pilosa_tpu.parallel import qwire
from pilosa_tpu.parallel.cluster import result_to_wire
from pilosa_tpu.server.server import Config, Server


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _seg(rng, nwords=30):
    s = np.zeros(SHARD_WORDS, dtype=np.uint32)
    idx = rng.choice(SHARD_WORDS, nwords, replace=False)
    s[idx] = rng.integers(1, 2**32, nwords, dtype=np.uint64).astype(
        np.uint32)
    return s


# -- codec round-trips -------------------------------------------------------


def test_roundtrip_every_result_shape(rng):
    """Every result shape survives encode->decode with the same meaning
    as the JSON wire (compared through result_to_wire, the codec the
    coordinator's reduce actually consumes)."""
    results = [
        RowResult({0: _seg(rng), 5: _seg(rng, 400)}, attrs={"a": 1}),
        RowResult({}),
        ValCount(42, 7),
        ValCount(2.5, 3),          # float val (Avg-style)
        ValCount(None, 0),         # absent val
        RowIdentifiers(rows=[1, 5, 9]),
        RowIdentifiers(rows=[], keys=["x", "y"]),
        [Pair(1, 10), Pair(2, 5)],
        [Pair(7, 9, "k1"), Pair(8, 4, "k2")],
        [],                        # empty pairs list
        [GroupCount([FieldRow("f", 1)], 3)],  # rides the JSON record
        123,                       # raw value
        None,
    ]
    trailer = {"execS": 0.01, "gens": [["f", 3]], "quarantined": 1,
               "load": {"inFlight": 0, "queued": 0}, "spans": []}
    body, nframes = qwire.encode_response(results, trailer)
    got, got_trailer, got_n = qwire.decode_response(body)
    assert got_trailer == trailer
    assert got_n == nframes == len(results) + 1
    assert len(got) == len(results)
    for want, have in zip(results, got):
        assert result_to_wire(want) == result_to_wire(have)


def test_request_roundtrip():
    calls = [{"name": "Row", "args": {"f": 3}},
             {"name": "Count", "children": [{"name": "Row"}]}]
    body = qwire.encode_request(calls, [0, 3, 1 << 40])
    got_calls, got_shards, nframes = qwire.decode_request(body)
    assert got_calls == calls
    assert got_shards == [0, 3, 1 << 40]
    assert nframes == 2
    # unpinned (None) shard list survives too
    _, shards, _ = qwire.decode_request(qwire.encode_request([], None))
    assert shards is None


def test_segment_encoding_choice(rng):
    """Sparse and run-structured segments travel roaring-packed (bytes
    scale with cardinality); dense-random segments fall back to raw
    words — whichever is smaller, decode always exact."""
    sparse = _seg(rng, 20)
    enc, blob = qwire.encode_segment(sparse)
    assert enc == qwire.SEG_PACKED
    assert len(blob) < SHARD_WORDS * 4 // 50
    assert np.array_equal(qwire.decode_segment(enc, blob), sparse)

    run = np.zeros(SHARD_WORDS, dtype=np.uint32)
    run[100:6000] = 0xFFFFFFFF   # Store'd-row shape: long runs
    enc, blob = qwire.encode_segment(run)
    assert enc == qwire.SEG_PACKED
    assert len(blob) < 1024
    assert np.array_equal(qwire.decode_segment(enc, blob), run)

    dense = rng.integers(0, 2**32, SHARD_WORDS, dtype=np.uint64).astype(
        np.uint32)
    enc, blob = qwire.encode_segment(dense)
    assert enc == qwire.SEG_RAW
    assert len(blob) == SHARD_WORDS * 4
    assert np.array_equal(qwire.decode_segment(enc, blob), dense)


def test_endianness_tag_rejected(rng):
    """A packed-array record whose endian tag is not little-endian is
    rejected loudly (a future big-endian or u64-word peer must never
    silently mis-merge) — CRC recomputed so ONLY the tag check fires."""
    body, _ = qwire.encode_response([RowResult({0: _seg(rng)})], {})
    frames = list(qwire.iter_frames(body))
    payload = bytearray(bytes(frames[0]))
    assert payload[0] == qwire.REC_ROW and payload[1] == qwire.ENDIAN_LE
    payload[1] = 1  # not ENDIAN_LE
    rebuilt = qwire.MAGIC + qwire.encode_frame(bytes(payload)) \
        + qwire.encode_frame(bytes(frames[1]))
    with pytest.raises(qwire.FrameError, match="little-endian"):
        qwire.decode_response(rebuilt)


# -- frame robustness (the PR 6/PR 9 fuzz pattern) ---------------------------


def _decoded_request(data):
    calls, shards, _ = qwire.decode_request(data)
    return calls, shards


def test_request_every_byte_corruption_rejected(rng):
    """Flip one bit at EVERY byte offset of a request stream and
    truncate at EVERY length: decode must reject (magic, bounds, CRC,
    record checks) — never yield a DIFFERENT call batch silently."""
    body = qwire.encode_request(
        [{"name": "Row", "args": {"f": int(rng.integers(0, 50))}}],
        [0, 2, 5])
    want = _decoded_request(body)
    for off in range(len(body)):
        bad = bytearray(body)
        bad[off] ^= 0x10
        try:
            got = _decoded_request(bytes(bad))
        except qwire.FrameError:
            continue
        assert got != want, f"corruption at byte {off} went undetected"
    for cut in range(len(body)):
        try:
            got = _decoded_request(body[:cut])
        except qwire.FrameError:
            continue
        assert got != want, f"truncation to {cut} bytes went undetected"


def _decoded_response(data):
    results, trailer, _ = qwire.decode_response(data)
    return [result_to_wire(r) for r in results], trailer


def test_response_every_byte_corruption_rejected(rng):
    """Same walk over a response stream carrying a packed row, a
    valcount, and the trailer.  The REQUIRED trailer frame doubles as
    the end-of-stream marker, so truncation at a frame boundary (which
    leaves every remaining frame CRC-clean) is still detected."""
    body, _ = qwire.encode_response(
        [RowResult({0: _seg(rng, 8)}), ValCount(9, 2)],
        {"execS": 0.5, "load": {"inFlight": 1, "queued": 0}})
    want = _decoded_response(body)
    for off in range(len(body)):
        bad = bytearray(body)
        bad[off] ^= 0x10
        try:
            got = _decoded_response(bytes(bad))
        except qwire.FrameError:
            continue
        assert got != want, f"corruption at byte {off} went undetected"
    for cut in range(len(body)):
        try:
            got = _decoded_response(body[:cut])
        except qwire.FrameError:
            continue
        assert got != want, f"truncation to {cut} bytes went undetected"


def test_frame_ceiling_and_junk():
    with pytest.raises(qwire.FrameError, match="magic"):
        list(qwire.iter_frames(b"NOTMAGIC" + b"\x00" * 16))
    with pytest.raises(qwire.FrameError):
        list(qwire.iter_frames(b"PT"))
    # a corrupted length field far over the ceiling is bounds-rejected
    # before any allocation
    huge = qwire.MAGIC + qwire.FRAME.pack(qwire.MAX_FRAME_BYTES + 1, 0)
    with pytest.raises(qwire.FrameError, match="outside"):
        list(qwire.iter_frames(huge))
    # a response with no trailer frame (e.g. severed mid-stream at a
    # clean frame boundary) is truncation, not success
    naked = qwire.MAGIC + qwire.encode_frame(
        qwire.encode_result(ValCount(1, 1)))
    with pytest.raises(qwire.FrameError, match="trailer"):
        qwire.decode_response(naked)


# -- cluster negotiation (in-process 2-node harness) -------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _req(port, method, path, data=None):
    body = None
    if data is not None:
        body = data.encode() if isinstance(data, str) \
            else json.dumps(data).encode()
    r = urllib.request.Request(
        f"http://localhost:{port}{path}", method=method, data=body)
    with urllib.request.urlopen(r, timeout=180) as resp:
        return json.loads(resp.read())


def _mk_cluster(tmp_path, wires):
    """One server per entry of ``wires`` (each "bin1" or "json")."""
    ports = _free_ports(len(wires))
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, (p, w) in enumerate(zip(ports, wires)):
        cfg = Config(
            data_dir=str(tmp_path / f"node{i}-{w}"),
            bind=f"localhost:{p}",
            node_id=f"node{i}",
            cluster_hosts=hosts,
            replica_n=1,
            anti_entropy_interval=0,
            internal_wire=w,
        )
        srv = Server(cfg)
        srv.open()
        servers.append(srv)
    return servers


def _seed_and_query(servers, index="q0"):
    """Write rows spanning several shards from the coordinator, then
    return the full public JSON response of a fan-out read (the
    byte-identity unit: merged rows, counts, TopN).

    Index name matters: placement jump-hashes (index, shard), and "q0"
    puts shards {0,1} on node1 and {2,3} on node0 of a 2-node ring — so
    a query from node0 ALWAYS fans out remotely (the wire under test
    actually carries traffic).  "qi", say, lands all 4 shards on node0
    and the counters never move."""
    p = servers[0].port
    _req(p, "POST", f"/index/{index}", {})
    _req(p, "POST", f"/index/{index}/field/f", {})
    pql = "".join(
        f"Set({c}, f={r})"
        for r in range(3)
        for c in range(r, 4 * SHARD_WIDTH, SHARD_WIDTH // 2 + 7 * (r + 1)))
    _req(p, "POST", f"/index/{index}/query", pql)
    return _req(p, "POST", f"/index/{index}/query",
                "Row(f=0)Count(Union(Row(f=0), Row(f=1)))"
                "TopN(f, n=3)Count(Intersect(Row(f=1), Row(f=2)))")


def _close_all(servers):
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


def test_binary_steady_state_counters_and_mode(tmp_path):
    """Both nodes bin1: fan-out rides the binary wire (frames counted,
    bytes counted both directions), /status advertises the capability,
    and /debug/vars shows the per-peer wire mode."""
    servers = _mk_cluster(tmp_path, ["bin1", "bin1"])
    try:
        out = _seed_and_query(servers)
        assert out["results"]
        st = _req(servers[0].port, "GET", "/status")
        assert st["wire"] == ["json", "bin1"]
        stats = servers[0].stats
        assert stats.count_value("cluster.wire_frames") > 0
        assert stats.count_value("cluster.wire_bytes_tx") > 0
        assert stats.count_value("cluster.wire_bytes_rx") > 0
        assert stats.count_value("cluster.wire_fallback") == 0
        dv = _req(servers[0].port, "GET", "/debug/vars")
        peers = dv["cluster"]["routing"]["peers"]
        assert {p["wire"] for p in peers.values()} == {"bin1"}
    finally:
        _close_all(servers)


def test_json_knob_restores_json_wire(tmp_path):
    """internal-wire=json on every node: no binary frames ever, the
    capability list omits bin1, and queries serve exactly as before."""
    servers = _mk_cluster(tmp_path, ["json", "json"])
    try:
        out = _seed_and_query(servers)
        assert out["results"]
        st = _req(servers[0].port, "GET", "/status")
        assert st["wire"] == ["json"]
        stats = servers[0].stats
        assert stats.count_value("cluster.wire_frames") == 0
        assert stats.count_value("cluster.wire_fallback") == 0
        # bytes are still counted on the JSON wire so bin1-vs-json
        # compare from the same counters
        assert stats.count_value("cluster.wire_bytes_tx") > 0
    finally:
        _close_all(servers)


def test_mixed_version_downgrade_byte_identical(tmp_path):
    """A bin1 coordinator fanning out to a JSON-pinned peer: the first
    binary POST is refused 415, the peer is latched to JSON (counted +
    journaled), the SAME request retries as JSON — and the merged public
    answer is byte-identical to an all-JSON cluster's."""
    servers = _mk_cluster(tmp_path, ["bin1", "json"])
    try:
        out = _seed_and_query(servers)
        stats = servers[0].stats
        assert stats.count_value("cluster.wire_fallback") >= 1
        ev = _req(servers[0].port, "GET", "/debug/events")
        kinds = [e["event"] for e in ev["events"]]
        assert "wire.downgrade" in kinds
        # latched: the peer's effective wire mode is now json
        dv = _req(servers[0].port, "GET", "/debug/vars")
        peers = dv["cluster"]["routing"]["peers"]
        assert "json" in {p["wire"] for p in peers.values()}
        # the downgrade costs ONE retry, then stays on JSON
        fallbacks = stats.count_value("cluster.wire_fallback")
        again = _req(servers[0].port, "POST", "/index/q0/query",
                     "Row(f=0)Count(Union(Row(f=0), Row(f=1)))"
                     "TopN(f, n=3)Count(Intersect(Row(f=1), Row(f=2)))")
        assert stats.count_value("cluster.wire_fallback") == fallbacks
        assert again == out
    finally:
        _close_all(servers)

    ref = _mk_cluster(tmp_path, ["json", "json"])
    try:
        want = _seed_and_query(ref)
    finally:
        _close_all(ref)
    assert json.dumps(out, sort_keys=True) == json.dumps(
        want, sort_keys=True)


def test_probe_folds_capability_and_recovers(tmp_path):
    """The /status probe fold clears a peer's JSON latch once it
    advertises bin1 again (rolling-upgrade recovery), and folds a
    json-only advertisement into the pre-dispatch choice."""
    servers = _mk_cluster(tmp_path, ["bin1", "bin1"])
    try:
        cl = servers[0].cluster
        host1 = cl.nodes[1].host
        # simulate an earlier refusal
        cl.client._wire_downgrade(host1, 415)
        assert cl.client.peer_wire_mode(host1) == "json"
        cl.probe_peers()  # peer advertises bin1 -> latch cleared
        assert cl.client.peer_wire_mode(host1) == "bin1"
        # a peer advertising json-only is never even attempted on binary
        cl.client.note_peer_wire(host1, ["json"])
        assert cl.client.peer_wire_mode(host1) == "json"
    finally:
        _close_all(servers)


def test_internal_wire_config_plumbing(tmp_path, monkeypatch):
    """Knob rides Config/env/TOML; an invalid value fails loudly."""
    assert Config().internal_wire == "bin1"
    monkeypatch.setenv("PILOSA_TPU_INTERNAL_WIRE", "json")
    assert Config.from_env().internal_wire == "json"
    toml = tmp_path / "c.toml"
    toml.write_text('internal-wire = "json"\n')
    assert Config.from_toml(str(toml)).internal_wire == "json"
    from pilosa_tpu.parallel.cluster import Cluster, ClusterError
    with pytest.raises(ClusterError, match="internal_wire"):
        Cluster("node0", ["localhost:1"], internal_wire="bin2")
