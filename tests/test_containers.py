"""Compressed-resident fragments (ops/containers.py): codec round-trip
for every container type at its boundary cardinalities, the device decode
against the host oracle, the density heuristic's dense fallback, and the
DIFFERENTIAL guarantee — a randomized query corpus executed with
compressed residency (including under eviction pressure) must return
results byte-identical to the dense-resident run.  A decode bug would
corrupt query results silently; the differential catches it as a
divergence."""

import numpy as np
import pytest

from pilosa_tpu.core import CONTAINER_WORDS, SHARD_WIDTH, SHARD_WORDS
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops import containers
from pilosa_tpu.ops.containers import (
    ARRAY_WORDS_MAX, RUN_MAX, TYPE_ARRAY, TYPE_BITMAP, TYPE_RUN,
    pack_words, pad_packed, pow2_bucket, unpack_packed, upload_decode,
)
from pilosa_tpu.storage import FieldOptions, Holder, fragment
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.membudget import DEFAULT_BUDGET, DeviceBudget

from test_differential import _norm, gen_query


def _store(dense_flat):
    """Sparse word store (sorted flat idx + values) of a flat dense
    uint32 array — the Fragment._idx/_val form pack_words takes."""
    idx = np.nonzero(dense_flat)[0].astype(np.int64)
    return idx, dense_flat[idx]


def _oracle(idx, val, rows):
    out = np.zeros(rows * SHARD_WORDS, dtype=np.uint32)
    out[idx] = val
    return out.reshape(rows, SHARD_WORDS)


def _roundtrip(idx, val, rows):
    """pack -> host unpack AND pack -> device decode, both against the
    dense oracle."""
    p = pack_words(idx, val)
    want = _oracle(idx, val, rows)
    np.testing.assert_array_equal(unpack_packed(p, rows), want)
    got = np.asarray(upload_decode(p, rows))
    np.testing.assert_array_equal(got, want)
    return p


# -- codec round-trip at boundary cardinalities -----------------------------

def test_empty_roundtrip():
    p = _roundtrip(np.zeros(0, np.int64), np.zeros(0, np.uint32), 2)
    assert p.keys.size == 0 and p.nbytes == 0


def test_array_bitmap_threshold():
    """Exactly ARRAY_WORDS_MAX scattered words stay an array container;
    one more flips to bitmap (every-other-word spacing defeats the run
    form on both sides of the boundary)."""
    rows = 1
    for n, want_type in ((ARRAY_WORDS_MAX, TYPE_ARRAY),
                         (ARRAY_WORDS_MAX + 1, TYPE_BITMAP)):
        flat = np.zeros(rows * SHARD_WORDS, dtype=np.uint32)
        flat[np.arange(n) * 2] = 7
        idx, val = _store(flat)
        p = _roundtrip(idx, val, rows)
        assert int(p.types[0]) == want_type, n


def test_full_container_run():
    """A fully-set container is one run — the maximal-run boundary —
    and a full shard row packs to runs, not bitmaps."""
    rows = 1
    flat = np.zeros(rows * SHARD_WORDS, dtype=np.uint32)
    flat[:CONTAINER_WORDS] = 0xFFFFFFFF
    p = _roundtrip(*_store(flat), rows)
    assert int(p.types[0]) == TYPE_RUN and int(p.counts[0]) == 1
    flat[:] = 0xFFFFFFFF  # full row: every container one run
    p = _roundtrip(*_store(flat), rows)
    assert set(p.types.tolist()) == {TYPE_RUN}
    assert p.nbytes < rows * SHARD_WORDS * 4 // 100  # >100x on full rows


def test_run_max_boundary():
    """RUN_MAX two-word bit-runs keep the run form (2 payload words per
    run undercut the array's 2 per word); past RUN_MAX the container
    falls back (here: array — the words stay sparse)."""
    rows = 1
    for n_runs, want_type in ((RUN_MAX, TYPE_RUN),
                              (RUN_MAX + 1, TYPE_ARRAY)):
        flat = np.zeros(rows * SHARD_WORDS, dtype=np.uint32)
        # two full words per run, a zero word between runs
        starts = np.arange(n_runs) * 3
        flat[starts] = 0xFFFFFFFF
        flat[starts + 1] = 0xFFFFFFFF
        idx, val = _store(flat)
        p = _roundtrip(idx, val, rows)
        assert int(p.types[0]) == want_type, n_runs
        if want_type == TYPE_RUN:
            assert int(p.counts[0]) == n_runs


def test_mixed_forms_roundtrip(rng):
    """One fragment mixing all three forms + empty containers between."""
    rows = 4
    flat = np.zeros(rows * SHARD_WORDS, dtype=np.uint32)
    flat[rng.choice(CONTAINER_WORDS, 40, replace=False)] = \
        rng.integers(1, 1 << 32, size=40, dtype=np.uint32)   # array
    flat[2 * CONTAINER_WORDS: 3 * CONTAINER_WORDS] = \
        rng.integers(1, 1 << 32, size=CONTAINER_WORDS,
                     dtype=np.uint32)                         # bitmap
    flat[5 * CONTAINER_WORDS: 6 * CONTAINER_WORDS] = 0xFFFFFFFF  # run
    # partial-word run straddling a container boundary
    s = 9 * CONTAINER_WORDS * 32 + 13
    for b in range(s, s + 200):
        flat[b // 32] |= np.uint32(1) << (b % 32)
    p = _roundtrip(*_store(flat), rows)
    h = p.type_histogram()
    assert h["array"] >= 1 and h["bitmap"] >= 1 and h["run"] >= 1


def test_random_stores_roundtrip(rng):
    """Randomized corpora: sparse scatter, clustered ranges, and dense
    blocks, each packed and decoded back to the oracle."""
    rows = 3
    total = rows * SHARD_WORDS
    for _ in range(5):
        flat = np.zeros(total, dtype=np.uint32)
        n = int(rng.integers(0, 3000))
        flat[rng.choice(total, n, replace=False)] = rng.integers(
            1, 1 << 32, size=n, dtype=np.uint32)
        a = int(rng.integers(0, total - 500))
        flat[a: a + int(rng.integers(0, 500))] = 0xFFFFFFFF
        _roundtrip(*_store(flat), rows)


def test_estimate_upper_bounds_packed(rng):
    """estimate_packed_bytes (the no-pack heuristic input) never
    undercounts the real packed stream."""
    rows = 2
    total = rows * SHARD_WORDS
    for n in (0, 1, 100, 5000, 40000):
        flat = np.zeros(total, dtype=np.uint32)
        flat[rng.choice(total, n, replace=False)] = 1
        idx, val = _store(flat)
        assert containers.estimate_packed_bytes(idx) >= \
            pack_words(idx, val).nbytes


def test_decode_bucket_padding(rng):
    """pad_packed's pow2-bucket padding (key/type -1 rows, zero payload
    tail) decodes identically to the exact stream."""
    rows = 2
    flat = np.zeros(rows * SHARD_WORDS, dtype=np.uint32)
    flat[rng.choice(3 * CONTAINER_WORDS, 90, replace=False)] = 5
    idx, val = _store(flat)
    p = pack_words(idx, val)
    import jax.numpy as jnp
    padded = [jnp.asarray(a) for a in pad_packed(p)]
    assert padded[0].size == pow2_bucket(p.keys.size)
    got = np.asarray(containers.decode_block(
        *padded, rows=rows, a_bucket=pow2_bucket(p.a_max),
        r_bucket=pow2_bucket(p.r_max)))
    np.testing.assert_array_equal(got, _oracle(idx, val, rows))


# -- density heuristic / fragment forms -------------------------------------

def test_device_form_heuristic():
    budget = DeviceBudget(limit_bytes=64 << 20)
    f = Fragment(None, "i", "f", "standard", 0, budget=budget)
    f.bulk_import(np.arange(8), np.arange(8) * 1000)
    assert f.device_form() == "compressed"
    assert f.device_nbytes() == f.packed_host().nbytes
    assert f.device_nbytes() < f._cap_rows * SHARD_WORDS * 4
    # unlimited budget: dense mirror is strictly faster -> dense
    budget.limit_bytes = None
    assert f.device_form() == "dense"
    budget.limit_bytes = 64 << 20
    # kill switch
    old = fragment.COMPRESSED_RESIDENT
    try:
        fragment.COMPRESSED_RESIDENT = False
        assert f.device_form() == "dense"
    finally:
        fragment.COMPRESSED_RESIDENT = old


def test_dense_data_stays_dense(rng):
    """A fragment dense enough that packing wins nothing must fall back
    to the dense form (all-bitmap streams are ~1x 'compression'): every
    cap row filled with random words — no zero words to drop, no runs."""
    budget = DeviceBudget(limit_bytes=64 << 20)
    f = Fragment(None, "i", "f", "standard", 0, budget=budget)
    f.set_bit(0, 0)
    for row in range(f._cap_rows):
        f.set_row(row, rng.integers(1, 1 << 32, size=SHARD_WORDS,
                                    dtype=np.uint32))
    assert f.device_form() == "dense"
    assert f.device_sig() == (f.n_rows, SHARD_WORDS)


def test_compressed_device_mirror_equals_dense():
    """Fragment.device()'s compressed upload path (ship packed, decode
    on device) produces the same mirror bytes as the dense upload."""
    budget = DeviceBudget(limit_bytes=64 << 20)
    f = Fragment(None, "i", "f", "standard", 0, budget=budget)
    rng = np.random.default_rng(7)
    f.bulk_import(rng.integers(0, 6, 4000), rng.integers(0, SHARD_WIDTH, 4000))
    assert f.device_form() == "compressed"
    got = np.asarray(f.device())
    np.testing.assert_array_equal(got, f.to_dense())


# -- differential: compressed-resident vs dense-resident --------------------

@pytest.fixture(scope="module")
def corpus():
    """16-shard index mixing sparse scatter (a, b), run-heavy clustered
    ranges (a row 11), BSI values (v), an emptied fragment (b row 5 set
    then cleared in shard 3), and existence — wide enough that the
    8-virtual-device mesh slices it under a tight budget."""
    rng = np.random.default_rng(99)
    h = Holder(None)
    idx = h.create_index("c")
    a = idx.create_field("a")
    b = idx.create_field("b")
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    n = 40_000
    cols = rng.integers(0, 16 * SHARD_WIDTH, size=n)
    a.import_bits(rng.integers(0, 10, size=n), cols)
    b.import_bits(rng.integers(0, 6, size=n), cols)
    # run-heavy: clustered contiguous ranges across every shard
    run_cols = np.concatenate([
        np.arange(s * SHARD_WIDTH + 1000, s * SHARD_WIDTH + 40_000)
        for s in range(16)])
    a.import_bits(np.full(run_cols.size, 11), run_cols)
    vcols = np.unique(cols[: n // 2])
    v.import_values(vcols, rng.integers(-500, 500, size=vcols.size))
    idx.add_existence(np.unique(np.concatenate([cols, run_cols])))
    # emptied fragment: set bits then clear them (empty packed stream)
    ecols = np.arange(3 * SHARD_WIDTH + 50, 3 * SHARD_WIDTH + 80)
    b.import_bits(np.full(30, 5), ecols)
    b.import_bits(np.full(30, 5), ecols, clear=True)
    return h


def _run_corpus(ex, queries):
    return [_norm(r) for q in queries for r in ex.execute("c", q)]


def test_compressed_differential(corpus):
    """The randomized corpus (plus run-heavy TopN and the emptied
    fragment's row) is byte-identical across dense-resident, compressed-
    resident, and compressed-under-eviction-pressure runs."""
    qrng = np.random.default_rng(1234)
    queries = [gen_query(qrng) for _ in range(4)]
    queries += ["TopN(a, n=3)", "Count(Row(a=11))", "Row(b=5)",
                "Count(Intersect(Row(a=11), Row(b=2)))"]
    ex = Executor(corpus, use_mesh=True)
    old = DEFAULT_BUDGET.limit_bytes
    try:
        # reference: dense-resident (compression never engages with no
        # budget limit)
        DEFAULT_BUDGET.limit_bytes = None
        want = _run_corpus(ex, queries)

        # compressed-resident, ample budget: everything stays resident
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        assert _run_corpus(ex, queries) == want
        st = DEFAULT_BUDGET.stats()
        assert st["compressedBytes"] > 0, \
            "no packed stream ever registered: the differential " \
            "exercised only the dense path"
        assert st["compressedBytes"] < 16 * 16 * SHARD_WORDS * 4

        # tight budget: eviction + re-staging of packed stacks
        DEFAULT_BUDGET.limit_bytes = 1 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        ev0 = DEFAULT_BUDGET.evictions
        assert _run_corpus(ex, queries) == want
        assert DEFAULT_BUDGET.evictions > ev0, \
            "budget never evicted: pressure leg exercised nothing"
        assert DEFAULT_BUDGET.stats()["pinnedBytes"] == 0
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()


def test_retrace_keeps_layout(corpus):
    """Regression: re-tracing a cached executable at a new stacked group
    size must keep the layout it was compiled with.  Mixed-bucket
    fragments (some with run containers, some without) queried at
    growing then shrinking subset sizes force re-traces; a re-trace that
    read another group's layout decodes with the wrong container buckets
    (r_bucket=0 silently drops every run container — the a=11 run rows
    here)."""
    ex = Executor(corpus, use_mesh=True)
    old = DEFAULT_BUDGET.limit_bytes
    q = "Count(Intersect(Row(a=11), Row(a=2)))"
    try:
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        want = {}
        for size in (16, 2, 9, 16, 1):
            sl = list(range(size))
            got = ex.execute("c", q, shards=sl)[0]
            if size in want:
                assert got == want[size], \
                    f"subset {size} diverged after re-trace"
            want[size] = got
        # the full-size answer must match the sum of disjoint halves
        lo = ex.execute("c", q, shards=list(range(8)))[0]
        hi = ex.execute("c", q, shards=list(range(8, 16)))[0]
        assert want[16] == lo + hi
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()


def test_compressed_stats_surface(corpus):
    """Holder.container_stats counts forms without packing on demand,
    and sees all three container types on the mixed corpus once packs
    exist."""
    st0 = Holder(None).container_stats()
    assert st0 == {"array": 0, "bitmap": 0, "run": 0,
                   "compressedFragments": 0, "denseFragments": 0}
    ex = Executor(corpus, use_mesh=True)
    old = DEFAULT_BUDGET.limit_bytes
    try:
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        ex.execute("c", "Count(Union(Row(a=1), Row(a=11)))")
        st = corpus.container_stats()
        assert st["compressedFragments"] > 0
        assert st["array"] > 0 and st["run"] > 0
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()
