"""Randomized differential testing: the mesh (shard_map) executor must
agree with the per-shard executor on a generated query workload — the
in-repo analog of the reference's query generator + race-detector strategy
(internal/test/querygenerator.go:29-200, SURVEY §5.2: functional purity +
golden-model equivalence replaces Go's race detector).

Queries are generated from a seeded grammar over bitmap algebra, BSI
conditions, aggregations, TopN, Rows, and GroupBy; every one executes on
both engines and the results must match exactly.
"""

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.storage import FieldOptions, Holder

# Enough to cover the grammar's shape space while keeping the suite fast
# (each novel plan shape costs an XLA compile on the CPU test mesh).
N_QUERIES = 60


@pytest.fixture(scope="module")
def engines():
    rng = np.random.default_rng(77)
    h = Holder(None)
    idx = h.create_index("d")
    a = idx.create_field("a")
    b = idx.create_field("b")
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    n = 6000
    cols = rng.integers(0, 5 * SHARD_WIDTH, size=n)
    a.import_bits(rng.integers(0, 10, size=n), cols)
    b.import_bits(rng.integers(0, 6, size=n), cols)
    vcols = np.unique(cols[: n // 2])
    v.import_values(vcols, rng.integers(-500, 500, size=vcols.size))
    idx.add_existence(cols)
    return Executor(h), Executor(h, use_mesh=True)


def gen_bitmap(rng, depth=0):
    choice = rng.integers(0, 8 if depth < 2 else 4)
    if choice == 0:
        return f"Row(a={rng.integers(0, 12)})"   # sometimes empty rows
    if choice == 1:
        return f"Row(b={rng.integers(0, 8)})"
    if choice == 2:
        op = rng.choice([">", "<", ">=", "<=", "==", "!="])
        return f"Row(v {op} {rng.integers(-600, 600)})"
    if choice == 3:
        lo = int(rng.integers(-550, 400))
        return f"Row({lo} < v < {lo + int(rng.integers(1, 400))})"
    kids = ", ".join(gen_bitmap(rng, depth + 1)
                     for _ in range(rng.integers(2, 4)))
    if choice == 4:
        return f"Intersect({kids})"
    if choice == 5:
        return f"Union({kids})"
    if choice == 6:
        return f"Difference({kids})"
    return f"Not({gen_bitmap(rng, depth + 1)})"


def gen_query(rng):
    kind = rng.integers(0, 8)
    bm = gen_bitmap(rng)
    if kind == 0:
        return bm
    if kind == 1:
        return f"Count({bm})"
    if kind == 2:
        return f"Sum({bm}, field=v)"
    if kind in (3, 4):
        which = "Min" if kind == 3 else "Max"
        return f"{which}({bm}, field=v)"
    if kind == 5:
        return f"TopN(a, {bm}, n={rng.integers(0, 6)})"
    if kind == 6:
        return f"Rows(a, limit={rng.integers(1, 12)})"
    return "GroupBy(Rows(b), Rows(a), " + bm + ")"


def _norm(r):
    if hasattr(r, "columns"):
        return ("row", tuple(int(c) for c in r.columns()))
    if isinstance(r, list):
        return tuple(_norm(x) for x in r)
    return r


def test_mesh_matches_pershard_on_generated_workload(engines):
    plain, meshy = engines
    rng = np.random.default_rng(1234)
    queries = [gen_query(rng) for _ in range(N_QUERIES)]
    # batch some multi-call requests too (the grouped dispatch path)
    i = 0
    while i < len(queries):
        take = int(rng.integers(1, 5))
        batch = " ".join(queries[i: i + take])
        i += take
        got_a = plain.execute("d", batch)
        got_b = meshy.execute("d", batch)
        assert len(got_a) == len(got_b)
        for ra, rb in zip(got_a, got_b):
            assert _norm(ra) == _norm(rb), (batch, ra, rb)
