"""Fused Pallas container kernels (ops/kernels.py): per-container-form
kernel goldens against the ``unpack_packed`` host oracle, the fused
decode+op+popcount kernel, backend resolution (the ``container-kernels``
knob and its kill switch), the device_sig kernel-backend axis (a flip
must rebuild stacks, not retrace — the PR 7 retrace class), and the
3-LEG DIFFERENTIAL: a mixed-forms corpus executed dense-resident,
compressed-jnp, and compressed-pallas-interpret must return
byte-identical results with zero retrace alarms.  Everything runs
through the Pallas INTERPRETER on the CPU tier-1 platform — the same
kernel logic a TPU compiles."""

import numpy as np
import pytest

from pilosa_tpu.core import CONTAINER_WORDS, SHARD_WIDTH, SHARD_WORDS
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops import containers, kernels
from pilosa_tpu.ops.containers import (
    ARRAY_WORDS_MAX, RUN_MAX, pack_words, pad_packed, pow2_bucket,
    unpack_packed, upload_decode,
)
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.membudget import DEFAULT_BUDGET, DeviceBudget
from pilosa_tpu.utils import devobs

from test_differential import _norm, gen_query


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def force_backend():
    """Set the container-kernels knob for one test, restoring after —
    the per-test analog of the server config apply."""
    old = kernels.CONTAINER_KERNELS

    def _set(mode):
        kernels.CONTAINER_KERNELS = mode

    yield _set
    kernels.CONTAINER_KERNELS = old


def _kernel_golden(idx, val, rows):
    """Pallas decode (interpret mode on CPU) of a packed stream vs the
    numpy host oracle; returns the Packed stream for form assertions."""
    import jax.numpy as jnp
    p = pack_words(idx, val)
    arrs = [jnp.asarray(a) for a in pad_packed(p)]
    got = np.asarray(kernels.decode_block(
        *arrs, rows=rows, a_bucket=pow2_bucket(p.a_max),
        r_bucket=pow2_bucket(p.r_max)))
    np.testing.assert_array_equal(got, unpack_packed(p, rows))
    return p


def _popcounts(dense):
    return np.unpackbits(
        np.ascontiguousarray(dense).view(np.uint8), axis=1).sum(
            axis=1).astype(np.int32)


# -- per-container-form kernel goldens vs the host oracle -------------------

def test_kernel_array_boundary(rng):
    """Array containers right at the array<->bitmap threshold on both
    sides decode exactly."""
    for n in (1, ARRAY_WORDS_MAX - 1, ARRAY_WORDS_MAX):
        slots = np.sort(rng.choice(CONTAINER_WORDS, n, replace=False))
        idx = (3 * CONTAINER_WORDS + slots).astype(np.int64)
        val = rng.integers(1, 1 << 32, n, dtype=np.uint64) \
            .astype(np.uint32)
        p = _kernel_golden(idx, val, rows=2)
        assert p.type_histogram()["array"] >= 1


def test_kernel_bitmap(rng):
    """A over-threshold container packs as bitmap and decodes by the
    kernel's contiguous VMEM copy."""
    n = ARRAY_WORDS_MAX + 1
    slots = np.sort(rng.choice(CONTAINER_WORDS, n, replace=False))
    idx = slots.astype(np.int64)
    val = rng.integers(1, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    p = _kernel_golden(idx, val, rows=1)
    assert p.type_histogram()["bitmap"] == 1


def test_kernel_run_boundary():
    """Run containers at RUN_MAX runs (and the single full-container
    run) decode via the kernel's range masks exactly."""
    # RUN_MAX disjoint 3-word runs of all-ones words (long enough that
    # 2 payload words per run beats the array form's 2 per word)
    idx = (np.arange(RUN_MAX)[:, None] * 4
           + np.arange(3)[None, :]).reshape(-1).astype(np.int64)
    val = np.full(idx.size, 0xFFFFFFFF, dtype=np.uint32)
    p = _kernel_golden(idx, val, rows=1)
    assert p.type_histogram()["run"] == 1
    # one full container of ones -> a single run
    idx2 = np.arange(CONTAINER_WORDS, dtype=np.int64) + CONTAINER_WORDS
    val2 = np.full(CONTAINER_WORDS, 0xFFFFFFFF, dtype=np.uint32)
    p2 = _kernel_golden(idx2, val2, rows=1)
    assert p2.type_histogram()["run"] == 1
    assert int(p2.counts[p2.types == containers.TYPE_RUN][0]) == 1


def test_kernel_empty_and_mixed(rng):
    """Empty stream (falls back to jnp zeros) and a mixed-form fragment
    spanning several rows."""
    _kernel_golden(np.zeros(0, np.int64), np.zeros(0, np.uint32), rows=2)
    rows = 4
    parts_i, parts_v = [], []
    # sparse scatter (arrays) across all rows
    i0 = np.sort(rng.choice(rows * SHARD_WORDS, 400, replace=False))
    parts_i.append(i0.astype(np.int64))
    parts_v.append(rng.integers(1, 1 << 32, 400, dtype=np.uint64)
                   .astype(np.uint32))
    # a dense container (bitmap) in row 1
    i1 = SHARD_WORDS + 7 * CONTAINER_WORDS + np.arange(CONTAINER_WORDS)
    parts_i.append(i1.astype(np.int64))
    parts_v.append(rng.integers(1, 1 << 32, CONTAINER_WORDS,
                                dtype=np.uint64).astype(np.uint32))
    # a run container (all ones) in row 3
    i2 = 3 * SHARD_WORDS + 2 * CONTAINER_WORDS + np.arange(CONTAINER_WORDS)
    parts_i.append(i2.astype(np.int64))
    parts_v.append(np.full(CONTAINER_WORDS, 0xFFFFFFFF, dtype=np.uint32))
    flat = np.concatenate(parts_i)
    vals = np.concatenate(parts_v)
    order = np.argsort(flat)
    flat, vals = flat[order], vals[order]
    keep = np.concatenate([[True], np.diff(flat) != 0])
    p = _kernel_golden(flat[keep], vals[keep], rows=rows)
    h = p.type_histogram()
    assert h["array"] and h["bitmap"] and h["run"]


def test_fused_row_counts_golden(rng):
    """The headline fusion (decode + AND + popcount in one kernel)
    matches the host oracle, filtered and unfiltered."""
    import jax.numpy as jnp
    rows = 3
    flat = np.sort(rng.choice(rows * SHARD_WORDS, 900, replace=False)) \
        .astype(np.int64)
    vals = rng.integers(1, 1 << 32, 900, dtype=np.uint64) \
        .astype(np.uint32)
    p = pack_words(flat, vals)
    arrs = [jnp.asarray(a) for a in pad_packed(p)]
    ab, rb = pow2_bucket(p.a_max), pow2_bucket(p.r_max)
    dense = unpack_packed(p, rows)
    got = np.asarray(kernels.fused_row_counts(
        *arrs, None, rows=rows, a_bucket=ab, r_bucket=rb))
    np.testing.assert_array_equal(got, _popcounts(dense))
    filt = rng.integers(0, 1 << 32, SHARD_WORDS, dtype=np.uint64) \
        .astype(np.uint32)
    got_f = np.asarray(kernels.fused_row_counts(
        *arrs, jnp.asarray(filt), rows=rows, a_bucket=ab, r_bucket=rb))
    np.testing.assert_array_equal(got_f,
                                  _popcounts(dense & filt[None, :]))


def test_vmem_budget_rule_falls_back(rng, monkeypatch):
    """A bucket whose working set exceeds the VMEM budget rule must
    take the jnp fallback — and still be exact (the rule is a schedule
    choice, never a correctness choice)."""
    monkeypatch.setattr(kernels, "VMEM_TILE_BUDGET_BYTES", 1024)
    assert not kernels.fits_vmem(1 << 20, 0, 0)
    flat = np.sort(rng.choice(SHARD_WORDS, 64, replace=False)) \
        .astype(np.int64)
    vals = rng.integers(1, 1 << 32, 64, dtype=np.uint64) \
        .astype(np.uint32)
    _kernel_golden(flat, vals, rows=1)


# -- backend resolution and the device_sig backend axis ---------------------

def test_resolve_backends(force_backend):
    """Knob semantics: jnp is the kill switch, pallas forces the
    kernels, auto picks by platform (jnp on the CPU tier-1 box)."""
    import jax
    force_backend("jnp")
    assert kernels.resolve() == "jnp"
    force_backend("pallas")
    assert kernels.resolve() == "pallas"
    force_backend("auto")
    want = "pallas" if jax.default_backend() == "tpu" else "jnp"
    assert kernels.resolve() == want
    assert kernels.interpret_mode() == (jax.default_backend() != "tpu")


def test_device_sig_backend_axis(force_backend):
    """Satellite regression (the PR 7 retrace class): flipping
    container-kernels changes a compressed fragment's device_sig — new
    signatures mean new plan keys and stack tokens, so the flip rebuilds
    instead of replaying a jnp-compiled executable with pallas-shaped
    expectations.  Dense signatures carry no backend axis."""
    budget = DeviceBudget(limit_bytes=64 << 20)
    f = Fragment(None, "i", "f", "standard", 0, budget=budget)
    f.bulk_import(np.arange(8), np.arange(8) * 1000)
    assert f.device_form() == "compressed"
    force_backend("jnp")
    sig_jnp = f.device_sig()
    assert sig_jnp[0] == "z" and sig_jnp[6] == "jnp"
    force_backend("pallas")
    sig_pl = f.device_sig()
    assert sig_pl[6] == "pallas" and sig_pl[:6] == sig_jnp[:6]
    # the sig cache is keyed by (gen, backend): flipping back must
    # return the jnp sig again, not the cached pallas one
    force_backend("jnp")
    assert f.device_sig() == sig_jnp
    assert kernels.sig_backend(sig_pl) == "pallas"
    # pre-backend-axis 6-tuples read as jnp (the decode they compiled)
    assert kernels.sig_backend(sig_jnp[:6]) == "jnp"


def test_upload_decode_pallas_ledger(force_backend):
    """The standalone compressed-upload decode honors the knob and
    registers its kernel launch in the launch ledger."""
    force_backend("pallas")
    rng = np.random.default_rng(3)
    flat = np.sort(rng.choice(2 * SHARD_WORDS, 120, replace=False)) \
        .astype(np.int64)
    vals = rng.integers(1, 1 << 32, 120, dtype=np.uint64) \
        .astype(np.uint32)
    p = pack_words(flat, vals)
    before = devobs.LEDGER.kernel_launches_total
    got = np.asarray(upload_decode(p, 2))
    np.testing.assert_array_equal(got, unpack_packed(p, 2))
    assert devobs.LEDGER.kernel_launches_total > before


# -- 3-leg differential on the mixed-forms corpus ---------------------------

@pytest.fixture(scope="module")
def corpus():
    """4-shard index mixing sparse scatter (arrays), boundary-dense
    containers (bitmaps), run-heavy clustered ranges, BSI values, and an
    emptied fragment — the PR 7 mixed corpus at a size the interpreted
    kernels execute quickly."""
    rng = np.random.default_rng(99)
    h = Holder(None)
    idx = h.create_index("k")
    a = idx.create_field("a")
    b = idx.create_field("b")
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    n = 12_000
    cols = rng.integers(0, 4 * SHARD_WIDTH, size=n)
    a.import_bits(rng.integers(0, 10, size=n), cols)
    b.import_bits(rng.integers(0, 6, size=n), cols)
    # run-heavy clustered ranges in every shard
    run_cols = np.concatenate([
        np.arange(s * SHARD_WIDTH + 1000, s * SHARD_WIDTH + 30_000)
        for s in range(4)])
    a.import_bits(np.full(run_cols.size, 11), run_cols)
    vcols = np.unique(cols[: n // 2])
    v.import_values(vcols, rng.integers(-500, 500, size=vcols.size))
    idx.add_existence(np.unique(np.concatenate([cols, run_cols])))
    # emptied fragment: set then clear (empty packed stream)
    ecols = np.arange(2 * SHARD_WIDTH + 50, 2 * SHARD_WIDTH + 80)
    b.import_bits(np.full(30, 5), ecols)
    b.import_bits(np.full(30, 5), ecols, clear=True)
    return h


def _run_corpus(ex, queries):
    return [_norm(r) for q in queries for r in ex.execute("k", q)]


def test_three_leg_differential(corpus, force_backend):
    """dense-resident / compressed-jnp / compressed-pallas-interpret
    are byte-identical on the mixed corpus; the pallas leg records
    kernel launches in the ledger; and the whole run — including the
    backend flip — raises ZERO retrace alarms (flips mint new
    signatures, they don't retrace old ones)."""
    qrng = np.random.default_rng(1234)
    queries = [gen_query(qrng) for _ in range(3)]
    queries += ["TopN(a, n=3)", "Count(Row(a=11))", "Row(b=5)",
                "Count(Intersect(Row(a=11), Row(b=2)))",
                "Sum(Row(a=1), field=v)"]
    ex = Executor(corpus, use_mesh=True)
    old = DEFAULT_BUDGET.limit_bytes
    retraces0 = devobs.COMPILES.totals()["retraces"]
    try:
        # leg 1 — dense-resident reference (no budget limit, no
        # compression, backend knob irrelevant)
        DEFAULT_BUDGET.limit_bytes = None
        force_backend("jnp")
        want = _run_corpus(ex, queries)

        # leg 2 — compressed residency, jnp decode (the PR 7 path);
        # the kill-switch leg must not launch any container kernel
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        kj = devobs.LEDGER.kernel_launches_total
        assert _run_corpus(ex, queries) == want
        st = DEFAULT_BUDGET.stats()
        assert st["compressedBytes"] > 0, \
            "corpus never compressed: the differential exercised " \
            "only the dense path"
        assert devobs.LEDGER.kernel_launches_total == kj, \
            "jnp kill-switch leg launched container kernels"

        # leg 3 — compressed residency, Pallas kernels (interpreted on
        # CPU): same bytes, plus kernel launches in the ledger
        force_backend("pallas")
        k0 = devobs.LEDGER.kernel_launches_total
        assert _run_corpus(ex, queries) == want
        assert devobs.LEDGER.kernel_launches_total > k0, \
            "pallas leg never launched a container kernel"

        # flip back: the kill switch restores the jnp path in place
        force_backend("jnp")
        assert _run_corpus(ex, queries) == want
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()
    assert devobs.COMPILES.totals()["retraces"] == retraces0, \
        "backend flip retraced an existing signature instead of " \
        "minting new ones"
