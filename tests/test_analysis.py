"""Project invariant analyzer tests (docs/static-analysis.md).

Golden bad-snippet fixtures per AST rule — each rule must catch its
motivating historical bug SHAPE (the PR 7 traced-closure loop capture,
the PR 6 anti-entropy swallow), reject the fixed spelling, and honor a
reasoned inline suppression — plus the lock-order detector's seeded
inversion (must report) and benign nesting (must not), and the
whole-tree invariant that the analyzer exits clean on this checkout.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from pilosa_tpu.analysis import lockcheck
from pilosa_tpu.analysis.astlint import (
    Suppressions,
    lint_source,
    run as run_analysis,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


def lint(src, *rules, rel="pilosa_tpu/executor/snippet.py"):
    return lint_source(textwrap.dedent(src), list(rules), rel=rel)


# -- traced-closure (the PR 7 silent-retrace bug shape) ---------------------

PR7_BUG = """
    import jax

    def segments_batch(self, groups):
        out = {}
        for shard_list, layout in groups:
            def per_shard(params, *arrays):
                # BUG: `layout` is read from the closure; a re-trace
                # after the loop moved on decodes with the WRONG buckets
                return unpack(layout, arrays)
            out[shard_list] = jax.jit(per_shard)
        return out
"""

PR7_FIXED = """
    import jax

    def segments_batch(self, groups):
        out = {}
        for shard_list, layout in groups:
            def per_shard(params, *arrays, _layout=layout):
                return unpack(_layout, arrays)
            out[shard_list] = jax.jit(per_shard)
        return out
"""


def test_traced_closure_catches_pr7_loop_capture():
    findings = lint(PR7_BUG, "traced-closure")
    assert len(findings) == 1
    assert "layout" in findings[0].message
    assert "loop-carried" in findings[0].message


def test_traced_closure_frozen_default_is_clean():
    assert lint(PR7_FIXED, "traced-closure") == []


def test_traced_closure_reassigned_local():
    src = """
        import jax
        def build(xs):
            acc = 0
            acc = prep(xs)
            fn = jax.jit(lambda p: p + acc)
            return fn
    """
    findings = lint(src, "traced-closure")
    assert len(findings) == 1
    assert "reassigned" in findings[0].message


def test_traced_closure_single_assignment_is_clean():
    src = """
        import jax
        def build(xs):
            table = prep(xs)
            return jax.jit(lambda p: p + table)
    """
    assert lint(src, "traced-closure") == []


def test_traced_closure_name_passed_to_wrapper():
    src = """
        import jax
        def build(groups):
            for layout in groups:
                def body(p):
                    return decode(layout, p)
                fn = jax.vmap(body)
            return fn
    """
    assert len(lint(src, "traced-closure")) == 1


def test_traced_closure_suppressed():
    src = PR7_BUG.replace(
        "return unpack(layout, arrays)",
        "# lint: allow(traced-closure) — executable never cached\n"
        "                return unpack(layout, arrays)")
    assert lint(src, "traced-closure") == []


# -- wall-clock -------------------------------------------------------------


def test_wallclock_flags_time_time():
    src = """
        import time
        def span_start():
            return time.time()
    """
    assert len(lint(src, "wall-clock")) == 1


def test_wallclock_catches_aliased_imports_the_grep_missed():
    src = """
        from time import time as now
        import time as t
        def f():
            return now() + t.time()
    """
    assert len(lint(src, "wall-clock")) == 2


def test_wallclock_perf_counter_and_wall_stamp_clean():
    src = """
        import time
        def _wall_stamp():
            return time.time()
        def dur():
            return time.perf_counter()
    """
    assert lint(src, "wall-clock") == []


def test_inline_allow_does_not_leak_to_next_line():
    src = """
        import time
        def f():
            a = time.time()  # lint: allow(wall-clock) — display stamp
            b = time.time()
            return a, b
    """
    findings = lint(src, "wall-clock")
    assert len(findings) == 1  # only the un-suppressed second call


def test_wallclock_suppressed_with_reason():
    src = """
        import time
        def f():
            # lint: allow(wall-clock) — uptime display only
            return time.time()
    """
    assert lint(src, "wall-clock") == []


# -- bare-except / swallowed-exception (the PR 6 AE-swallow shape) ----------

PR6_BUG = """
    def sync_shard(self, nid):
        try:
            self.fetch_blocks(nid)
        except Exception:
            pass  # a failed poll now LOOKS like a clean pass
"""


def test_swallow_catches_pr6_shape():
    findings = lint(PR6_BUG, "swallowed-exception")
    assert len(findings) == 1
    assert "swallows" in findings[0].message


def test_swallow_logged_counted_or_raised_is_clean():
    src = """
        def f(self):
            try:
                work()
            except Exception as e:
                self.logger.event("sync.failed", err=str(e))
        def g(self):
            try:
                work()
            except Exception:
                self.stats.count("errors")
        def h(self):
            try:
                work()
            except Exception:
                raise RuntimeError("wrapped")
        def k(self):
            try:
                work()
            except Exception as e:
                return None, e
    """
    assert lint(src, "swallowed-exception") == []


def test_swallow_matches_word_stems_not_substrings():
    # 'down' ⊄ shutdown, list.count is not a stat — both still swallow
    src = """
        def f(sock):
            try:
                work()
            except Exception:
                sock.shutdown()
        def g(xs):
            try:
                work()
            except Exception:
                n = xs.count(1)
    """
    assert len(lint(src, "swallowed-exception")) == 2


def test_bare_except_flagged_and_named_clean():
    assert len(lint("try:\n    x()\nexcept:\n    pass\n",
                    "bare-except")) == 1
    assert lint("try:\n    x()\nexcept OSError:\n    pass\n",
                "bare-except") == []


def test_swallow_suppressed_with_reason():
    src = """
        def close_all(conns):
            for c in conns:
                try:
                    c.close()
                # lint: allow(swallowed-exception) — teardown close
                except Exception:
                    pass
    """
    assert lint(src, "swallowed-exception") == []


# -- batcher-bypass ---------------------------------------------------------


def test_batcher_bypass_direct_dispatch_flagged():
    src = """
        def run(self, plan):
            return self.executor.mesh.segments(plan)
    """
    assert len(lint(src, "batcher-bypass")) == 1


def test_batcher_bypass_alias_tracking_beats_the_grep():
    src = """
        def run(self, plan):
            m = MeshExecutor()
            return m.row_counts(plan)
    """
    assert len(lint(src, "batcher-bypass")) == 1


def test_batcher_bypass_allowed_inside_parallel_and_via_batcher():
    src = """
        def run(self, plan):
            return self.mesh.segments(plan)
    """
    assert lint(src, "batcher-bypass",
                rel="pilosa_tpu/parallel/batcher.py") == []
    via = """
        def run(self, plan):
            return self.batcher.segments(plan)
    """
    assert lint(via, "batcher-bypass") == []


# -- thread-context ---------------------------------------------------------


def test_thread_context_unattached_target_flagged():
    src = """
        def fan_out(self, pool):
            def work(shard):
                with qprof.stage("slice"):
                    return run(shard)
            return pool.submit(work, 1)
    """
    assert len(lint(src, "thread-context")) == 1


def test_thread_context_attached_target_clean():
    src = """
        def fan_out(self, pool, tracer):
            ctx = tracer.capture()
            def work(shard):
                with tracer.attach(ctx):
                    with qprof.stage("slice"):
                        return run(shard)
            return pool.submit(work, 1)
    """
    assert lint(src, "thread-context") == []


def test_thread_context_task_wrapped_callsite_clean():
    src = """
        def fan_out(self, pool, tracer):
            def work(shard):
                with qprof.stage("slice"):
                    return run(shard)
            return pool.submit(tracer.task(work), 1)
    """
    assert lint(src, "thread-context") == []


# -- suppression hygiene ----------------------------------------------------


def test_suppression_without_reason_is_recorded():
    sup = Suppressions("x = 1  # lint: allow(wall-clock)\n")
    assert sup.missing_reason and sup.missing_reason[0][0] == 1


def test_docstring_text_is_not_a_suppression():
    sup = Suppressions('"""docs: # lint: allow(wall-clock) — nope"""\n')
    assert sup.by_line == {}


# -- project rules on a synthetic tree --------------------------------------


def _mini_tree(tmp_path, extra_test="", catalog="| `a.b` | x |"):
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(
        'FAULTS.hit("fragment.wal", key="k")\n'
        'stats.count("a.b")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "<!-- metrics-catalog:begin -->\n"
        f"{catalog}\n"
        "<!-- metrics-catalog:end -->\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_x.py").write_text(extra_test)
    return tmp_path


def test_failpoint_typo_flagged_and_real_name_clean(tmp_path):
    root = _mini_tree(
        tmp_path,
        # the bad spec is split with a `+` so THIS file's constants
        # can't match the spec shape; the generated mini-tree file
        # still contains the full typo'd literal
        extra_test='FAULTS.arm("fragment.waal")\n'
                   'FAULTS.arm("fragment.wal")\n'
                   'SPEC = "fragment.wall' + '=kill:2"\n')
    findings = [f for f in run_analysis(root, ["failpoint-names"])]
    names = {f.message.split("'")[1] for f in findings}
    assert names == {"fragment.waal", "fragment.wall"}


def test_metrics_docs_two_way(tmp_path):
    root = _mini_tree(tmp_path, catalog="| `a.b` | x |\n| `dang.ling` | y |")
    (root / "pilosa_tpu" / "mod2.py").write_text(
        'mystats.count("un.documented")\n')
    findings = run_analysis(root, ["metrics-docs"])
    msgs = " | ".join(f.message for f in findings)
    assert "un.documented" in msgs
    assert "dang.ling" in msgs
    assert "a.b" not in msgs


# -- the tree itself is clean (the analyzer-exits-0 acceptance gate) --------


def test_repo_tree_is_clean():
    findings = run_analysis(REPO_ROOT)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_unknown_rule_id_errors():
    # a typo'd --rule must not silently analyze nothing and exit 0
    with pytest.raises(ValueError, match="traced-closur "):
        run_analysis(REPO_ROOT, ["traced-closur"])


# -- lockcheck: runtime lock-order race detector ----------------------------


@pytest.fixture
def clean_graph():
    lockcheck.GRAPH.reset()
    yield
    lockcheck.GRAPH.reset()


def _abba(lock_a, lock_b):
    import threading
    import time as _t
    bar = threading.Barrier(2)

    def one(x, y):
        with x:
            bar.wait()
            _t.sleep(0.01)
            if y.acquire(timeout=0.5):
                y.release()

    t1 = threading.Thread(target=one, args=(lock_a, lock_b))
    t2 = threading.Thread(target=one, args=(lock_b, lock_a))
    t1.start(), t2.start()
    t1.join(), t2.join()


def test_seeded_inversion_is_reported(clean_graph):
    _abba(lockcheck.CheckedLock("alpha"), lockcheck.CheckedLock("beta"))
    rep = lockcheck.report()
    kinds = {v["kind"] for v in rep["violations"]}
    assert "order-inversion" in kinds
    detail = next(v["detail"] for v in rep["violations"]
                  if v["kind"] == "order-inversion")
    assert "alpha" in detail and "beta" in detail


def test_benign_consistent_nesting_is_not_reported(clean_graph):
    import threading
    a, b = lockcheck.CheckedRLock("holder"), lockcheck.CheckedRLock("frag")

    def nest():
        with a:
            with b:
                pass

    ts = [threading.Thread(target=nest) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    rep = lockcheck.report()
    assert rep["violations"] == []
    assert any(e["from"] == "holder" and e["to"] == "frag"
               for e in rep["edges"])


def test_same_class_nesting_flagged_unless_declared(clean_graph):
    f1, f2 = lockcheck.CheckedRLock("fragment"), \
        lockcheck.CheckedRLock("fragment")
    with f1:
        with f2:
            pass
    kinds = {v["kind"] for v in lockcheck.report()["violations"]}
    assert "same-class-nesting" in kinds

    lockcheck.GRAPH.reset()
    s1, s2 = lockcheck.CheckedLock("stats"), lockcheck.CheckedLock("stats")
    with s1:
        with s2:
            pass
    assert lockcheck.report()["violations"] == []


def test_rlock_reentrancy_and_condition_bookkeeping(clean_graph):
    import threading
    rl = lockcheck.CheckedRLock("holder")
    with rl:
        with rl:
            pass
    assert lockcheck.report()["violations"] == []

    cond = lockcheck.checked_condition("committer")
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=2)
            hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    import time as _t
    _t.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join()
    assert hits == [1]


def test_cross_thread_handoff_does_not_fabricate_edges(clean_graph):
    import threading
    a = lockcheck.CheckedLock("handoff")
    b = lockcheck.CheckedLock("other")
    a.acquire()
    t = threading.Thread(target=a.release)  # legal for threading.Lock
    t.start()
    t.join()
    with b:  # the stale 'handoff' stack entry must be pruned, not held
        pass
    rep = lockcheck.report()
    assert rep["violations"] == []
    assert not any(e["from"] == "handoff" for e in rep["edges"])


def test_unarmed_factories_return_plain_primitives():
    import threading
    from pilosa_tpu.utils import locks
    if locks.ARMED:
        pytest.skip("process runs with PILOSA_TPU_LOCKCHECK armed")
    assert isinstance(locks.make_lock("x"), type(threading.Lock()))
    rep = locks.report()
    assert rep["armed"] is False


STRICT_SCRIPT = """
import threading, time
from pilosa_tpu.utils import locks

a = locks.make_lock("alpha")
b = locks.make_lock("beta")
bar = threading.Barrier(2)

def one(x, y):
    with x:
        bar.wait()
        time.sleep(0.01)
        if y.acquire(timeout=0.5):
            y.release()

t1 = threading.Thread(target=one, args=(a, b))
t2 = threading.Thread(target=one, args=(b, a))
t1.start(); t2.start(); t1.join(); t2.join()
print("body done")
"""


def test_strict_mode_fails_process_on_seeded_inversion():
    """The CI contract: a strict-armed process with an inversion dies
    loudly at exit (after the test body itself passed)."""
    proc = subprocess.run(
        [sys.executable, "-c", STRICT_SCRIPT],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             "PILOSA_TPU_LOCKCHECK": "strict"})
    assert "body done" in proc.stdout
    assert proc.returncode == 70, proc.stderr
    assert "order-inversion" in proc.stderr


def test_observe_mode_reports_but_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-c", STRICT_SCRIPT],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             "PILOSA_TPU_LOCKCHECK": "1"})
    assert proc.returncode == 0, proc.stderr
    assert "order-inversion" in proc.stderr
