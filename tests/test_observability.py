"""End-to-end query observability (ISSUE 5, docs/observability.md):
cluster-wide trace propagation with correct cross-node span parenting,
per-query profile trees, log-bucket latency histograms with golden
percentile math, the slow-query log, and the Prometheus exposition
round-tripped through a minimal text parser."""

import json
import socket
import time
import urllib.request

import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.utils.stats import (NopStatsClient, StatsClient,
                                    StatsdClient, TIMING_BUCKETS, _Hist)
from pilosa_tpu.utils.slowlog import SlowQueryLog
from pilosa_tpu.utils.tracing import (PROBE_HEADER, TRACE_HEADER, Tracer,
                                      format_trace_header,
                                      parse_trace_header)


def _req(port, method, path, data=None, headers=None, timeout=60):
    body = None
    if data is not None:
        body = data.encode() if isinstance(data, str) else \
            json.dumps(data).encode()
    r = urllib.request.Request(
        f"http://localhost:{port}{path}", method=method, data=body,
        headers=headers or {})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def make_server(tmp_path, name="srv", **cfg):
    cfg.setdefault("anti_entropy_interval", 0)
    cfg.setdefault("bind", "localhost:0")
    s = Server(Config(data_dir=str(tmp_path / name), **cfg))
    s.open()
    return s


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for sk in socks:
        sk.bind(("localhost", 0))
    ports = [sk.getsockname()[1] for sk in socks]
    for sk in socks:
        sk.close()
    return ports


def _walk(node):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


# -- histogram math (golden values) ------------------------------------------

def test_hist_bucket_and_percentile_golden():
    h = _Hist()
    vals = [0.0002, 0.0004, 0.003, 0.004, 0.07, 0.2, 30.0, 200.0]
    for v in vals:
        h.observe(v)
    assert h.count == 8
    assert h.total == pytest.approx(sum(vals))
    # bucket placement: inclusive upper edges
    by_edge = dict(zip(TIMING_BUCKETS, h.buckets))
    assert by_edge[0.00025] == 1 and by_edge[0.0005] == 1
    assert by_edge[0.005] == 2
    assert by_edge[0.1] == 1 and by_edge[0.25] == 1
    assert by_edge[50.0] == 1
    assert h.buckets[-1] == 1  # 200 s -> +Inf
    # interpolated order statistics (hand-computed golden values):
    # p50 target=4.0 lands exactly at the top of the (0.0025, 0.005]
    # bucket; p75 target=6.0 at the top of (0.1, 0.25]; p99 target=7.92
    # falls in the +Inf bucket and clamps to the last edge.
    assert h.percentile(0.50) == pytest.approx(0.005)
    assert h.percentile(0.75) == pytest.approx(0.25)
    assert h.percentile(0.99) == pytest.approx(100.0)
    assert _Hist().percentile(0.5) is None


def test_stats_client_percentiles_and_snapshot():
    st = StatsClient()
    for ms in (1, 2, 3, 4, 5, 6, 7, 8, 9, 10):
        st.timing("op", ms / 1000.0)
    snap = st.snapshot()["timings"]["op"]
    assert snap["count"] == 10
    assert snap["sum"] == pytest.approx(0.055)
    for q in ("p50", "p95", "p99"):
        assert snap[q] is not None
    # percentile() answers the same math directly, tags share state
    assert st.percentile("op", 0.5) == pytest.approx(snap["p50"])
    assert st.with_tags("index:i").percentile("op", 0.5) is None  # new key
    assert st.percentile("absent", 0.5) is None


def test_set_value_cardinality_cap():
    st = StatsClient()
    for i in range(200):
        st.set_value("v", f"val{i}")
    keys = [k for k in st.snapshot()["gauges"] if k.startswith("v:")]
    # first CAP distinct values keep their own series; the rest collapse
    assert len(keys) == StatsClient.SET_VALUE_CAP + 1
    assert "v:__other__" in keys


def test_nop_and_statsd_clients_implement_histogram_api():
    nop = NopStatsClient()
    nop.count("a")
    nop.gauge("b", 1)
    nop.timing("c", 0.1)
    nop.histogram("d", 2.0)
    nop.set_value("e", "x")
    with nop.timer("f"):
        pass
    assert nop.percentile("c", 0.5) is None
    assert nop.snapshot()["timings"] == {}

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("localhost", 0))
    recv.settimeout(2)
    st = StatsdClient("localhost", recv.getsockname()[1])
    st.histogram("lat", 0.004)
    st.set_value("who", "alice")
    got = {recv.recvfrom(1024)[0].decode() for _ in range(2)}
    assert "lat:0.004|h" in got
    assert "who:alice|s" in got
    # in-process the histogram is bucketed like any timing
    assert st.percentile("lat", 0.5) is not None
    assert st.snapshot()["timings"]["lat"]["count"] == 1
    recv.close()


# -- trace context plumbing --------------------------------------------------

def test_trace_header_round_trip():
    assert parse_trace_header(None) == (None, None, True)
    assert parse_trace_header("abc") == ("abc", None, True)  # legacy form
    assert parse_trace_header(format_trace_header("t1", "s1")) == \
        ("t1", "s1", True)
    assert parse_trace_header(format_trace_header("t1", "s1", False)) == \
        ("t1", "s1", False)


def test_tracer_context_crosses_thread_pools():
    from concurrent.futures import ThreadPoolExecutor
    tr = Tracer()
    with ThreadPoolExecutor(1) as pool:
        with tr.span("root") as root:
            # task() re-installs the submitting thread's context in the
            # worker; a plain thread-local would return None here
            seen = pool.submit(tr.task(lambda: tr.current())).result()
            assert seen.trace_id == root.trace_id
            assert seen.span_id == root.span_id
            with_span = pool.submit(
                tr.task(lambda: tr.current().span_id, name="child"))
            assert with_span.result() != root.span_id
    spans = {s["name"]: s for s in tr.spans(root.trace_id)}
    assert spans["child"]["parentID"] == root.span_id


def test_trace_sampling_is_decided_at_the_root():
    tr = Tracer()
    tr.sample_rate = 0.0
    with tr.span("root") as root:
        with tr.span("child"):
            pass
    assert tr.spans(root.trace_id) == []
    # an unsampled remote continuation (":0" on the wire) stays unsampled
    with tr.span("remote", trace_id="t9", parent_id="p1", sampled=False):
        pass
    assert tr.spans("t9") == []


def test_slowlog_ring_is_bounded():
    log = SlowQueryLog(threshold_s=0.001, size=3)
    for i in range(10):
        log.record(index="i", query=f"Q{i}" + "x" * 2000,
                   duration_s=0.5, trace_id=f"t{i}")
    snap = log.snapshot()
    assert snap["recorded"] == 10
    assert len(snap["entries"]) == 3
    assert snap["entries"][-1]["traceID"] == "t9"
    assert len(snap["entries"][0]["query"]) <= 512
    assert not SlowQueryLog(threshold_s=0).enabled


# -- served surfaces ---------------------------------------------------------

def test_profile_tree_and_slowlog_http(tmp_path):
    srv = make_server(tmp_path, slow_query_threshold=1e-9,
                      result_cache_mb=8)
    p = srv.port
    try:
        _req(p, "POST", "/index/i", {})
        _req(p, "POST", "/index/i/field/f", {})
        _req(p, "POST", "/index/i/query", "Set(1, f=1)Set(99, f=1)")
        out, hdrs = _req(p, "POST", "/index/i/query?profile=true",
                         "Count(Row(f=1))")
        assert out["results"] == [2]
        # one trace id, echoed in the response header too
        assert out["traceID"] == hdrs[TRACE_HEADER]
        names = [n["name"] for n in _walk(out["profile"])]
        assert names[0] == "query"
        assert "admission" in names
        # the device launch went through the cross-query batcher
        assert "batcher.queue" in names and "batcher.launch" in names
        stages = {n["name"]: n for n in _walk(out["profile"])}
        assert stages["query"]["durationMS"] > 0
        assert stages["query"]["tags"]["index"] == "i"
        # repeat: served from the result cache, and the profile says so
        out2, _ = _req(p, "POST", "/index/i/query?profile=true",
                       "Count(Row(f=1))")
        lookups = [n for n in _walk(out2["profile"])
                   if n["name"] == "resultcache.lookup"]
        assert lookups and lookups[0]["tags"]["outcome"] == "hit"
        # without ?profile= the response carries no tree
        out3, _ = _req(p, "POST", "/index/i/query", "Count(Row(f=1))")
        assert "profile" not in out3

        # every query crossed the 1ns threshold -> slow-query ring
        # (recording runs AFTER the response is sent — handler._observe
        # in the finally block — so poll rather than race it)
        deadline = time.perf_counter() + 5
        while True:
            slow, _ = _req(p, "GET", "/debug/slow")
            if slow["recorded"] >= 4 or time.perf_counter() > deadline:
                break
            time.sleep(0.01)
        assert slow["recorded"] >= 4
        entry = slow["entries"][-1]
        assert entry["index"] == "i"
        assert entry["query"] == "Count(Row(f=1))"
        assert entry["traceID"]
        # the repeat Count was a result-cache hit: it dispatched against
        # no shards, so its entry carries none — the first (uncached)
        # Count recorded the real shard count
        assert entry["shards"] is None
        assert any(e["shards"] == 1 for e in slow["entries"])
        assert entry["profile"]["name"] == "query"
        # the trace id in the entry is retrievable from /debug/traces
        spans, _ = _req(p, "GET",
                        f"/debug/traces?trace={entry['traceID']}")
        assert any(s["name"] == "api.Query" for s in spans["spans"])
        dv, _ = _req(p, "GET", "/debug/vars")
        assert dv["slowLog"]["recorded"] >= 4
    finally:
        srv.close()


def test_probes_excluded_from_histograms_and_slowlog(tmp_path):
    srv = make_server(tmp_path, slow_query_threshold=1e-9)
    p = srv.port
    try:
        _req(p, "POST", "/index/i", {})
        _req(p, "POST", "/index/i/field/f", {})
        _req(p, "POST", "/index/i/query", "Set(1, f=1)")

        def counts():
            dv, _ = _req(p, "GET", "/debug/vars")
            t = dv["timings"]
            return (t.get("http.request", {}).get("count", 0),
                    t.get("http.query", {}).get("count", 0),
                    dv["slowLog"]["recorded"])

        def settled(min_query):
            # post-request accounting runs AFTER the response is sent
            # (handler._observe in the finally block), so a /debug/vars
            # read can race it; poll until the expected query count
            # lands before asserting
            deadline = time.perf_counter() + 5
            c = counts()
            while c[1] < min_query and time.perf_counter() < deadline:
                time.sleep(0.01)
                c = counts()
            return c

        req0, query0, slow0 = settled(1)
        assert req0 >= 1 and query0 >= 1 and slow0 >= 1
        # background paths: status/metrics/debug never reach the
        # histograms (the /debug/vars reads above are themselves exempt)
        _req(p, "GET", "/status")
        with urllib.request.urlopen(
                f"http://localhost:{p}/metrics", timeout=30) as resp:
            resp.read()
        _req(p, "GET", "/debug/traces")
        # a probe-TAGGED query (the wire tag health probes carry) is
        # excluded from histograms and can never land in the slow log
        _req(p, "POST", "/index/i/query", "Count(Row(f=1))",
             headers={PROBE_HEADER: "1"})
        req1, query1, slow1 = counts()
        assert (req1, query1, slow1) == (req0, query0, slow0)
        # an untagged query still counts everywhere
        _req(p, "POST", "/index/i/query", "Count(Row(f=1))")
        req2, query2, slow2 = settled(query0 + 1)
        assert (req2, query2, slow2) == (req0 + 1, query0 + 1, slow0 + 1)
        # background requests never root recorded traces either — probe
        # cadence must not evict real query traces from the span ring
        spans, _ = _req(p, "GET", "/debug/traces")
        assert not any(s["name"].startswith("GET /status")
                       for s in spans["spans"])
    finally:
        srv.close()


def _parse_prometheus(text):
    """Minimal Prometheus text-format parser: name -> {types, samples}
    where samples maps (name, frozenset(labels)) -> float."""
    types = {}
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line.startswith("# TYPE "):
                _, _, rest = line.partition("# TYPE ")
                fam, typ = rest.split()
                types[fam] = typ
            continue
        # OpenMetrics exemplar suffix (` # {trace_id="..."} v ts`) is
        # metadata, not the sample value — strip it like a real
        # exemplar-aware scraper does
        line = line.split(" # ")[0]
        metric, _, value = line.rpartition(" ")
        name, _, labelstr = metric.partition("{")
        labels = frozenset(
            kv for kv in labelstr.rstrip("}").split(",") if kv) \
            if labelstr else frozenset()
        samples[(name, labels)] = float(value)
    return types, samples


def test_metrics_histogram_round_trip(tmp_path):
    srv = make_server(tmp_path)
    p = srv.port
    try:
        _req(p, "POST", "/index/i", {})
        _req(p, "POST", "/index/i/field/f", {})
        for _ in range(3):
            _req(p, "POST", "/index/i/query", "Count(Row(f=1))")
        fam = "pilosa_tpu_http_query_seconds"
        # the histogram observation lands in post-response accounting
        # (the _observe finally block), so poll the scrape until the
        # last query's sample settles
        deadline = time.monotonic() + 5.0
        while True:
            r = urllib.request.Request(f"http://localhost:{p}/metrics")
            with urllib.request.urlopen(r, timeout=30) as resp:
                text = resp.read().decode()
            types, samples = _parse_prometheus(text)
            if samples.get((f"{fam}_count", frozenset())) == 3 \
                    or time.monotonic() > deadline:
                break
            time.sleep(0.01)
        assert types[fam] == "histogram"
        buckets = sorted(
            ((float(next(iter(ls)).split('"')[1])
              if '"+Inf"' not in next(iter(ls)) else float("inf")), v)
            for (n, ls) in samples if n == f"{fam}_bucket"
            for v in [samples[(n, ls)]])
        # cumulative and monotone, +Inf equals _count
        assert [v for _, v in buckets] == \
            sorted(v for _, v in buckets)
        assert buckets[-1][0] == float("inf")
        assert buckets[-1][1] == samples[(f"{fam}_count", frozenset())]
        assert samples[(f"{fam}_count", frozenset())] == 3
        assert samples[(f"{fam}_sum", frozenset())] > 0
        # p99 is derivable from the buckets (histogram_quantile shape)
        # and agrees with the server's own interpolation
        target = 0.99 * buckets[-1][1]
        cum_prev, lo = 0.0, 0.0
        for edge, cum in buckets:
            if cum >= target:
                n_in = cum - cum_prev
                frac = (target - cum_prev) / n_in if n_in else 1.0
                p99 = lo + frac * (edge - lo)
                break
            cum_prev, lo = cum, edge
        assert p99 == pytest.approx(
            srv.stats.percentile("http.query", 0.99))
    finally:
        srv.close()


# -- 2-node cluster: one trace spans both nodes ------------------------------

def test_cluster_trace_parenting_and_profile(tmp_path):
    ports = _free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    try:
        for i in range(2):
            srv = Server(Config(
                data_dir=str(tmp_path / f"n{i}"), bind=hosts[i],
                node_id=f"node{i}", cluster_hosts=hosts, replica_n=1,
                anti_entropy_interval=0, use_mesh=False))
            servers.append(srv)
            srv.open()
        coord = servers[0]
        p0 = ports[0]
        _req(p0, "POST", "/index/ci", {})
        _req(p0, "POST", "/index/ci/field/f", {})
        # a shard owned SOLELY by the remote node, so the query must fan
        # out and the trace must cross the wire
        shard = next(
            s for s in range(64)
            if coord.cluster.placement.shard_nodes("ci", s) == ["node1"])
        col0 = shard * SHARD_WIDTH + 11
        _req(p0, "POST", "/index/ci/field/f/import",
             {"rowIDs": [3, 3], "columnIDs": [col0, col0 + 1]})
        out, _ = _req(p0, "POST", "/index/ci/query?profile=true",
                      "Count(Row(f=3))")
        assert out["results"] == [2]
        tid = out["traceID"]
        # coordinator profile: per-peer fan-out RTT with the peer's own
        # execution time split out
        peers = [n for n in _walk(out["profile"])
                 if n["name"].startswith("peer.")]
        assert peers and peers[0]["name"] == "peer.node1"
        assert peers[0]["tags"]["shards"] == 1
        assert peers[0]["tags"]["peerExecS"] >= 0
        assert peers[0]["tags"]["wireS"] >= 0

        spans, _ = _req(p0, "GET", f"/debug/traces?trace={tid}")
        spans = spans["spans"]
        assert spans and all(s["traceID"] == tid for s in spans)
        by_id = {s["spanID"]: s for s in spans}
        # remote span summaries were piggybacked on the /internal/query
        # response and adopted into the coordinator's ring
        remote = [s for s in spans if s.get("remote")]
        assert remote, "no remote spans adopted by the coordinator"
        rpc = next(s for s in spans
                   if s["name"].startswith("cluster.rpc node1"))
        remote_root = next(s for s in remote
                           if s["name"].startswith("POST /internal/query"))
        # cross-node parent links: remote handler span parents under the
        # coordinator's rpc span; remote execution under the handler span
        assert remote_root["parentID"] == rpc["spanID"]
        remote_exec = next(s for s in remote
                           if s["name"] == "executor.execute")
        assert remote_exec["parentID"] == remote_root["spanID"]
        # and the whole chain roots at the public request span
        assert by_id[rpc["parentID"]]["name"] == "api.Query"
        root = next(s for s in spans if s["parentID"] is None)
        assert root["name"].startswith("POST /index/ci/query")
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
