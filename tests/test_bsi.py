"""Differential tests for BSI kernels against a naive dict oracle —
mirrors the reference's BSI coverage in fragment_internal_test.go
(SetValue/Sum/Min/Max/Range under every comparison op, negative values)."""

import numpy as np
import pytest

from pilosa_tpu.ops import bitset, bsi

WORDS = 256
NBITS = WORDS * 32
DEPTH = 16


def make(rng, n=300, lo=-5000, hi=5000, depth=DEPTH):
    cols = np.unique(rng.integers(0, NBITS, size=n))
    vals = rng.integers(lo, hi, size=cols.size)
    frag = bsi.pack_values(cols, vals, depth=depth, words=WORDS)
    return cols, vals, frag


def test_pack_unpack_roundtrip(rng):
    cols, vals, frag = make(rng)
    c2, v2 = bsi.unpack_values(frag)
    assert np.array_equal(c2, cols)
    assert np.array_equal(v2, vals)


OPS = {
    "eq": lambda v, p: v == p,
    "neq": lambda v, p: v != p,
    "lt": lambda v, p: v < p,
    "le": lambda v, p: v <= p,
    "gt": lambda v, p: v > p,
    "ge": lambda v, p: v >= p,
}


@pytest.mark.parametrize("op", list(OPS))
@pytest.mark.parametrize("pred", [-70000, -4999, -123, -1, 0, 1, 57, 4999, 70000])
def test_range_op(rng, op, pred):
    cols, vals, frag = make(rng)
    got = set(bitset.unpack_columns(np.asarray(bsi.range_op(frag, op, pred))).tolist())
    expect = {int(c) for c, v in zip(cols, vals) if OPS[op](v, pred)}
    assert got == expect


def test_range_op_zero_with_negative_zero_sign(rng):
    # A column whose magnitude is 0 but sign bit is set still holds value 0.
    frag = np.zeros((2 + 4, WORDS), dtype=np.uint32)
    frag[bsi.EXISTS_ROW, 0] = 0b1  # col 0 exists
    frag[bsi.SIGN_ROW, 0] = 0b1    # sign set, magnitude 0
    assert set(bitset.unpack_columns(
        np.asarray(bsi.range_op(frag, "eq", 0))).tolist()) == {0}
    assert set(bitset.unpack_columns(
        np.asarray(bsi.range_op(frag, "lt", 0))).tolist()) == set()
    assert set(bitset.unpack_columns(
        np.asarray(bsi.range_op(frag, "gt", -1))).tolist()) == {0}


def test_range_between(rng):
    cols, vals, frag = make(rng)
    got = set(bitset.unpack_columns(
        np.asarray(bsi.range_between(frag, -100, 250))).tolist())
    expect = {int(c) for c, v in zip(cols, vals) if -100 <= v <= 250}
    assert got == expect


def test_sum(rng):
    cols, vals, frag = make(rng)
    s, n = bsi.weighted_sum(np.asarray(bsi.sum_counts(frag)))
    assert s == int(vals.sum())
    assert n == cols.size


def test_sum_with_filter(rng):
    cols, vals, frag = make(rng)
    keep = cols[: cols.size // 2]
    filt = bitset.pack_columns(keep, words=WORDS)
    s, n = bsi.weighted_sum(np.asarray(bsi.sum_counts(frag, filt)))
    assert s == int(vals[: cols.size // 2].sum())
    assert n == keep.size


@pytest.mark.parametrize("want_max", [False, True])
def test_min_max(rng, want_max):
    cols, vals, frag = make(rng)
    out = bsi.min_max_bits(frag, want_max=want_max)
    val, cnt = bsi.reconstruct_min_max(*[np.asarray(x) for x in out])
    target = int(vals.max() if want_max else vals.min())
    assert val == target
    assert cnt == int((vals == target).sum())


@pytest.mark.parametrize("case", [
    [5, 7, 9], [-5, -7, -9], [-5, 0, 5], [0], [-3, -3, 8],
])
def test_min_max_small(case):
    cols = np.arange(len(case))
    vals = np.array(case)
    frag = bsi.pack_values(cols, vals, depth=8, words=WORDS)
    for want_max in (False, True):
        out = bsi.min_max_bits(frag, want_max=want_max)
        val, cnt = bsi.reconstruct_min_max(*[np.asarray(x) for x in out])
        target = max(case) if want_max else min(case)
        assert val == target, (case, want_max)
        assert cnt == case.count(target)


def test_min_max_with_filter(rng):
    cols = np.array([1, 2, 3, 4])
    vals = np.array([10, -20, 30, -40])
    frag = bsi.pack_values(cols, vals, depth=8, words=WORDS)
    filt = bitset.pack_columns(np.array([1, 3]), words=WORDS)
    out = bsi.min_max_bits(frag, filter_seg=filt, want_max=False)
    val, cnt = bsi.reconstruct_min_max(*[np.asarray(x) for x in out])
    assert (val, cnt) == (10, 1)
    out = bsi.min_max_bits(frag, filter_seg=filt, want_max=True)
    val, cnt = bsi.reconstruct_min_max(*[np.asarray(x) for x in out])
    assert (val, cnt) == (30, 1)


def test_pack_values_overflow_raises():
    with pytest.raises(ValueError):
        bsi.pack_values(np.array([0]), np.array([70000]), depth=16, words=WORDS)


def test_min_max_empty_returns_zero_count():
    frag = np.zeros((2 + 4, WORDS), dtype=np.uint32)
    out = bsi.min_max_bits(frag, want_max=False)
    val, cnt = bsi.reconstruct_min_max(*[np.asarray(x) for x in out])
    assert (val, cnt) == (0, 0)
