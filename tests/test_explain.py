"""Query EXPLAIN (utils/explain.py, docs/observability.md "Cluster
plane"): ?explain=true assembles the per-query decision record — plan
lowering (whole-query program signature cross-checked against the
launch ledger), cache outcomes, device launches — with answers
byte-identical to explain-off; slow-log entries carry the record; trace
exemplars on /metrics resolve at /debug/traces, which also gained
search by index/duration/status."""

import re
import time
import urllib.request

import pytest

from test_observability import _req, make_server


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    s = make_server(tmp_path_factory.mktemp("explain"),
                    result_cache_mb=16, slow_query_threshold=1e-9)
    p = s.port
    _req(p, "POST", "/index/ei", {})
    _req(p, "POST", "/index/ei/field/f", {})
    _req(p, "POST", "/index/ei/field/ranked",
         {"options": {"cacheType": "ranked", "cacheSize": 100}})
    _req(p, "POST", "/index/ei/query",
         "".join(f"Set({c}, f={r})" for r in range(4)
                 for c in range(0, 40, 3)))
    _req(p, "POST", "/index/ei/query",
         "".join(f"Set({c}, ranked={r})" for r in range(6)
                 for c in range(r * 7)))
    yield s
    s.close()


def test_explain_answers_byte_identical(srv):
    q = "Count(Intersect(Row(f=1), Row(f=2)))"
    plain, _ = _req(srv.port, "POST", "/index/ei/query", q)
    explained, _ = _req(srv.port, "POST", "/index/ei/query?explain=true",
                        q)
    assert explained["results"] == plain["results"]
    assert "explain" in explained
    assert "explain" not in plain
    # explain does not force the profile into the response
    assert "profile" not in explained


def test_explain_plan_names_program_sig_in_ledger(srv):
    out, _ = _req(srv.port, "POST", "/index/ei/query?explain=true",
                  "Count(Row(f=1))")
    plan = out["explain"]["plan"]
    assert plan[0]["mode"] == "wholequery"
    sig = plan[0]["program"]
    assert sig and sig.startswith("wholequery:")
    assert plan[0]["nodes"] == ["count"]
    # cross-check: the ledger recorded a launch under the SAME signature
    led, _ = _req(srv.port, "GET", "/debug/launches")
    assert any(e["sig"] == sig and e["kind"] == "wholequery"
               for e in led["entries"])


def test_explain_launches_assembled_from_profile(srv):
    out, _ = _req(srv.port, "POST", "/index/ei/query?explain=true",
                  "Count(Row(f=3))")
    launches = out["explain"]["launches"]
    assert launches, "launches section missing"
    ev = launches[0]
    assert ev["stage"] in ("device.launch", "batcher.launch")
    if ev["stage"] == "device.launch":
        assert "sig" in ev and "batchRows" in ev \
            and "decodeBytes" in ev


def test_explain_result_cache_outcomes(srv):
    q = "Count(Union(Row(f=0), Row(f=3)))"
    first, _ = _req(srv.port, "POST", "/index/ei/query?explain=true", q)
    second, _ = _req(srv.port, "POST", "/index/ei/query?explain=true", q)
    assert second["results"] == first["results"]

    def outcomes(resp):
        return [(c["cache"], c["outcome"])
                for c in resp["explain"].get("caches", [])]

    assert ("result", "miss") in outcomes(first)
    assert ("result", "hit") in outcomes(second)
    # key COMPONENTS are named, not an opaque blob
    entry = next(c for c in second["explain"]["caches"]
                 if c["cache"] == "result")
    assert entry["key"]["index"] == "ei"
    assert entry["key"]["shards"] >= 1


def test_explain_rank_cache_prune(srv):
    out, _ = _req(srv.port, "POST", "/index/ei/query?explain=true",
                  "TopN(ranked, n=3)")
    pairs = out["results"][0]
    assert [p["id"] for p in pairs] == [5, 4, 3]
    rank = [c for c in out["explain"].get("caches", [])
            if c["cache"] == "rank"]
    assert rank and rank[0]["outcome"] == "prune"
    assert rank[0]["candidates"] >= 3


def test_explain_legacy_mode_named_when_wholequery_off(tmp_path):
    s = make_server(tmp_path, name="legacy", whole_query=False,
                    slow_query_threshold=0)
    try:
        _req(s.port, "POST", "/index/li", {})
        _req(s.port, "POST", "/index/li/field/f", {})
        _req(s.port, "POST", "/index/li/query", "Set(1, f=1)")
        out, _ = _req(s.port, "POST", "/index/li/query?explain=true",
                      "Count(Row(f=1))")
        assert out["results"] == [1]
        modes = [p["mode"] for p in out["explain"]["plan"]]
        # the kill switch means NO whole-query program may be claimed;
        # the request ran prepared/legacy instead
        assert modes
        assert "wholequery" not in modes
        assert all(m.startswith(("legacy", "prepared")) for m in modes)
    finally:
        s.close()


def test_slow_log_entries_carry_explain(srv):
    _req(srv.port, "POST", "/index/ei/query", "Count(Row(f=1))")
    # post-response accounting: poll (the PR 11 deflake pattern)
    deadline = time.monotonic() + 5.0
    entries = []
    while not entries and time.monotonic() < deadline:
        slow, _ = _req(srv.port, "GET", "/debug/slow")
        entries = [e for e in slow["entries"] if e.get("index") == "ei"]
        if not entries:
            time.sleep(0.01)
    assert entries
    last = entries[-1]
    assert "explain" in last
    assert last["explain"]["plan"][0]["mode"] in (
        "wholequery", "legacy-grouped", "legacy-per-call")
    assert not last.get("textTruncated")


def test_slow_log_text_truncation_flag(tmp_path):
    s = make_server(tmp_path, name="trunc", slow_query_threshold=1e-9,
                    slow_log_text_max=16)
    try:
        _req(s.port, "POST", "/index/ti", {})
        _req(s.port, "POST", "/index/ti/field/f", {})
        long_q = "Count(Union(" + ", ".join(
            f"Row(f={i})" for i in range(40)) + "))"
        _req(s.port, "POST", "/index/ti/query", long_q)
        # the slow entry lands in post-response accounting: poll (the
        # PR 11 deflake pattern)
        deadline = time.monotonic() + 5.0
        entries = []
        while not entries and time.monotonic() < deadline:
            slow, _ = _req(s.port, "GET", "/debug/slow")
            entries = [x for x in slow["entries"]
                       if x.get("index") == "ti"]
            if not entries:
                time.sleep(0.01)
        assert slow["textMax"] == 16
        e = entries[-1]
        assert e["textTruncated"] is True
        assert len(e["query"]) == 16
    finally:
        s.close()


# -- trace exemplars + search ------------------------------------------------


def _raw(port, path, accept=None):
    r = urllib.request.Request(f"http://localhost:{port}{path}")
    if accept is not None:
        r.add_header("Accept", accept)
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.read().decode()


def test_metrics_exemplar_resolves_at_debug_traces(srv):
    _req(srv.port, "POST", "/index/ei/query", "Count(Row(f=1))")
    # exemplars attach in the handler's post-response accounting: poll
    # (the PR 11 deflake pattern).  They are OpenMetrics-only syntax,
    # served only on the explicit ?exemplars=true opt-in.
    rx = (r'pilosa_tpu_http_query_seconds_bucket\{le="[^"]+"\} \d+'
          r' # \{trace_id="([0-9a-f]+)"\} [0-9.e-]+ [0-9.]+')
    deadline = time.monotonic() + 5.0
    m = None
    while m is None and time.monotonic() < deadline:
        m = re.search(rx, _raw(srv.port, "/metrics?exemplars=true"))
        if m is None:
            time.sleep(0.02)
    assert m, "no exemplar on the http_query histogram"
    # a plain scrape — including one ADVERTISING OpenMetrics, as stock
    # Prometheus does by default — must NOT carry exemplars: a classic
    # 0.0.4 parser rejects the `# {...}` suffix and the scrape goes
    # dark, and this exposition's counter names predate the OpenMetrics
    # `_total` rule so answering the Accept with it would break too
    assert " # {trace_id=" not in _raw(srv.port, "/metrics")
    assert " # {trace_id=" not in _raw(
        srv.port, "/metrics",
        accept="application/openmetrics-text;version=1.0.0")
    tid = m.group(1)
    spans, _ = _req(srv.port, "GET", f"/debug/traces?trace={tid}")
    assert spans["spans"], f"exemplar trace {tid} did not resolve"
    assert all(s["traceID"] == tid for s in spans["spans"])


def test_debug_traces_search_by_index_duration_status(srv):
    _req(srv.port, "POST", "/index/ei/query", "Count(Row(f=1))")
    # the status tag is stamped by the handler's post-response
    # accounting — poll instead of read-once (the PR 11 deflake
    # pattern)
    deadline = time.monotonic() + 5.0
    t = None
    while time.monotonic() < deadline:
        got, _ = _req(srv.port, "GET", "/debug/traces?index=ei")
        if got["traces"] and got["traces"][0].get("status") == 200:
            t = got["traces"][0]
            break
        time.sleep(0.02)
    assert t is not None, "no completed root span matched index=ei"
    assert t["index"] == "ei" and t["status"] == 200
    assert t["traceID"] and t["spans"] >= 1
    # a trace id from the summary resolves to its full tree
    full, _ = _req(srv.port, "GET",
                   f"/debug/traces?trace={t['traceID']}")
    assert full["spans"]
    # duration filter: nothing took 10 minutes
    none, _ = _req(srv.port, "GET",
                   "/debug/traces?index=ei&minMs=600000")
    assert none["traces"] == []
    # unknown index matches nothing
    none2, _ = _req(srv.port, "GET", "/debug/traces?index=nope")
    assert none2["traces"] == []


def test_section_cap_bounds_construction_via_wants():
    """The SECTION_MAX cap must bound CONSTRUCTION, not just storage:
    wants() flips False at capacity (the router's per-shard gate), and
    over-cap notes land in the record's `truncated` count."""
    from pilosa_tpu.utils import explain as qexplain

    assert qexplain.wants("routing") is False  # no active record
    rec = qexplain.ExplainRecord()
    with qexplain.activate(rec):
        for i in range(qexplain.SECTION_MAX):
            assert qexplain.wants("routing")
            qexplain.note("routing", {"shard": i})
        assert qexplain.wants("routing") is False
        qexplain.note("routing", {"shard": -1})  # dropped, counted
    out = rec.to_dict()
    assert len(out["routing"]) == qexplain.SECTION_MAX
    assert out["truncated"]["routing"] == 1
