"""Native fingerprint scanner: exact equivalence with the Python regex.

The C scanner (native/fingerprint.c) sits in front of the prepared-
statement cache on every request; any divergence from the regex
(prepared._FP) would silently mis-key the cache or mis-extract literals,
so it is differential-fuzzed against the Python path (the same oracle
pattern as tests/test_fuzz.py; reference roaring/fuzzer.go:28).
"""

import numpy as np
import pytest

from pilosa_tpu.executor.prepared import _fingerprint_py, fingerprint
from pilosa_tpu.native import fingerprint_native


def _native_or_skip(q):
    out = fingerprint_native(q)
    if out is None and fingerprint_native("probe") is None:
        pytest.skip("native fingerprint library unavailable")
    return out


def test_native_builds_and_matches_basic():
    q = "Count(Row(stargazer=14)) TopN(language, Row(stars=-3), n=50)"
    nat = _native_or_skip(q)
    assert nat is not None
    t, v = nat
    pt, pv = _fingerprint_py(q)
    assert t == pt
    assert [int(x) for x in v] == pv


def test_native_quotes_timestamps_floats():
    cases = [
        "Row(f='ab12cd') Row(g=\"9\") Sum(Row(v > 123456), field=v)",
        "Range(v > 2017-01-01T00:00)",
        "Row(f=1.5) Row(g=field1) Row(h=1a2b)",
        "Set(100, f=2)",
        "Row(f='unterminated 12",
        "Row(f='esc\\'aped 7') Count(Row(g=8))",
    ]
    for q in cases:
        nat = _native_or_skip(q)
        assert nat is not None, q
        pt, pv = _fingerprint_py(q)
        assert nat[0] == pt, q
        assert [int(x) for x in nat[1]] == pv, q


def test_native_overflow_falls_back():
    q = "Row(x=99999999999999999999)"
    assert fingerprint_native(q) is None or \
        fingerprint_native("probe") is None
    # the public fingerprint() still answers via the regex path
    t, v = fingerprint(q)
    assert t == "Row(x=?)"
    assert list(v) == [99999999999999999999]


def test_overflow_literal_reaches_classic_path():
    """A >int64 literal must not blow up inside the prepared cache's
    int64 params coercion (r5 review: OverflowError escaped execute());
    it falls through to the classic path, which reports a clean query
    error."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pql.parser import ParseError
    from pilosa_tpu.storage import Holder

    h = Holder(None)
    idx = h.create_index("ovf", track_existence=False)
    idx.create_field("f")
    ex = Executor(h, use_mesh=True)
    with pytest.raises(ParseError):
        ex.execute("ovf", "Count(Row(f=99999999999999999999))")


def test_native_non_ascii_falls_back():
    # \w matches Unicode word chars in the regex; the byte-wise scanner
    # must decline rather than diverge
    assert fingerprint_native("Row(f=Ă 9)") is None


def test_native_differential_fuzz():
    if fingerprint_native("probe") is None:
        pytest.skip("native fingerprint library unavailable")
    rng = np.random.default_rng(11)
    alphabet = list("abzAZ019_.:-'\"\\()=<>, \tRow(stargazer=)Count")
    for _ in range(4000):
        n = int(rng.integers(0, 60))
        s = "".join(rng.choice(alphabet) for _ in range(n))
        py_t, py_v = _fingerprint_py(s)
        nat = fingerprint_native(s)
        assert nat is not None, s
        assert nat[0] == py_t, repr(s)
        assert [int(x) for x in nat[1]] == py_v, repr(s)
