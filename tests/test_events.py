"""Event journal (utils/events.py, docs/observability.md "Cluster
plane"): ring + cursor semantics, the framed on-disk log's torn-tail
recovery, emission from real state-transition sites (breaker,
backpressure, drain), the /debug/events endpoint, and the event-names
analyzer rule's two-way catalog check."""

import json
import os
import urllib.request

import pytest

from pilosa_tpu.utils.events import (EVENT_LOG_MAGIC, EVENTS,
                                     EventJournal)

from test_observability import _req, make_server


# -- ring + cursor -----------------------------------------------------------


def test_emit_seq_and_since_cursor():
    j = EventJournal(size=16)
    j.node_id = "nodeX"
    first = j.emit("breaker.open", host="h1", fails=5)
    assert first["seq"] == 1
    assert first["node"] == "nodeX"
    assert first["event"] == "breaker.open"
    for i in range(4):
        j.emit("node.down", peer=f"n{i}", reason="r")
    assert j.last_seq() == 5
    # cursor: strictly-after semantics, oldest first
    tail = j.since(1)
    assert [e["seq"] for e in tail] == [2, 3, 4, 5]
    assert j.since(5) == []
    # limit keeps the OLDEST entries: a cursor-advancing reader (the
    # fleet rollup) resumes losslessly from the last seq it folded,
    # instead of skipping the burst's middle forever
    lim = j.since(0, limit=2)
    assert [e["seq"] for e in lim] == [1, 2]
    assert [e["seq"] for e in j.since(2, limit=2)] == [3, 4]
    assert j.since(0, limit=0) == []


def test_ring_bound_and_resize():
    j = EventJournal(size=4)
    for i in range(10):
        j.emit("node.up", peer=f"n{i}")
    snap = j.snapshot()
    assert len(snap["events"]) == 4
    assert snap["emitted"] == 10
    assert [e["seq"] for e in snap["events"]] == [7, 8, 9, 10]
    j.resize(2)
    assert [e["seq"] for e in j.snapshot()["events"]] == [9, 10]
    # None-valued fields are dropped, not serialized as null
    e = j.emit("node.down", peer="n1", reason=None)
    assert "reason" not in e


def test_emit_never_raises_on_dead_log(tmp_path):
    j = EventJournal(size=8)
    j.open_log(str(tmp_path / "nodir" / "deeper" / "events.log"))
    assert j.write_errors == 1  # open failed, counted
    e = j.emit("server.drain", budgetS=1.0)  # ring still records
    assert e["seq"] == 1


# -- framed on-disk log ------------------------------------------------------


def test_log_round_trip_and_reopen(tmp_path):
    path = str(tmp_path / "events.log")
    j = EventJournal(size=8)
    j.open_log(path)
    j.emit("breaker.open", host="h", fails=3)
    j.emit("breaker.close", host="h")
    j.close_log()
    got = EventJournal.read_log(path)
    assert [e["event"] for e in got] == ["breaker.open", "breaker.close"]
    assert got[0]["fails"] == 3
    # reopen appends after the existing frames
    j2 = EventJournal(size=8)
    j2.open_log(path)
    j2.emit("node.up", peer="n2")
    j2.close_log()
    assert [e["event"] for e in EventJournal.read_log(path)] == \
        ["breaker.open", "breaker.close", "node.up"]


def test_log_torn_tail_truncates_at_frame_boundary(tmp_path):
    path = str(tmp_path / "events.log")
    j = EventJournal(size=8)
    j.open_log(path)
    j.emit("node.down", peer="a", reason="x")
    j.emit("node.up", peer="a")
    j.close_log()
    whole = os.path.getsize(path)
    # tear mid-frame: drop the last 3 bytes of the final frame
    with open(path, "r+b") as f:
        f.truncate(whole - 3)
    j2 = EventJournal(size=8)
    j2.open_log(path)
    j2.emit("server.drain", budgetS=2.0)
    j2.close_log()
    events = EventJournal.read_log(path)
    # the torn second frame is gone; the valid prefix + new frame remain
    assert [e["event"] for e in events] == ["node.down", "server.drain"]


def test_log_corrupt_byte_truncates(tmp_path):
    path = str(tmp_path / "events.log")
    j = EventJournal(size=8)
    j.open_log(path)
    j.emit("node.down", peer="a", reason="x")
    j.emit("node.up", peer="a")
    j.close_log()
    data = open(path, "rb").read()
    # flip one payload byte of frame 2 -> CRC mismatch -> truncate there
    flip_at = len(data) - 4
    with open(path, "r+b") as f:
        f.seek(flip_at)
        b = f.read(1)
        f.seek(flip_at)
        f.write(bytes([b[0] ^ 0xFF]))
    assert [e["event"] for e in EventJournal.read_log(path)] == \
        ["node.down"]
    j2 = EventJournal(size=8)
    j2.open_log(path)  # truncates the bad tail durably
    j2.close_log()
    assert os.path.getsize(path) < len(data)
    data2 = open(path, "rb").read()
    assert data2.startswith(EVENT_LOG_MAGIC)


def test_garbage_file_rewritten(tmp_path):
    path = str(tmp_path / "events.log")
    with open(path, "wb") as f:
        f.write(b"not an event log at all")
    j = EventJournal(size=8)
    j.open_log(path)
    j.emit("node.up", peer="z")
    j.close_log()
    assert [e["event"] for e in EventJournal.read_log(path)] == \
        ["node.up"]


# -- real emission sites -----------------------------------------------------


def test_breaker_transitions_emit_events():
    from pilosa_tpu.parallel.cluster import CircuitOpenError, InternalClient
    seq0 = EVENTS.last_seq()
    c = InternalClient(breaker_threshold=2)
    try:
        for _ in range(2):
            c._breaker_failure("hostA:1")
        names = [e["event"] for e in EVENTS.since(seq0)]
        assert "breaker.open" in names
        # open breaker: fail fast, no new transition event
        with pytest.raises(CircuitOpenError):
            c._breaker_allow("hostA:1")
        # the probe's trial admission is the half-open transition
        c._breaker_allow("hostA:1", trial=True)
        c._breaker_success("hostA:1")
        names = [e["event"] for e in EVENTS.since(seq0)]
        assert names.count("breaker.open") == 1
        assert "breaker.half_open" in names
        assert "breaker.close" in names
    finally:
        c.close()


def test_backpressure_engage_release_events(tmp_path):
    from pilosa_tpu.ingest.committer import GroupCommitter
    from pilosa_tpu.storage import Holder
    holder = Holder(str(tmp_path / "h"))
    holder.open()
    try:
        com = GroupCommitter(holder, flush_ms=0, high_water_bytes=64)
        seq0 = EVENTS.last_seq()
        idx = holder.create_index("i")
        idx.create_field("f")
        com.submit("i", "f", rows=list(range(16)), cols=list(range(16)))
        assert com.wait_capacity(timeout=0.01) is False  # over high-water
        names = [e["event"] for e in EVENTS.since(seq0)]
        assert names.count("ingest.backpressure_engage") == 1
        # second refusal in the same episode: no duplicate engage event
        assert com.wait_capacity(timeout=0.01) is False
        names = [e["event"] for e in EVENTS.since(seq0)]
        assert names.count("ingest.backpressure_engage") == 1
        com.wait_flushed(com._submit_seq)  # inline flush drains it
        names = [e["event"] for e in EVENTS.since(seq0)]
        assert "ingest.backpressure_release" in names
        com.close()
    finally:
        holder.close()


def test_server_drain_event_and_debug_events_endpoint(tmp_path):
    srv = make_server(tmp_path, slow_query_threshold=0)
    p = srv.port
    try:
        seq0 = EVENTS.last_seq()
        EVENTS.emit("node.up", peer="synthetic")
        out, _ = _req(p, "GET", f"/debug/events?since={seq0}")
        assert [e["event"] for e in out["events"]] == ["node.up"]
        assert out["seq"] >= seq0 + 1
        # no cursor: full snapshot shape
        full, _ = _req(p, "GET", "/debug/events")
        assert full["size"] == srv.config.event_journal_size
        assert any(e["event"] == "node.up" for e in full["events"])
        # limit applies
        lim, _ = _req(p, "GET", "/debug/events?limit=1")
        assert len(lim["events"]) == 1
    finally:
        seq1 = EVENTS.last_seq()
        srv.close()
    assert any(e["event"] == "server.drain"
               for e in EVENTS.since(seq1 - 1))


def test_event_log_knob_persists_across_restart(tmp_path):
    srv = make_server(tmp_path, name="n", event_log=True,
                      slow_query_threshold=0)
    try:
        EVENTS.emit("node.down", peer="x", reason="test")
        path = os.path.join(os.path.expanduser(srv.config.data_dir),
                            "events.log")
        assert os.path.exists(path)
    finally:
        srv.close()
    events = EventJournal.read_log(path)
    assert any(e["event"] == "node.down" and e["peer"] == "x"
               for e in events)
    assert any(e["event"] == "server.drain" for e in events)


# -- event-names analyzer rule ----------------------------------------------


CATALOG_DOC = """# obs
<!-- events-catalog:begin -->
| event | fields | meaning |
|---|---|---|
| `breaker.open` | `host` | x |
<!-- events-catalog:end -->
"""


def _run_event_rule(tmp_path, code, doc=CATALOG_DOC):
    from pilosa_tpu.analysis.astlint import run as lint_run
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(code)
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "observability.md").write_text(doc)
    findings = lint_run(tmp_path, rule_ids=["event-names"])
    return [f.message for f in findings]


def test_event_names_rule_flags_uncataloged_emit(tmp_path):
    msgs = _run_event_rule(
        tmp_path,
        "from .utils import events\n"
        "events.emit('breaker.open', host='h')\n"
        "events.emit('breaker.opeen', host='h')\n")
    assert any("breaker.opeen" in m for m in msgs)
    assert not any("'breaker.open'" in m for m in msgs)


def test_event_names_rule_flags_dangling_row(tmp_path):
    msgs = _run_event_rule(
        tmp_path,
        "from .utils import events\n"
        "events.emit('breaker.open', host='h')\n",
        doc=CATALOG_DOC.replace(
            "| `breaker.open` | `host` | x |",
            "| `breaker.open` | `host` | x |\n"
            "| `ghost.event` | | never emitted |"))
    assert any("ghost.event" in m for m in msgs)


def test_event_names_rule_clean_on_match(tmp_path):
    msgs = _run_event_rule(
        tmp_path,
        "from .utils import events\n"
        "events.emit('breaker.open', host='h')\n")
    assert msgs == []
