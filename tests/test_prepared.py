"""Differential tests for the prepared-statement cache (executor/prepared).

Every test drives the SAME query text through (a) a mesh executor whose
prepared cache serves repeats and (b) a fresh classic executor with the
cache disabled, asserting identical results — the analog of the kernel
suite's numpy-oracle differential strategy (SURVEY.md §5.2), applied to
the statement-cache layer where a stale or mis-guarded replay would be a
silent wrong answer.
"""

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.prepared import fingerprint
from pilosa_tpu.storage import FieldOptions, Holder


@pytest.fixture(scope="module")
def holder():
    rng = np.random.default_rng(3)
    h = Holder(None)
    idx = h.create_index("prep", track_existence=True)
    f = idx.create_field("f")
    n = 20_000
    rows = rng.integers(0, 16, size=n)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=n)
    f.import_bits(rows, cols)
    idx.add_existence(cols)
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    vcols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, size=5000))
    vvals = rng.integers(-500, 501, size=vcols.size)
    v.import_values(vcols, vvals)
    idx.add_existence(vcols)
    return h


@pytest.fixture(scope="module")
def cached(holder):
    return Executor(holder, use_mesh=True)


@pytest.fixture()
def classic(holder):
    ex = Executor(holder, use_mesh=True)
    ex.prepared = None  # same mesh engine, no statement cache
    return ex


def test_fingerprint_literals():
    from pilosa_tpu.executor.prepared import fingerprint_spans
    q = "Count(Row(f=14)) Row(v > -3) TopN(f, n=50, ids=[1,2])"
    t, vals = fingerprint(q)
    assert t == "Count(Row(f=?)) Row(v > ?) TopN(f, n=?, ids=[?,?])"
    assert vals == [14, -3, 50, 1, 2]
    assert len(fingerprint_spans(q)) == 5


def test_fingerprint_preserves_strings_timestamps_and_words():
    q = ("Row(f=7, from='2017-01-01T00:00', to=2018-06-02T11:30) "
         "Set('k9', f=3) Count(Row(g1=1a2b)) Row(x=1.5)")
    t, vals = fingerprint(q)
    assert "'2017-01-01T00:00'" in t
    assert "2018-06-02T11:30" in t
    assert "'k9'" in t
    assert "1a2b" in t
    assert "1.5" in t
    assert vals == [7, 3]


def _check(cached, classic, queries):
    """Same template, varying literals: first query populates the cache,
    the rest replay it; classic executor must agree on every one."""
    for q in queries:
        assert cached.execute("prep", q) == classic.execute("prep", q), q


def test_count_row_replay(cached, classic):
    _check(cached, classic,
           [f"Count(Row(f={r}))" for r in (1, 5, 0, 15, 9, 400)])
    assert cached.prepared.hits > 0


def test_multi_call_batch_replay(cached, classic):
    rng = np.random.default_rng(11)
    qs = []
    for _ in range(3):
        rows = rng.integers(0, 16, size=8)
        qs.append(" ".join(
            f"Count(Intersect(Row(f={a}), Row(f={b})))"
            for a, b in zip(rows[::2], rows[1::2])))
    _check(cached, classic, qs)


def test_bsi_regime_guards(cached, classic):
    # values crossing every _resolve_bsi branch: normal, clamp, fast-path
    # notnull, out-of-range empty, sign flip, zero
    vals = [5, -5, 0, 499, 500, 501, -499, -500, -501, 1000, -1000,
            2000, 100000]
    _check(cached, classic, [f"Count(Row(v > {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v < {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v == {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v != {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v >= {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v <= {x}))" for x in vals])


def test_between_guards(cached, classic):
    pairs = [(0, 10), (-10, 10), (-500, 500), (-501, 501), (-2000, -600),
             (600, 2000), (5, 5), (490, 510), (-510, -490)]
    _check(cached, classic,
           [f"Count(Row({lo} <= v <= {hi}))" for lo, hi in pairs])
    _check(cached, classic,
           [f"Count(Row({lo} < v < {hi}))" for lo, hi in pairs])


def test_sum_and_topn_replay(cached, classic):
    _check(cached, classic,
           [f"Sum(Row(v > {x}), field=v)" for x in (0, 100, -100, 499)])
    _check(cached, classic,
           [f"TopN(f, Row(v > {x}), n=5)" for x in (0, 50, -50)])
    # structural literal (n) change -> equality guard miss -> still correct
    _check(cached, classic, ["TopN(f, Row(v > 10), n=3)"])


def test_row_id_beyond_capacity(cached, classic):
    _check(cached, classic, ["Count(Row(f=2))", "Count(Row(f=500000))"])


def test_epoch_invalidation(cached, classic, holder):
    q = "Count(Row(f=3))"
    assert cached.execute("prep", q) == classic.execute("prep", q)
    # DDL bumps the schema epoch; the entry must not be replayed stale
    holder.index("prep").create_field("tmp_epoch")
    holder.index("prep").delete_field("tmp_epoch")
    assert cached.execute("prep", q) == classic.execute("prep", q)


def test_writes_not_cached(cached, holder):
    q = "Set(999999, f=2)"
    cached.execute("prep", q)
    assert (("prep", fingerprint(q)[0]) not in
            [k for k, v in cached.prepared._entries.items()
             if not isinstance(v, str)])
    # the write actually landed and reads observe it
    assert cached.execute("prep", "Count(Row(f=2))")[0] == \
        cached.execute("prep", "Count(Row(f = 2))")[0]
    holder.field("prep", "f").clear_bit(2, 999999)


def test_mutation_invalidates_results_not_plan(cached, classic, holder):
    """A Set between two identical-template queries must be visible —
    the plan replays but the data path re-reads the fragments."""
    q = "Count(Row(f=6))"
    before = cached.execute("prep", q)[0]
    col = 3 * SHARD_WIDTH - 7  # within existing shards
    changed = holder.field("prep", "f").set_bit(6, col)
    after = cached.execute("prep", q)[0]
    assert after == before + (1 if changed else 0)
    assert cached.execute("prep", q) == classic.execute("prep", q)
    holder.field("prep", "f").clear_bit(6, col)


def test_conditional_both_bounds_dynamic(cached, classic):
    qs = ["Count(Row(4 <= v < 9))", "Count(Row(-3 <= v < 100))",
          "Count(Row(0 <= v < 1))"]
    _check(cached, classic, qs)
