"""Differential tests for the prepared-statement cache (executor/prepared).

Every test drives the SAME query text through (a) a mesh executor whose
prepared cache serves repeats and (b) a fresh classic executor with the
cache disabled, asserting identical results — the analog of the kernel
suite's numpy-oracle differential strategy (SURVEY.md §5.2), applied to
the statement-cache layer where a stale or mis-guarded replay would be a
silent wrong answer.
"""

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.prepared import fingerprint
from pilosa_tpu.storage import FieldOptions, Holder


@pytest.fixture(scope="module")
def holder():
    rng = np.random.default_rng(3)
    h = Holder(None)
    idx = h.create_index("prep", track_existence=True)
    f = idx.create_field("f")
    n = 20_000
    rows = rng.integers(0, 16, size=n)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=n)
    f.import_bits(rows, cols)
    idx.add_existence(cols)
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    vcols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, size=5000))
    vvals = rng.integers(-500, 501, size=vcols.size)
    v.import_values(vcols, vvals)
    idx.add_existence(vcols)
    # second int field with a different base offset (min) — multi-group
    # Sum queries must keep each group's base (late-binding regression)
    w = idx.create_field("w", FieldOptions(type="int", min=1000, max=2000))
    w.import_values(vcols, rng.integers(1000, 2001, size=vcols.size))
    return h


@pytest.fixture(scope="module")
def cached(holder):
    return Executor(holder, use_mesh=True)


@pytest.fixture()
def classic(holder):
    ex = Executor(holder, use_mesh=True)
    ex.prepared = None  # same mesh engine, no statement cache
    return ex


def test_fingerprint_literals():
    from pilosa_tpu.executor.prepared import fingerprint_spans
    q = "Count(Row(f=14)) Row(v > -3) TopN(f, n=50, ids=[1,2])"
    t, vals = fingerprint(q)
    assert t == "Count(Row(f=?)) Row(v > ?) TopN(f, n=?, ids=[?,?])"
    assert vals == [14, -3, 50, 1, 2]
    assert len(fingerprint_spans(q)) == 5


def test_fingerprint_preserves_strings_timestamps_and_words():
    q = ("Row(f=7, from='2017-01-01T00:00', to=2018-06-02T11:30) "
         "Set('k9', f=3) Count(Row(g1=1a2b)) Row(x=1.5)")
    t, vals = fingerprint(q)
    assert "'2017-01-01T00:00'" in t
    assert "2018-06-02T11:30" in t
    assert "'k9'" in t
    assert "1a2b" in t
    assert "1.5" in t
    assert vals == [7, 3]


def _check(cached, classic, queries):
    """Same template, varying literals: first query populates the cache,
    the rest replay it; classic executor must agree on every one."""
    for q in queries:
        assert cached.execute("prep", q) == classic.execute("prep", q), q


def test_count_row_replay(cached, classic):
    _check(cached, classic,
           [f"Count(Row(f={r}))" for r in (1, 5, 0, 15, 9, 400)])
    assert cached.prepared.hits > 0


def test_multi_call_batch_replay(cached, classic):
    rng = np.random.default_rng(11)
    qs = []
    for _ in range(3):
        rows = rng.integers(0, 16, size=8)
        qs.append(" ".join(
            f"Count(Intersect(Row(f={a}), Row(f={b})))"
            for a, b in zip(rows[::2], rows[1::2])))
    _check(cached, classic, qs)


def test_bsi_regime_guards(cached, classic):
    # values crossing every _resolve_bsi branch: normal, clamp, fast-path
    # notnull, out-of-range empty, sign flip, zero
    vals = [5, -5, 0, 499, 500, 501, -499, -500, -501, 1000, -1000,
            2000, 100000]
    _check(cached, classic, [f"Count(Row(v > {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v < {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v == {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v != {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v >= {x}))" for x in vals])
    _check(cached, classic, [f"Count(Row(v <= {x}))" for x in vals])


def test_between_guards(cached, classic):
    pairs = [(0, 10), (-10, 10), (-500, 500), (-501, 501), (-2000, -600),
             (600, 2000), (5, 5), (490, 510), (-510, -490)]
    _check(cached, classic,
           [f"Count(Row({lo} <= v <= {hi}))" for lo, hi in pairs])
    _check(cached, classic,
           [f"Count(Row({lo} < v < {hi}))" for lo, hi in pairs])


def test_sum_and_topn_replay(cached, classic):
    _check(cached, classic,
           [f"Sum(Row(v > {x}), field=v)" for x in (0, 100, -100, 499)])
    _check(cached, classic,
           [f"TopN(f, Row(v > {x}), n=5)" for x in (0, 50, -50)])
    # structural literal (n) change -> equality guard miss -> still correct
    _check(cached, classic, ["TopN(f, Row(v > 10), n=3)"])


def test_row_id_beyond_capacity(cached, classic):
    _check(cached, classic, ["Count(Row(f=2))", "Count(Row(f=500000))"])


def test_epoch_invalidation(cached, classic, holder):
    q = "Count(Row(f=3))"
    assert cached.execute("prep", q) == classic.execute("prep", q)
    # DDL bumps the schema epoch; the entry must not be replayed stale
    holder.index("prep").create_field("tmp_epoch")
    holder.index("prep").delete_field("tmp_epoch")
    assert cached.execute("prep", q) == classic.execute("prep", q)


def test_writes_not_cached(cached, holder):
    q = "Set(999999, f=2)"
    cached.execute("prep", q)
    assert (("prep", fingerprint(q)[0]) not in
            [k for k, v in cached.prepared._entries.items()
             if not isinstance(v, str)])
    # the write actually landed and reads observe it
    assert cached.execute("prep", "Count(Row(f=2))")[0] == \
        cached.execute("prep", "Count(Row(f = 2))")[0]
    holder.field("prep", "f").clear_bit(2, 999999)


def test_mutation_invalidates_results_not_plan(cached, classic, holder):
    """A Set between two identical-template queries must be visible —
    the plan replays but the data path re-reads the fragments."""
    q = "Count(Row(f=6))"
    before = cached.execute("prep", q)[0]
    col = 3 * SHARD_WIDTH - 7  # within existing shards
    changed = holder.field("prep", "f").set_bit(6, col)
    after = cached.execute("prep", q)[0]
    assert after == before + (1 if changed else 0)
    assert cached.execute("prep", q) == classic.execute("prep", q)
    holder.field("prep", "f").clear_bit(6, col)


def test_conditional_both_bounds_dynamic(cached, classic):
    qs = ["Count(Row(4 <= v < 9))", "Count(Row(-3 <= v < 100))",
          "Count(Row(0 <= v < 1))"]
    _check(cached, classic, qs)


def test_chunked_batch_dispatch(holder, classic, monkeypatch):
    """A batch larger than the dispatch chunk must split into multiple
    padded power-of-two dispatches (bounding per-dispatch HBM gather
    temps) and still return per-call-exact results, on both the prepared
    and the classic grouped paths."""
    from pilosa_tpu.executor import executor as exmod

    # shrink the temp budget so chunking kicks in at tiny B: with P=2 and
    # 2 shards over the 8-device test mesh (1 stacked shard per device),
    # chunk = budget / (2*1*SHARD_WORDS*4) = 16 rows per dispatch
    monkeypatch.setattr(exmod, "BATCH_TEMP_BYTES", 2 * 2 * 32768 * 4 * 8)
    monkeypatch.setattr(exmod, "BATCH_CHUNK_MIN", 1)

    rng = np.random.default_rng(11)
    pairs = [(int(a), int(b))
             for a, b in zip(rng.integers(0, 16, size=21),
                             rng.integers(0, 16, size=21))]
    q = " ".join(f"Count(Intersect(Row(f={a}), Row(f={b})))"
                 for a, b in pairs)

    ex = Executor(holder, use_mesh=True)  # fresh prepared cache
    build = ex.execute("prep", q)          # miss -> prepare -> chunked run
    hit = ex.execute("prep", q)            # prepared-hit chunked run
    grouped = classic.execute("prep", q)   # classic grouped chunked run
    percall = [classic.execute("prep",
                               f"Count(Intersect(Row(f={a}), Row(f={b})))")[0]
               for a, b in pairs]
    assert build == hit == grouped == percall
    ex.close()


def test_batch_chunks_padding():
    from pilosa_tpu.executor.executor import _batch_chunks

    mat = np.arange(42, dtype=np.int64).reshape(21, 2)
    chunks = list(_batch_chunks(mat, n_shards=1))
    # default budget: no split at this size, padded to 32
    assert [(lo, n) for lo, n, _ in chunks] == [(0, 21)]
    assert chunks[0][2].shape == (32, 2)
    # padding repeats the last real row (always in-range row ids)
    assert (chunks[0][2][21:] == mat[20]).all()


def test_multi_group_sum_bases(cached, classic):
    """Two Sum groups with different base offsets in ONE query: each
    group's finalizer must use its own base (a free-variable _sum_fin
    late-bound across groups once computed every group with the last
    group's base)."""
    _check(cached, classic,
           ["Sum(Row(f=1), field=v) Sum(Row(f=2), field=v)"
            " Sum(Row(f=1), field=w) Sum(Row(f=2), field=w)",
            "Sum(Row(f=3), field=v) Sum(Row(f=4), field=v)"
            " Sum(Row(f=3), field=w) Sum(Row(f=4), field=w)"])


def test_topn_per_call_n_and_ids(cached, classic):
    """TopN calls sharing one group (same field, same filter shape) but
    different n / ids must keep their own values on the prepared path —
    the group key omits n/ids."""
    _check(cached, classic,
           ["TopN(f, n=2) TopN(f, n=5)",
            "TopN(f, n=3) TopN(f, n=7)",
            "TopN(f, ids=[1,2], n=0) TopN(f, ids=[3], n=0)"])
    # and sanity: the two calls really do return different lengths
    out = cached.execute("prep", "TopN(f, n=2) TopN(f, n=5)")
    assert len(out[0]) == 2 and len(out[1]) == 5
