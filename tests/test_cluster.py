"""Multi-node cluster tests via the in-process harness (reference
test/pilosa.go:243-330 test.Cluster — N real servers in one process wired
through real HTTP on localhost ephemeral ports).

Covers: DDL broadcast, shard-grouped query fan-out with reduce
(Intersect/Count/TopN/Sum/GroupBy/Rows), replica write fan-out, import
regroup/forward, node-down degradation with replica retry, and a basic
anti-entropy repair pass."""

import json
import socket
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.server.server import Config, Server


def _free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def make_cluster(tmp_path, n=3, replica_n=2):
    ports = _free_ports(n)
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        cfg = Config(
            data_dir=str(tmp_path / f"node{i}"),
            bind=f"localhost:{p}",
            node_id=f"node{i}",
            cluster_hosts=hosts,
            replica_n=replica_n,
            anti_entropy_interval=0,  # driven manually in tests
        )
        srv = Server(cfg)
        srv.open()
        servers.append(srv)
    return servers


@pytest.fixture
def cluster3(tmp_path):
    servers = make_cluster(tmp_path, n=3, replica_n=2)
    yield servers
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


def _req(port, method, path, data=None):
    body = None
    if data is not None:
        body = data.encode() if isinstance(data, str) else json.dumps(
            data).encode()
    r = urllib.request.Request(
        f"http://localhost:{port}{path}", method=method, data=body)
    # explicit timeout: urllib's default is infinite, and one hung
    # request would wedge the whole suite (observed once under heavy
    # concurrent load)
    with urllib.request.urlopen(r, timeout=180) as resp:
        return json.loads(resp.read())


def query(port, index, pql):
    return _req(port, "POST", f"/index/{index}/query", pql)["results"]


def setup_index(servers, name="ci"):
    p = servers[0].port
    _req(p, "POST", f"/index/{name}", {})
    _req(p, "POST", f"/index/{name}/field/f", {})
    _req(p, "POST", f"/index/{name}/field/v",
         {"options": {"type": "int", "min": 0, "max": 1000}})
    return name


def test_ddl_broadcast(cluster3):
    setup_index(cluster3)
    # schema visible on every node without any query traffic
    for srv in cluster3:
        schema = _req(srv.port, "GET", "/schema")["indexes"]
        assert [i["name"] for i in schema] == ["ci"]
        fields = {f["name"] for f in schema[0]["fields"]}
        assert {"f", "v"} <= fields


def test_status_reports_nodes(cluster3):
    st = _req(cluster3[0].port, "GET", "/status")
    assert st["state"] == "NORMAL"
    assert len(st["nodes"]) == 3
    assert st["nodes"][0]["isCoordinator"]


def test_import_and_distributed_queries(cluster3):
    setup_index(cluster3)
    rng = np.random.default_rng(7)
    n_shards = 6
    cols = rng.choice(n_shards * SHARD_WIDTH, size=3000, replace=False)
    rows = rng.integers(0, 8, size=3000)
    vals = rng.integers(0, 1000, size=1500)

    p0 = cluster3[0].port
    _req(p0, "POST", "/index/ci/field/f/import",
         {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    _req(p0, "POST", "/index/ci/field/v/import",
         {"columnIDs": cols[:1500].tolist(), "values": vals.tolist()})

    # oracle
    by_row = {r: set(cols[rows == r].tolist()) for r in range(8)}

    # every node answers identically (fan-out + reduce from any node)
    for srv in cluster3:
        [count] = query(srv.port, "ci", "Count(Row(f=3))")
        assert count == len(by_row[3])

    [cols_out] = query(cluster3[1].port, "ci", "Row(f=1)")
    assert set(cols_out["columns"]) == by_row[1]

    [inter] = query(cluster3[2].port, "ci",
                    "Count(Intersect(Row(f=1), Row(f=2)))")
    assert inter == len(by_row[1] & by_row[2])

    [topn] = query(cluster3[0].port, "ci", "TopN(f, n=3)")
    exact = sorted(((len(v), -r) for r, v in by_row.items()), reverse=True)
    assert [(p["count"]) for p in topn] == [c for c, _ in exact[:3]]

    [s] = query(cluster3[1].port, "ci", "Sum(field=v)")
    assert s["value"] == int(vals.sum())

    [rws] = query(cluster3[2].port, "ci", "Rows(f)")
    assert rws["rows"] == sorted(by_row)


def test_batched_multicall_matches_per_call(cluster3):
    """A multi-call read query rides ONE pinned multi-call POST per node
    (cluster._execute_calls_batched) — answers must be identical to the
    per-call fan-out, including bounded-TopN two-phase results."""
    setup_index(cluster3)
    rng = np.random.default_rng(13)
    n_shards = 6
    cols = rng.choice(n_shards * SHARD_WIDTH, size=4000, replace=False)
    rows = rng.integers(0, 8, size=4000)
    vals = rng.integers(0, 1000, size=2000)
    p0 = cluster3[0].port
    _req(p0, "POST", "/index/ci/field/f/import",
         {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    _req(p0, "POST", "/index/ci/field/v/import",
         {"columnIDs": cols[:2000].tolist(), "values": vals.tolist()})

    multi = ("Count(Row(f=1)) TopN(f, n=3) Sum(Row(f=2), field=v) "
             "Count(Intersect(Row(f=3), Row(f=4))) Rows(f) Row(f=5)")
    batched = query(p0, "ci", multi)
    # per-call ground truth through the same cluster
    singles = []
    for q in ("Count(Row(f=1))", "TopN(f, n=3)",
              "Sum(Row(f=2), field=v)",
              "Count(Intersect(Row(f=3), Row(f=4)))", "Rows(f)",
              "Row(f=5)"):
        singles.append(query(cluster3[1].port, "ci", q)[0])
    assert batched == singles
    # every node agrees (coordinator or not)
    assert query(cluster3[2].port, "ci", multi) == singles
    # the breakdown instrumentation populated
    snap = _req(p0, "GET", "/debug/vars")
    assert "cluster.multi.peer_exec" in snap["timings"]
    assert "cluster.multi.reduce" in snap["timings"]


def test_batched_multicall_replica_retry(cluster3):
    """Batched fan-out keeps the per-call path's replica retry: killing a
    node mid-cluster must not change multi-call answers."""
    setup_index(cluster3)
    p0 = cluster3[0].port
    query(p0, "ci", "Set(5, f=1) Set(300000, f=1) Set(2097200, f=2)")
    want = query(p0, "ci", "Count(Row(f=1)) Count(Row(f=2)) TopN(f, n=2)")
    cluster3[2].close()
    cluster3[0].cluster.probe_peers()
    got = query(p0, "ci", "Count(Row(f=1)) Count(Row(f=2)) TopN(f, n=2)")
    assert got == want


def test_replica_write_fanout(cluster3):
    setup_index(cluster3)
    # write through a NON-owner node: must reach all replicas of the shard
    col = 3 * SHARD_WIDTH + 17
    [changed] = query(cluster3[1].port, "ci", f"Set({col}, f=5)")
    assert changed is True

    cl = cluster3[0].cluster
    owners = cl.placement.shard_nodes("ci", 3)
    assert len(owners) == 2
    for srv in cluster3:
        nid = srv.cluster.node_id
        frag = srv.holder.fragment("ci", "f", "standard", 3)
        if nid in owners:
            assert frag is not None, f"{nid} owns shard 3 but has no data"
            assert col % SHARD_WIDTH in frag.row_columns(5)
        else:
            assert frag is None or col % SHARD_WIDTH not in \
                frag.row_columns(5)

    # every node sees the bit through queries regardless of placement
    for srv in cluster3:
        [cnt] = query(srv.port, "ci", "Count(Row(f=5))")
        assert cnt == 1


def test_store_and_clearrow_cluster_wide(cluster3):
    setup_index(cluster3)
    cols = [10, SHARD_WIDTH + 5, 4 * SHARD_WIDTH + 2]
    for c in cols:
        query(cluster3[0].port, "ci", f"Set({c}, f=1)")
    [ok] = query(cluster3[1].port, "ci", "Store(Row(f=1), f=9)")
    assert ok is True
    [out] = query(cluster3[2].port, "ci", "Row(f=9)")
    assert set(out["columns"]) == set(cols)
    [ok] = query(cluster3[0].port, "ci", "ClearRow(f=9)")
    assert ok is True
    [cnt] = query(cluster3[1].port, "ci", "Count(Row(f=9))")
    assert cnt == 0


def test_node_down_replica_retry(cluster3):
    setup_index(cluster3)
    rng = np.random.default_rng(11)
    cols = rng.choice(4 * SHARD_WIDTH, size=1000, replace=False)
    rows = rng.integers(0, 4, size=1000)
    _req(cluster3[0].port, "POST", "/index/ci/field/f/import",
         {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    expect = int((rows == 2).sum())

    [cnt] = query(cluster3[0].port, "ci", "Count(Row(f=2))")
    assert cnt == expect

    # kill node2; with ReplicaN=2 every shard still has a live owner
    cluster3[2].close()
    cluster3[0].cluster.probe_peers()
    assert cluster3[0].cluster.state == "DEGRADED"

    [cnt] = query(cluster3[0].port, "ci", "Count(Row(f=2))")
    assert cnt == expect
    [topn] = query(cluster3[0].port, "ci", "TopN(f, n=2)")
    assert len(topn) == 2


def test_options_wrapped_aggregates_reduce_correctly(cluster3):
    """Options(...) must reduce by its CHILD call's semantics across
    nodes: Count sums, Sum adds, TopN merges with n applied globally."""
    setup_index(cluster3)
    rng = np.random.default_rng(11)
    cols = rng.choice(6 * SHARD_WIDTH, size=1200, replace=False)
    rows = rng.integers(0, 4, size=1200)
    p0 = cluster3[0].port
    _req(p0, "POST", "/index/ci/field/f/import",
         {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    _req(p0, "POST", "/index/ci/field/v/import",
         {"columnIDs": cols.tolist(),
          "values": [int(v) for v in rng.integers(0, 1000, size=1200)]})
    for srv in cluster3:
        [plain] = query(srv.port, "ci", "Count(Row(f=1))")
        [wrapped] = query(srv.port, "ci", "Options(Count(Row(f=1)))")
        assert wrapped == plain == int((rows == 1).sum())
        [s_plain] = query(srv.port, "ci", "Sum(field=v)")
        [s_wrapped] = query(srv.port, "ci", "Options(Sum(field=v))")
        assert s_wrapped == s_plain
        [t_plain] = query(srv.port, "ci", "TopN(f, n=2)")
        [t_wrapped] = query(srv.port, "ci", "Options(TopN(f, n=2))")
        assert t_wrapped == t_plain and len(t_wrapped) == 2
    # shaping flags still honored together with shards pinning
    col = int(cols[rows == 1][0])
    _req(p0, "POST", "/index/ci/query",
         f'SetColumnAttrs({col}, tier="gold")')
    out = _req(p0, "POST", "/index/ci/query",
               f"Options(Row(f=1), columnAttrs=true, "
               f"shards=[{col // SHARD_WIDTH}])")
    assert out["columnAttrs"] == [{"id": col, "attrs": {"tier": "gold"}}]


def test_topn_tanimoto_matches_single_node(cluster3, tmp_path):
    """Tanimoto must be computed on GLOBAL counts: a row split across
    nodes would be kept/dropped differently under per-node filtering
    (fragment.go:1704 semantics, finalized at the coordinator)."""
    setup_index(cluster3)
    # row 0 (src) and row 1 overlap heavily but their columns span many
    # shards (nodes); row 2 overlaps little
    src_cols = list(range(0, 6 * SHARD_WIDTH, SHARD_WIDTH // 2))  # 12 cols
    r1_cols = src_cols[:10] + [7, 8]
    r2_cols = src_cols[:3] + [100, 101, 102, 103, 104, 105]
    rows, cols_ = [], []
    for r, cs in [(0, src_cols), (1, r1_cols), (2, r2_cols)]:
        rows += [r] * len(cs)
        cols_ += cs
    p0 = cluster3[0].port
    _req(p0, "POST", "/index/ci/field/f/import",
         {"rowIDs": rows, "columnIDs": cols_})
    q = "TopN(f, Row(f=0), tanimotoThreshold=60)"
    got = [query(s.port, "ci", q)[0] for s in cluster3]
    # single-node oracle
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.executor import Executor
    h = Holder(None)
    f1 = h.create_index("ci").create_field("f")
    f1.import_bits(np.array(rows), np.array(cols_))
    want = [{"id": p.id, "count": p.count}
            for p in Executor(h).execute("ci", q)[0]]
    assert want  # non-trivial
    for g in got:
        assert g == want


def test_group_by_across_nodes(cluster3):
    setup_index(cluster3)
    _req(cluster3[0].port, "POST", "/index/ci/field/g", {})
    cols = [1, 2, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 3]
    for c in cols:
        query(cluster3[0].port, "ci", f"Set({c}, f=1)")
        query(cluster3[0].port, "ci", f"Set({c}, g={c % 2})")
    [groups] = query(cluster3[1].port, "ci", "GroupBy(Rows(f), Rows(g))")
    got = {(tuple((fr["field"], fr["rowID"]) for fr in g["group"])):
           g["count"] for g in groups}
    odd = sum(1 for c in cols if c % 2 == 1)
    even = len(cols) - odd
    assert got[(("f", 1), ("g", 0))] == even
    assert got[(("f", 1), ("g", 1))] == odd


def test_anti_entropy_repair(cluster3):
    setup_index(cluster3)
    col = 2 * SHARD_WIDTH + 9
    query(cluster3[0].port, "ci", f"Set({col}, f=4)")
    cl0 = cluster3[0].cluster
    owners = cl0.placement.shard_nodes("ci", 2)
    # wipe the fragment on one owner
    victim = next(s for s in cluster3 if s.cluster.node_id == owners[1])
    idx = victim.holder.index("ci")
    f = idx.field("f")
    v = f.view("standard")
    assert v is not None and v.fragment(2) is not None
    del v.fragments[2]
    # run anti-entropy on the victim: it must pull the fragment back
    victim.cluster.sync_holder()
    frag = victim.holder.fragment("ci", "f", "standard", 2)
    assert frag is not None
    assert col % SHARD_WIDTH in frag.row_columns(4)


def test_anti_entropy_majority_clear_and_push(tmp_path):
    """mergeBlock parity (fragment.go:1875): a bit cleared on a majority
    of replicas is CLEARED on the minority holder (not resurrected), and
    repairs are PUSHED to disagreeing peers, not just pulled."""
    servers = make_cluster(tmp_path, n=3, replica_n=3)
    try:
        setup_index(servers)
        col = 9
        query(servers[0].port, "ci", f"Set({col}, f=4)")
        for s in servers:  # replica_n=3: every node holds the bit
            assert s.holder.fragment("ci", "f", "standard", 0) is not None
        # diverge: clear the bit directly on nodes 0 and 1 (majority clear)
        for s in servers[:2]:
            s.holder.fragment("ci", "f", "standard", 0).clear_bit(4, col)
        # sync on a CLEAR-holding node: consensus=clear must push the
        # clear to node2 (which still holds the bit) and not resurrect it
        servers[0].cluster.sync_holder()
        for s in servers:
            frag = s.holder.fragment("ci", "f", "standard", 0)
            assert col not in frag.row_columns(4), s.cluster.node_id
        # now diverge the other way: bit set on majority, wiped on one
        query(servers[0].port, "ci", f"Set({col + 1}, f=4)")
        servers[2].holder.fragment("ci", "f", "standard", 0) \
            .clear_bit(4, col + 1)
        servers[0].cluster.sync_holder()  # push path: 0 repairs 2
        frag2 = servers[2].holder.fragment("ci", "f", "standard", 0)
        assert col + 1 in frag2.row_columns(4)
    finally:
        for s in servers:
            s.close()


def test_anti_entropy_attr_sync(cluster3):
    """holder.go:1002-1096: attr stores sync by block diff — a replica
    missing/stale on an attr converges to its peers on its own pass."""
    setup_index(cluster3)
    query(cluster3[0].port, "ci", "Set(1, f=2)")
    # write an attr through the cluster (replicated), then diverge node1
    query(cluster3[0].port, "ci", 'SetRowAttrs(f, 2, team="core")')
    f1 = cluster3[1].holder.index("ci").field("f")
    f1.row_attrs.set_attrs(2, {"team": "stale", "extra": None})
    col_attrs = cluster3[1].holder.index("ci").column_attrs
    col_attrs.set_attrs(7, {"ghost": True})
    cluster3[1].cluster.sync_holder()
    assert f1.row_attrs.attrs(2)["team"] == "core"
    # column attrs flow the other way too: node0 pulls node1's id 7 attr
    cluster3[0].cluster.sync_holder()
    assert cluster3[0].holder.index("ci").column_attrs.attrs(7) == \
        {"ghost": True}


def _owned_frag_count(srv, index="ci"):
    n = 0
    idx = srv.holder.index(index)
    if idx is None:
        return 0
    for f in idx.fields.values():
        for v in f.views.values():
            n += len(v.fragments)
    return n


def test_node_crash_recovery_lifecycle(tmp_path):
    """The full §5.3 failure story: a node dies -> cluster DEGRADED but
    reads keep serving from replicas -> the node restarts on its data dir
    -> schema written while it was down catches up on the next probe ->
    anti-entropy repairs the bits it missed -> NORMAL."""
    servers = make_cluster(tmp_path, n=3, replica_n=2)
    try:
        setup_index(servers)
        col = 5
        query(servers[0].port, "ci", f"Set({col}, f=2)")
        # kill node2 (keep its config + data dir for the restart)
        dead_cfg = servers[2].config
        servers[2].close()
        servers[0].cluster.probe_peers()
        assert servers[0].cluster.state == "DEGRADED"
        # reads still answer from surviving replicas
        [cnt] = query(servers[0].port, "ci", "Count(Row(f=2))")
        assert cnt == 1
        # DDL is disallowed while DEGRADED (api.go:99 validAPIMethods)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _req(servers[0].port, "POST", "/index/ci/field/g", {})
        assert exc.value.code == 400

        # restart the node on its old data dir
        servers[2] = Server(dead_cfg)
        servers[2].open()
        servers[0].cluster.probe_peers()  # probes + schema catch-up
        assert servers[0].cluster.state == "NORMAL"
        # DDL works again and broadcasts everywhere incl. the restartee
        _req(servers[0].port, "POST", "/index/ci/field/g", {})
        schema = _req(servers[2].port, "GET", "/schema")["indexes"]
        assert {f["name"] for f in schema[0]["fields"]} >= {"f", "g"}
        # anti-entropy on the restarted node pulls anything it missed
        servers[2].cluster.probe_peers()
        servers[2].cluster.sync_holder()
        for srv in servers:
            [cnt] = query(srv.port, "ci", "Count(Row(f=2))")
            assert cnt == 1, srv.cluster.node_id
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_resize_grow_and_shrink(tmp_path):
    """cluster.go:1196-1561 resize parity: 2->3 grow then 3->2 shrink with
    data intact, placement rebalanced, and unowned fragments GC'd
    (holder.go:1131 holderCleaner)."""
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]

    def mk(i, host_list):
        cfg = Config(data_dir=str(tmp_path / f"node{i}"),
                     bind=host_list[i], node_id=f"node{i}",
                     cluster_hosts=host_list, replica_n=2,
                     anti_entropy_interval=0)
        cfg.bind = host_list[i]
        srv = Server(cfg)
        srv.open()
        return srv

    servers = [mk(0, hosts[:2]), mk(1, hosts[:2])]
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/ci", {})
        _req(p0, "POST", "/index/ci/field/f", {})
        rng = np.random.default_rng(3)
        n_shards = 8
        cols = rng.choice(n_shards * SHARD_WIDTH, size=4000, replace=False)
        rows = rng.integers(0, 6, size=4000)
        _req(p0, "POST", "/index/ci/field/f/import",
             {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
        oracle = {r: int((rows == r).sum()) for r in range(6)}

        # grow: start node2 with the full host list, then add it
        servers.append(mk(2, hosts))
        _req(p0, "POST", "/cluster/resize/add-node",
             {"id": "node2", "host": hosts[2]})
        assert len(_req(p0, "GET", "/status")["nodes"]) == 3
        for srv in servers:
            assert srv.cluster.state == "NORMAL"
            assert len(srv.cluster.nodes) == 3
            for r in range(6):
                [cnt] = query(srv.port, "ci", f"Count(Row(f={r}))")
                assert cnt == oracle[r], (srv.cluster.node_id, r)
        # the new node actually owns data (placement rebalanced onto it)
        assert _owned_frag_count(servers[2]) > 0
        # and owners hold exactly their placement's fragments once the
        # (deferred) cleaner runs — reads during the adoption window rely
        # on old owners retaining data, so GC is not inline
        for srv in servers:
            srv.cluster._holder_cleaner()
        pl = servers[0].cluster.placement
        for srv in servers:
            nid = srv.cluster.node_id
            idx = srv.holder.index("ci")
            for f in idx.fields.values():
                for v in f.views.values():
                    for s in v.fragments:
                        assert nid in pl.shard_nodes("ci", s), (nid, s)

        # shrink back to 2 nodes: node2's exclusive data must survive
        _req(p0, "POST", "/cluster/resize/remove-node", {"id": "node2"})
        for srv in servers[:2]:
            assert len(srv.cluster.nodes) == 2
            for r in range(6):
                [cnt] = query(srv.port, "ci", f"Count(Row(f={r}))")
                assert cnt == oracle[r], (srv.cluster.node_id, r)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_reads_serve_writes_blocked_during_resize(cluster3):
    """The reference keeps serving queries during a resize; here reads
    keep answering (old placement + deferred GC keep them exact) while
    write calls and DDL are rejected until the resize completes."""
    setup_index(cluster3)
    query(cluster3[0].port, "ci", "Set(5, f=1) Set(2097200, f=2)")
    for srv in cluster3:
        srv.cluster.state = "RESIZING"
    try:
        for srv in cluster3:
            [cnt] = query(srv.port, "ci", "Count(Row(f=1))")
            assert cnt == 1
            got = query(srv.port, "ci",
                        "Count(Row(f=1)) Count(Row(f=2)) TopN(f, n=1)")
            assert got[0] == 1 and got[1] == 1
        with pytest.raises(urllib.error.HTTPError) as exc:
            query(cluster3[0].port, "ci", "Set(6, f=1)")
        assert exc.value.code == 400
        # Options wrapping must not smuggle a write past the block
        with pytest.raises(urllib.error.HTTPError) as exc:
            query(cluster3[0].port, "ci", "Options(Set(6, f=1))")
        assert exc.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as exc:
            _req(cluster3[0].port, "POST", "/index/ci/field/h", {})
        assert exc.value.code == 400
    finally:
        for srv in cluster3:
            srv.cluster.state = "NORMAL"
    [cnt] = query(cluster3[0].port, "ci", "Count(Row(f=1))")
    assert cnt == 1


def test_resize_abort_restores_service(cluster3):
    """A failed resize (unreachable joiner) must put every node back to
    NORMAL under the old membership — not strand them in RESIZING where
    queries are rejected."""
    setup_index(cluster3)
    query(cluster3[0].port, "ci", "Set(5, f=1)")
    dead = _free_ports(1)[0]
    with pytest.raises(urllib.error.HTTPError):
        _req(cluster3[0].port, "POST", "/cluster/resize/add-node",
             {"id": "node3", "host": f"localhost:{dead}"})
    for srv in cluster3:
        assert srv.cluster.state == "NORMAL"
        assert len(srv.cluster.nodes) == 3
        [cnt] = query(srv.port, "ci", "Count(Row(f=1))")
        assert cnt == 1


def _make_certs(tmp_path):
    """Self-signed CA + a server/client cert for localhost (the
    clustertests' TLS fixture, server/cluster_test.go:640
    TestClusterMutualTLS)."""
    import subprocess

    ca_key, ca_crt = tmp_path / "ca.key", tmp_path / "ca.crt"
    key, csr, crt = tmp_path / "node.key", tmp_path / "node.csr", \
        tmp_path / "node.crt"
    ext = tmp_path / "ext.cnf"
    ext.write_text("subjectAltName=DNS:localhost,IP:127.0.0.1\n")
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(key), "-out", str(csr), "-subj", "/CN=localhost")
    run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
        "-CAkey", str(ca_key), "-CAcreateserial", "-days", "1",
        "-extfile", str(ext), "-out", str(crt))
    return str(ca_crt), str(crt), str(key)


def test_cluster_mutual_tls(tmp_path):
    """Mutual-TLS cluster: HTTPS node-to-node with client certificates
    required; plaintext and cert-less clients are rejected."""
    import ssl
    import pytest as _pytest
    try:
        ca, crt, key = _make_certs(tmp_path)
    except Exception as e:  # pragma: no cover - missing openssl
        _pytest.skip(f"openssl unavailable: {e}")
    ports = _free_ports(2)
    hosts = [f"https://localhost:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        srv = Server(Config(
            data_dir=str(tmp_path / f"node{i}"), bind=f"localhost:{p}",
            node_id=f"node{i}", cluster_hosts=hosts, replica_n=2,
            anti_entropy_interval=0, tls_certificate=crt, tls_key=key,
            tls_ca_certificate=ca))
        srv.open()
        servers.append(srv)
    try:
        ctx = ssl.create_default_context(cafile=ca)
        ctx.load_cert_chain(crt, key)

        def req(port, method, path, data=None):
            body = json.dumps(data).encode() if isinstance(data, dict) \
                else (data.encode() if isinstance(data, str) else data)
            r = urllib.request.Request(
                f"https://localhost:{port}{path}", method=method, data=body)
            with urllib.request.urlopen(r, context=ctx, timeout=30) as resp:
                return json.loads(resp.read() or b"{}")

        req(ports[0], "POST", "/index/ti", {})
        req(ports[0], "POST", "/index/ti/field/f", {})
        # write through node1: DDL broadcast + replica fan-out ride HTTPS
        out = req(ports[1], "POST", "/index/ti/query",
                  "Set(3, f=1) Set(9, f=1)")
        assert out["results"] == [True, True]
        for p in ports:
            out = req(p, "POST", "/index/ti/query", "Count(Row(f=1))")
            assert out["results"] == [2]
        # a client without a certificate must be rejected by the handshake
        nocert = ssl.create_default_context(cafile=ca)
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"https://localhost:{ports[0]}/status", context=nocert,
                timeout=10)
    finally:
        for s in servers:
            s.close()


def test_write_fails_when_replica_down(cluster3):
    """A write whose replica set is not fully reachable must ERROR, not
    silently skip the down owner (which union-only anti-entropy could
    later resurrect stale bits from)."""
    setup_index(cluster3)
    cluster3[2].close()
    cluster3[0].cluster.probe_peers()
    # find a column whose shard is owned by the dead node
    cl = cluster3[0].cluster
    shard = next(s for s in range(32)
                 if "node2" in cl.placement.shard_nodes("ci", s))
    col = shard * SHARD_WIDTH + 1
    with pytest.raises(urllib.error.HTTPError) as exc:
        query(cluster3[0].port, "ci", f"Set({col}, f=1)")
    assert exc.value.code == 500
    assert "unavailable" in exc.value.read().decode()


def test_schema_catchup_after_recovery(tmp_path):
    """DDL issued while a node is down is replayed when it recovers."""
    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        a, b = servers
        # simulate b being temporarily unreachable
        a.cluster.by_id["node1"].state = "DOWN"
        _req(a.port, "POST", "/index/late", {})
        _req(a.port, "POST", "/index/late/field/f", {})
        assert b.holder.index("late") is None  # missed the broadcast
        a.cluster.probe_peers()  # detects recovery, pushes schema
        assert a.cluster.by_id["node1"].state == "READY"
        idx = b.holder.index("late")
        assert idx is not None and idx.field("f") is not None
    finally:
        for s in servers:
            s.close()


def test_cluster_hosts_config_no_crash(tmp_path):
    """VERDICT: configuring cluster_hosts used to crash with
    ModuleNotFoundError (server.py imported a nonexistent module)."""
    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        st = _req(servers[0].port, "GET", "/status")
        assert st["state"] == "NORMAL"
        assert len(st["nodes"]) == 2
    finally:
        for s in servers:
            s.close()


def test_app_error_does_not_mark_peer_down(cluster3):
    """A peer that RESPONDS with an HTTP error (application failure) is
    alive: the fan-out must retry the shards on a replica without
    poisoning membership (one bad query must not flip the cluster to
    DEGRADED and reroute every later query)."""
    from pilosa_tpu.parallel.cluster import ClusterError

    setup_index(cluster3)
    query(cluster3[0].port, "ci",
          "Set(5, f=1) Set(2097200, f=1) Set(4194400, f=1)")
    coord = cluster3[0].cluster
    real = coord.client.query_calls
    failed_hosts = []

    def flaky(host, index, calls, shards):
        if not failed_hosts:
            failed_hosts.append(host)
            raise ClusterError(f"{host}: 500 injected app error")
        return real(host, index, calls, shards)

    coord.client.query_calls = flaky
    try:
        [cnt] = query(cluster3[0].port, "ci", "Count(Row(f=1))")
    finally:
        coord.client.query_calls = real
    assert cnt == 3
    assert failed_hosts, "fan-out never reached a peer"
    # the erroring peer must still be READY and the cluster NORMAL
    assert all(n.state == "READY" for n in coord.nodes)
    assert coord.state == "NORMAL"


def test_sole_owner_transient_failure_retried(tmp_path):
    """With ReplicaN=1 a shard has ONE owner; a single transient failure
    of that owner must be retried against it (slow != dead) instead of
    failing the query with 'no available node'."""
    from pilosa_tpu.parallel.cluster import ClusterError

    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/ri", {})
        _req(p0, "POST", "/index/ri/field/f", {})
        query(p0, "ri", "Set(5, f=1) Set(2097200, f=1) Set(4194400, f=1)")
        coord = servers[0].cluster
        real = coord.client.query_calls
        fails = []

        def transient(host, index, calls, shards):
            if not fails:
                fails.append(host)
                raise ClusterError(f"{host}: 500 transient")
            return real(host, index, calls, shards)

        coord.client.query_calls = transient
        try:
            [cnt] = query(p0, "ri", "Count(Row(f=1))")
        finally:
            coord.client.query_calls = real
        assert cnt == 3
        assert fails, "no peer-owned shard was exercised"
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_dead_sole_owner_fails_loud_not_partial(tmp_path):
    """When the ONLY owner of some shards dies (ReplicaN=1), a read over
    them must FAIL, not silently return the surviving nodes' partial
    answer: remote shard availability is remembered across peer death
    (field.go:263 remote available-shard tracking), so the fan-out still
    covers the dead node's shards and surfaces the error."""
    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/lo", {})
        _req(p0, "POST", "/index/lo/field/f", {})
        query(p0, "lo", " ".join(
            f"Set({s * SHARD_WIDTH + 9}, f=1)" for s in range(12)))
        [cnt] = query(p0, "lo", "Count(Row(f=1))")
        assert cnt == 12
        owners = {s: servers[0].cluster.placement.shard_nodes("lo", s)[0]
                  for s in range(12)}
        assert "node1" in owners.values(), "placement never used node1"

        servers[1].close()
        servers[0].cluster.probe_peers()
        assert servers[0].cluster.state == "DEGRADED"
        with pytest.raises(urllib.error.HTTPError) as ei:
            query(p0, "lo", "Count(Row(f=1))")
        assert ei.value.code == 500
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_remove_dead_sole_owner_succeeds_with_data_loss(tmp_path):
    """Removing a DEAD node whose shards had no replica (ReplicaN=1) must
    complete the resize — accepting the loss of its unreplicated shards —
    rather than aborting 'no live source' forever.  Queries afterwards
    legitimately cover only the surviving shards."""
    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/rm", {})
        _req(p0, "POST", "/index/rm/field/f", {})
        query(p0, "rm", " ".join(
            f"Set({s * SHARD_WIDTH + 9}, f=1)" for s in range(12)))
        [cnt] = query(p0, "rm", "Count(Row(f=1))")
        assert cnt == 12
        cl = servers[0].cluster
        node0_shards = [s for s in range(12)
                        if cl.placement.shard_nodes("rm", s)[0] == "node0"]
        assert 0 < len(node0_shards) < 12

        servers[1].close()
        cl.probe_peers()
        assert cl.state == "DEGRADED"
        # reads over the dead node's shards fail loudly...
        with pytest.raises(urllib.error.HTTPError):
            query(p0, "rm", "Count(Row(f=1))")
        # ...until the operator explicitly removes the dead node
        _req(p0, "POST", "/cluster/resize/remove-node", {"id": "node1"})
        assert cl.state == "NORMAL"
        assert len(cl.nodes) == 1
        [cnt] = query(p0, "rm", "Count(Row(f=1))")
        assert cnt == len(node0_shards)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_pooled_conn_idle_replacement(cluster3, monkeypatch):
    """A pooled keep-alive older than POOL_IDLE_MAX must be replaced
    before reuse: the server closes idle connections (handler timeout),
    and a FIN'd socket often fails only at response time, where POSTs
    must not retry."""
    import time as _time

    from pilosa_tpu.parallel.cluster import InternalClient

    setup_index(cluster3)
    client = cluster3[0].cluster.client
    host = cluster3[1].cluster.nodes[1].host
    status, _ = client._request(host, "GET", "/status")
    assert status == 200
    first = client._local.conns[host]

    monkeypatch.setattr(InternalClient, "POOL_IDLE_MAX", 0.05)
    _time.sleep(0.1)
    status, _ = client._request(host, "GET", "/status")
    assert status == 200
    assert client._local.conns[host] is not first  # replaced, not reused

    # within the idle window the SAME connection is reused
    second = client._local.conns[host]
    monkeypatch.setattr(InternalClient, "POOL_IDLE_MAX", 60.0)
    status, _ = client._request(host, "GET", "/status")
    assert status == 200
    assert client._local.conns[host] is second


def test_resize_complete_prunes_lost_shards_everywhere():
    """Data-loss shards ride the resize-complete broadcast so EVERY
    node's availability maps drop them (r5 advisor: coordinator-only
    pruning let peer polls re-propagate forgotten shards forever)."""
    from pilosa_tpu.parallel.cluster import Cluster
    from pilosa_tpu.storage import Holder

    h = Holder(None)
    idx = h.create_index("i")
    f = idx.create_field("f")
    f.remote_available_shards.update({2, 3, 9})
    c = Cluster("node0", ["localhost:1", "localhost:2"], holder=h)
    c.cleaner_grace = 0
    c._remote_shards["i"] = {1, 2, 3}
    c.handle_message({
        "type": "resize-complete", "epoch": 1, "replicaN": 1,
        "membership": [{"id": "node0", "uri": "localhost:1"},
                       {"id": "node1", "uri": "localhost:2"}],
        "lostShards": {"i": [2, 3], "ghost": [7]}})
    assert c._remote_shards["i"] == {1}
    assert f.remote_available_shards == {9}
    assert c.epoch == 1
    # the prune runs on FIRST application only: shard 2 re-imported
    # after the resize must survive a re-driven duplicate (same epoch)
    # and a stale older-epoch message alike
    for dup_epoch in (1, 0):
        c._remote_shards["i"] = {1, 2}
        c.handle_message({
            "type": "resize-complete", "epoch": dup_epoch, "replicaN": 1,
            "membership": [{"id": "node0", "uri": "localhost:1"},
                           {"id": "node1", "uri": "localhost:2"}],
            "lostShards": {"i": [2, 3]}})
        assert c._remote_shards["i"] == {1, 2}, dup_epoch
