"""Crash-consistent, self-healing storage (docs/robustness.md
"Durability & recovery").

Covers the on-disk contract end to end: checksummed v4 snapshot codec,
CRC-framed WAL with torn-tail truncation, byte-level corruption fuzz
(truncate / bit-flip at EVERY offset — open() must recover-or-quarantine,
never raise), lenient loading of pre-checksum legacy files, the
checksums-on-vs-off differential, Fragment.close() ordering, the
quarantine lifecycle (empty reads, refused writes, sidecar marker,
replica restore), the server-level degraded surfaces, and 2-node
replica-driven repair convergence with anti-entropy observability.

The process-level kill -9 harness lives in tests/test_crash.py.
"""

import json
import os
import shutil
import socket
import struct
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.storage import fragment as fragment_mod
from pilosa_tpu.storage.fragment import (
    Fragment,
    FragmentQuarantinedError,
    storage_events,
)
from pilosa_tpu.storage.roaring_io import (
    SnapshotFormatError,
    pack_snapshot,
    unpack_snapshot,
)
from pilosa_tpu.utils.faults import FAULTS


SHARD_WORDS = SHARD_WIDTH // 32


def _mk_fragment(path, **kw):
    kw.setdefault("max_op_n", 10 ** 6)
    return Fragment(path, "i", "f", "standard", 0, **kw)


def _bits(frag, rows=range(12)):
    """Bitmap as a comparable set of (row, col) pairs."""
    out = set()
    for r in rows:
        for c in frag.row_columns(r).tolist():
            out.add((r, c))
    return out


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    FAULTS.disarm()


# -- snapshot codec ---------------------------------------------------------

def test_snapshot_codec_roundtrip():
    idx = np.array([0, 5, SHARD_WORDS + 3, 7 * SHARD_WORDS], dtype=np.int64)
    val = np.array([1, 0xFFFFFFFF, 2, 9], dtype=np.uint32)
    blob = pack_snapshot(8, idx, val, SHARD_WORDS)
    cap, ridx, rval = unpack_snapshot(blob, SHARD_WORDS)
    assert cap == 8
    assert ridx.tolist() == idx.tolist()
    assert rval.tolist() == val.tolist()
    # empty store round-trips too
    cap, ridx, rval = unpack_snapshot(
        pack_snapshot(0, idx[:0], val[:0], SHARD_WORDS), SHARD_WORDS)
    assert (cap, ridx.size, rval.size) == (0, 0, 0)


def test_snapshot_codec_detects_every_byte_flip():
    """Every single-byte corruption of a v4 snapshot must raise
    SnapshotFormatError — header flips via the header CRC (before nnz is
    trusted), payload flips via the trailer CRC, CRC-byte flips via
    their own mismatch."""
    idx = np.arange(10, dtype=np.int64) * 3
    val = np.arange(1, 11, dtype=np.uint32)
    blob = pack_snapshot(4, idx, val, SHARD_WORDS)
    for off in range(len(blob)):
        bad = bytearray(blob)
        bad[off] ^= 0xFF
        with pytest.raises(SnapshotFormatError):
            unpack_snapshot(bytes(bad), SHARD_WORDS)


def test_snapshot_codec_detects_truncation_and_garbage():
    blob = pack_snapshot(4, np.array([1], dtype=np.int64),
                         np.array([7], dtype=np.uint32), SHARD_WORDS)
    for cut in range(len(blob)):
        with pytest.raises(SnapshotFormatError):
            unpack_snapshot(blob[:cut], SHARD_WORDS)
    with pytest.raises(SnapshotFormatError):
        unpack_snapshot(blob + b"\x00", SHARD_WORDS)  # appended garbage


# -- byte-level corruption fuzz over Fragment.open() ------------------------

def _seed_fragment_dir(tmp_path, wal_bits=0):
    """A fragment dir with a snapshotted prefix and (optionally) a framed
    WAL of `wal_bits` single-op frames.  Returns (path, snapshot_state,
    per-op (row, col) list)."""
    path = str(tmp_path / "seed" / "frag")
    f = _mk_fragment(path)
    for c in range(10):
        f.set_bit(c % 3, 17 * c + 1)
    f.snapshot()
    snap_state = _bits(f)
    ops = []
    for i in range(wal_bits):
        row, col = 5 + (i % 2), 1000 + i
        f.set_bit(row, col)
        ops.append((row, col))
    f._wal_file.flush()
    del f
    return path, snap_state, ops


def _fuzz_open(path):
    """Open a (possibly corrupted) fragment the way the server does.
    The contract under test: NEVER an exception, whatever the bytes.
    Returns (fragment, recovered bits, WAL size right after open) — the
    size is captured BEFORE close(), which snapshots replayed ops and
    truncates the WAL to a fresh magic."""
    frag = _mk_fragment(path)
    got = _bits(frag)
    wal_size = os.path.getsize(path + ".wal") \
        if os.path.exists(path + ".wal") else None
    frag.close()
    return frag, got, wal_size


def _copy_seed(seed_path, tmp_path, case):
    dst = str(tmp_path / f"case{case}" / "frag")
    os.makedirs(os.path.dirname(dst))
    shutil.copy(seed_path, dst)
    if os.path.exists(seed_path + ".wal"):
        shutil.copy(seed_path + ".wal", dst + ".wal")
    return dst


def test_snapshot_truncation_fuzz(tmp_path):
    seed, snap_state, _ = _seed_fragment_dir(tmp_path)
    size = os.path.getsize(seed)
    for cut in range(size + 1):
        path = _copy_seed(seed, tmp_path, f"t{cut}")
        with open(path, "r+b") as fh:
            fh.truncate(cut)
        frag, got, _ = _fuzz_open(path)
        if cut == size:
            assert frag.quarantined is None and got == snap_state
        else:
            # a truncated snapshot has lost data: quarantine, never a
            # partial answer and never a crash
            assert frag.quarantined is not None, cut
            assert got == set()
            assert os.path.exists(path + ".quarantine"), cut


def test_snapshot_bitflip_fuzz(tmp_path):
    seed, snap_state, _ = _seed_fragment_dir(tmp_path)
    blob = open(seed, "rb").read()
    for off in range(len(blob)):
        path = _copy_seed(seed, tmp_path, f"f{off}")
        bad = bytearray(blob)
        bad[off] ^= 1 << (off % 8)
        with open(path, "wb") as fh:
            fh.write(bytes(bad))
        frag, got, _ = _fuzz_open(path)
        # CRC32 catches every single-bit flip: always quarantined
        assert frag.quarantined is not None, off
        assert got == set()


def test_wal_truncation_fuzz(tmp_path):
    """Truncation at EVERY WAL offset: open() recovers the longest valid
    frame prefix, durably truncates the tail, and never raises.  The
    recovered bitmap must be exactly snapshot + that prefix — nothing
    dropped before the tear, nothing invented after it."""
    seed, snap_state, ops = _seed_fragment_dir(tmp_path, wal_bits=6)
    wal = open(seed + ".wal", "rb").read()
    frame = (len(wal) - 8) // len(ops)  # fixed per-op frame size
    assert 8 + frame * len(ops) == len(wal)
    for cut in range(len(wal) + 1):
        path = _copy_seed(seed, tmp_path, f"w{cut}")
        with open(path + ".wal", "r+b") as fh:
            fh.truncate(cut)
        frag, got, wal_size = _fuzz_open(path)
        assert frag.quarantined is None, cut
        n_frames = max(0, (cut - 8) // frame)
        assert got == snap_state | set(ops[:n_frames]), cut
        # the torn tail was truncated at the last valid frame boundary
        # (a cut inside the magic itself truncates to empty, and the
        # append-handle open lays down a fresh magic)
        assert wal_size == 8 + n_frames * frame, cut


def test_wal_bitflip_fuzz(tmp_path):
    """A flipped bit at EVERY WAL offset: open() never raises, and the
    outcome is always one of (a) quarantined (mid-log corruption — valid
    frames follow the bad one, so truncation would drop acknowledged
    writes), (b) a valid frame prefix (tail frame corrupt -> truncated),
    or (c) everything (flip in the final frame detected as tail)."""
    seed, snap_state, ops = _seed_fragment_dir(tmp_path, wal_bits=6)
    wal = open(seed + ".wal", "rb").read()
    valid = [snap_state | set(ops[:k]) for k in range(len(ops) + 1)]
    for off in range(len(wal)):
        path = _copy_seed(seed, tmp_path, f"b{off}")
        bad = bytearray(wal)
        bad[off] ^= 1 << (off % 8)
        with open(path + ".wal", "wb") as fh:
            fh.write(bytes(bad))
        frag, got, _ = _fuzz_open(path)
        if frag.quarantined is not None:
            assert got == set(), off
        else:
            assert got in valid, off


def test_midlog_wal_corruption_quarantines(tmp_path):
    """A bad frame with valid frames AFTER it must quarantine, not
    truncate: the later frames are acknowledged writes, and dropping
    them silently would violate the durability contract."""
    seed, _, ops = _seed_fragment_dir(tmp_path, wal_bits=6)
    wal = bytearray(open(seed + ".wal", "rb").read())
    frame = (len(wal) - 8) // len(ops)
    wal[8 + frame + 10] ^= 0xFF  # inside frame #2's payload
    with open(seed + ".wal", "wb") as fh:
        fh.write(bytes(wal))
    frag, got, _ = _fuzz_open(seed)
    assert frag.quarantined is not None
    assert "CRC mismatch" in frag.quarantined
    assert got == set()


# -- legacy (pre-checksum) format compatibility -----------------------------

def _write_legacy_v3(path, cap_rows, idx, val):
    """The exact v3 writer this PR replaced: bare header + arrays, no
    CRCs anywhere."""
    with open(path, "wb") as f:
        f.write(struct.pack("<8sIIQ", b"PTPUFRG3", cap_rows, SHARD_WORDS,
                            idx.size))
        idx.astype("<u8").tofile(f)
        val.astype("<u4").tofile(f)


def _write_legacy_wal(path, ops):
    """The pre-framing WAL: a bare stream of <u8 op, i64 row, i64 col>
    records, no magic, no CRCs."""
    with open(path, "wb") as f:
        for op, row, col in ops:
            f.write(struct.pack("<Bqq", op, row, col))


def test_legacy_files_load_leniently(tmp_path):
    path = str(tmp_path / "legacy" / "frag")
    os.makedirs(os.path.dirname(path))
    idx = np.array([0, SHARD_WORDS * 2 + 1], dtype=np.int64)
    val = np.array([0b101, 7], dtype=np.uint32)
    _write_legacy_v3(path, 4, idx, val)
    _write_legacy_wal(path + ".wal", [(0, 9, 50), (0, 9, 51), (1, 9, 50)])
    frag = _mk_fragment(path)
    assert frag.quarantined is None
    assert set(frag.row_columns(0).tolist()) == {0, 2}
    assert set(frag.row_columns(9).tolist()) == {51}
    # appends keep the file's own legacy format (no mixed files) ...
    frag.set_bit(9, 52)
    frag._wal_file.flush()
    assert not open(path + ".wal", "rb").read().startswith(b"PTPUWAL1")
    # ... and the next snapshot truncation upgrades both files
    frag.snapshot()
    assert open(path, "rb").read(8) == b"PTPUFRG4"
    assert open(path + ".wal", "rb").read() == b"PTPUWAL1"
    frag.close()
    reopened = _mk_fragment(path)
    assert set(reopened.row_columns(9).tolist()) == {51, 52}


def test_legacy_torn_tail_still_dropped(tmp_path):
    """The legacy bare stream keeps its old recovery semantics: a
    trailing partial record is a torn write, dropped on replay."""
    path = str(tmp_path / "legacy2" / "frag")
    os.makedirs(os.path.dirname(path))
    _write_legacy_wal(path + ".wal", [(0, 1, 10), (0, 1, 11)])
    with open(path + ".wal", "ab") as f:
        f.write(b"\x00\x05")  # torn partial record
    frag = _mk_fragment(path)
    assert frag.quarantined is None
    assert set(frag.row_columns(1).tolist()) == {10, 11}


def test_wal_crc_on_off_differential(tmp_path):
    """The same op sequence with wal-crc on vs off must produce
    byte-identical query results, and both must survive a reopen."""
    states = {}
    for crc in (True, False):
        old = fragment_mod.WAL_CRC
        fragment_mod.WAL_CRC = crc
        try:
            path = str(tmp_path / f"crc{crc}" / "frag")
            f = _mk_fragment(path)
            rng = np.random.default_rng(11)
            rows = rng.integers(0, 8, size=200)
            cols = rng.integers(0, SHARD_WIDTH, size=200)
            f.bulk_import(rows[:120], cols[:120])
            f.set_bit(3, 12345)
            f.bulk_import(rows[:40], cols[:40], clear=True)
            f.snapshot()
            f.bulk_import(rows[120:], cols[120:])
            f.clear_bit(3, 12345)
            f._wal_file.flush()
            del f  # crash-style: no close, reopen replays the WAL
            g = _mk_fragment(path)
            assert g.quarantined is None
            framed = open(path + ".wal", "rb").read(8) == b"PTPUWAL1"
            assert framed is crc
            states[crc] = (g.pairs()[0].tobytes(), g.pairs()[1].tobytes())
            g.close()
        finally:
            fragment_mod.WAL_CRC = old
    assert states[True] == states[False]


# -- Fragment.close() ordering ----------------------------------------------

def test_close_fsyncs_wal_before_failed_snapshot(tmp_path):
    """close() must put the WAL on stable storage BEFORE attempting the
    snapshot: if the snapshot fails (disk full, injected fault), every
    acknowledged append still replays on reopen."""
    path = str(tmp_path / "c1" / "frag")
    f = _mk_fragment(path)
    f.set_bit(1, 10)
    f.set_bit(2, 20)
    before = _bits(f)
    FAULTS.arm("fragment.snapshot", "error")
    try:
        with pytest.raises(OSError):
            f.close()
    finally:
        FAULTS.disarm()
    # WAL handle was released even though the snapshot failed
    assert f._wal_file is None
    g = _mk_fragment(path)
    assert _bits(g) == before  # differential: identical bitmap


def test_close_kill_window_reopen_differential(tmp_path):
    """A crash in the close+kill window (WAL flushed, snapshot not yet
    rewritten) replays to the identical bitmap."""
    path = str(tmp_path / "c2" / "frag")
    f = _mk_fragment(path)
    rng = np.random.default_rng(5)
    f.bulk_import(rng.integers(0, 6, size=50),
                  rng.integers(0, SHARD_WIDTH, size=50))
    f.snapshot()
    f.set_bit(7, 77)
    f.clear_bit(7, 77)
    f.set_bit(7, 78)
    before = _bits(f)
    f._wal_file.flush()
    # simulate kill -9 mid-close: copy the on-disk state as-is
    frozen = str(tmp_path / "c2-frozen" / "frag")
    os.makedirs(os.path.dirname(frozen))
    shutil.copy(path, frozen)
    shutil.copy(path + ".wal", frozen + ".wal")
    g = _mk_fragment(frozen)
    assert _bits(g) == before


# -- quarantine lifecycle ---------------------------------------------------

def test_quarantine_lifecycle_and_repair(tmp_path):
    path = str(tmp_path / "q" / "frag")
    f = _mk_fragment(path)
    f.set_bit(2, 7)
    f.set_bit(9, 100)
    f.snapshot()
    f.close()
    blob_good = bytearray(open(path, "rb").read())
    blob_good[-2] ^= 0x10
    with open(path, "wb") as fh:
        fh.write(bytes(blob_good))

    ev0 = storage_events()
    g = _mk_fragment(path)
    assert g.quarantined is not None
    assert storage_events()["quarantine"] == ev0["quarantine"] + 1
    # reads answer EMPTY (degraded), never raise
    assert g.row_columns(9).size == 0
    assert g.to_dense().sum() == 0
    # writes are refused with the retryable error
    with pytest.raises(FragmentQuarantinedError):
        g.set_bit(1, 1)
    with pytest.raises(FragmentQuarantinedError):
        g.bulk_import(np.array([1]), np.array([1]))
    # sidecar marker persists the state across restarts without
    # re-parsing the corrupt bytes
    assert os.path.exists(path + ".quarantine")
    g2 = _mk_fragment(path)
    assert g2.quarantined is not None

    # replica repair: verified blob swaps in, marker clears, generation
    # bumps (derived caches must invalidate), writes work again
    donor = _mk_fragment(str(tmp_path / "donor" / "frag"))
    donor.set_bit(2, 7)
    donor.set_bit(9, 100)
    blob = donor.snapshot_bytes()
    gen0 = g2.gen
    g2.restore_snapshot_bytes(blob)
    assert g2.quarantined is None
    assert g2.gen != gen0
    assert not os.path.exists(path + ".quarantine")
    assert open(path, "rb").read() == blob  # byte-identical to source
    assert set(g2.row_columns(9).tolist()) == {100}
    assert g2.set_bit(1, 1)
    assert storage_events()["repair"] == ev0["repair"] + 1
    # corrupt bytes in flight must NOT launder into a repaired fragment
    g2.close()
    g3 = _mk_fragment(path)
    bad = bytearray(blob)
    bad[30] ^= 0xFF
    with pytest.raises(SnapshotFormatError):
        g3.restore_snapshot_bytes(bytes(bad))


def test_quarantine_off_is_fail_stop(tmp_path):
    """quarantine-on-corruption = false restores fail-stop opens (the
    offline check/inspect tools and single-node forensics)."""
    path = str(tmp_path / "fs" / "frag")
    f = _mk_fragment(path)
    f.set_bit(0, 1)
    f.snapshot()
    f.close()
    with open(path, "r+b") as fh:
        fh.truncate(10)
    old = fragment_mod.QUARANTINE_ON_CORRUPTION
    fragment_mod.QUARANTINE_ON_CORRUPTION = False
    try:
        with pytest.raises(ValueError):
            _mk_fragment(path)
    finally:
        fragment_mod.QUARANTINE_ON_CORRUPTION = old
    assert not os.path.exists(path + ".quarantine")
    # a sidecar left by a previous quarantining run must NOT satisfy a
    # fail-stop open either: check/inspect would report corrupt data as
    # an empty-but-healthy fragment
    g = _mk_fragment(path)  # quarantines (writes the sidecar)
    assert g.quarantined is not None
    assert os.path.exists(path + ".quarantine")
    fragment_mod.QUARANTINE_ON_CORRUPTION = False
    try:
        with pytest.raises(ValueError):
            _mk_fragment(path)
    finally:
        fragment_mod.QUARANTINE_ON_CORRUPTION = old


def test_corrupt_attr_store_resets_and_surfaces(tmp_path):
    """A corrupt attr-store JSON must not kill startup: the bad bytes
    move aside (.corrupt), the store restarts empty (attr anti-entropy
    re-pulls from peers), and the reset is DATA — an event counter and
    a /debug/vars listing, not just a moved file."""
    from pilosa_tpu.storage.attrs import AttrStore
    from pilosa_tpu.storage.holder import Holder

    ev0 = storage_events()["attr_corrupt"]
    path = str(tmp_path / "attrs.json")
    with open(path, "w") as f:
        f.write('{"1": {"name": "ok"}')  # truncated JSON
    store = AttrStore(path)
    assert store.corrupt is not None
    assert store.attrs(1) == {}
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    assert storage_events()["attr_corrupt"] == ev0 + 1
    # holder-level surface (what /debug/vars storage.corruptAttrStores
    # serves)
    holder = Holder(str(tmp_path / "holder"))
    holder.open()
    holder.create_index("ai")
    bad = os.path.join(str(tmp_path / "holder"), "ai", ".column_attrs")
    holder.indexes["ai"].column_attrs.set_attrs(3, {"k": "v"})
    holder.close()
    with open(bad, "w") as f:
        f.write("not json at all {{{")
    holder2 = Holder(str(tmp_path / "holder"))
    holder2.open()
    listed = holder2.corrupt_attr_stores()
    assert listed and listed[0]["index"] == "ai"
    assert listed[0]["field"] is None
    holder2.close()


# -- server-level degraded surfaces -----------------------------------------

def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _req(port, method, path, data=None):
    body = None
    if data is not None:
        body = data.encode() if isinstance(data, str) else json.dumps(
            data).encode()
    r = urllib.request.Request(
        f"http://localhost:{port}{path}", method=method, data=body)
    with urllib.request.urlopen(r, timeout=60) as resp:
        return json.loads(resp.read())


def _raw(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=60) as resp:
        return resp.read().decode()


def _frag_files(data_dir):
    out = []
    for dirpath, _dirnames, filenames in os.walk(data_dir):
        if os.path.basename(dirpath) != "fragments":
            continue
        for fn in filenames:
            if not fn.endswith((".wal", ".quarantine", ".tmp")):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def test_server_degraded_serving(tmp_path):
    """A corrupt fragment on a single node: the server starts (degraded,
    not down), reads answer with an explicit degraded flag, writes to the
    quarantined fragment get a retryable 503, and /debug/vars + /metrics
    carry the quarantine state."""
    from pilosa_tpu.server.server import Config, Server

    data_dir = str(tmp_path / "node")
    (port,) = _free_ports(1)
    cfg = Config(data_dir=data_dir, bind=f"localhost:{port}",
                 anti_entropy_interval=0, repair_interval=0)
    srv = Server(cfg)
    srv.open()
    try:
        _req(srv.port, "POST", "/index/di", {})
        _req(srv.port, "POST", "/index/di/field/f", {})
        _req(srv.port, "POST", "/index/di/query", "Set(5, f=1)")
        q = _req(srv.port, "POST", "/index/di/query", "Row(f=1)")
        assert "degraded" not in q
    finally:
        srv.close()

    # target field f's fragment specifically — the index also carries an
    # internal _exists field whose fragment file sorts first
    frag_file = [p for p in _frag_files(data_dir) if "/fields/f/" in p][0]
    with open(frag_file, "r+b") as fh:
        fh.seek(28)
        b = fh.read(1)
        fh.seek(28)
        fh.write(bytes([b[0] ^ 0xFF]))

    (port2,) = _free_ports(1)
    srv = Server(Config(data_dir=data_dir, bind=f"localhost:{port2}",
                        anti_entropy_interval=0, repair_interval=0))
    srv.open()  # startup must NOT die on the corrupt file
    try:
        st = _req(srv.port, "GET", "/status")
        assert st["storage"]["degraded"] is True
        assert st["storage"]["quarantinedFragments"] == 1
        # reads serve (empty from the quarantined fragment) + say so
        q = _req(srv.port, "POST", "/index/di/query", "Row(f=1)")
        assert q["results"][0]["columns"] == []
        assert q["degraded"]["quarantinedFragments"] >= 1
        # writes are refused with a retryable 503
        with pytest.raises(urllib.error.HTTPError) as err:
            _req(srv.port, "POST", "/index/di/query", "Set(6, f=1)")
        assert err.value.code == 503
        body = json.loads(err.value.read())
        assert body["retryable"] is True
        assert err.value.headers["Retry-After"]
        # observability surfaces
        dv = _req(srv.port, "GET", "/debug/vars")
        assert dv["storage"]["quarantined"][0]["index"] == "di"
        assert dv["storage"]["events"]["quarantine"] >= 1
        metrics = _raw(srv.port, "/metrics")
        assert "storage_quarantined_fragments 1" in metrics
    finally:
        srv.close()


# -- 2-node replica repair convergence --------------------------------------

def _make_pair(tmp_path, tag=""):
    from pilosa_tpu.server.server import Config, Server

    ports = _free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        cfg = Config(data_dir=str(tmp_path / f"{tag}node{i}"),
                     bind=f"localhost:{p}", node_id=f"node{i}",
                     cluster_hosts=hosts, replica_n=2,
                     anti_entropy_interval=0, repair_interval=0)
        srv = Server(cfg)
        srv.open()
        servers.append(srv)
    return servers


def test_two_node_repair_convergence(tmp_path):
    """The acceptance scenario: corrupt a replica's fragment on disk,
    restart it -> quarantined; one repair pass re-fetches the fragment
    wholesale from the healthy peer, checksum-verified, atomically
    swapped in, generation bumped — and the node's on-disk bytes equal
    the source's snapshot exactly."""
    from pilosa_tpu.server.server import Config, Server

    servers = _make_pair(tmp_path)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/ri", {})
        _req(p0, "POST", "/index/ri/field/f", {})
        rng = np.random.default_rng(3)
        cols = np.unique(rng.integers(0, 2 * SHARD_WIDTH, size=800))
        rows = rng.integers(0, 5, size=cols.size)
        _req(p0, "POST", "/index/ri/field/f/import",
             {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
        oracle = {r: set(cols[rows == r].tolist()) for r in range(5)}
        [got] = _req(p0, "POST", "/index/ri/query", "Row(f=2)")["results"]
        assert set(got["columns"]) == oracle[2]

        # restart node1 with a corrupted fragment file
        node1_cfg = servers[1].config
        servers[1].close()
        victims = [p for p in _frag_files(node1_cfg.data_dir)
                   if "/ri/" in p and "/fields/f/" in p]
        victim = victims[0]
        blob = bytearray(open(victim, "rb").read())
        blob[35] ^= 0x40
        with open(victim, "wb") as fh:
            fh.write(bytes(blob))
        servers[1] = Server(node1_cfg)
        servers[1].open()
        p1 = servers[1].port

        st = _req(p1, "GET", "/status")
        assert st["storage"]["degraded"] is True
        quarantined = servers[1].holder.quarantined_fragments()
        assert len(quarantined) == 1 and quarantined[0]["index"] == "ri"
        shard = quarantined[0]["shard"]
        frag = servers[1].holder.fragment("ri", "f", "standard", shard)
        gen0 = frag.gen

        # node0 must see node1 as READY again before repair can route
        servers[0].cluster.probe_peers()
        servers[1].cluster.probe_peers()

        repaired = servers[1].cluster.repair_quarantined()
        assert repaired == 1
        assert frag.quarantined is None
        assert frag.gen != gen0  # result caches keyed on gens invalidate

        # byte-identical to the source replica's snapshot
        src = servers[0].holder.fragment("ri", "f", "standard", shard)
        assert open(victim, "rb").read() == src.snapshot_bytes()
        assert not os.path.exists(victim + ".quarantine")

        # converged: both nodes answer the oracle, degraded flag gone
        for port in (servers[0].port, p1):
            [got] = _req(port, "POST", "/index/ri/query",
                         "Row(f=2)")["results"]
            assert set(got["columns"]) == oracle[2]
        q = _req(p1, "POST", "/index/ri/query", "Row(f=2)")
        assert "degraded" not in q
        st = _req(p1, "GET", "/status")
        assert st["storage"]["degraded"] is False

        # repair is visible as data: counter + metrics line
        dv = _req(p1, "GET", "/debug/vars")
        assert dv["counts"].get("antientropy.repairs", 0) >= 1
        assert dv["storage"]["events"]["repair"] >= 1
        # writes accepted again post-repair
        _req(p1, "POST", "/index/ri/query",
             f"Set({int(shard) * SHARD_WIDTH + 9}, f=2)")
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_antientropy_errors_surface_as_data(tmp_path):
    """Satellite: anti-entropy loop failures are counters + last-error
    state in /debug/vars, not just a log line — and a healthy pass
    stamps last-success."""
    servers = _make_pair(tmp_path, tag="ae")
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/ae", {})
        _req(p0, "POST", "/index/ae/field/f", {})
        _req(p0, "POST", "/index/ae/query", "Set(1, f=1)")

        servers[0].cluster.sync_holder()
        dv = _req(p0, "GET", "/debug/vars")
        ae = dv["storage"]["antiEntropy"]
        assert ae["lastSuccessTs"] is not None
        assert dv["counts"].get("antientropy.runs", 0) >= 1
        errs0 = dv["counts"].get("antientropy.errors", 0)

        # every internal request to node1 fails at the transport level
        FAULTS.arm("client.request", "error",
                   match=servers[1].config.bind)
        try:
            servers[0].cluster.sync_holder()
        finally:
            FAULTS.disarm()
        dv = _req(p0, "GET", "/debug/vars")
        ae = dv["storage"]["antiEntropy"]
        assert dv["counts"].get("antientropy.errors", 0) > errs0
        assert ae["lastError"] is not None
        assert ae["lastErrorTs"] is not None
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
