"""Storage tree tests — mirrors fragment_internal_test.go /
field_internal_test.go / holder_internal_test.go coverage: setBit/clearBit,
WAL+snapshot persistence, BSI set_value/auto-depth, mutex/bool semantics,
time-view fan-out, import, existence tracking, schema round-trip."""

import numpy as np
import pytest
from datetime import datetime

from pilosa_tpu.core import SHARD_WIDTH, EXISTENCE_FIELD_NAME
from pilosa_tpu.ops import bitset
from pilosa_tpu.storage import Field, FieldOptions, Fragment, Holder
from pilosa_tpu.storage import time_quantum as tq


# -- fragment ---------------------------------------------------------------

def test_fragment_set_clear_bit():
    f = Fragment(None, "i", "f", "standard", 0)
    assert f.set_bit(3, 100)
    assert not f.set_bit(3, 100)  # already set
    assert set(f.row_columns(3).tolist()) == {100}
    assert f.clear_bit(3, 100)
    assert not f.clear_bit(3, 100)
    assert f.row_columns(3).size == 0


def test_fragment_row_growth():
    f = Fragment(None, "i", "f", "standard", 0)
    f.set_bit(0, 1)
    f.set_bit(1000, 5)
    assert f.n_rows >= 1001
    assert f.max_row_id() == 1000
    assert set(f.row_columns(1000).tolist()) == {5}


def test_fragment_bulk_import_and_count():
    f = Fragment(None, "i", "f", "standard", 0)
    rows = np.array([0, 0, 1, 5, 5, 5])
    cols = np.array([1, 2, 3, 4, 5, 4])  # (5,4) duplicated
    changed = f.bulk_import(rows, cols)
    assert changed == 5
    assert f.bulk_import(rows, cols) == 0  # idempotent
    assert f.bulk_import(np.array([0]), np.array([1]), clear=True) == 1
    assert set(f.row_columns(0).tolist()) == {2}


def test_fragment_persistence(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0, max_op_n=1000)
    f.set_bit(2, 7)
    f.set_bit(9, SHARD_WIDTH - 1)
    f.clear_bit(2, 7)
    f.set_value(5, 8, -42)
    del f
    g = Fragment(path, "i", "f", "standard", 0)
    assert g.row_columns(2).size == 0
    assert set(g.row_columns(9).tolist()) == {SHARD_WIDTH - 1}
    g.close()
    # closed fragment reopens identically (snapshot path)
    h = Fragment(path, "i", "f", "standard", 0)
    assert set(h.row_columns(9).tolist()) == {SHARD_WIDTH - 1}


def test_fragment_wal_replay_without_snapshot(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0)
    f.set_bit(1, 10)
    f.set_bit(1, 11)
    f._wal_file.flush()
    # simulate crash: do NOT close/snapshot
    g = Fragment(path, "i", "f", "standard", 0)
    assert set(g.row_columns(1).tolist()) == {10, 11}


def test_fragment_snapshot_after_max_opn(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0, max_op_n=5)
    for c in range(7):
        f.set_bit(0, c)
    assert f._op_n < 5  # snapshot triggered and reset
    g = Fragment(path, "i", "f", "standard", 0)
    assert set(g.row_columns(0).tolist()) == set(range(7))


def test_fragment_set_value_and_blocks():
    f = Fragment(None, "i", "f", "bsig_f", 0)
    f.set_value(10, 8, 42)
    f.set_value(11, 8, -17)
    from pilosa_tpu.ops import bsi
    cols, vals = bsi.unpack_values(f.words)
    assert cols.tolist() == [10, 11]
    assert vals.tolist() == [42, -17]
    f.set_value(10, 8, 3)  # overwrite clears stale bits
    cols, vals = bsi.unpack_values(f.words)
    assert vals.tolist() == [3, -17]
    blocks = f.blocks()
    assert set(blocks) == {0}
    r, c = f.block_data(0)
    assert r.size > 0


def test_fragment_import_values_overwrites():
    f = Fragment(None, "i", "f", "bsig_f", 0)
    f.import_values(np.array([1, 2, 3]), np.array([10, 20, 30]), 8)
    f.import_values(np.array([2]), np.array([-5]), 8)
    from pilosa_tpu.ops import bsi
    cols, vals = bsi.unpack_values(f.words)
    assert cols.tolist() == [1, 2, 3]
    assert vals.tolist() == [10, -5, 30]


def test_fragment_set_row():
    f = Fragment(None, "i", "f", "standard", 0)
    f.set_bit(0, 5)
    seg = bitset.pack_columns(np.array([7, 8]))
    f.set_row(0, seg)
    assert set(f.row_columns(0).tolist()) == {7, 8}
    f.set_row(0, None)
    assert f.row_columns(0).size == 0


# -- field ------------------------------------------------------------------

def test_field_set_bit_multi_shard():
    f = Field(None, "i", "f")
    f.set_bit(1, 5)
    f.set_bit(1, SHARD_WIDTH + 5)
    assert f.available_shards() == {0, 1}
    segs = f.row(1)
    assert set(bitset.unpack_columns(segs[0]).tolist()) == {5}
    assert set(bitset.unpack_columns(segs[1]).tolist()) == {5}


def test_field_mutex():
    f = Field(None, "i", "f", FieldOptions(type="mutex"))
    f.set_bit(1, 100)
    f.set_bit(2, 100)  # clears row 1
    segs = f.row(1)
    assert bitset.unpack_columns(segs[0]).size == 0
    assert set(bitset.unpack_columns(f.row(2)[0]).tolist()) == {100}


def test_field_bool_validates_rows():
    f = Field(None, "i", "f", FieldOptions(type="bool"))
    f.set_bit(0, 1)
    f.set_bit(1, 1)  # flips to true
    with pytest.raises(Exception):
        f.set_bit(2, 1)


def test_field_time_views():
    f = Field(None, "i", "f", FieldOptions(type="time", time_quantum="YMD"))
    ts = datetime(2017, 3, 20, 10)
    f.set_bit(4, 30, ts=ts)
    assert set(f.views) == {"standard", "standard_2017", "standard_201703",
                            "standard_20170320"}
    for vname in f.views:
        assert set(bitset.unpack_columns(f.row(4, vname)[0]).tolist()) == {30}


def test_field_int_values_and_base():
    f = Field(None, "i", "f", FieldOptions(type="int", min=100, max=200))
    assert f.options.base == 100
    f.set_value(9, 150)
    assert f.value(9) == (150, True)
    assert f.value(10) == (0, False)
    f.set_value(9, 101)
    assert f.value(9) == (101, True)


def test_field_int_auto_depth_growth():
    f = Field(None, "i", "f", FieldOptions(type="int", min=0, max=1000000))
    f.set_value(1, 5)
    before = f.options.bit_depth
    assert before < 20  # lazy depth: declared range does not pre-inflate it
    f.set_value(0, 100000)
    assert f.options.bit_depth > before
    assert f.value(0) == (100000, True)
    assert f.value(1) == (5, True)


def test_field_int_declared_range_enforced():
    """field.go:1082-1086 ErrBSIGroupValueTooLow/High — writes outside the
    declared [min, max] are rejected, which makes the planner's
    options.min/max shortcut paths sound."""
    f = Field(None, "i", "f", FieldOptions(type="int", min=10, max=30))
    with pytest.raises(ValueError, match="too low"):
        f.set_value(0, 9)
    with pytest.raises(ValueError, match="too high"):
        f.set_value(0, 31)
    with pytest.raises(ValueError, match="too high"):
        f.import_values(np.array([1, 2]), np.array([15, 1000]))
    f.set_value(0, 10)
    f.set_value(1, 30)
    assert f.value(0) == (10, True)
    assert f.value(1) == (30, True)


def test_field_int_unbounded_range_defaults():
    """An int field created without explicit min/max defaults to the full
    int64 range (reference http/handler.go:781 MinInt64/MaxInt64) instead
    of rejecting all non-zero writes against a 0/0 declared range."""
    f = Field(None, "i", "f", FieldOptions.from_dict({"type": "int"}))
    assert f.set_value(1, 5)
    assert f.value(1) == (5, True)
    assert f.set_value(2, -12345)
    assert f.value(2) == (-12345, True)
    # direct-constructed options behave the same
    f2 = Field(None, "i", "f2", FieldOptions(type="int"))
    assert f2.set_value(0, 7)
    assert f2.value(0) == (7, True)
    # -2**63 is NOT representable in sign+magnitude BSI; it must be
    # rejected, not silently truncated to 0
    with pytest.raises(ValueError, match="too low"):
        f2.set_value(3, -(1 << 63))
    assert f2.set_value(3, -((1 << 63) - 1))
    assert f2.value(3) == (-((1 << 63) - 1), True)


def test_fragment_row_id_cap_per_instance():
    """The cap is per-instance (threaded from server config), not a
    process-wide class global (ADVICE r2)."""
    small = Fragment(None, "i", "f", "standard", 0, row_id_cap=100)
    big = Fragment(None, "i", "f", "standard", 1, row_id_cap=10_000)
    with pytest.raises(ValueError, match="max_row_id"):
        small.set_bit(101, 0)
    assert big.set_bit(101, 0)  # independent caps


def test_fragment_row_id_cap():
    """Hostile row ids must be rejected before the dense allocation
    (ADVICE: rowIDs=[2**40] would attempt a terabyte-scale allocation)."""
    frag = Fragment(None, "i", "f", "standard", 0)
    with pytest.raises(ValueError, match="max_row_id"):
        frag.set_bit(2 ** 40, 0)
    with pytest.raises(ValueError, match="max_row_id"):
        frag.bulk_import(np.array([1, 2 ** 40]), np.array([0, 1]))
    assert frag.n_rows == 0  # nothing allocated


def test_fragment_clear_above_cap_is_noop():
    """clear_bit beyond capacity (or even beyond row_id_cap) is a silent
    no-op: those rows cannot hold set bits, and growing capacity for a
    clear would force a device-shape recompile (r3 advisor)."""
    frag = Fragment(None, "i", "f", "standard", 0)
    frag.set_bit(1, 7)
    cap = frag.n_rows
    assert frag.clear_bit(cap + 5, 7) is False
    assert frag.clear_bit(2 ** 40, 7) is False  # above row_id_cap: no raise
    assert frag.n_rows == cap  # no capacity growth
    assert frag.bulk_import(np.array([cap + 1]), np.array([3]),
                            clear=True) == 0
    assert frag.n_rows == cap


def test_mutex_import_noop_counts_zero():
    """Re-importing the identical winner bits must report 0 changes
    (fragment.go:2106 bulkImportMutex reports real deltas; r3 advisor)."""
    frag = Fragment(None, "i", "f", "standard", 0)
    rows = np.array([2, 3, 2])
    cols = np.array([10, 11, 12])
    first = frag.mutex_import(rows, cols)
    assert first == 3
    gen = frag.gen
    assert frag.mutex_import(rows, cols) == 0
    assert frag.gen == gen  # no-op must not invalidate derived caches
    # moving one column to a new row counts the clear and the set
    assert frag.mutex_import(np.array([4]), np.array([10])) == 2
    assert frag.gen != gen


def test_field_import_values():
    f = Field(None, "i", "f", FieldOptions(type="int", min=-100, max=100))
    cols = np.array([1, SHARD_WIDTH + 2, 3])
    vals = np.array([-50, 75, 0])
    f.import_values(cols, vals)
    assert f.value(1) == (-50, True)
    assert f.value(SHARD_WIDTH + 2) == (75, True)
    assert f.value(3) == (0, True)


def test_field_import_bits_with_time():
    f = Field(None, "i", "f", FieldOptions(type="time", time_quantum="YM"))
    ts = datetime(2018, 1, 2)
    f.import_bits(np.array([1, 1]), np.array([5, 6]), [ts, None])
    assert set(f.views) == {"standard", "standard_2018", "standard_201801"}
    assert set(bitset.unpack_columns(f.row(1)[0]).tolist()) == {5, 6}
    assert set(bitset.unpack_columns(
        f.row(1, "standard_2018")[0]).tolist()) == {5}


# -- holder/index -----------------------------------------------------------

def test_holder_schema_and_persistence(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("myindex")
    idx.create_field("myfield", FieldOptions(type="set"))
    idx.create_field("quant", FieldOptions(type="int", min=0, max=1000))
    f = idx.field("myfield")
    f.set_bit(1, 200)
    idx.field("quant").set_value(200, 55)
    idx.add_existence(np.array([200]))
    h.close()

    h2 = Holder(str(tmp_path / "data"))
    h2.open()
    idx2 = h2.index("myindex")
    assert idx2 is not None
    assert {f["name"] for f in h2.schema()[0]["fields"]} == {"myfield", "quant"}
    assert set(bitset.unpack_columns(
        idx2.field("myfield").row(1)[0]).tolist()) == {200}
    assert idx2.field("quant").value(200) == (55, True)
    assert set(bitset.unpack_columns(
        idx2.existence_row()[0]).tolist()) == {200}
    h2.close()


def test_holder_delete_index(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    h.create_index("a")
    h.delete_index("a")
    assert h.index("a") is None
    with pytest.raises(ValueError):
        h.delete_index("a")


def test_index_validates_names(tmp_path):
    h = Holder(None)
    with pytest.raises(ValueError):
        h.create_index("Bad")
    with pytest.raises(ValueError):
        h.create_index("9start")
    idx = h.create_index("ok")
    with pytest.raises(Exception):
        idx.create_field("_reserved")


def test_existence_field_tracks_columns():
    h = Holder(None)
    idx = h.create_index("i")
    assert EXISTENCE_FIELD_NAME in idx.fields
    idx.add_existence(np.array([1, 2, SHARD_WIDTH + 3]))
    segs = idx.existence_row()
    assert set(bitset.unpack_columns(segs[0]).tolist()) == {1, 2}
    assert set(bitset.unpack_columns(segs[1]).tolist()) == {3}


# -- time quantum (time_internal_test.go mirror) ----------------------------

def test_views_by_time():
    ts = datetime(2017, 3, 20, 10)
    assert tq.views_by_time("std", ts, "YMDH") == [
        "std_2017", "std_201703", "std_20170320", "std_2017032010"]


def test_views_by_time_range_ymdh():
    # mirrors time_internal_test.go TestViewsByTimeRange
    got = tq.views_by_time_range(
        "F", datetime(2016, 12, 30, 22), datetime(2017, 1, 2, 8), "YMDH")
    assert got == [
        "F_2016123022", "F_2016123023", "F_20161231",
        "F_20170101", "F_2017010200", "F_2017010201", "F_2017010202",
        "F_2017010203", "F_2017010204", "F_2017010205", "F_2017010206",
        "F_2017010207"]


def test_views_by_time_range_y():
    got = tq.views_by_time_range(
        "F", datetime(2015, 1, 1), datetime(2018, 1, 1), "Y")
    assert got == ["F_2015", "F_2016", "F_2017"]


def test_min_max_views():
    views = ["f_2017", "f_201701", "f_20170101", "f_2016"]
    lo, hi = tq.min_max_views(views, "YMD")
    assert (lo, hi) == ("f_2016", "f_2017")


def test_quantum_validation():
    tq.validate_quantum("YMDH")
    with pytest.raises(tq.InvalidTimeQuantumError):
        tq.validate_quantum("X")
    with pytest.raises(tq.InvalidTimeQuantumError):
        tq.validate_quantum("HY")
