"""Tail-tolerant cluster reads under NETWORK faults (ISSUE 14,
docs/robustness.md "Tail-tolerant fan-out" / "Network chaos").

Unlike the failpoint suite (test_overload.py), the cluster tests here
inject faults at the SOCKET layer: every peer is dialed through a
ChaosProxy (utils/netchaos.py), so stragglers, mid-stream RSTs, and
partitions are real TCP behavior, not in-process exceptions.

Covers: ChaosProxy forwarding + fault modes; the shared failpoint spec
grammar; hedge-delay derivation and hedge-candidate selection; the
shard-discovery poll routing through the prober's consecutive-miss
accounting (one transient poll failure must not flip a READY node
DOWN); hedged reads beating a proxied straggler with byte-identical
answers; immediate mid-query failover off a partitioned peer; the
partial-results contract (degraded.missingShards names EXACTLY the
lost shards); the hedging differential (on vs off answers identical);
writes never hedging; and — slow-marked — a 20-cycle churn soak
(kill/restart/partition under concurrent queries + streaming ingest,
zero wrong answers, zero acked-write loss, bounded p99).
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.parallel.cluster import Cluster
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.storage import Holder
from pilosa_tpu.utils import degraded
from pilosa_tpu.utils.faults import parse_spec
from pilosa_tpu.utils.netchaos import ChaosProxy

from test_cluster import _free_ports, _req, query


# -- unit: shared spec grammar + proxy mechanics ----------------------------

def test_parse_spec_shared_grammar():
    got = parse_spec("down=latency:0.25@peer1#3; connect=partition")
    assert got == [("down", "latency", 0.25, "peer1", 3),
                   ("connect", "partition", 0.0, None, None)]
    with pytest.raises(ValueError):
        parse_spec("nomode")


def test_chaos_proxy_rejects_unknown_sites_and_modes():
    srv = socket.socket()
    srv.bind(("localhost", 0))
    srv.listen(1)
    proxy = ChaosProxy("localhost", srv.getsockname()[1])
    try:
        with pytest.raises(ValueError):
            proxy.arm("sideways", "latency")
        with pytest.raises(ValueError):
            proxy.arm("down", "explode")
        # failpoint-registry modes are NOT network modes: the shared
        # grammar parses, the proxy's own mode set rejects
        with pytest.raises(ValueError):
            # lint: allow(failpoint-names) — deliberately-bad proxy spec
            # (registry mode on a proxy site); never armed on FAULTS
            proxy.configure("down=delay:0.1")
    finally:
        proxy.close()
        srv.close()


def _echo_server():
    """A tiny TCP echo server; returns (sock, port, closer)."""
    srv = socket.socket()
    srv.bind(("localhost", 0))
    srv.listen(8)

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            def pump(c=conn):
                try:
                    while True:
                        b = c.recv(65536)
                        if not b:
                            return
                        c.sendall(b)
                except OSError:
                    pass
                finally:
                    c.close()
            threading.Thread(target=pump, daemon=True).start()

    threading.Thread(target=serve, daemon=True).start()
    return srv, srv.getsockname()[1]


def test_chaos_proxy_forwards_latency_and_rst():
    srv, port = _echo_server()
    proxy = ChaosProxy("localhost", port)
    try:
        # clean forwarding round trip
        c = socket.create_connection(("localhost", proxy.port), timeout=5)
        c.sendall(b"hello")
        assert c.recv(64) == b"hello"
        # latency on the response direction
        proxy.configure("down=latency:0.15")
        t0 = time.perf_counter()
        c.sendall(b"slow")
        assert c.recv(64) == b"slow"
        assert time.perf_counter() - t0 >= 0.14
        proxy.heal()
        c.close()
        # mid-response RST: the client sees a reset, not a FIN
        proxy.configure("down=rst")
        c2 = socket.create_connection(("localhost", proxy.port), timeout=5)
        c2.sendall(b"boom")
        with pytest.raises(OSError):
            got = c2.recv(64)
            if got == b"":          # platform surfaced the RST as EOF:
                raise ConnectionResetError  # still a dead connection
        c2.close()
        snap = proxy.snapshot()
        assert snap["bytesUp"] >= 9 and snap["bytesDown"] >= 9
        assert snap["rsts"] >= 1
        assert snap["connections"] >= 2
    finally:
        proxy.close()
        srv.close()


def test_chaos_proxy_partition_and_blackhole():
    srv, port = _echo_server()
    proxy = ChaosProxy("localhost", port)
    try:
        proxy.configure("connect=partition")
        with pytest.raises(OSError):
            c = socket.create_connection(("localhost", proxy.port),
                                         timeout=2)
            c.sendall(b"x")
            if c.recv(16) == b"":
                raise ConnectionResetError
        proxy.heal()
        # half-open drop: bytes vanish, the sender's read times out
        proxy.configure("up=blackhole")
        c2 = socket.create_connection(("localhost", proxy.port), timeout=2)
        c2.settimeout(0.3)
        c2.sendall(b"into-the-void")
        with pytest.raises(TimeoutError):
            c2.recv(64)
        c2.close()
        assert proxy.snapshot()["droppedBytes"] >= 13
    finally:
        proxy.close()
        srv.close()


# -- unit: discovery polls ride the prober's miss accounting ----------------

def test_single_poll_failure_keeps_node_ready():
    """Satellite bugfix: one transient _available_shards poll failure
    must count ONE probe miss (health-down-threshold discipline), not
    flip the peer DOWN outright and silently shrink every later
    fan-out wave."""
    cl = Cluster("node0", ["localhost:1", "localhost:2"], replica_n=1,
                 holder=Holder(None), health_down_threshold=2)
    try:
        calls = {"n": 0}

        def boom(host, index, timeout=None):
            calls["n"] += 1
            raise socket.timeout("discovery poll timed out")

        cl.client.available_shards = boom
        cl._available_shards("i")
        assert cl.by_id["node1"].state == "READY"      # one miss
        assert cl.by_id["node1"].probe_fails == 1
        assert cl.state != "DEGRADED"
        cl._available_shards("i")
        assert cl.by_id["node1"].state == "DOWN"       # second miss
        # success clears the streak exactly like a successful probe
        cl.by_id["node1"].state = "READY"
        cl.client.available_shards = lambda host, index, timeout=None: [0]
        cl._available_shards("i")
        assert cl.by_id["node1"].probe_fails == 0
        # informational callers never touch health accounting
        cl.client.available_shards = boom
        cl._available_shards("i", mark_down=False)
        assert cl.by_id["node1"].probe_fails == 0
    finally:
        cl.close()


# -- unit: hedge delay + candidate selection --------------------------------

def test_hedge_delay_derivation():
    cl = Cluster("node0", ["localhost:1", "localhost:2", "localhost:3"],
                 replica_n=2, holder=Holder(None))
    try:
        r = cl.router
        assert r.hedge_delay(0.2) == 0.2         # fixed knob wins
        assert r.hedge_delay(0.0) is None        # cold: never hedge blind
        r.note_dispatch("node1", 1)
        r.note_done("node1", 0.05)
        r.note_dispatch("node2", 1)
        r.note_done("node2", 0.5)
        # 4x the CHEAPEST known EWMA — not the straggler's own
        assert abs(r.hedge_delay(0.0) - 0.2) < 1e-9
        r.note_done("node1", None, ok=False)     # errors don't feed EWMA
        assert abs(r.hedge_delay(0.0) - 0.2) < 1e-9
    finally:
        cl.close()


def test_hedge_candidate_owns_all_shards_and_skips_self():
    cl = Cluster("node0", ["localhost:1", "localhost:2", "localhost:3"],
                 replica_n=2, holder=Holder(None))
    try:
        shard = next(s for s in range(64)
                     if "node0" not in cl.placement.shard_nodes("i", s))
        a, b = cl.placement.shard_nodes("i", shard)
        # hedging the group dispatched to `a`: only `b` qualifies
        # (node0 is excluded as self — local execution never hedges)
        assert cl.router.hedge_candidate("i", [shard], {a}) == b
        # a DOWN candidate never hedges
        cl.by_id[b].state = "DOWN"
        assert cl.router.hedge_candidate("i", [shard], {a}) is None
        cl.by_id[b].state = "READY"
        # a group spanning shards with no COMMON remaining owner can't
        # hedge (a partial hedge would double-count shards inside the
        # group's aggregate answer): pick a shard `b` does NOT own —
        # its owners are then a subset of {node0, a}, both excluded
        other = next(s for s in range(64)
                     if b not in cl.placement.shard_nodes("i", s))
        assert cl.router.hedge_candidate("i", [shard, other],
                                         {a}) is None
    finally:
        cl.close()


# -- unit: degraded accumulator (partial contract) --------------------------

def test_degraded_partial_accumulator():
    assert degraded.partial_allowed() is False   # inert outside collect
    degraded.note_missing("i", [1, 2])           # no-op, no crash
    with degraded.collect(allow_partial=False) as acc:
        assert degraded.partial_allowed() is False
        degraded.note(2)
        assert degraded.to_response(acc) == {"quarantinedFragments": 2}
    with degraded.collect(allow_partial=True) as acc:
        assert degraded.partial_allowed() is True
        assert degraded.is_partial() is False
        degraded.note_missing("i", [3, 1], nodes=["node1"])
        degraded.note_missing("i", [3, 7], nodes=["node2"])
        assert degraded.is_partial() is True
        out = degraded.to_response(acc)
        assert out["missingShards"] == {"i": [1, 3, 7]}
        assert out["missingNodes"] == ["node1", "node2"]
    assert degraded.is_partial() is False


# -- proxied 3-node cluster (real sockets) ----------------------------------

N_SHARDS = 8


class _ProxiedCluster:
    """3 real servers; node1/node2 are dialed THROUGH ChaosProxies by
    every peer, so network faults on them are real TCP behavior."""

    def __init__(self, tmp_path):
        binds = _free_ports(3)
        self.servers = []
        self.proxies = {}
        hosts = [f"localhost:{binds[0]}"]
        for i in (1, 2):
            proxy = ChaosProxy("localhost", binds[i])
            self.proxies[f"node{i}"] = proxy
            hosts.append(proxy.address)
        for i, p in enumerate(binds):
            srv = Server(Config(
                data_dir=str(tmp_path / f"node{i}"),
                bind=f"localhost:{p}", node_id=f"node{i}",
                cluster_hosts=hosts, replica_n=2,
                anti_entropy_interval=0,
                read_routing="primary",     # deterministic targeting
                hedge_delay_ms=40.0))
            srv.open()
            self.servers.append(srv)
        self.port = self.servers[0].port
        self.cl = self.servers[0].cluster
        # pick an index name whose placement gives node0 SOME shards
        # but not all (jump-hash is name-keyed; a tiny shard count can
        # land every replica set on node0 by chance) — the partial-
        # results test needs both truly-remote and locally-served shards
        self.index = next(
            name for name in (f"tt{i}" for i in range(64))
            if 0 < len(self._remote_owned(name)) < N_SHARDS)
        _req(self.port, "POST", f"/index/{self.index}", {})
        _req(self.port, "POST", f"/index/{self.index}/field/f", {})
        cols = [s * SHARD_WIDTH + (s % 5) for s in range(N_SHARDS)]
        _req(self.port, "POST", f"/index/{self.index}/field/f/import",
             {"rowIDs": [1] * len(cols), "columnIDs": cols})
        [self.count_all] = query(self.port, self.index,
                                 "Count(Row(f=1))")

    def heal(self):
        for proxy in self.proxies.values():
            proxy.heal()
        # force probe recovery instead of waiting out the health cadence
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            self.cl.probe_peers()
            if all(n.state == "READY" for n in self.cl.nodes):
                return
            time.sleep(0.1)
        raise AssertionError(
            f"peers never recovered: "
            f"{[(n.id, n.state) for n in self.cl.nodes]}")

    def close(self):
        for s in self.servers:
            try:
                s.close()
            except Exception:
                pass
        for proxy in self.proxies.values():
            proxy.close()

    def _remote_owned(self, index):
        return [s for s in range(N_SHARDS)
                if "node0" not in
                self.cl.placement.shard_nodes(index, s)]

    def remote_owned(self):
        """Shards owned by node1+node2 only (node0 holds no replica)."""
        return self._remote_owned(self.index)


@pytest.fixture(scope="module")
def proxied(tmp_path_factory):
    # module-scoped on purpose: one 3-node spin-up (seconds of XLA +
    # server setup) amortizes over every real-socket test below; each
    # test heals the proxies and restores READY before returning
    c = _ProxiedCluster(tmp_path_factory.mktemp("churn"))
    yield c
    c.close()


def _counts(port):
    return _req(port, "GET", "/debug/vars")["counts"]


def test_hedged_read_beats_proxied_straggler(proxied):
    """A replica delayed FAR past the hedge delay must not set the
    query's latency: the hedge fires at the other replica and its
    answer wins, byte-identical to the healthy answer."""
    shards = proxied.remote_owned()
    assert shards, "placement gave node0 every shard replica?"
    s = shards[0]
    straggler = proxied.cl._ready_owner_order(proxied.index, s)[0]
    [want] = query(proxied.port, proxied.index, "Count(Row(f=1))")
    before = _counts(proxied.port)
    delay = 1.0
    proxied.proxies[straggler].configure(f"down=latency:{delay}")
    try:
        t0 = time.perf_counter()
        got = _req(proxied.port, "POST",
                   f"/index/{proxied.index}/query?shards={s}", "Count(Row(f=1))")
        elapsed = time.perf_counter() - t0
    finally:
        proxied.heal()
    assert got["results"] == [1]
    assert "degraded" not in got          # hedged != partial
    assert elapsed < delay * 0.7, \
        f"hedge never rescued the query ({elapsed:.2f}s)"
    after = _counts(proxied.port)
    assert after.get("cluster.hedges", 0) > before.get("cluster.hedges", 0)
    assert after.get("cluster.hedge_wins", 0) > \
        before.get("cluster.hedge_wins", 0)
    # per-peer hedge state surfaces at /debug/vars cluster.routing
    peers = _req(proxied.port, "GET",
                 "/debug/vars")["cluster"]["routing"]["peers"]
    assert any(p.get("hedgeWins", 0) >= 1 for p in peers.values())
    # full query afterwards: answers unchanged
    assert query(proxied.port, proxied.index, "Count(Row(f=1))") == [want]


def test_hedged_full_query_straggler_group(proxied):
    """A FULL-index query's straggler group rarely has one common
    alternate owner under jump-hash: the hedge then splits across
    replica subgroups via the router's own grouping — every shard still
    gets its speculative second chance, and the straggler never sets
    the query's latency."""
    shards = proxied.remote_owned()
    straggler = proxied.cl._ready_owner_order(proxied.index,
                                              shards[0])[0]
    before = _counts(proxied.port)
    delay = 1.0
    proxied.proxies[straggler].configure(f"down=latency:{delay}")
    try:
        t0 = time.perf_counter()
        got = _req(proxied.port, "POST",
                   f"/index/{proxied.index}/query", "Count(Row(f=1))")
        elapsed = time.perf_counter() - t0
    finally:
        proxied.heal()
    assert got["results"] == [proxied.count_all]
    assert "degraded" not in got
    assert elapsed < delay * 0.7, \
        f"full-query hedge never rescued ({elapsed:.2f}s)"
    after = _counts(proxied.port)
    assert after.get("cluster.hedges", 0) > before.get("cluster.hedges", 0)


def test_partitioned_peer_fails_over_mid_query(proxied):
    """Hard partition (accept+RST, live flows severed) on one replica:
    the fan-out re-dispatches its shards to the surviving owner
    IMMEDIATELY (cluster.retry_waves) and the answer stays complete."""
    before = _counts(proxied.port)
    proxy = proxied.proxies["node1"]
    proxy.configure("connect=partition")
    proxy.sever()
    try:
        t0 = time.perf_counter()
        [got] = query(proxied.port, proxied.index, "Count(Row(f=1))")
        elapsed = time.perf_counter() - t0
    finally:
        proxied.heal()
    assert got == proxied.count_all       # full answer off replicas
    assert elapsed < 20.0                 # never a full socket timeout
    after = _counts(proxied.port)
    assert after.get("cluster.retry_waves", 0) > \
        before.get("cluster.retry_waves", 0)


def test_partial_results_names_exact_missing_shards(proxied):
    """With BOTH remote nodes partitioned, shards node0 doesn't own are
    truly unservable: without the opt-in the query fails with the
    per-node attempt log; with ?partialResults=true it answers 200 and
    degraded.missingShards lists EXACTLY those shards."""
    lost = proxied.remote_owned()
    served = [s for s in range(N_SHARDS) if s not in lost]
    for nid in ("node1", "node2"):
        proxied.proxies[nid].configure("connect=partition")
        proxied.proxies[nid].sever()
    try:
        # loud failure without the opt-in, with the attempt trail
        try:
            query(proxied.port, proxied.index, "Count(Row(f=1))")
            raise AssertionError("unservable shards answered without "
                                 "partialResults")
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            assert "attempts:" in body["error"]
        got = _req(proxied.port, "POST",
                   f"/index/{proxied.index}/query?partialResults=true",
                   "Count(Row(f=1))")
        assert got["results"] == [len(served)]
        deg = got["degraded"]
        assert deg["missingShards"] == {proxied.index: sorted(lost)}
        assert set(deg["missingNodes"]) <= {"node1", "node2"}
        assert _counts(proxied.port).get("cluster.partial_results", 0) >= 1
        # Row over the partial scope: the served segments are intact
        row = _req(proxied.port, "POST",
                   f"/index/{proxied.index}/query?partialResults=true", "Row(f=1)")
        assert "degraded" in row
    finally:
        proxied.heal()
    # healed: complete answers, no degraded object
    full = _req(proxied.port, "POST", f"/index/{proxied.index}/query",
                "Count(Row(f=1))")
    assert full["results"] == [proxied.count_all]
    assert "degraded" not in full


def test_hedging_differential_no_fault_byte_identical(proxied):
    """With no fault armed, aggressive hedging must be invisible in the
    answers: every query result byte-identical to the hedge-off run."""
    queries = ["Count(Row(f=1))", "Row(f=1)", "TopN(f, n=0)",
               "Count(Intersect(Row(f=1), Row(f=1)))"]
    cl = proxied.cl
    old_delay = cl.hedge_delay_ms
    cl.hedge_delay_ms = 0.001     # hedge every remote dispatch
    try:
        before = _counts(proxied.port)
        hedged = [query(proxied.port, proxied.index, q) for q in queries]
        assert _counts(proxied.port).get("cluster.hedges", 0) > \
            before.get("cluster.hedges", 0), "hedges never fired"
        cl.hedge_reads = False
        unhedged = [query(proxied.port, proxied.index, q) for q in queries]
    finally:
        cl.hedge_reads = True
        cl.hedge_delay_ms = old_delay
    assert json.dumps(hedged, sort_keys=True) == \
        json.dumps(unhedged, sort_keys=True)


def test_writes_are_never_hedged(proxied):
    """Writes fan through their replica-synchronous paths: even with an
    instant hedge delay, no write dispatch may hedge."""
    cl = proxied.cl
    old_delay = cl.hedge_delay_ms
    cl.hedge_delay_ms = 0.001
    try:
        before = _counts(proxied.port).get("cluster.hedges", 0)
        for s in range(4):
            query(proxied.port, proxied.index,
                  f"Set({s * SHARD_WIDTH + 99}, f=7)")
        _req(proxied.port, "POST", f"/index/{proxied.index}/field/f/import",
             {"rowIDs": [8, 8], "columnIDs": [5, SHARD_WIDTH + 5]})
        assert _counts(proxied.port).get("cluster.hedges", 0) == before
    finally:
        cl.hedge_delay_ms = old_delay
        for s in range(4):
            query(proxied.port, proxied.index,
                  f"Clear({s * SHARD_WIDTH + 99}, f=7)")


# -- churn soak (slow): kill/restart/partition under live load --------------

@pytest.mark.slow
def test_churn_soak_no_wrong_answers_no_acked_loss(tmp_path):
    """20 churn cycles (partition / straggler / mid-response RSTs /
    kill -> restart) against a 3-node proxied cluster under concurrent
    reads + binary streaming ingest.  Invariants: a 200 read's count is
    never below the acked-distinct-column watermark at issue time nor
    above the sent total (zero wrong answers), every acked ingest
    column survives to the end (zero acked-write loss), and
    successful-read p99 stays bounded."""
    from pilosa_tpu.ingest import wire

    binds = _free_ports(3)
    proxies = {}
    hosts = [f"localhost:{binds[0]}"]
    for i in (1, 2):
        proxies[f"node{i}"] = ChaosProxy("localhost", binds[i])
        hosts.append(proxies[f"node{i}"].address)
    cfgs = [Config(data_dir=str(tmp_path / f"node{i}"),
                   bind=f"localhost:{binds[i]}", node_id=f"node{i}",
                   cluster_hosts=hosts, replica_n=2,
                   anti_entropy_interval=0)
            for i in range(3)]
    servers = [Server(c) for c in cfgs]
    for s in servers:
        s.open()
    p0 = servers[0].port
    stop = threading.Event()
    state_lock = threading.Lock()
    acked_cols: set[int] = {1}     # cols whose ingest ack arrived
    sent_cols: set[int] = {1}      # cols ever sent (acked or not —
    #                                an ack lost mid-churn may still
    #                                have durably applied)
    lats: list[float] = []
    wrong: list[str] = []

    try:
        _req(p0, "POST", "/index/ch", {})
        _req(p0, "POST", "/index/ch/field/f", {})
        query(p0, "ch", "Set(1, f=1)")

        def writer():
            # deterministic fresh batches, spread over 4 shards; a
            # failed batch retries verbatim (idempotent frames) before
            # the next one, so `acked_cols` only ever grows
            batch_no = 0
            while not stop.is_set():
                base = 8 + batch_no * 16
                cols = np.asarray(
                    [(base + j) * 977 % (4 * SHARD_WIDTH)
                     for j in range(16)], dtype=np.int64)
                body = wire.encode_records(
                    np.ones(cols.size, dtype=np.int64), cols)
                with state_lock:
                    sent_cols.update(int(c) for c in cols)
                req = urllib.request.Request(
                    f"http://localhost:{p0}/index/ch/field/f/ingest",
                    method="POST", data=body)
                while not stop.is_set():
                    try:
                        with urllib.request.urlopen(req,
                                                    timeout=30) as resp:
                            resp.read()
                        with state_lock:
                            acked_cols.update(int(c) for c in cols)
                        break
                    except Exception:
                        time.sleep(0.05)   # refused/cut: retry verbatim
                batch_no += 1
                time.sleep(0.005)

        def reader():
            while not stop.is_set():
                with state_lock:
                    floor = len(acked_cols)
                t0 = time.perf_counter()
                try:
                    [n] = query(p0, "ch", "Count(Row(f=1))")
                except Exception:
                    continue  # churn may refuse/cut queries; only
                    #           ANSWERS are held to correctness
                lats.append(time.perf_counter() - t0)
                with state_lock:
                    ceil = len(sent_cols)
                if n < floor:
                    wrong.append(f"count {n} < acked floor {floor}")
                if n > ceil:
                    wrong.append(f"count {n} > sent ceiling {ceil}")
                time.sleep(0.01)

        wt = threading.Thread(target=writer, daemon=True)
        rt = threading.Thread(target=reader, daemon=True)
        wt.start()
        rt.start()

        for cycle in range(20):
            ev = cycle % 4
            nid = f"node{1 + (cycle % 2)}"
            if ev == 0:       # hard partition + heal
                proxies[nid].configure("connect=partition")
                proxies[nid].sever()
                time.sleep(0.4)
                proxies[nid].heal()
            elif ev == 1:     # straggler
                proxies[nid].configure("down=latency:0.3")
                time.sleep(0.4)
                proxies[nid].heal()
            elif ev == 2:     # mid-response resets
                proxies[nid].configure("down=rst#2")
                time.sleep(0.3)
                proxies[nid].heal()
            else:             # kill -> restart (same port, same data)
                i = 1 + (cycle % 2)
                servers[i].close()
                time.sleep(0.2)
                servers[i] = Server(cfgs[i])
                servers[i].open()
            servers[0].cluster.probe_peers()
        stop.set()
        wt.join(timeout=60)
        rt.join(timeout=60)
        assert not (wt.is_alive() or rt.is_alive()), "hung load thread"
        assert not wrong, wrong[:5]

        # quiesce: heal everything, restore READY, let anti-entropy
        # converge any divergence churn left behind
        for proxy in proxies.values():
            proxy.heal()
        deadline = time.monotonic() + 20
        cl = servers[0].cluster
        while time.monotonic() < deadline:
            cl.probe_peers()
            if all(n.state == "READY" for n in cl.nodes):
                break
            time.sleep(0.2)
        for s in servers:
            s.cluster.sync_holder()

        # zero acked-write loss: every acked column is present
        with state_lock:
            want_cols = set(acked_cols)
        row = query(p0, "ch", "Row(f=1)")[0]
        got_cols = set(row["columns"])
        missing = want_cols - got_cols
        assert not missing, f"acked writes lost: {sorted(missing)[:10]}"

        # bounded p99 across the whole churn
        assert lats, "reader never completed a query"
        lats.sort()
        p99 = lats[max(int(len(lats) * 0.99) - 1, 0)]
        assert p99 < 30.0, f"p99 {p99:.2f}s under churn"
        # every node answers identically after convergence
        counts = {s.config.node_id:
                  query(s.port, "ch", "Count(Row(f=1))")[0]
                  for s in servers}
        assert len(set(counts.values())) == 1, counts
    finally:
        stop.set()
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
        for proxy in proxies.values():
            proxy.close()
