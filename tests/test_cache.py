"""Query cache subsystem tests (docs/caching.md).

Covers: FieldOptions cache-option validation (satellite), RankCache
build/incremental/bound semantics, the exact TopN candidate-pruning path,
a differential suite asserting cached results byte-identical to
``cache-type: none`` across a PQL corpus with interleaved
set/clear/import/repair/attr writes, result-cache hit/invalidate/evict
behavior, and a 2-node test that a remote import invalidates the
coordinator's result-cache entry."""

import json

import numpy as np
import pytest

from pilosa_tpu.api import API, ApiError
from pilosa_tpu.cache.rank import RankCache, topn_from_rank
from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.handler import serialize_result
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.field import FieldError


# -- FieldOptions validation (satellite) ------------------------------------

def test_field_options_rejects_unknown_cache_type():
    with pytest.raises(FieldError, match="cacheType"):
        FieldOptions(cache_type="bogus")


def test_field_options_rejects_negative_cache_size():
    with pytest.raises(FieldError, match="cacheSize"):
        FieldOptions(cache_size=-1)
    with pytest.raises(FieldError, match="cacheSize"):
        FieldOptions.from_dict({"cacheSize": "fifty"})


def test_create_field_bad_cache_options_is_api_error():
    """The HTTP layer maps ApiError to 400 — a bad cacheType must fail at
    field creation, not be silently persisted into the schema."""
    api = API(Holder(None), use_mesh=False)
    api.create_index("i")
    with pytest.raises(ApiError, match="cacheType"):
        api.create_field("i", "f", {"cacheType": "rankedd"})
    with pytest.raises(ApiError, match="cacheSize"):
        api.create_field("i", "g", {"cacheSize": -5})
    # valid options still work
    api.create_field("i", "h", {"cacheType": "lru", "cacheSize": 10})


# -- RankCache unit behavior -------------------------------------------------

def _frag_with_counts(holder, counts, field="f", index="i"):
    """One-shard field whose row r has ``counts[r]`` bits."""
    idx = holder.index(index) or holder.create_index(
        index, track_existence=False)
    f = idx.field(field) or idx.create_field(field)
    rows, cols = [], []
    for r, c in enumerate(counts):
        rows += [r] * c
        cols += list(range(c))
    f.import_bits(np.array(rows), np.array(cols))
    from pilosa_tpu.core import VIEW_STANDARD
    return f, f.view(VIEW_STANDARD).fragment(0)


def test_rank_cache_complete_build():
    h = Holder(None)
    _f, frag = _frag_with_counts(h, [5, 3, 10, 1])
    rc = frag.rank_cache
    assert rc is not None and rc.dirty  # lazily built
    rc.ensure(frag)
    assert rc.complete and rc.bound == 0
    assert rc.rows == {0: 5, 1: 3, 2: 10, 3: 1}


def test_rank_cache_incremental_and_zero_row_removal():
    h = Holder(None)
    f, frag = _frag_with_counts(h, [5, 3])
    frag.rank_cache.ensure(frag)
    f.set_bit(1, 100)
    assert frag.rank_cache.rows[1] == 4
    f.clear_bit(0, 0)
    assert frag.rank_cache.rows[0] == 4
    for c in range(4):
        f.clear_bit(1, c if c < 3 else 100)
    assert 1 not in frag.rank_cache.rows
    assert frag.rank_cache.complete  # still knows every nonzero row


def test_rank_cache_bound_ratchets_on_eviction():
    h = Holder(None)
    _f, frag = _frag_with_counts(h, [10, 9, 8, 7, 6])
    rc = RankCache("ranked", 3)
    frag.rank_cache = rc
    rc.build(frag)
    assert not rc.complete
    assert set(rc.rows) == {0, 1, 2}
    assert rc.bound == 7  # best excluded count
    # a write pushing row 4 above the floor evicts row 2 and ratchets
    frag.bulk_import(np.full(3, 4), np.arange(100, 103))
    assert 4 in rc.rows and 2 not in rc.rows
    assert rc.bound == 8 and rc.degraded()


def test_rank_cache_bulk_write_marks_dirty():
    from pilosa_tpu.cache import rank as rank_mod
    h = Holder(None)
    _f, frag = _frag_with_counts(h, [2, 2])
    frag.rank_cache.ensure(frag)
    old = rank_mod.RANK_REBUILD_ROWS
    rank_mod.RANK_REBUILD_ROWS = 4
    try:
        rows = np.arange(10)
        frag.bulk_import(rows, rows + 50)
        assert frag.rank_cache.dirty
        frag.rank_cache.ensure(frag)
        assert not frag.rank_cache.dirty
    finally:
        rank_mod.RANK_REBUILD_ROWS = old


def test_topn_from_rank_pruning_and_fallback():
    h = Holder(None)
    f, frag = _frag_with_counts(h, [100, 90, 80, 10, 9, 8])
    frag.rank_cache = RankCache("ranked", 3)
    # n=1: top candidate (100) strictly beats the bound (10) -> exact
    pairs = topn_from_rank(f, [0], 1)
    assert [(p.id, p.count) for p in pairs] == [(0, 100)]
    # n=0 (unlimited) needs every nonzero row: incomplete cache -> fallback
    assert topn_from_rank(f, [0], 0) is None
    # n=4: the 4th candidate doesn't exist in the cache -> fallback
    assert topn_from_rank(f, [0], 4) is None


# -- differential suite: cached vs cache-type=none ---------------------------

CORPUS = [
    "TopN(f)",
    "TopN(f, n=1)",
    "TopN(f, n=2)",
    "TopN(f, Row(f=1), n=2)",
    "Count(Row(f=1))",
    "Count(Union(Row(f=0), Row(f=2)))",
    "Row(f=0)",
    "Row(f=1)",
    "Rows(f)",
    "Sum(Row(v > 10), field=v)",
    "Min(field=v)",
    "GroupBy(Rows(f))",
]


def _build_pair(rng, cache_type):
    """Two identically-loaded holders differing only in f's cacheType."""
    h = Holder(None)
    idx = h.create_index("i", track_existence=False)
    idx.create_field("f", FieldOptions(cache_type=cache_type, cache_size=4))
    idx.create_field("v", FieldOptions(type="int", min=0, max=1000))
    f = idx.field("f")
    f.import_bits(rng.integers(0, 8, size=400),
                  rng.integers(0, 2 * SHARD_WIDTH, size=400))
    cols_v = np.unique(rng.integers(0, SHARD_WIDTH, size=100)) + 7
    idx.field("v").import_values(cols_v,
                                 rng.integers(0, 1000, size=cols_v.size))
    return h


def _snap(ex, index="i"):
    return [json.dumps(serialize_result(ex.execute(index, q)[0]),
                       sort_keys=True) for q in CORPUS]


def test_differential_cached_vs_none_interleaved_writes(rng):
    """Byte-identical results between cacheType=ranked (+ result cache)
    and cacheType=none across the corpus, with set/clear/import/repair/
    attr writes interleaved between query rounds."""
    rng2 = np.random.default_rng(42)
    h_ranked = _build_pair(rng, "ranked")
    h_none = _build_pair(rng2, "none")
    ex_ranked = Executor(h_ranked)
    ex_none = Executor(h_none)
    ex_ranked.result_cache.limit_bytes = 8 << 20  # caches ON vs OFF

    def mutate(h):
        f = h.index("i").field("f")
        f.set_bit(2, 123)
        f.clear_bit(1, 5)
        f.import_bits(np.array([0, 3, 9]), np.array([7, 8, 9]))
        # anti-entropy repair analog: a clear-side bulk import
        from pilosa_tpu.core import VIEW_STANDARD
        frag = f.view(VIEW_STANDARD).fragment(0)
        frag.bulk_import(np.array([0, 2]), np.array([7, 123]), clear=True)
        h.index("i").field("v").import_values(np.array([7, 8]),
                                              np.array([500, 2]))
        f.row_attrs.set_attrs(1, {"tag": "x"})

    for _round in range(3):
        # query twice per round so the second pass rides the result cache
        assert _snap(ex_ranked) == _snap(ex_none)
        assert _snap(ex_ranked) == _snap(ex_none)
        mutate(h_ranked)
        mutate(h_none)
    assert _snap(ex_ranked) == _snap(ex_none)
    snap = ex_ranked.result_cache.snapshot()
    assert snap["hits"] > 0  # the cache actually served repeats


# -- result cache behavior ---------------------------------------------------

def test_result_cache_hit_and_structural_invalidation(rng):
    h = _build_pair(rng, "ranked")
    ex = Executor(h)
    ex.result_cache.limit_bytes = 8 << 20
    q = "Count(Row(f=1))"
    before = ex.execute("i", q)[0]
    assert ex.execute("i", q)[0] == before
    assert ex.result_cache.hits == 1
    # a write bumps the fragment gen: the entry stops matching, the next
    # fill supersedes it (counted as an invalidation), and the result is
    # fresh — never stale
    h.index("i").field("f").set_bit(1, 999_000)
    after = ex.execute("i", q)[0]
    assert after == before + 1
    ex.execute("i", q)
    snap = ex.result_cache.snapshot()
    assert snap["invalidates"] >= 1
    assert snap["hits"] >= 2


def test_result_cache_never_caches_writes(rng):
    h = _build_pair(rng, "ranked")
    ex = Executor(h)
    ex.result_cache.limit_bytes = 8 << 20
    assert ex.execute("i", "Set(77, f=7)")[0] is True
    assert ex.execute("i", "Set(77, f=7)")[0] is False  # re-executed
    assert ex.result_cache.snapshot()["entries"] == 0


def test_result_cache_byte_budget_evicts(rng):
    h = _build_pair(rng, "ranked")
    ex = Executor(h)
    # room for exactly one small entry: the second fill evicts the first
    ex.result_cache.limit_bytes = 150
    ex.execute("i", "Count(Row(f=1))")
    ex.execute("i", "Count(Row(f=2))")
    snap = ex.result_cache.snapshot()
    assert snap["entries"] == 1 and snap["evicts"] >= 1
    # an oversized result is never admitted at all
    ex.result_cache.limit_bytes = 1
    ex.execute("i", "Count(Row(f=3))")
    assert ex.result_cache.snapshot()["entries"] == 1


def test_debug_vars_and_cache_clear_route(tmp_path):
    """Counters visible at /debug/vars and /metrics; the admin clear
    route flushes both layers."""
    import urllib.request
    from pilosa_tpu.server.server import Config, Server

    srv = Server(Config(data_dir=str(tmp_path / "d"), bind="localhost:0",
                        anti_entropy_interval=0, use_mesh=False,
                        result_cache_mb=8))
    try:
        srv.open()

        def req(method, path, data=None):
            r = urllib.request.Request(
                f"http://localhost:{srv.port}{path}", method=method,
                data=data)
            with urllib.request.urlopen(r, timeout=60) as resp:
                return resp.read()

        req("POST", "/index/ci", b"{}")
        req("POST", "/index/ci/field/f", b"{}")
        req("POST", "/index/ci/query", b"Set(1, f=1) Set(5, f=2)")
        for _ in range(2):
            req("POST", "/index/ci/query", b"TopN(f, n=2)")
        dv = json.loads(req("GET", "/debug/vars"))
        assert dv["resultCache"]["hits"] >= 1
        counts = dv["counts"]
        assert counts.get("resultcache.hit", 0) >= 1
        assert counts.get("resultcache.miss", 0) >= 1
        assert counts.get("rankcache.hit", 0) >= 1
        metrics = req("GET", "/metrics").decode()
        assert "pilosa_tpu_resultcache_hit" in metrics
        assert "pilosa_tpu_rankcache_hit" in metrics
        out = json.loads(req("POST", "/internal/cache/clear", b""))
        assert out["resultEntries"] >= 1
        assert out["rankCaches"] >= 1
        assert json.loads(req(
            "GET", "/debug/vars"))["resultCache"]["entries"] == 0
    finally:
        srv.close()


# -- 2-node: a remote import invalidates the coordinator's entry -------------

def test_remote_import_invalidates_coordinator_cache(tmp_path):
    import socket
    import urllib.request
    from pilosa_tpu.server.server import Config, Server

    socks = [socket.socket() for _ in range(2)]
    for s in socks:
        s.bind(("localhost", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    try:
        for i in range(2):
            srv = Server(Config(
                data_dir=str(tmp_path / f"n{i}"), bind=hosts[i],
                node_id=f"node{i}", cluster_hosts=hosts, replica_n=1,
                anti_entropy_interval=0, use_mesh=False,
                result_cache_mb=8))
            servers.append(srv)
            srv.open()
        coord = servers[0]

        def req(port, method, path, data=None):
            r = urllib.request.Request(
                f"http://localhost:{port}{path}", method=method,
                data=data if data is None or isinstance(data, bytes)
                else json.dumps(data).encode())
            with urllib.request.urlopen(r, timeout=120) as resp:
                return json.loads(resp.read())

        req(ports[0], "POST", "/index/ci", {})
        req(ports[0], "POST", "/index/ci/field/f", {})
        # a shard owned SOLELY by the remote node (replica_n=1)
        shard = next(
            s for s in range(64)
            if coord.cluster.placement.shard_nodes("ci", s) == ["node1"])
        col0 = shard * SHARD_WIDTH + 11

        def count():
            return req(ports[0], "POST", "/index/ci/query",
                       b"Count(Row(f=3))")["results"][0]

        req(ports[0], "POST", "/index/ci/field/f/import",
            {"rowIDs": [3, 3], "columnIDs": [col0, col0 + 1]})
        assert count() == 2
        assert count() == 2  # warm: served from the coordinator cache
        hits0 = coord.api.executor.result_cache.snapshot()["hits"]
        assert hits0 >= 1
        # import forwarded THROUGH the coordinator to the remote owner:
        # note_peer_write bumps node1's data version, so the cached entry
        # stops matching and the next query recomputes
        req(ports[0], "POST", "/index/ci/field/f/import",
            {"rowIDs": [3], "columnIDs": [col0 + 2]})
        assert count() == 3
        # import posted DIRECTLY to the remote node (never crossing the
        # coordinator): the probe piggyback (status dataGens) catches it
        assert count() == 3  # re-warm the cache
        req(ports[1], "POST", "/index/ci/field/f/import",
            {"rowIDs": [3], "columnIDs": [col0 + 3]})
        coord.cluster.probe_peers()
        assert count() == 4
        snap = coord.api.executor.result_cache.snapshot()
        assert snap["invalidates"] >= 1
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
