"""Whole-query pjit programs (docs/whole-query.md): a read request
compiles to ONE XLA computation over the mesh.

The load-bearing guarantees tested here:

* Differential: whole-query results are byte-identical to the legacy
  per-stage path across a mixed corpus — nested Intersect/Union/Not/
  Shift, BSI ranges, time-quantum views, TopN, GroupBy, Min/Max — in
  dense-resident, compressed-resident, and eviction-pressure legs.
* One launch: a `Count(Intersect(...))`-class request is ONE device
  launch (verified by the launch ledger), and a mixed multi-call
  request is STILL one launch where the legacy path takes several.
* Fallbacks are loud: unsupported shapes reroute with the
  `wholequery.fallback` counter and a structured log event naming the
  unsupported node; the "error" policy raises instead.
* The kill switch (`whole-query = false`) restores the legacy path
  exactly.
* Re-trace regression (the PR 7 class): re-tracing the cached program
  at a new stacked bucket keeps its frozen layouts/schedule.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import ExecutionError
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.membudget import DEFAULT_BUDGET
from pilosa_tpu.utils import devobs

N_SHARDS = 20


@pytest.fixture(scope="module")
def corpus():
    """20-shard index mixing ragged set fields (a, b — different max
    rows per shard so stacking splits into multiple shape groups), a
    BSI field (v), run-heavy clustered ranges (a row 11), a
    time-quantum field (t), existence, and a shard with no fragments at
    all (bits only in shards 0..17; shards 18-19 stay empty —
    and wide enough that the 8-virtual-device mesh must slice it under
    a tight budget, forcing the streaming fallback leg)."""
    rng = np.random.default_rng(99)
    h = Holder(None)
    idx = h.create_index("w")
    a = idx.create_field("a")
    b = idx.create_field("b")
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    t = idx.create_field("t", FieldOptions(type="time",
                                           time_quantum="YMD"))
    n = 30_000
    cols = rng.integers(0, 18 * SHARD_WIDTH, size=n)
    a.import_bits(rng.integers(0, 10, size=n), cols)
    b.import_bits(rng.integers(0, 6, size=n), cols)
    # ragged rows: high row ids only in the first shards -> the stacked
    # shape signature differs between shard groups
    ragged = rng.integers(0, 3 * SHARD_WIDTH, size=2000)
    a.import_bits(rng.integers(20, 25, size=2000), ragged)
    # run-heavy clustered ranges (compressed residency's run form)
    run_cols = np.concatenate([
        np.arange(s * SHARD_WIDTH + 1000, s * SHARD_WIDTH + 30_000)
        for s in range(18)])
    a.import_bits(np.full(run_cols.size, 11), run_cols)
    vcols = np.unique(cols[: n // 2])
    v.import_values(vcols, rng.integers(-500, 500, size=vcols.size))
    from datetime import datetime
    tcols = np.unique(cols[: n // 4])
    t.import_bits(np.full(tcols.size, 2), tcols,
                  timestamps=[datetime(2017, 5, 15)] * tcols.size)
    idx.add_existence(np.unique(np.concatenate([cols, ragged, run_cols])))
    return h


QUERIES = [
    "Count(Intersect(Row(a=1), Row(b=2)))",
    "Count(Union(Row(a=0), Not(Row(b=3)), Shift(Row(a=2), n=5)))",
    "Row(a=3)",
    "Difference(Row(a=11), Row(b=1))",
    "Count(Row(-200 < v < 200))",
    "Sum(Row(v > 17), field=v)",
    "Sum(field=v)",
    "Min(field=v) Max(Row(a=2), field=v)",
    "TopN(a, Row(b=1), n=3)",
    "TopN(a, n=4)",
    "Rows(a)",
    "MinRow(field=a) MaxRow(field=a)",
    "GroupBy(Rows(b), Rows(a), Row(v > 0))",
    "Row(t=2, from=2017-01-01T00:00, to=2017-12-31T00:00)",
    "Count(Row(t=2, from=2017-05-01T00:00, to=2017-06-01T00:00))",
    "Count(Row(a=1)) Count(Row(a=7)) Sum(Row(a=1), field=v) "
    "TopN(b, Row(a=4), n=2) Row(b=0)",
]


def _norm(r):
    if hasattr(r, "columns"):
        return ("row", tuple(int(c) for c in r.columns()))
    if isinstance(r, list):
        return tuple(_norm(x) for x in r)
    return r


def _run_corpus(ex, queries=QUERIES):
    return [_norm(r) for q in queries for r in ex.execute("w", q)]


# legs 2/3 rerun a representative subset (one query per reducer kind):
# compressed layouts and the pressure fallback recompile every program
# shape, and 16 shapes x 2 extra legs of XLA compiles is tier-1 budget,
# not coverage
SUBSET = [QUERIES[0], QUERIES[3], QUERIES[5], QUERIES[7], QUERIES[8],
          QUERIES[12], QUERIES[15]]


def test_differential_three_legs(corpus):
    """Whole-query results byte-identical to the legacy path in
    dense-resident, compressed-resident, and eviction-pressure legs.
    Under eviction pressure the over-budget requests fall back (the
    streaming slice planner owns them) — the fallback must be counted
    AND still byte-identical."""
    legacy = Executor(corpus, use_mesh=True, whole_query=False)
    wq = Executor(corpus, use_mesh=True)
    old = DEFAULT_BUDGET.limit_bytes
    try:
        # dense-resident
        DEFAULT_BUDGET.limit_bytes = None
        want = _run_corpus(legacy)
        assert _run_corpus(wq) == want
        assert wq.wq_requests > 0
        want_sub = _run_corpus(legacy, SUBSET)

        # compressed-resident: ample budget, packed stacks stay staged
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        assert _run_corpus(wq, SUBSET) == want_sub
        assert DEFAULT_BUDGET.stats()["compressedBytes"] > 0, \
            "compressed leg never staged a packed stream"

        # eviction pressure: tight budget forces the streaming planner
        DEFAULT_BUDGET.limit_bytes = 1 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        ev0 = DEFAULT_BUDGET.evictions
        fb0 = wq.wq_fallbacks
        assert _run_corpus(wq, SUBSET) == want_sub
        assert DEFAULT_BUDGET.evictions > ev0, \
            "pressure leg never evicted"
        assert wq.wq_fallbacks > fb0, \
            "over-budget requests should fall back to the streaming path"
        assert DEFAULT_BUDGET.stats()["pinnedBytes"] == 0
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        legacy.close()
        wq.close()


def test_single_launch_per_request(corpus):
    """Acceptance: a Count(Intersect(...)) read query executes as ONE
    launch (ledger-verified, kind wholequery), and a mixed Count + Sum
    + TopN + bitmap request is STILL one launch while the legacy path
    takes one per reducer stage."""
    wq = Executor(corpus, use_mesh=True, whole_query_fallback="error")
    legacy = Executor(corpus, use_mesh=True, whole_query=False)
    mixed = ("Count(Intersect(Row(a=1), Row(b=2))) Sum(Row(a=1), field=v)"
             " TopN(b, Row(a=4), n=2) Row(b=0)")
    try:
        # warm both paths (compiles + stacks), then count launches
        wq.execute("w", "Count(Intersect(Row(a=8), Row(b=5)))")
        wq.execute("w", mixed)
        before = devobs.LEDGER.launches_total
        wq.execute("w", "Count(Intersect(Row(a=1), Row(b=2)))")
        assert devobs.LEDGER.launches_total - before == 1
        entry = devobs.LEDGER.snapshot()["entries"][-1]
        assert entry["kind"] == "wholequery"
        # shards 18-19 hold no fragments: only the 18
        # fragment-bearing shards reach the device
        assert entry["shards"] == 18

        before = devobs.LEDGER.launches_total
        wq.execute("w", mixed)
        assert devobs.LEDGER.launches_total - before == 1

        legacy.execute("w", mixed)  # warm
        before = devobs.LEDGER.launches_total
        legacy.execute("w", mixed)
        assert devobs.LEDGER.launches_total - before > 1, \
            "legacy path should take one launch per reducer stage"
    finally:
        wq.close()
        legacy.close()


def test_kill_switch_restores_legacy(corpus):
    ex = Executor(corpus, use_mesh=True, whole_query=False)
    try:
        before = devobs.LEDGER.launches_total
        ex.execute("w", "Count(Row(a=1))")
        assert ex.wq_requests == 0 and ex.wq_fallbacks == 0
        kinds = {e["kind"] for e in devobs.LEDGER.snapshot()["entries"]
                 [-(devobs.LEDGER.launches_total - before):]}
        assert "wholequery" not in kinds
    finally:
        ex.close()


class _CaptureLog:
    def __init__(self):
        self.events = []

    def event(self, name, **fields):
        self.events.append((name, fields))


def test_fallback_counted_and_logged(corpus):
    """An unsupported node falls back with the counter, a structured
    log event naming the node, and /debug/vars-visible state — and the
    'error' policy raises instead of silently rerouting."""
    ex = Executor(corpus, use_mesh=True)
    log = _CaptureLog()
    ex.logger = log
    try:
        fb0 = ex.wq_fallbacks
        # Options() carries per-call shard overrides: fallback matrix
        out = ex.execute("w", "Options(Row(a=1), shards=[0, 1])")
        assert ex.wq_fallbacks == fb0 + 1
        assert ex.wq_last_fallback.startswith("options")
        names = [n for n, _ in log.events]
        assert "wholequery.fallback" in names
        _, fields = log.events[-1]
        assert fields["node"] == "options"
        # answers still correct through the legacy path
        legacy = Executor(corpus, use_mesh=True, whole_query=False)
        try:
            want = legacy.execute("w", "Options(Row(a=1), shards=[0, 1])")
            assert _norm(out[0]) == _norm(want[0])
        finally:
            legacy.close()
    finally:
        ex.close()

    strict = Executor(corpus, use_mesh=True,
                      whole_query_fallback="error")
    try:
        with pytest.raises(ExecutionError, match="whole-query fallback"):
            strict.execute("w", "Options(Row(a=1), shards=[0])")
    finally:
        strict.close()


def test_groupby_and_minmax_join_or_fall_back(corpus):
    """group_counts and bsi_minmax either ride the whole-query program
    (counted as requests, single launch) or fall back cleanly with the
    counter — no silent slow paths."""
    ex = Executor(corpus, use_mesh=True)
    try:
        # small grid GroupBy and Min/Max JOIN the path
        r0, fb0 = ex.wq_requests, ex.wq_fallbacks
        ex.execute("w", "GroupBy(Rows(b), Rows(a))")
        ex.execute("w", "Min(field=v) Max(field=v)")
        assert ex.wq_requests == r0 + 2 and ex.wq_fallbacks == fb0
        # a Rows child with args needs Rows execution: clean fallback
        fb0 = ex.wq_fallbacks
        ex.execute("w", "GroupBy(Rows(b, limit=3), Rows(a))")
        assert ex.wq_fallbacks == fb0 + 1
        assert ex.wq_last_fallback.startswith("group_counts")
    finally:
        ex.close()


def test_retrace_keeps_results(corpus):
    """PR 7-style regression: growing/shrinking shard subsets re-trace
    the cached whole-query program at new stacked buckets; the re-trace
    must keep its frozen layouts/schedule (answers stable per subset,
    full set equals the sum of disjoint halves)."""
    ex = Executor(corpus, use_mesh=True, whole_query_fallback="error")
    old = DEFAULT_BUDGET.limit_bytes
    q = "Count(Intersect(Row(a=11), Row(a=2)))"
    try:
        DEFAULT_BUDGET.limit_bytes = 256 << 20
        want = {}
        for size in (20, 2, 9, 20, 1):
            got = ex.execute("w", q, shards=list(range(size)))[0]
            if size in want:
                assert got == want[size], \
                    f"subset {size} diverged after re-trace"
            want[size] = got
        lo = ex.execute("w", q, shards=list(range(10)))[0]
        hi = ex.execute("w", q, shards=list(range(10, 20)))[0]
        assert want[20] == lo + hi
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()


def test_fused_wholequery_tickets(corpus):
    """Concurrent same-shape requests fuse in the dispatch batcher: the
    batched parameter axis rides ONE compiled program (docs/batching.md
    composition), with per-ticket slices byte-identical to solo runs."""
    ex = Executor(corpus, use_mesh=True, dispatch_batch=True,
                  dispatch_batch_window_us=50_000)
    try:
        want = {i: ex.execute("w", f"Count(Row(a={i}))")[0]
                for i in range(8)}
        f0 = ex.batcher.fused_launches
        results: dict = {}
        barrier = threading.Barrier(8)

        def worker(i):
            barrier.wait()
            results[i] = ex.execute("w", f"Count(Row(a={i}))")[0]

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == want
        assert ex.batcher.fused_launches > f0, \
            "concurrent whole-query tickets never fused"
    finally:
        ex.close()


def test_config_knobs(monkeypatch, tmp_path):
    from pilosa_tpu.server.server import Config
    assert Config().whole_query is True
    assert Config().whole_query_fallback == "legacy"
    monkeypatch.setenv("PILOSA_TPU_WHOLE_QUERY", "false")
    monkeypatch.setenv("PILOSA_TPU_WHOLE_QUERY_FALLBACK", "error")
    cfg = Config.from_env()
    assert cfg.whole_query is False
    assert cfg.whole_query_fallback == "error"
    monkeypatch.delenv("PILOSA_TPU_WHOLE_QUERY")
    monkeypatch.delenv("PILOSA_TPU_WHOLE_QUERY_FALLBACK")
    toml = tmp_path / "c.toml"
    toml.write_text('whole-query = false\n'
                    'whole-query-fallback = "error"\n')
    cfg = Config.from_toml(str(toml))
    assert cfg.whole_query is False
    assert cfg.whole_query_fallback == "error"


def test_debug_vars_section(corpus):
    """The executor's /debug/vars wholeQuery section reflects requests
    and fallbacks (wired by the handler; asserted here at the executor
    surface the handler reads)."""
    ex = Executor(corpus, use_mesh=True)
    try:
        ex.execute("w", "Count(Row(a=1))")
        ex.execute("w", "Options(Row(a=1), shards=[0])")
        assert ex.wq_requests >= 1
        assert ex.wq_fallbacks >= 1
        assert ex.wq_last_fallback
    finally:
        ex.close()
