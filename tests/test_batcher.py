"""Cross-query dynamic batching (parallel/batcher.py, docs/batching.md):
differential correctness under concurrency, the singleton fall-through,
queued-deadline drop-out, knob plumbing, and the client-abort stat."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.handler import serialize_result
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.utils.deadline import DeadlineExceeded, QueryContext


@pytest.fixture(scope="module")
def corpus_holder():
    rng = np.random.default_rng(11)
    h = Holder(None)
    idx = h.create_index("b", track_existence=False)
    f = idx.create_field("f")
    f.import_bits(rng.integers(0, 32, size=4000),
                  rng.integers(0, 3 * SHARD_WIDTH, size=4000))
    v = idx.create_field("v", FieldOptions(type="int", min=0, max=1000))
    cols = np.unique(rng.integers(0, 3 * SHARD_WIDTH, size=800))
    v.import_values(cols, rng.integers(0, 1000, size=cols.size))
    yield h
    h.close()


def _mixed_corpus(n):
    out = []
    for i in range(n):
        out += [
            f"Count(Row(f={i % 32}))",
            f"Row(f={(i * 5) % 32})",
            f"Count(Intersect(Row(f={i % 32}), Row(f={(i + 3) % 32})))",
            f"TopN(f, Row(f={(i + 1) % 32}), n=4)",
            f"Sum(Row(v > {(i * 83) % 1000}), field=v)",
        ]
    return out


def _run_threaded(ex, queries, n_threads):
    """Execute the corpus from n_threads concurrent clients; results are
    serialized to JSON text so comparison is byte-level."""
    out = [None] * len(queries)
    errs = []
    barrier = threading.Barrier(n_threads)

    def worker(k):
        barrier.wait()
        for i in range(k, len(queries), n_threads):
            try:
                out[i] = json.dumps(
                    serialize_result(ex.execute("b", queries[i])))
            except Exception as e:  # surfaced below, not swallowed
                errs.append((queries[i], repr(e)))
    ts = [threading.Thread(target=worker, args=(k,))
          for k in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs[:3]
    return out


def test_batched_vs_off_byte_identical(corpus_holder):
    """The acceptance differential: a mixed Count/Row/Intersect/TopN/Sum
    corpus from >=8 concurrent threads is byte-identical between
    dispatch-batch on and off — and the on-run actually fused."""
    queries = _mixed_corpus(16)
    ex_on = Executor(corpus_holder, use_mesh=True, dispatch_batch=True,
                     dispatch_batch_window_us=20000)
    ex_off = Executor(corpus_holder, use_mesh=True, dispatch_batch=False)
    try:
        got = _run_threaded(ex_on, queries, 8)
        want = _run_threaded(ex_off, queries, 8)
        assert got == want
        assert ex_on.batcher.fused_launches > 0, \
            "8 concurrent threads never fused a launch"
        # off-mode batcher is pure delegation: no dispatcher activity
        assert ex_off.batcher.fused_launches == 0
        assert ex_off.batcher.single_launches == 0
    finally:
        ex_on.close()
        ex_off.close()


def test_solo_query_takes_unvmapped_fallthrough(corpus_holder):
    """A lone ticket falls through to the existing un-vmapped executables
    (the solo-latency guarantee): singleton launches, no fused ones."""
    ex = Executor(corpus_holder, use_mesh=True, dispatch_batch=True,
                  dispatch_batch_window_us=100)
    try:
        [n] = ex.execute("b", "Count(Row(f=3))")
        ex_off = Executor(corpus_holder, use_mesh=True,
                          dispatch_batch=False)
        try:
            assert ex.execute("b", "Count(Row(f=3))") == \
                ex_off.execute("b", "Count(Row(f=3))")
        finally:
            ex_off.close()
        assert ex.batcher.single_launches >= 1
        assert ex.batcher.fused_launches == 0
        hist = ex.batcher.batch_size_hist.snapshot()
        assert hist["le_1"] == hist["count"]  # every batch was size 1
    finally:
        ex.close()


def test_expired_ticket_dropped_before_launch(corpus_holder):
    """A ticket whose deadline expires while queued in the batch window
    is dropped BEFORE the fused launch (DeadlineExceeded to its waiter),
    while a healthy ticket sharing the window still gets its answer."""
    ex = Executor(corpus_holder, use_mesh=True, dispatch_batch=True,
                  dispatch_batch_window_us=300_000)  # 0.3 s window
    try:
        ex.execute("b", "Count(Row(f=1))")  # warm compiles (solo)
        results, errors = [], []

        def doomed():
            # budget far shorter than the window: expires while queued
            try:
                ex.execute("b", "Count(Row(f=2))",
                           ctx=QueryContext(0.05))
            except DeadlineExceeded as e:
                errors.append(str(e))

        def healthy():
            results.append(ex.execute("b", "Count(Row(f=2))")[0])

        t1 = threading.Thread(target=doomed)
        t2 = threading.Thread(target=healthy)
        t1.start()
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert errors and "deadline" in errors[0]
        off = Executor(corpus_holder, use_mesh=True, dispatch_batch=False)
        try:
            assert results == [off.execute("b", "Count(Row(f=2))")[0]]
        finally:
            off.close()
        assert ex.batcher.expired_drops >= 1
        # the doomed ticket is absent from the launch: whatever batch ran
        # carried only the healthy query
        hist = ex.batcher.batch_size_hist.snapshot()
        assert hist["le_inf"] == 0 and hist["count"] >= 1
    finally:
        ex.close()


def test_queued_expiry_maps_to_504_via_server(tmp_path):
    """End to end: with a batch window longer than the query budget, the
    queued expiry surfaces as HTTP 504 (the deadline drop-out satellite)."""
    srv = Server(Config(data_dir=str(tmp_path / "d"), bind="localhost:0",
                        anti_entropy_interval=0,
                        dispatch_batch_window_us=400_000))
    try:
        srv.open()

        def post(path, body, timeout=60):
            req = urllib.request.Request(
                f"http://localhost:{srv.port}{path}", method="POST",
                data=body.encode())
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        assert post("/index/dl", "{}")[0] == 200
        assert post("/index/dl/field/f", "{}")[0] == 200
        # writes don't ride the batcher; the timed read below does
        assert post("/index/dl/query", "Set(1, f=1)")[0] == 200
        code, body = post("/index/dl/query?timeout=0.05",
                          "Count(Row(f=1))")
        assert code == 504, body
        assert b"deadline" in body
        snap = json.loads(urllib.request.urlopen(
            f"http://localhost:{srv.port}/debug/vars",
            timeout=30).read())
        assert snap["dispatchBatcher"]["expiredDrops"] >= 1
        assert snap["counts"]["dispatch.expired_drop"] >= 1
    finally:
        srv.close()


def test_knob_plumbing_env_and_debug_vars(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_DISPATCH_BATCH", "false")
    monkeypatch.setenv("PILOSA_TPU_DISPATCH_BATCH_MAX", "7")
    monkeypatch.setenv("PILOSA_TPU_DISPATCH_BATCH_WINDOW_US", "123")
    cfg = Config.from_env()
    assert cfg.dispatch_batch is False
    assert cfg.dispatch_batch_max == 7
    assert cfg.dispatch_batch_window_us == 123.0
    monkeypatch.delenv("PILOSA_TPU_DISPATCH_BATCH")
    srv = Server(Config(data_dir=str(tmp_path / "k"), bind="localhost:0",
                        anti_entropy_interval=0, dispatch_batch_max=7,
                        dispatch_batch_window_us=123))
    try:
        srv.open()
        b = srv.api.executor.batcher
        assert b.enabled and b.max_batch == 7
        snap = json.loads(urllib.request.urlopen(
            f"http://localhost:{srv.port}/debug/vars",
            timeout=30).read())
        assert snap["dispatchBatcher"]["maxBatch"] == 7
        assert snap["dispatchBatcher"]["windowUs"] == 123.0
        # /metrics carries the batch-size histogram + window-wait summary
        text = urllib.request.urlopen(
            f"http://localhost:{srv.port}/metrics",
            timeout=30).read().decode()
        assert "pilosa_tpu_dispatch_batch_size_bucket" in text
        assert "pilosa_tpu_dispatch_window_wait_seconds_count" in text
    finally:
        srv.close()


def test_client_abort_counted_not_traced(tmp_path, capfd):
    """A client that disconnects mid-response yields an http.client_abort
    stat, not a traceback (the BrokenPipeError satellite)."""
    import http.client

    srv = Server(Config(data_dir=str(tmp_path / "a"), bind="localhost:0",
                        anti_entropy_interval=0))
    try:
        srv.open()

        def post(path, body):
            conn = http.client.HTTPConnection("localhost", srv.port,
                                              timeout=30)
            conn.request("POST", path, body=body.encode())
            resp = conn.getresponse()
            resp.read()
            conn.close()
            return resp.status

        assert post("/index/ab", "{}") == 200
        assert post("/index/ab/field/f", "{}") == 200
        assert post("/index/ab/query", " ".join(
            f"Set({c}, f=0)" for c in range(500))) == 200
        # ask for a large response and slam the socket before reading it
        import socket
        for _ in range(3):
            s = socket.create_connection(("localhost", srv.port),
                                         timeout=30)
            q = b"Row(f=0)"
            s.sendall(b"POST /index/ab/query HTTP/1.1\r\n"
                      b"Host: localhost\r\n"
                      b"Content-Length: " + str(len(q)).encode() +
                      b"\r\n\r\n" + q)
            # reset instead of FIN: pending response data -> RST/EPIPE in
            # the handler's write path
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         __import__("struct").pack("ii", 1, 0))
            s.close()
        deadline = time.monotonic() + 10
        aborts = 0
        while time.monotonic() < deadline:
            aborts = srv.stats.snapshot()["counts"].get(
                "http.client_abort", 0)
            if aborts >= 1:
                break
            time.sleep(0.05)
        assert aborts >= 1, "client abort was never counted"
        err = capfd.readouterr().err
        assert "BrokenPipeError" not in err
        assert "ConnectionResetError" not in err
    finally:
        srv.close()
