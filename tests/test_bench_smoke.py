"""bench.py --smoke as a slow-marked pytest: the resident AND the
budgeted/streaming paths run end-to-end (tiny shard counts, seconds) so
the shard-streaming pipeline stays covered without bloating tier-1."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_resident_and_budgeted():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    data = json.loads(out.stdout.strip().splitlines()[-1])
    # the smoke asserts answer-identity internally; re-check the pipeline
    # engagement signals it publishes
    assert data["smoke"] is True
    assert data["evictions"] > 0
    assert data["prefetch_hits"] + data["prefetch_misses"] > 0
    assert data["pinned_bytes"] == 0  # all pins released
    # whole-query leg (docs/whole-query.md): answers identical with the
    # program path on vs off, and a Count(Intersect)-class request was
    # exactly ONE launch on the ledger (kind wholequery) — the
    # single-launch-per-request acceptance
    wq = data["wholequery"]
    assert wq["answers_identical"] is True
    assert wq["single_launch"] is True
    assert wq["qps_on"] > 0 and wq["qps_off"] > 0
    assert wq["wq_requests"] > 0
    # compressed-residency leg (docs/memory-budget.md): the budget held
    # under a limit below the dense working set, the staged footprint is
    # genuinely compressed, and results were identical to the dense run
    # (the identity assert lives in bench.py)
    comp = data["compressed"]
    assert comp["budget_held"] is True
    assert comp["compressed_mb"] < comp["dense_resident_mb"]
    assert comp["effective_capacity_ratio"] > 1
    # ingest leg (docs/ingest.md): the binary-streamed corpus answered
    # identically to the bulk-imported twin (overlay-resident AND after
    # the merge — asserted in bench.py), the stream actually journaled
    # overlays, and the read-under-ingest retention was measured (the
    # >=80% floor is judged on real hardware, not this CPU smoke)
    ing = data["ingest"]
    assert ing["answers_identical"] is True
    assert ing["records_per_s"] > 0
    assert ing["flushes"] >= 1
    assert 0 < ing["read_qps_retention"]
    assert ing["read_qps_under_ingest"] > 0
    # cache leg (docs/caching.md): warm repeats must ride the result
    # cache and clear the 5x acceptance floor
    assert data["cache"]["speedup"] >= 5
    assert data["cache"]["hit_ratio"] == 1.0
    # dynamic-batching leg (docs/batching.md): 16 concurrent clients must
    # produce fused launches, and both modes agreed on the sample answer
    # (the assert lives in bench.py); the 4x qps floor is judged on real
    # hardware where the dispatch floor dominates, not on CPU
    assert data["http_batch"]["fused_launches"] > 0
    assert data["http_batch"]["qps_on"] > 0 \
        and data["http_batch"]["qps_off"] > 0
    # elastic-routing leg (docs/cluster.md "Read routing &
    # rebalancing"): loaded routing answered byte-identically to
    # primary-pinned on the skew corpus (asserted in bench.py), the hot
    # shards were served by more than one node, and both modes measured
    rt = data["routing"]
    assert rt["answers_identical"] is True
    assert rt["hot_shard_nodes"] > 1
    assert rt["qps_loaded"] > 0 and rt["qps_primary"] > 0
    # tail-tolerance leg (docs/robustness.md "Tail-tolerant fan-out"):
    # under a real-socket ChaosProxy straggler, hedged reads held p99
    # under the injected delay while the unhedged run was bound by it,
    # with answers byte-identical across baseline/hedged/unhedged (the
    # asserts live in bench.py; re-check the published signals)
    ch = data["chaos"]
    assert ch["answers_identical"] is True
    assert ch["hedges"] > 0 and ch["hedge_wins"] > 0
    assert ch["p99_hedged_ms"] < ch["injected_delay_ms"]
    assert ch["p99_hedged_ms"] < ch["p99_unhedged_ms"]
    # SLO/alerting leg (docs/observability.md "SLOs & alerting"): the
    # ChaosProxy straggler fired the latency burn-rate alert within 2
    # evaluation passes, the on-fire hook landed a readable flight-
    # recorder bundle inside its disk budget, the heal resolved the
    # alert, and burn-rate evaluation cost nothing on the serving path
    # (>=0.95x qps vs evaluation-off, answers byte-identical — the
    # asserts live in bench.py; re-check the published signals)
    sl = data["slo"]
    assert sl["alert"]["fired"] is True
    assert sl["alert"]["evals_to_fire"] <= 2
    assert sl["alert"]["bundle_ok"] is True and sl["alert"]["bundle_kb"] > 0
    assert sl["alert"]["budget_held"] is True
    assert sl["alert"]["resolved"] is True
    assert sl["answers_identical"] is True
    assert sl["qps_ratio"] >= 0.95
    assert sl["evaluations_on"] > 0
    # internal-wire leg (docs/cluster.md "Internal query wire"): binary
    # PTPUQRY1 answered byte-identically to the JSON wire on the same
    # recorded corpus (asserted in bench.py), the roaring framing
    # actually shrank sparse results on the wire, and the mixed-version
    # 415 downgrade fired and answered identically
    wr = data["wire"]
    assert wr["answers_identical"] is True
    assert wr["sparse_wire_bytes_per_q"]["bin1"] \
        < wr["sparse_wire_bytes_per_q"]["json"]
    assert wr["sparse_bytes_ratio"] > 1.5
    assert wr["qps_bin1"] > 0 and wr["qps_json"] > 0
    assert wr["fallback"]["count"] >= 1
    assert wr["fallback"]["answers_identical"] is True
    # tenant-isolation leg (docs/robustness.md "Tenant isolation"):
    # under a hostile flood the sheds land on the hostile tenant, the
    # polite tenant is never shed with weighted-fair admission on, and
    # admitted answers are byte-identical across idle / isolation-on /
    # isolation-off (asserted in bench.py; re-check the signals).  The
    # 1.5x polite-p99 bound is recorded, judged on real hardware.
    tn = data["tenant"]
    assert tn["answers_identical"] is True
    assert tn["isolation_on"]["fair"] is True
    assert tn["isolation_off"]["fair"] is False
    assert tn["isolation_on"]["polite_sheds"] == 0
    assert tn["isolation_on"]["total_sheds"] > 0
    assert tn["isolation_on"]["shed_attribution"] >= 0.95
    assert tn["isolation_on"]["p99_flood_ms"] > 0
    # observability leg (docs/observability.md): profile-off serving
    # stays within 5% of the batching leg (asserted in bench.py) and
    # profile-on returned a populated stage tree + resolvable trace
    assert data["observability"]["qps"] > 0
    assert data["observability"]["profile_stages"] > 0
    assert data["observability"]["slow_recorded"] >= 1
    # restart leg (docs/warmup.md): a kill -9'd server restarted on the
    # same data dir replayed its durable corpus with zero retraces and
    # beat the wiped-clean cold restart's first query (bench.py asserts
    # the same; the "within 2x steady / >=5x over cold" p99 ratios are
    # judged on real hardware, not this CPU smoke)
    rs = data["restart"]
    assert rs["replayed"] >= 1
    assert rs["retraces_during_warm"] == 0
    assert rs["warm_first_ms"] < rs["cold_first_ms"]
    assert rs["steady_ms"] > 0 and rs["warm_vs_cold"] > 1
    # container-kernel leg (docs/architecture.md "On native code and
    # Pallas"): the SSB corpus answered byte-identically across dense /
    # compressed-jnp / compressed-pallas (asserted in bench.py); re-check
    # that the pallas leg really launched container kernels and the jnp
    # kill-switch leg launched none
    ssb = data["ssb"]
    assert ssb["pallas"]["device"]["kernel_backend"] == "pallas"
    assert ssb["pallas"]["device"]["kernel_launches"] > 0
    assert ssb["jnp"]["device"]["kernel_backend"] == "jnp"
    assert ssb["jnp"]["device"]["kernel_launches"] == 0
    assert ssb["compressed_mb"] > 0
