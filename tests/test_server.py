"""HTTP server integration tests — the in-process harness of the reference
(test/pilosa.go test.Command: real server, ephemeral port) driving the real
HTTP surface (server/handler_test.go coverage)."""

import base64
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.server.server import Config, Server


@pytest.fixture
def srv(tmp_path):
    cfg = Config(data_dir=str(tmp_path / "data"), bind="localhost:0")
    s = Server(cfg)
    s.open()
    yield s
    s.close()


def call(srv, method, path, body=None, ctype="application/json", raw=False):
    url = f"http://localhost:{srv.port}{path}"
    data = body if isinstance(body, (bytes, type(None))) else \
        json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", ctype)
    with urllib.request.urlopen(req) as resp:
        payload = resp.read()
    if raw:
        return payload
    return json.loads(payload) if payload.strip() else {}


def call_err(srv, method, path, body=None):
    try:
        call(srv, method, path, body)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    raise AssertionError("expected HTTP error")


def test_home_version_info_status(srv):
    assert "message" in call(srv, "GET", "/")
    assert call(srv, "GET", "/version")["version"]
    assert call(srv, "GET", "/info")["shardWidth"] == 1 << 20
    st = call(srv, "GET", "/status")
    assert st["state"] == "NORMAL"
    assert len(st["nodes"]) == 1


def test_ddl_and_query_lifecycle(srv):
    assert call(srv, "POST", "/index/i", {}) == {}
    assert call(srv, "POST", "/index/i/field/f", {}) == {}
    # schema reflects both
    schema = call(srv, "GET", "/schema")["indexes"]
    assert schema[0]["name"] == "i"
    assert schema[0]["fields"][0]["name"] == "f"
    # write + read via PQL
    out = call(srv, "POST", "/index/i/query", b"Set(2, f=10)")
    assert out["results"] == [True]
    out = call(srv, "POST", "/index/i/query", b"Row(f=10)")
    assert out["results"][0]["columns"] == [2]
    out = call(srv, "POST", "/index/i/query", b"Count(Row(f=10))")
    assert out["results"] == [1]
    # DELETE
    assert call(srv, "DELETE", "/index/i/field/f") == {}
    assert call(srv, "DELETE", "/index/i") == {}
    assert call(srv, "GET", "/schema")["indexes"] == []


def test_errors(srv):
    code, body = call_err(srv, "POST", "/index/i/query", b"Row(f=1)")
    assert code == 400
    assert "index not found" in body["error"]
    call(srv, "POST", "/index/i", {})
    code, body = call_err(srv, "POST", "/index/i", {})
    assert code == 409
    code, body = call_err(srv, "GET", "/index/nope")
    assert code == 404
    code, body = call_err(srv, "POST", "/index/i/query", b"Row(f=")
    assert code == 400
    assert "parse error" in body["error"]
    # unknown path / wrong method
    code, _ = call_err(srv, "POST", "/definitely-not-a-route")
    assert code == 404
    code, _ = call_err(srv, "DELETE", "/schema")
    assert code == 405


def test_import_and_export(srv):
    call(srv, "POST", "/index/i", {})
    call(srv, "POST", "/index/i/field/f", {})
    call(srv, "POST", "/index/i/field/f/import", {
        "rowIDs": [1, 1, 2], "columnIDs": [10, 20, 10]})
    out = call(srv, "POST", "/index/i/query", b"Count(Row(f=1))")
    assert out["results"] == [2]
    csv = call(srv, "GET", "/export?index=i&field=f&shard=0",
               raw=True).decode()
    assert set(csv.strip().split("\n")) == {"1,10", "1,20", "2,10"}


def test_import_values_and_sum(srv):
    call(srv, "POST", "/index/i", {})
    call(srv, "POST", "/index/i/field/v",
         {"options": {"type": "int", "min": 0, "max": 1000}})
    call(srv, "POST", "/index/i/field/v/import", {
        "columnIDs": [1, 2, 3], "values": [10, 20, 30]})
    out = call(srv, "POST", "/index/i/query", b"Sum(field=v)")
    assert out["results"][0] == {"value": 60, "count": 3}


def test_import_roaring(srv):
    from pilosa_tpu.storage.roaring_io import pack_roaring

    call(srv, "POST", "/index/i", {})
    call(srv, "POST", "/index/i/field/f", {})
    blob = pack_roaring(np.array([0, 0, 3]), np.array([5, 9, 100]))
    call(srv, "POST", "/index/i/field/f/import-roaring/0", blob,
         ctype="application/octet-stream")
    out = call(srv, "POST", "/index/i/query", b"Row(f=0)")
    assert out["results"][0]["columns"] == [5, 9]
    out = call(srv, "POST", "/index/i/query", b"Row(f=3)")
    assert out["results"][0]["columns"] == [100]
    # JSON-wrapped views variant
    blob2 = pack_roaring(np.array([7]), np.array([42]))
    call(srv, "POST", "/index/i/field/f/import-roaring/1", {
        "views": {"": base64.b64encode(blob2).decode()}})
    out = call(srv, "POST", "/index/i/query", b"Row(f=7)")
    assert out["results"][0]["columns"] == [(1 << 20) + 42]


def test_schema_roundtrip(srv):
    schema = {"indexes": [{
        "name": "myidx",
        "options": {"keys": False, "trackExistence": True},
        "fields": [
            {"name": "a", "options": {"type": "set"}},
            {"name": "b", "options": {"type": "int", "min": -5, "max": 5}},
        ],
    }]}
    call(srv, "POST", "/schema", schema)
    got = call(srv, "GET", "/schema")["indexes"]
    assert got[0]["name"] == "myidx"
    assert {f["name"] for f in got[0]["fields"]} == {"a", "b"}
    # idempotent
    call(srv, "POST", "/schema", schema)


def test_persistence_across_restart(tmp_path):
    cfg = Config(data_dir=str(tmp_path / "data"), bind="localhost:0")
    s = Server(cfg)
    s.open()
    call(s, "POST", "/index/i", {})
    call(s, "POST", "/index/i/field/f", {})
    call(s, "POST", "/index/i/query", b"Set(7, f=3)")
    s.close()

    s2 = Server(cfg)
    s2.open()
    out = call(s2, "POST", "/index/i/query", b"Row(f=3)")
    assert out["results"][0]["columns"] == [7]
    s2.close()


def test_metrics_and_debug_vars(srv):
    call(srv, "POST", "/index/i", {})
    call(srv, "POST", "/index/i/field/f", {})
    call(srv, "POST", "/index/i/query", b"Set(1, f=1)")
    text = call(srv, "GET", "/metrics", raw=True).decode()
    assert "pilosa_tpu_query" in text
    call(srv, "POST", "/index/i/query", b"Count(Row(f=1))")
    snap = call(srv, "GET", "/debug/vars")
    assert snap["counts"]["query"] >= 1
    # phase-level attribution (r3 verdict #10): parse/dispatch/fetch
    # timings, budget + cache state
    assert snap["timings"]["query.dispatch"]["count"] >= 1
    assert snap["timings"]["query.fetch"]["count"] >= 1
    assert "residentBytes" in snap["deviceBudget"]
    assert snap["preparedCache"]["misses"] + \
        snap["preparedCache"]["hits"] >= 1
    assert snap["stackCache"]["executables"] >= 1


def test_options_column_attrs_over_http(srv):
    call(srv, "POST", "/index/oi", {})
    call(srv, "POST", "/index/oi/field/f", {})
    call(srv, "POST", "/index/oi/query",
         b'Set(7, f=1) SetColumnAttrs(7, city="pdx") '
         b'SetRowAttrs(f, 1, kind="x")')
    out = call(srv, "POST", "/index/oi/query",
               b"Options(Row(f=1), columnAttrs=true)")
    assert out["columnAttrs"] == [{"id": 7, "attrs": {"city": "pdx"}}]
    assert out["results"][0]["attrs"] == {"kind": "x"}
    out = call(srv, "POST", "/index/oi/query",
               b"Options(Row(f=1), excludeRowAttrs=true, "
               b"excludeColumns=true)")
    assert out["results"][0]["columns"] == []
    assert "attrs" not in out["results"][0]


def test_pprof_and_runtime_stats(srv):
    threads = call(srv, "GET", "/debug/pprof/threads", raw=True).decode()
    assert "thread " in threads and "handler.py" in threads
    prof = call(srv, "GET", "/debug/pprof/profile?seconds=0.2",
                raw=True).decode()
    assert prof == "" or " " in prof.splitlines()[0]
    srv.collect_runtime_stats()
    snap = call(srv, "GET", "/debug/vars")
    assert snap["gauges"]["runtime.rss_bytes"] > 0
    assert snap["gauges"]["runtime.threads"] >= 1


def test_pprof_profile_validates_and_serializes(srv):
    """?seconds must be validated (garbage was an unhandled 500) and only
    one profile may run at a time (409 for the second) — r4 advisor."""
    code, body = call_err(srv, "GET", "/debug/pprof/profile?seconds=abc")
    assert code == 400 and "seconds" in body["error"]

    import threading
    results = []

    def profile():
        try:
            call(srv, "GET", "/debug/pprof/profile?seconds=1", raw=True)
            results.append(200)
        except urllib.error.HTTPError as e:
            results.append(e.code)

    threads = [threading.Thread(target=profile) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == [200, 409]
    # the lock is released: a fresh profile succeeds
    call(srv, "GET", "/debug/pprof/profile?seconds=0.1", raw=True)


def test_column_attrs_deduped_across_calls(srv):
    """Multiple Options(columnAttrs=true) calls over the same column must
    emit ONE top-level entry (reference's deduplicated ColumnAttrSets) —
    r4 advisor."""
    call(srv, "POST", "/index/ca", {})
    call(srv, "POST", "/index/ca/field/f", {})
    call(srv, "POST", "/index/ca/query",
         b'Set(7, f=1) Set(7, f=2) SetColumnAttrs(7, city="pdx")')
    out = call(srv, "POST", "/index/ca/query",
               b"Options(Row(f=1), columnAttrs=true) "
               b"Options(Row(f=2), columnAttrs=true)")
    assert out["columnAttrs"] == [{"id": 7, "attrs": {"city": "pdx"}}]


def test_gcnotify_gauges(srv):
    """gcnotify.go parity: GC cycle counts and pause totals surface as
    runtime gauges."""
    import gc

    from pilosa_tpu.utils.gcnotify import global_notifier
    before = global_notifier().snapshot()["collections"][2]
    gc.collect()
    srv.collect_runtime_stats()
    snap = call(srv, "GET", "/debug/vars")
    assert snap["gauges"]["runtime.gc_collections_gen2"] >= before + 1
    assert "runtime.gc_pause_ms_gen2" in snap["gauges"]


def test_diagnostics_reporting(srv):
    """diagnostics.go parity, inverted default: OFF unless the operator
    configures an endpoint; the payload carries anonymized scale info."""
    import http.server
    import threading

    assert srv.diagnostics._thread is None  # default: no reporting loop
    call(srv, "POST", "/index/di", {})
    call(srv, "POST", "/index/di/field/f", {})
    got = {}

    class Sink(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            got["body"] = json.loads(
                self.rfile.read(int(self.headers["Content-Length"])))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    sink = http.server.HTTPServer(("localhost", 0), Sink)
    threading.Thread(target=sink.handle_request, daemon=True).start()
    srv.diagnostics.endpoint = \
        f"http://localhost:{sink.server_address[1]}/d"
    assert srv.diagnostics.report_once()
    assert got["body"]["numIndexes"] >= 1
    assert got["body"]["version"]
    assert "uptimeSeconds" in got["body"]
    sink.server_close()


def test_statsd_client_emits_datagrams():
    import socket
    from pilosa_tpu.utils.stats import StatsdClient, make_stats_client

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("localhost", 0))
    recv.settimeout(2)
    port = recv.getsockname()[1]
    st = StatsdClient("localhost", port)
    st.count("query", 2)
    st.with_tags("index:i").gauge("shards", 5)
    got = {recv.recvfrom(1024)[0].decode() for _ in range(2)}
    assert "query:2|c" in got
    assert "shards:5|g|#index:i" in got
    # in-process snapshot stays live for /debug/vars + /metrics
    assert st.snapshot()["counts"]["query"] == 2
    assert st.snapshot()["gauges"]["shards{index:i}"] == 5
    assert isinstance(make_stats_client("statsd", f"localhost:{port}"),
                      StatsdClient)
    recv.close()


def test_shards_max_and_fragment_nodes(srv):
    call(srv, "POST", "/index/i", {})
    call(srv, "POST", "/index/i/field/f", {})
    call(srv, "POST", "/index/i/query",
         b"Set(1, f=1)Set(3145729, f=1)")  # shards 0 and 3
    out = call(srv, "GET", "/internal/shards/max")
    assert out["standard"]["i"] == 3
    nodes = call(srv, "GET", "/internal/fragment/nodes?index=i&shard=0")
    assert nodes[0]["id"] == "node0"


def test_topn_groupby_over_http(srv):
    call(srv, "POST", "/index/i", {})
    call(srv, "POST", "/index/i/field/f", {})
    call(srv, "POST", "/index/i/field/g", {})
    call(srv, "POST", "/index/i/field/f/import", {
        "rowIDs": [0, 0, 0, 1], "columnIDs": [1, 2, 3, 1]})
    call(srv, "POST", "/index/i/field/g/import", {
        "rowIDs": [5, 5], "columnIDs": [1, 2]})
    out = call(srv, "POST", "/index/i/query", b"TopN(f, n=1)")
    assert out["results"][0] == [{"id": 0, "count": 3}]
    out = call(srv, "POST", "/index/i/query", b"GroupBy(Rows(f), Rows(g))")
    assert out["results"][0] == [
        {"group": [{"field": "f", "rowID": 0},
                   {"field": "g", "rowID": 5}], "count": 2},
        {"group": [{"field": "f", "rowID": 1},
                   {"field": "g", "rowID": 5}], "count": 1},
    ]


def test_body_size_limit(tmp_path):
    """POST bodies above max-body-mb get 413 without buffering; a garbage
    Content-Length gets 400 (both previously crashed or buffered
    unbounded)."""
    import http.client

    cfg = Config(data_dir=str(tmp_path / "bl"), bind="localhost:0",
                 max_body_mb=1, max_body_internal_mb=4)
    s = Server(cfg)
    s.open()
    try:
        code, err = call_err(s, "POST", "/index/big/query",
                             b"x" * ((1 << 20) + 1))
        assert code == 413 and "exceeds limit" in err["error"]
        # a claimed-huge Content-Length is rejected without reading
        conn = http.client.HTTPConnection("localhost", s.port, timeout=10)
        conn.putrequest("POST", "/index/big/query")
        conn.putheader("Content-Length", str(50 << 30))
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 413
        conn.close()
        # garbage Content-Length -> 400 AND the connection closes (any
        # in-flight body bytes would desync the keep-alive stream)
        conn = http.client.HTTPConnection("localhost", s.port, timeout=10)
        conn.putrequest("POST", "/index/big/query")
        conn.putheader("Content-Length", "banana")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 400
        resp.read()
        conn.close()
        # a normal-size request still works
        assert call(s, "POST", "/index/big", {}) == {}
        # the INTERNAL plane gets its own OPT-IN ceiling (roaring import
        # fan-out and resize fragment copies can exceed the public cap):
        # a body over the public limit but under max-body-internal-mb is
        # read and routed (404 here — no cluster routes registered),
        # while one over the internal ceiling still gets 413
        code, err = call_err(s, "POST", "/internal/bogus",
                             b"x" * ((1 << 20) + 1))
        assert code == 404 and "exceeds limit" not in err["error"]
        code, err = call_err(s, "POST", "/internal/bogus",
                             b"x" * ((4 << 20) + 1))
        assert code == 413 and "exceeds limit" in err["error"]
    finally:
        s.close()

    # 0 = unlimited (device-budget-mb convention)
    cfg0 = Config(data_dir=str(tmp_path / "bl0"), bind="localhost:0",
                  max_body_mb=0)
    s0 = Server(cfg0)
    s0.open()
    try:
        code, err = call_err(s0, "POST", "/index/big/query",
                             b"Count(Row(f=1)) " * 200_000)  # ~3 MB
        assert code == 400  # parses (index missing), not 413
        assert "exceeds" not in err["error"]
    finally:
        s0.close()
