"""Cluster observability plane (ISSUE 15, docs/observability.md
"Cluster plane"): the fleet rollup's golden agreement with per-node
/debug/vars, staleness stamping that never blocks a scrape on a dead
peer, the merged event timeline carrying the breaker-open and repair
events chaos actually caused, EXPLAIN naming the actually-chosen
replica per shard, the pilosa_tpu_cluster_* exposition, and golden
tests for both dashboard pages against live fixtures."""

import json
import re
import time
import urllib.request

import pytest

from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.utils.events import EVENTS

from test_observability import _req, _free_ports, make_server


@pytest.fixture(scope="module")
def cluster3(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs3")
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        cfg = Config(
            data_dir=str(tmp / f"node{i}"),
            bind=f"localhost:{p}",
            node_id=f"node{i}",
            cluster_hosts=hosts,
            replica_n=2,
            anti_entropy_interval=0,   # driven manually
            breaker_threshold=2,       # two probe misses open a breaker
            slow_query_threshold=0,    # keep the ring quiet
        )
        srv = Server(cfg)
        srv.open()
        servers.append(srv)
    p0 = ports[0]
    _req(p0, "POST", "/index/ci", {})
    _req(p0, "POST", "/index/ci/field/f", {})
    from pilosa_tpu.core import SHARD_WIDTH
    sets = "".join(f"Set({s * SHARD_WIDTH + c}, f={r})"
                   for s in range(6) for r in range(3) for c in range(8))
    _req(p0, "POST", "/index/ci/query", sets)
    yield servers, ports
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


def test_rollup_agrees_with_per_node_vars(cluster3):
    servers, ports = cluster3
    p0 = ports[0]
    for i in range(4):
        _req(p0, "POST", "/index/ci/query", f"Count(Row(f={i % 3}))")
    roll, _ = _req(p0, "GET", "/debug/cluster?refresh=true", timeout=30)
    assert set(roll["nodes"]) == {"node0", "node1", "node2"}
    assert roll["coordinator"] == "node0"
    # golden: each node's rollup summary equals that node's OWN
    # /debug/vars surface (no traffic between the two reads)
    for i, p in enumerate(ports):
        v, _ = _req(p, "GET", "/debug/vars")
        n = roll["nodes"][f"node{i}"]
        assert n["stale"] is False
        hq = v["timings"].get("http.query") or {}
        assert n["queries"] == hq.get("count", 0)
        assert n["evictions"] == v["deviceBudget"]["evictions"]
        assert n["retraces"] == v["device"]["compiles"]["retraces"]
        assert n["hedges"] == int(
            v["counts"].get("cluster.hedges", 0))
        assert n["quarantinedFragments"] == \
            len(v["storage"]["quarantined"])
        assert n["overlayEpoch"] == v["cluster"]["overlay"]["epoch"]
    # the coordinator served at least the queries this test just sent
    assert roll["nodes"]["node0"]["queries"] >= 4


def test_cluster_metrics_family_with_node_labels(cluster3):
    servers, ports = cluster3
    with urllib.request.urlopen(
            f"http://localhost:{ports[0]}/metrics", timeout=30) as r:
        text = r.read().decode()
    for nid in ("node0", "node1", "node2"):
        assert re.search(
            rf'pilosa_tpu_cluster_qps{{node="{nid}"}} ', text)
        assert re.search(
            rf'pilosa_tpu_cluster_stale{{node="{nid}"}} 0', text)
    assert "# TYPE pilosa_tpu_cluster_hedges gauge" in text


def test_explain_names_chosen_replica_per_shard(cluster3):
    servers, ports = cluster3
    out, _ = _req(ports[0], "POST", "/index/ci/query?explain=true",
                  "Count(Row(f=1))")
    exp = out["explain"]
    routing = exp.get("routing") or []
    assert routing, "no routing section on a cluster query"
    cl = servers[0].cluster
    chosen_by_shard = {}
    for e in routing:
        assert e["chosen"] in e["candidates"]
        # the chosen node really owns the shard (overlay-aware)
        assert cl.owns_shard(e["chosen"], "ci", e["shard"])
        chosen_by_shard[e["shard"]] = e["chosen"]
    # ACCEPTANCE: the wave-0 dispatch went to exactly the replicas the
    # routing section names, shard by shard
    dispatched = {}
    for d in exp.get("dispatch") or []:
        if d.get("wave") == 0 and not d.get("hedge"):
            for s in d["shards"]:
                dispatched[s] = d["node"]
    assert dispatched == chosen_by_shard
    # loaded-policy score breakdowns name the components
    scored = [e for e in routing if "scores" in e]
    if scored:
        s0 = next(iter(scored[0]["scores"].values()))
        if isinstance(s0, dict):
            assert {"ewmaMs", "pressure", "residencyTier",
                    "score"} <= set(s0)


def test_chaos_timeline_and_stale_peer(cluster3):
    """The acceptance scenario: kill a peer — the rollup marks it stale
    WITHOUT blocking the scrape, the breaker-open event the death
    caused lands in the merged timeline, and a quarantine+repair cycle
    lands its repair event too."""
    servers, ports = cluster3
    p0 = ports[0]
    cl0 = servers[0].cluster

    # warm the rollup so node2 has a last-known summary to go stale
    _req(p0, "GET", "/debug/cluster?refresh=true", timeout=30)

    # -- chaos: kill node2, then probe twice (threshold=2 opens the
    # breaker; the probe path also flips NODE_DOWN)
    servers[2].close()
    cl0.probe_peers()
    cl0.probe_peers()
    host2 = cl0.by_id["node2"].host
    assert cl0.client.breaker_open(host2)
    assert cl0.by_id["node2"].state == "DOWN"

    # -- chaos: corrupt a fragment on node0 that node1 replicates, then
    # run the repair sweep
    shard = next(s for s in range(64)
                 if {"node0", "node1"} <=
                 set(cl0.shard_owner_nodes("ci", s)))
    from pilosa_tpu.core import SHARD_WIDTH
    _req(p0, "POST", "/index/ci/query",
         f"Set({shard * SHARD_WIDTH + 2}, f=9)")
    for srv in servers[:2]:
        srv.cluster.sync_holder()  # both replicas hold the bit
    frag = servers[0].holder.fragment("ci", "f", "standard", shard)
    assert frag is not None
    frag._enter_quarantine("chaos: injected corruption")
    assert servers[0].holder.quarantined_fragments("ci")
    repaired = cl0.repair_quarantined()
    assert repaired >= 1

    # -- the scrape: bounded despite the dead peer, stale-stamped
    t0 = time.perf_counter()
    roll, _ = _req(p0, "GET", "/debug/cluster?refresh=true", timeout=30)
    elapsed = time.perf_counter() - t0
    assert elapsed < 5.0, f"scrape blocked {elapsed:.1f}s on a dead peer"
    n2 = roll["nodes"]["node2"]
    assert n2["state"] == "DOWN"
    assert n2["stale"] is True
    assert n2.get("queries") is not None  # last-known summary retained
    assert roll["nodes"]["node0"]["stale"] is False

    # -- ACCEPTANCE: the merged timeline contains the events the chaos
    # actually caused
    names = [e["event"] for e in roll["timeline"]]
    assert "breaker.open" in names
    assert "node.down" in names
    assert "storage.quarantine" in names
    assert "storage.repair" in names
    # (filter by index: the process-global journal may also hold repair
    # events other tests in this process emitted — the breaker pattern
    # below)
    rep = next(e for e in roll["timeline"]
               if e["event"] == "storage.repair"
               and e.get("index") == "ci")
    assert rep["shard"] == shard
    # (search by host: the process-global journal may also hold
    # breaker events other tests in this process emitted)
    assert any(e["event"] == "breaker.open" and e.get("host") == host2
               for e in roll["timeline"])
    # stale /metrics stamp flips for the dead node
    with urllib.request.urlopen(
            f"http://localhost:{p0}/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'pilosa_tpu_cluster_stale{node="node2"} 1' in text


# -- dashboard golden tests --------------------------------------------------


def _html(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/html")
        return r.read().decode()


def test_dashboard_page_fields_exist_in_timeseries(tmp_path):
    """Golden: every `s.<field>` the node dashboard's chart functions
    read must exist in a real time-series sample — a renamed sample key
    would otherwise ship a silently-flat chart."""
    srv = make_server(tmp_path, timeseries_interval=0.05,
                      slow_query_threshold=0)
    try:
        html = _html(srv.port, "/debug/dashboard")
        assert "device runtime" in html
        assert srv.sample_timeseries(force=True)
        sample = srv.timeseries.last(1)[0]
        refs = set(re.findall(r"\bs\.(\w+)", html))
        # `s` also names the samples ARRAY in render(): drop JS
        # builtins, keep the per-sample field reads
        refs -= {"length", "map", "slice", "filter", "forEach"}
        assert refs, "no field references parsed from the dashboard"
        missing = sorted(r for r in refs if r not in sample)
        assert not missing, f"dashboard reads absent fields: {missing}"
        # the satellite's cluster-health columns are sampled
        for key in ("hedgesDelta", "retryWavesDelta",
                    "partialResultsDelta", "routingFallbacksDelta",
                    "balancerHandoffsDelta", "fleetEventsDelta"):
            assert key in sample
    finally:
        srv.close()


def test_cluster_dashboard_fields_exist_in_rollup(cluster3):
    """Golden: every `n.<field>` the fleet page reads from a node entry
    must exist in a real rollup summary, and every `c.<field>` in the
    snapshot envelope."""
    servers, ports = cluster3
    html = _html(ports[0], "/debug/dashboard/cluster")
    assert "fleet" in html
    roll, _ = _req(ports[0], "GET", "/debug/cluster?refresh=true",
                   timeout=30)
    node0 = roll["nodes"]["node0"]
    n_refs = set(re.findall(r"\bn\.(\w+)\b", html))
    # staleS/error only appear on degraded entries; qps/stale always
    always = n_refs - {"staleS", "error"}
    missing = sorted(r for r in always if r not in node0)
    assert not missing, f"fleet page reads absent node fields: {missing}"
    c_refs = set(re.findall(r"\bc\.(\w+)\b", html))
    missing_c = sorted(r for r in c_refs - {"ttlS"}
                       if r not in roll)
    assert not missing_c, \
        f"fleet page reads absent snapshot fields: {missing_c}"
    assert "ttlS" in roll


def test_debug_cluster_single_node_fallback(tmp_path):
    """A clusterless server still answers /debug/cluster with its own
    summary, so dashboards work unchanged on one box."""
    srv = make_server(tmp_path, slow_query_threshold=0)
    try:
        out, _ = _req(srv.port, "GET", "/debug/cluster")
        assert set(out["nodes"]) == {"local"}
        info = out["nodes"]["local"]
        assert info["stale"] is False
        assert "queries" in info and "hbmResidentBytes" in info
        assert isinstance(out["timeline"], list)
    finally:
        srv.close()


def test_debug_events_since_cursor_over_http(cluster3):
    servers, ports = cluster3
    seq0 = EVENTS.last_seq()
    EVENTS.emit("node.up", peer="cursor-probe")
    out, _ = _req(ports[1], "GET", f"/debug/events?since={seq0}")
    assert any(e["event"] == "node.up"
               and e.get("peer") == "cursor-probe"
               for e in out["events"])
