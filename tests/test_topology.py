"""Topology persistence + crash-safe resize (reference cluster.go:1580-1692
Topology/considerTopology, :1413-1441/:1504-1561 resizeJob).

r4 verdict items 3+4: a completed resize must survive restarts (no silent
revert to the config host list = split brain), and a coordinator crash
between resize phases must converge to a single membership when it comes
back, driven by the persisted job record + epoch-gated resize-complete
(re-pushed by probe reconciliation)."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.parallel.cluster import ClusterError
from pilosa_tpu.server.server import Config, Server

from test_cluster import _free_ports, _req, query


def _mk(tmp_path, i, host_list, my_host=None):
    cfg = Config(data_dir=str(tmp_path / f"node{i}"),
                 bind=my_host or host_list[i], node_id=f"node{i}",
                 cluster_hosts=host_list, replica_n=2,
                 anti_entropy_interval=0)
    srv = Server(cfg)
    srv.open()
    return srv


def _seed(p0, n_shards=6, n=3000):
    _req(p0, "POST", "/index/ci", {})
    _req(p0, "POST", "/index/ci/field/f", {})
    rng = np.random.default_rng(5)
    cols = rng.choice(n_shards * SHARD_WIDTH, size=n, replace=False)
    rows = rng.integers(0, 4, size=n)
    _req(p0, "POST", "/index/ci/field/f/import",
         {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    return {r: int((rows == r).sum()) for r in range(4)}


def test_topology_persists_across_restart(tmp_path):
    """Resize 2->3, restart EVERY node (node0/node1 still carrying the
    stale 2-host config list): all must adopt the persisted 3-node
    membership, placement and data intact."""
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [_mk(tmp_path, 0, hosts[:2]), _mk(tmp_path, 1, hosts[:2])]
    try:
        p0 = servers[0].port
        oracle = _seed(p0)
        servers.append(_mk(tmp_path, 2, hosts))
        _req(p0, "POST", "/cluster/resize/add-node",
             {"id": "node2", "host": hosts[2]})
        for srv in servers:
            assert srv.cluster.epoch == 1
            top = json.load(open(os.path.join(
                srv.holder.path, ".topology")))
            assert top["epoch"] == 1
            assert len(top["membership"]) == 3

        # full restart; node0/node1 configs still list only 2 hosts
        for s in servers:
            s.close()
        servers = [_mk(tmp_path, 0, hosts[:2], my_host=hosts[0]),
                   _mk(tmp_path, 1, hosts[:2], my_host=hosts[1]),
                   _mk(tmp_path, 2, hosts)]
        for srv in servers:
            assert len(srv.cluster.nodes) == 3, srv.cluster.node_id
            assert srv.cluster.epoch == 1
            for r in range(4):
                [cnt] = query(srv.port, "ci", f"Count(Row(f={r}))")
                assert cnt == oracle[r], (srv.cluster.node_id, r)
        # placements agree
        pl0 = servers[0].cluster.placement
        for srv in servers[1:]:
            for s in range(6):
                assert srv.cluster.placement.shard_nodes("ci", s) == \
                    pl0.shard_nodes("ci", s)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_topology_mismatch_rejected(tmp_path):
    """considerTopology: a node whose persisted topology does not include
    it must refuse to start rather than serve a divergent placement."""
    ports = _free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    os.makedirs(tmp_path / "node0", exist_ok=True)
    with open(tmp_path / "node0" / ".topology", "w") as f:
        json.dump({"epoch": 3, "replicaN": 1, "membership": [
            {"id": "nodeX", "uri": "localhost:1"}]}, f)
    with pytest.raises(ClusterError, match="not in the persisted"):
        _mk(tmp_path, 0, hosts)


def test_resize_straggler_reconverges_by_probe(tmp_path):
    """A peer that misses every resize-complete send stays on the old
    membership only until the next probe pass: the coordinator sees its
    stale epoch and re-pushes, epoch-gated."""
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [_mk(tmp_path, 0, hosts[:2]), _mk(tmp_path, 1, hosts[:2])]
    try:
        p0 = servers[0].port
        oracle = _seed(p0)
        servers.append(_mk(tmp_path, 2, hosts))

        coord = servers[0].cluster
        orig_send = coord.client.send_message
        drop_host = hosts[1]

        def flaky_send(host, msg, timeout=None):
            if msg.get("type") == "resize-complete" and host == drop_host:
                raise OSError("injected: node1 unreachable for complete")
            return orig_send(host, msg, timeout) if timeout is not None \
                else orig_send(host, msg)

        coord.client.send_message = flaky_send
        try:
            _req(p0, "POST", "/cluster/resize/add-node",
                 {"id": "node2", "host": hosts[2]})
        finally:
            coord.client.send_message = orig_send

        # coordinator + node2 adopted; node1 is behind; job record kept
        assert coord.epoch == 1
        assert len(coord.nodes) == 3
        assert servers[1].cluster.epoch == 0
        assert coord._load_resize_job() is not None

        coord.probe_peers()  # reconciliation pushes the missed complete
        assert servers[1].cluster.epoch == 1
        assert len(servers[1].cluster.nodes) == 3
        assert servers[1].cluster.state == "NORMAL"
        assert coord._load_resize_job() is None
        for srv in servers:
            for r in range(4):
                [cnt] = query(srv.port, "ci", f"Count(Row(f={r}))")
                assert cnt == oracle[r], (srv.cluster.node_id, r)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_coordinator_crash_midresize_recovers_on_restart(tmp_path):
    """Kill the coordinator between phase 1 (fetch done, job persisted)
    and phase 2 (nobody adopted): peers are latched RESIZING; the
    restarted coordinator finds the job record and drives completion, and
    the cluster converges to one membership with data intact
    (cluster.go:1504-1561)."""
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [_mk(tmp_path, 0, hosts[:2]), _mk(tmp_path, 1, hosts[:2])]
    try:
        p0 = servers[0].port
        oracle = _seed(p0)
        servers.append(_mk(tmp_path, 2, hosts))

        coord = servers[0].cluster
        orig_handle = coord.handle_message
        orig_send = coord.client.send_message

        def crashing_handle(msg):
            if msg.get("type") == "resize-complete":
                raise RuntimeError("injected coordinator crash")
            return orig_handle(msg)

        def dropping_send(host, msg, timeout=None):
            if msg.get("type") == "resize-complete":
                raise OSError("injected: crashed before sending")
            return orig_send(host, msg, timeout) if timeout is not None \
                else orig_send(host, msg)

        coord.handle_message = crashing_handle
        coord.client.send_message = dropping_send
        with pytest.raises(urllib.error.HTTPError):
            _req(p0, "POST", "/cluster/resize/add-node",
                 {"id": "node2", "host": hosts[2]})

        # phase 1 ran, job persisted, nobody adopted; peers latched
        assert coord._load_resize_job() is not None
        assert servers[1].cluster.state == "RESIZING"
        assert len(servers[1].cluster.nodes) == 2

        # the "crash": close the coordinator process state entirely
        dead_cfg = servers[0].config
        servers[0].close()
        servers[0] = Server(dead_cfg)
        servers[0].open()  # _recover_resize_job drives completion

        for srv in servers:
            assert len(srv.cluster.nodes) == 3, srv.cluster.node_id
            assert srv.cluster.epoch == 1
            assert srv.cluster.state == "NORMAL"
        assert servers[0].cluster._load_resize_job() is None
        for srv in servers:
            for r in range(4):
                [cnt] = query(srv.port, "ci", f"Count(Row(f={r}))")
                assert cnt == oracle[r], (srv.cluster.node_id, r)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_removed_node_recovers_after_coordinator_crash(tmp_path):
    """Coordinator crashes mid-way through a REMOVE resize: the removed
    node, latched RESIZING, must still get its single-node revert when
    the coordinator recovers the job (r5 review finding — without the
    job's removed list it was stranded RESIZING forever)."""
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [_mk(tmp_path, i, hosts) for i in range(3)]
    try:
        p0 = servers[0].port
        _seed(p0)
        coord = servers[0].cluster
        orig_handle = coord.handle_message
        orig_send = coord.client.send_message

        def crashing_handle(msg):
            if msg.get("type") == "resize-complete":
                raise RuntimeError("injected coordinator crash")
            return orig_handle(msg)

        def dropping_send(host, msg, timeout=None):
            if msg.get("type") == "resize-complete":
                raise OSError("injected: crashed before sending")
            return orig_send(host, msg, timeout) if timeout is not None \
                else orig_send(host, msg)

        coord.handle_message = crashing_handle
        coord.client.send_message = dropping_send
        with pytest.raises(urllib.error.HTTPError):
            _req(p0, "POST", "/cluster/resize/remove-node", {"id": "node2"})
        assert servers[2].cluster.state == "RESIZING"

        dead_cfg = servers[0].config
        servers[0].close()
        servers[0] = Server(dead_cfg)
        servers[0].open()

        # survivors on the 2-node membership, removed node reverted to a
        # single-node view — nobody latched
        for srv in servers[:2]:
            assert len(srv.cluster.nodes) == 2, srv.cluster.node_id
            assert srv.cluster.state in ("NORMAL", "DEGRADED")
        assert [n.id for n in servers[2].cluster.nodes] == ["node2"]
        assert servers[2].cluster.state == "NORMAL"
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_removed_node_unlatches_via_probe_safety_net(tmp_path):
    """Even with no revert message at all (dropped by both the resize and
    recovery), a removed node latched RESIZING discovers its removal on
    the next probe of the old coordinator and adopts a single-node
    view."""
    ports = _free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [_mk(tmp_path, i, hosts) for i in range(3)]
    try:
        p0 = servers[0].port
        _seed(p0)
        coord = servers[0].cluster
        orig_send = coord.client.send_message
        drop_host = hosts[2]

        def dropping_send(host, msg, timeout=None):
            if msg.get("type") == "resize-complete" and host == drop_host:
                raise OSError("injected: removed node unreachable")
            return orig_send(host, msg, timeout) if timeout is not None \
                else orig_send(host, msg)

        coord.client.send_message = dropping_send
        try:
            _req(p0, "POST", "/cluster/resize/remove-node", {"id": "node2"})
        finally:
            coord.client.send_message = orig_send
        assert servers[2].cluster.state == "RESIZING"
        assert len(servers[2].cluster.nodes) == 3

        servers[2].cluster.probe_peers()
        assert servers[2].cluster.state == "NORMAL"
        assert [n.id for n in servers[2].cluster.nodes] == ["node2"]
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_stale_resizing_latch_unlatches_by_probe(tmp_path):
    """A peer latched RESIZING by a resize whose coordinator died before
    persisting the job (phase 1 in flight) must unlatch once it probes
    the coordinator and sees no resize in progress at its own epoch."""
    ports = _free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [_mk(tmp_path, 0, hosts), _mk(tmp_path, 1, hosts)]
    try:
        c1 = servers[1].cluster
        c1.handle_message({"type": "set-state", "state": "RESIZING"})
        assert c1.state == "RESIZING"
        c1.probe_peers()
        assert c1.state == "NORMAL"
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
