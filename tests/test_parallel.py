"""Placement + mesh execution tests.

Placement mirrors cluster_internal_test.go (TestCluster_Partition /
partitionNodes); mesh execution runs real shard_map over the 8 virtual CPU
devices from conftest and must agree with the per-shard executor."""

import jax
import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel import (
    JmpHasher, MeshExecutor, ModHasher, Placement, default_mesh, jump_hash,
)
from pilosa_tpu.storage import FieldOptions, Holder


# -- placement --------------------------------------------------------------

def test_jump_hash_properties():
    # deterministic, in range, monotone-consistency on bucket growth
    for key in [0, 1, 12345, 2**63]:
        for n in [1, 2, 7, 100]:
            b = jump_hash(key, n)
            assert 0 <= b < n
            assert jump_hash(key, n) == b
    # jump-hash consistency: growing n only moves keys to the NEW bucket
    moved_elsewhere = 0
    for key in range(1000):
        b5, b6 = jump_hash(key, 5), jump_hash(key, 6)
        if b5 != b6:
            assert b6 == 5
    # roughly 1/6 of keys move
    moved = sum(jump_hash(k, 5) != jump_hash(k, 6) for k in range(6000))
    assert 500 < moved < 1500


def test_partition_stability():
    p = Placement(["a", "b", "c"], replica_n=1)
    # partition is a pure function of (index, shard)
    assert p.partition("i", 0) == p.partition("i", 0)
    assert p.partition("i", 0) != p.partition("other", 0) or True
    parts = {p.partition("i", s) for s in range(100)}
    assert len(parts) > 50  # well spread over 256 partitions


def test_replication_ring():
    p = Placement(["n0", "n1", "n2", "n3"], replica_n=2, hasher=ModHasher())
    owners = p.partition_nodes(1)
    assert owners == ["n1", "n2"]  # ring successors
    owners = p.partition_nodes(3)
    assert owners == ["n3", "n0"]  # wraps
    # replica_n capped at node count
    p2 = Placement(["x"], replica_n=3)
    assert p2.partition_nodes(0) == ["x"]


def test_owned_and_grouped_shards():
    p = Placement(["n0", "n1", "n2"], replica_n=2)
    shards = list(range(20))
    by_node = p.shards_by_node("i", shards)
    assert sorted(s for lst in by_node.values() for s in lst) == shards
    # every shard owned by exactly replica_n nodes
    for s in shards:
        owners = [n for n in p.nodes if p.owns_shard(n, "i", s)]
        assert len(owners) == 2
        assert p.primary("i", s) == p.shard_nodes("i", s)[0]


# -- mesh execution ---------------------------------------------------------

N_SHARDS = 11  # deliberately not a multiple of 8 devices


@pytest.fixture
def loaded(tmp_path):
    h = Holder(None)
    idx = h.create_index("i")
    f = idx.create_field("f")
    v = idx.create_field("v", FieldOptions(type="int", min=0, max=1000))
    rng = np.random.default_rng(9)
    cols = rng.integers(0, N_SHARDS * SHARD_WIDTH, size=5000)
    rows = rng.integers(0, 8, size=5000)
    f.import_bits(rows, cols)
    v.import_values(cols, rng.integers(0, 1000, size=5000))
    idx.add_existence(cols)
    return h, rows, cols


def test_mesh_matches_pershard(loaded):
    h, rows, cols = loaded
    assert len(jax.devices()) == 8  # conftest virtual mesh
    plain = Executor(h)
    meshy = Executor(h, use_mesh=True)
    for q in ["Count(Row(f=1))",
              "Count(Intersect(Row(f=1), Row(f=2)))",
              "Count(Union(Row(f=0), Row(f=3), Row(f=7)))",
              "Count(Not(Row(f=1)))",
              "Count(Row(v > 500))"]:
        assert plain.execute("i", q) == meshy.execute("i", q), q


def test_mesh_bitmap_segments(loaded):
    h, rows, cols = loaded
    plain = Executor(h)
    meshy = Executor(h, use_mesh=True)
    a = plain.execute("i", "Union(Row(f=1), Row(f=4))")[0]
    b = meshy.execute("i", "Union(Row(f=1), Row(f=4))")[0]
    assert np.array_equal(a.columns(), b.columns())
    assert set(a.segments) == set(b.segments)


def test_mesh_sum_with_filter(loaded):
    h, _, _ = loaded
    plain = Executor(h)
    meshy = Executor(h, use_mesh=True)
    assert plain.execute("i", "Sum(Row(f=1), field=v)") == \
        meshy.execute("i", "Sum(Row(f=1), field=v)")


def test_mesh_empty_and_missing_fragments(loaded):
    h, _, _ = loaded
    meshy = Executor(h, use_mesh=True)
    # field exists but row beyond data
    assert meshy.execute("i", "Count(Row(f=500))") == [0]
    # difference touching missing fragments in some shards
    out = meshy.execute("i", "Count(Difference(Row(f=1), Row(f=1)))")
    assert out == [0]


def test_mesh_executor_cache(loaded):
    h, _, _ = loaded
    me = Executor(h, use_mesh=True)
    me.execute("i", "Count(Row(f=1))")
    n = len(me.mesh_exec._cache)
    me.execute("i", "Count(Row(f=1))")
    assert len(me.mesh_exec._cache) == n


def test_stacks_register_with_device_budget(loaded):
    """Stacked shard blocks account against the DeviceBudget and evict as
    one unit (r3 advisor: stacks bypassed the budget entirely)."""
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET
    h, _, _ = loaded
    me = Executor(h, use_mesh=True)
    me.execute("i", "Count(Row(f=1))")
    # (no global resident_bytes delta check: GC finalizers of earlier
    # tests' executors may unregister concurrently)
    sc = me.mesh_exec._stack_cache
    assert len(sc) == 1
    ckey = next(iter(sc))
    key = ("stack", id(me.mesh_exec), ckey)
    assert key in DEFAULT_BUDGET._entries
    nbytes = DEFAULT_BUDGET._entries[key][0]
    assert nbytes > 0
    # budget eviction drops the stack-cache entry
    DEFAULT_BUDGET._entries[key][1]()
    assert ckey not in sc
    DEFAULT_BUDGET.unregister(key)
    # close() unregisters whatever remains
    me.execute("i", "Count(Row(f=1))")
    assert ("stack", id(me.mesh_exec), ckey) in DEFAULT_BUDGET._entries
    mid = id(me.mesh_exec)
    me.close()
    assert ("stack", mid, ckey) not in DEFAULT_BUDGET._entries


def test_server_config_sets_device_budget(tmp_path):
    from pilosa_tpu.server import Config, Server
    from pilosa_tpu.storage.membudget import DEFAULT_BUDGET
    old = DEFAULT_BUDGET.limit_bytes
    try:
        srv = Server(Config(data_dir=str(tmp_path), bind="localhost:0",
                            device_budget_mb=256))
        assert DEFAULT_BUDGET.limit_bytes == 256 << 20
        srv.httpd.server_close()
    finally:
        DEFAULT_BUDGET.limit_bytes = old


def test_global_mesh_executor(loaded):
    """multihost.global_mesh: a mesh over every process device drives the
    same executor path (single process here; multi-process differs only
    in where jax.devices() live)."""
    from pilosa_tpu.parallel import multihost
    h, _, _ = loaded
    mesh = multihost.global_mesh()
    assert mesh.devices.size == 8
    me = Executor(h, mesh=mesh)
    plain = Executor(h)
    q = "Count(Intersect(Row(f=1), Row(f=2)))"
    assert me.execute("i", q) == plain.execute("i", q)
    lo, hi = multihost.process_shard_slice(10)
    assert (lo, hi) == (0, 10)
    with pytest.raises(ValueError):
        multihost.init_distributed("localhost:1", 0, 0)
    with pytest.raises(ValueError):
        multihost.init_distributed("localhost:1", 2, 5)


def test_plan_cache_keyed_by_shape(loaded):
    """Distinct row ids and BSI predicate values must share ONE compiled
    executable — literals are runtime params, not baked constants
    (SURVEY §7: plan cache keyed by call tree shape).  A recompile per
    distinct query value would cost seconds each on TPU."""
    h, _, _ = loaded
    me = Executor(h, use_mesh=True)
    me.execute("i", "Count(Row(f=1))")
    n = len(me.mesh_exec._cache)
    for q in ["Count(Row(f=2))", "Count(Row(f=7))", "Count(Row(f=999))"]:
        me.execute("i", q)
    assert len(me.mesh_exec._cache) == n, "row id recompiled the plan"
    me.execute("i", "Count(Row(v > 10))")
    n = len(me.mesh_exec._cache)
    for q in ["Count(Row(v > 500))", "Count(Row(v > 3))"]:
        me.execute("i", q)
    assert len(me.mesh_exec._cache) == n, "BSI value recompiled the plan"
    # per-shard compiler shares executables the same way
    plain = Executor(h)
    plain.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")
    n = len(plain.compiler._cache)
    plain.execute("i", "Count(Intersect(Row(f=3), Row(f=4)))")
    assert len(plain.compiler._cache) == n
    # correctness across the shared executable
    assert plain.execute("i", "Count(Row(f=2))") == \
        me.execute("i", "Count(Row(f=2))")


def test_mesh_topn_rows_minmax_match_pershard(loaded):
    """The round-3 reducers (row_counts, bsi_sum, bsi_min_max,
    group_counts) must agree with the per-shard host loop on every
    aggregation call (VERDICT r2: 'route the remaining reducers through
    the mesh')."""
    h, _, _ = loaded
    plain = Executor(h)
    meshy = Executor(h, use_mesh=True)
    for q in ["TopN(f, n=3)",
              "TopN(f)",
              "TopN(f, Row(f=2), n=2)",
              "Min(field=v)", "Max(field=v)",
              "Min(Row(f=1), field=v)", "Max(Row(f=1), field=v)",
              "MinRow(field=f)", "MaxRow(field=f)",
              "Rows(f)", "Rows(f, limit=3)", "Rows(f, previous=2)",
              "GroupBy(Rows(f))",
              "GroupBy(Rows(f), limit=4)"]:
        assert plain.execute("i", q) == meshy.execute("i", q), q


def test_mesh_groupby_two_fields_and_filter():
    h = Holder(None)
    idx = h.create_index("i")
    a = idx.create_field("a")
    b = idx.create_field("b")
    g = idx.create_field("g")
    rng = np.random.default_rng(3)
    cols = rng.integers(0, 3 * SHARD_WIDTH, size=3000)
    a.import_bits(rng.integers(0, 3, size=3000), cols)
    b.import_bits(rng.integers(0, 4, size=3000), cols)
    g.import_bits(rng.integers(0, 2, size=3000), cols)
    idx.add_existence(cols)
    plain = Executor(h)
    meshy = Executor(h, use_mesh=True)
    for q in ["GroupBy(Rows(a), Rows(b))",
              "GroupBy(Rows(a), Rows(b), Row(g=1))",
              "GroupBy(Rows(a), Rows(b), limit=5)"]:
        assert plain.execute("i", q) == meshy.execute("i", q), q


def test_mesh_groupby_single_executable():
    """Every combo of a GroupBy must share one compiled executable —
    prefix row ids are dynamic args, not baked constants (a recompile per
    combo would dwarf the query)."""
    h = Holder(None)
    idx = h.create_index("i")
    a = idx.create_field("a")
    b = idx.create_field("b")
    rng = np.random.default_rng(5)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=2000)
    a.import_bits(rng.integers(0, 6, size=2000), cols)
    b.import_bits(rng.integers(0, 6, size=2000), cols)
    idx.add_existence(cols)
    meshy = Executor(h, use_mesh=True)
    meshy.execute("i", "GroupBy(Rows(a), Rows(b))")  # 36 combos
    n_compiled = len(meshy.mesh_exec._cache)
    meshy.execute("i", "GroupBy(Rows(a), Rows(b))")
    assert len(meshy.mesh_exec._cache) == n_compiled
    # 6x6 combos but only O(1) executables: Rows row_counts (1 per field,
    # same shapes may share) + 1 group_counts
    assert n_compiled <= 4


def test_mesh_negative_bsi_values():
    h = Holder(None)
    idx = h.create_index("i")
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    rng = np.random.default_rng(11)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=1000)
    vals = rng.integers(-500, 500, size=1000)
    v.import_values(cols, vals)
    idx.add_existence(cols)
    plain = Executor(h)
    meshy = Executor(h, use_mesh=True)
    for q in ["Sum(field=v)", "Min(field=v)", "Max(field=v)",
              "Count(Row(v < 0))", "Count(Row(v >< [-100, 100]))"]:
        assert plain.execute("i", q) == meshy.execute("i", q), q


def test_mesh_mixed_write_read_query_sequential(loaded):
    """Batched grouping must NOT reorder dispatch around writes: a read
    after a write in the same multi-call query sees the write (the
    reference executes calls sequentially, executor.go:113)."""
    h, _, _ = loaded
    me = Executor(h, use_mesh=True)
    before = me.execute("i", "Count(Row(f=1))")[0]
    out = me.execute(
        "i", "Set(999999, f=1) Count(Row(f=1)) Count(Row(f=2))")
    assert out[0] is True
    assert out[1] == before + 1  # read AFTER the write sees the new bit
    # read-only multi-call queries still batch (single fetch)
    out2 = me.execute("i", "Count(Row(f=1)) Count(Row(f=1))")
    assert out2[0] == out2[1] == before + 1


def test_mesh_stack_cache_bounded(loaded):
    """The placed-stack cache is LRU-bounded so stale shard sets don't pin
    device memory forever."""
    h, _, _ = loaded
    me = Executor(h, use_mesh=True)
    me.mesh_exec.stack_cache_max = 2
    me.execute("i", "Count(Row(f=1))")
    me.execute("i", "Count(Row(v > 3))")
    me.execute("i", "Count(Intersect(Row(f=1), Row(v > 2)))")
    me.execute("i", "TopN(f, n=1)")
    assert len(me.mesh_exec._stack_cache) <= 2
    # evicted entries re-place transparently with correct results
    plain = Executor(h)
    assert plain.execute("i", "Count(Row(f=1))") == \
        me.execute("i", "Count(Row(f=1))")


def test_mesh_stack_cache_invalidation(loaded):
    """Placed shard-stacks are reused across queries and rebuilt when a
    fragment mirror changes (a write), so results never go stale."""
    h, _, _ = loaded
    me = Executor(h, use_mesh=True)
    before = me.execute("i", "Count(Row(f=1))")[0]
    token0 = {k: v[0] for k, v in me.mesh_exec._stack_cache.items()}
    me.execute("i", "Count(Row(f=2))")  # same shape, repeat gather
    for k, v in me.mesh_exec._stack_cache.items():
        assert v[0] == token0[k]  # reused, not re-placed
    # write invalidates: new mirror -> new stack -> fresh result
    f = h.field("i", "f")
    free_col = 0
    assert f.set_bit(1, free_col) or True
    after = me.execute("i", "Count(Row(f=1))")[0]
    oracle = Executor(h).execute("i", "Count(Row(f=1))")[0]
    assert after == oracle
    assert after >= before


def test_mesh_single_shard(tmp_path):
    h = Holder(None)
    idx = h.create_index("i")
    idx.field("_exists")  # noqa
    f = idx.create_field("f")
    f.set_bit(1, 42)
    meshy = Executor(h, use_mesh=True)
    assert meshy.execute("i", "Count(Row(f=1))") == [1]
    res = meshy.execute("i", "Row(f=1)")[0]
    assert res.columns().tolist() == [42]
