"""CLI tests (reference ctl/*_test.go coverage): import/export round-trip
against a live server, check/inspect on fragment files, generate-config."""

import json

import pytest

from pilosa_tpu.cli import main
from pilosa_tpu.server.server import Config, Server


@pytest.fixture
def srv(tmp_path):
    s = Server(Config(data_dir=str(tmp_path / "data"), bind="localhost:0"))
    s.open()
    yield s
    s.close()


def test_import_export_roundtrip(srv, tmp_path, capsys):
    csv = tmp_path / "in.csv"
    csv.write_text("1,10\n1,20\n2,1048586\n")
    rc = main(["import", "-host", f"localhost:{srv.port}",
               "-i", "x", "-f", "f", "--create", str(csv)])
    assert rc == 0
    out = tmp_path / "out.csv"
    rc = main(["export", "-host", f"localhost:{srv.port}",
               "-i", "x", "-f", "f", "-o", str(out)])
    assert rc == 0
    assert set(out.read_text().strip().split("\n")) == \
        {"1,10", "1,20", "2,1048586"}


def test_cluster_export_covers_remote_shards(tmp_path):
    """Export through ONE node must fetch each shard from an owner —
    shards placed on other nodes are not silently dropped
    (ctl/export.go fragment-nodes routing)."""
    from tests.test_cluster import make_cluster

    servers = make_cluster(tmp_path, n=3, replica_n=1)
    try:
        from pilosa_tpu.core import SHARD_WIDTH
        csv = tmp_path / "in.csv"
        lines = [f"1,{s * SHARD_WIDTH + 7}" for s in range(8)]
        csv.write_text("\n".join(lines) + "\n")
        p0 = servers[0].port
        rc = main(["import", "-host", f"localhost:{p0}",
                   "-i", "x", "-f", "f", "--create", str(csv)])
        assert rc == 0
        # replica_n=1: some of the 8 shards live only on nodes 1/2
        owned0 = {s for s in range(8)
                  if "node0" in
                  servers[0].cluster.placement.shard_nodes("x", s)}
        assert owned0 != set(range(8))
        out = tmp_path / "out.csv"
        rc = main(["export", "-host", f"localhost:{p0}",
                   "-i", "x", "-f", "f", "-o", str(out)])
        assert rc == 0
        assert set(out.read_text().strip().split("\n")) == set(lines)
    finally:
        for s in servers:
            s.close()


def test_import_int_field(srv, tmp_path):
    csv = tmp_path / "vals.csv"
    csv.write_text("1,100\n2,-5\n")
    rc = main(["import", "-host", f"localhost:{srv.port}",
               "-i", "x", "-f", "v", "--create", "--field-type", "int",
               "--min", "-100", "--max", "1000", str(csv)])
    assert rc == 0
    import urllib.request
    req = urllib.request.Request(
        f"http://localhost:{srv.port}/index/x/query",
        data=b"Sum(field=v)", method="POST")
    body = json.loads(urllib.request.urlopen(req).read())
    assert body["results"][0] == {"value": 95, "count": 2}


def test_check_and_inspect(tmp_path, capsys):
    from pilosa_tpu.storage.fragment import Fragment

    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0)
    f.set_bit(1, 100)
    f.close()
    assert main(["check", path]) == 0
    out = capsys.readouterr().out
    assert "OK" in out
    assert main(["inspect", path]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["bits"] == 1

    # corrupt it
    with open(path, "r+b") as fh:
        fh.write(b"XXXXXXXX")
    assert main(["check", path]) == 1
    assert "CORRUPT" in capsys.readouterr().out


def test_config_resolved(tmp_path, capsys, monkeypatch):
    """`config` prints the resolved cascade: TOML overridden by env."""
    toml = tmp_path / "c.toml"
    toml.write_text('data-dir = "/tmp/x"\nbind = "localhost:7777"\n')
    monkeypatch.setenv("PILOSA_TPU_BIND", "localhost:8888")
    rc = main(["config", "-c", str(toml)])
    assert rc == 0
    out = capsys.readouterr().out
    assert 'data-dir = "/tmp/x"' in out       # from TOML
    assert 'bind = "localhost:8888"' in out   # env wins over TOML
    assert "[cluster]" in out
    # round-trips: the printed output parses as the same config
    rt = tmp_path / "rt.toml"
    rt.write_text(out)
    monkeypatch.delenv("PILOSA_TPU_BIND")
    from pilosa_tpu.server.server import Config
    cfg = Config.from_toml(str(rt))
    assert cfg.bind == "localhost:8888"
    assert cfg.data_dir == "/tmp/x"


def test_generate_config(capsys):
    assert main(["generate-config"]) == 0
    out = capsys.readouterr().out
    assert "data-dir" in out
    from pilosa_tpu.utils import toml
    toml.loads(out)  # valid TOML (tomllib, or tomli on py3.10)


def test_import_create_idempotent(srv, tmp_path):
    csv = tmp_path / "a.csv"
    csv.write_text("1,1\n")
    for _ in range(2):  # second run hits 409 on create; must succeed
        assert main(["import", "-host", f"localhost:{srv.port}",
                     "-i", "y", "-f", "f", "--create", str(csv)]) == 0


def test_import_batching(srv, tmp_path):
    csv = tmp_path / "b.csv"
    csv.write_text("".join(f"1,{i}\n" for i in range(25)))
    assert main(["import", "-host", f"localhost:{srv.port}", "-i", "z",
                 "-f", "f", "--create", "--batch-size", "10", str(csv)]) == 0
    import urllib.request
    req = urllib.request.Request(
        f"http://localhost:{srv.port}/index/z/query",
        data=b"Count(Row(f=1))", method="POST")
    assert json.loads(urllib.request.urlopen(req).read())["results"] == [25]
