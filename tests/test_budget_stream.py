"""Out-of-core shard streaming: pin-aware budget eviction, the host-side
dense staging cache, residency-aware slice scheduling with prefetch, and
the budgeted-eviction DIFFERENTIAL guarantee — a query corpus run under a
budget small enough to force evictions (and streaming) mid-batch must
return results identical to the unbudgeted run.  A pinning bug would
corrupt in-flight buffers silently; the differential catches it as a
divergence."""

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import _batch_chunks
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage import fragment
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.membudget import (
    DEFAULT_BUDGET, HOST_STAGE_BUDGET, DeviceBudget,
)

from test_differential import _norm, gen_query


# -- pin-aware eviction (unit) ----------------------------------------------

def test_pinned_entry_never_evicted():
    b = DeviceBudget(limit_bytes=100)
    dropped = []
    b.register(("a",), 60, lambda: dropped.append("a"))
    assert b.pin(("a",))
    # over budget, but the only candidate is pinned: admitted over-limit
    b.register(("b",), 60, lambda: dropped.append("b"))
    assert dropped == []
    assert b.resident_bytes == 120
    assert b.stats()["pinnedBytes"] == 60
    # unpinned again: LRU order (a, then b) drains normally
    b.unpin(("a",))
    b.register(("c",), 50, lambda: dropped.append("c"))
    assert dropped == ["a", "b"]
    assert b.resident_bytes == 50
    assert b.evictions == 2


def test_eviction_prefers_unpinned_coldest():
    b = DeviceBudget(limit_bytes=100)
    dropped = []
    b.register(("cold",), 40, lambda: dropped.append("cold"))
    b.register(("pinned",), 40, lambda: dropped.append("pinned"))
    b.pin(("pinned",))
    b.touch(("cold",))  # cold is now MRU, pinned is LRU
    b.register(("new",), 40, lambda: dropped.append("new"))
    # pinned (LRU) skipped; cold (unpinned, though warmer) evicted
    assert dropped == ["cold"]


def test_pin_unknown_key_and_counters():
    b = DeviceBudget(limit_bytes=None)
    assert not b.pin(("nope",))
    b.unpin(("nope",))  # no-op
    b.register(("x",), 10, lambda: None)
    b.register(("x",), 30, lambda: None)  # re-register accumulates uploads
    b.note_prefetch(True)
    b.note_prefetch(False)
    s = b.stats()
    assert s["uploadBytes"] == 40
    assert s["prefetchHits"] == 1 and s["prefetchMisses"] == 1
    # pins survive a re-register (an in-flight user still holds the key)
    b.pin(("x",))
    b.register(("x",), 50, lambda: None)
    assert b.stats()["pinnedBytes"] == 50


# -- filter-less chunk fix (r5 advisor) -------------------------------------

def test_filterless_group_dispatches_single_chunk():
    mat = np.zeros((40000, 3), dtype=np.int32)
    chunks = list(_batch_chunks(mat, n_shards=0))
    assert [(lo, n) for lo, n, _ in chunks] == [(0, 40000)]
    assert chunks[0][2].shape[0] == 65536  # padded to pow2
    # with a filter (n_shards > 0) the cap still applies
    assert len(list(_batch_chunks(mat, n_shards=1))) > 1


# -- host staging cache -----------------------------------------------------

def test_staged_dense_caches_until_mutation():
    # a LIMITED device budget: with no limit nothing ever re-uploads,
    # so staged_dense deliberately skips caching
    f = Fragment(None, "i", "f", "standard", 0,
                 budget=DeviceBudget(limit_bytes=1 << 20))
    f.bulk_import(np.array([0, 1, 2]), np.array([5, 6, 7]))
    d1 = f.staged_dense()
    d2 = f.staged_dense()
    assert d1 is d2  # served from the stage cache
    assert (d1 == f.to_dense()).all()
    f.set_bit(3, 9)  # gen bump invalidates
    d3 = f.staged_dense()
    assert d3 is not d1
    assert (d3 == f.to_dense()).all()
    # budget eviction drops the cached expansion; next call rebuilds
    key = ("stage", id(f))
    assert key in HOST_STAGE_BUDGET._entries
    HOST_STAGE_BUDGET._entries[key][1]()
    assert f._stage is None
    assert (f.staged_dense() == f.to_dense()).all()
    f._drop_stage()
    assert key not in HOST_STAGE_BUDGET._entries


def test_staged_dense_disabled_at_zero_limit():
    old = HOST_STAGE_BUDGET.limit_bytes
    try:
        HOST_STAGE_BUDGET.limit_bytes = 0
        f = Fragment(None, "i", "f", "standard", 0,
                     budget=DeviceBudget(limit_bytes=1 << 20))
        f.bulk_import(np.array([0]), np.array([1]))
        assert f.staged_dense() is not f.staged_dense()
        assert f._stage is None
    finally:
        HOST_STAGE_BUDGET.limit_bytes = old


def test_staged_dense_transient_under_unlimited_device_budget():
    # nothing can evict -> no re-upload to accelerate -> no cache growth
    f = Fragment(None, "i", "f", "standard", 0)  # DEFAULT_BUDGET, no limit
    old = DEFAULT_BUDGET.limit_bytes
    try:
        DEFAULT_BUDGET.limit_bytes = None
        f.bulk_import(np.array([0]), np.array([1]))
        assert f.staged_dense() is not f.staged_dense()
        assert f._stage is None
    finally:
        DEFAULT_BUDGET.limit_bytes = old


# -- residency-aware slicing ------------------------------------------------

@pytest.fixture
def wide(rng):
    """16-shard index: wide enough that the 8-virtual-device test mesh
    can split it into two mesh-width slices."""
    h = Holder(None)
    idx = h.create_index("w", track_existence=False)
    f = idx.create_field("f")
    n = 40_000
    f.import_bits(rng.integers(0, 10, size=n),
                  rng.integers(0, 16 * SHARD_WIDTH, size=n))
    return h


def test_shard_schedule_slices_and_orders_by_residency(wide, monkeypatch):
    # this test exercises the DENSE slicing machinery; compressed
    # residency would shrink the working set under the budget and
    # (correctly) stop carving slices — pin the dense form
    monkeypatch.setattr(fragment, "COMPRESSED_RESIDENT", False)
    ex = Executor(wide, use_mesh=True)
    me = ex.mesh_exec
    shards = list(range(16))
    keys = [("f", "standard")]
    old = DEFAULT_BUDGET.limit_bytes
    try:
        # unlimited budget: one slice, identical to the unsliced path
        DEFAULT_BUDGET.limit_bytes = None
        assert me.shard_schedule(wide, "w", [keys], shards).slices == \
            [shards]
        # 16 shards x 16 rows x 128KB = 32MB working set; a 12MB budget
        # must carve mesh-width slices
        DEFAULT_BUDGET.limit_bytes = 12 << 20
        sched = me.shard_schedule(wide, "w", [keys], shards)
        assert sched.slices == [shards[:8], shards[8:]]
        assert sched.max_slice_len == 8
        # stage the SECOND slice; the next schedule drains it first
        me._placed_groups(keys, wide, "w", shards[8:])
        sched = me.shard_schedule(wide, "w", [keys], shards)
        assert sched.slices == [shards[8:], shards[:8]]
        # streamed execution over the schedule equals the unbudgeted run
        want = None
        for limit in (None, 12 << 20):
            DEFAULT_BUDGET.limit_bytes = limit
            got = ex.execute("w", "Count(Union(Row(f=1), Row(f=3)))")
            if want is None:
                want = got
            assert got == want
        assert DEFAULT_BUDGET.stats()["prefetchHits"] + \
            DEFAULT_BUDGET.stats()["prefetchMisses"] > 0
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()


# -- budgeted-eviction differential ----------------------------------------

def test_budgeted_run_matches_unbudgeted(wide, rng):
    """The differential query corpus under a budget that forces eviction
    (and streaming) mid-batch returns results identical to the
    unbudgeted run — pinned entries are never popped mid-dispatch."""
    h = wide
    idx = h.indexes["w"]
    b = idx.create_field("b")
    v = idx.create_field("v", FieldOptions(type="int", min=-500, max=500))
    n = 30_000
    cols = rng.integers(0, 16 * SHARD_WIDTH, size=n)
    b.import_bits(rng.integers(0, 6, size=n), cols)
    vcols = np.unique(cols[: n // 2])
    v.import_values(vcols, rng.integers(-500, 500, size=vcols.size))
    idx.add_existence(cols)

    # the differential grammar references fields a/b/v; alias a -> f
    qrng = np.random.default_rng(4321)
    queries = [gen_query(qrng).replace("Row(a=", "Row(f=")
               .replace("Rows(a", "Rows(f").replace("TopN(a", "TopN(f")
               for _ in range(12)]
    batches = []
    i = 0
    while i < len(queries):
        take = int(qrng.integers(1, 4))
        batches.append(" ".join(queries[i: i + take]))
        i += take

    ex = Executor(h, use_mesh=True)
    old = DEFAULT_BUDGET.limit_bytes
    try:
        DEFAULT_BUDGET.limit_bytes = None
        want = [_norm(r) for bt in batches for r in ex.execute("w", bt)]
        DEFAULT_BUDGET.limit_bytes = 12 << 20
        DEFAULT_BUDGET.shrink_to_limit()
        ev0 = DEFAULT_BUDGET.evictions
        got = [_norm(r) for bt in batches for r in ex.execute("w", bt)]
        assert got == want
        assert DEFAULT_BUDGET.evictions > ev0, \
            "budget never evicted: the differential exercised nothing"
        assert DEFAULT_BUDGET.stats()["pinnedBytes"] == 0, \
            "pins leaked past their dispatch"
    finally:
        DEFAULT_BUDGET.limit_bytes = old
        ex.close()
