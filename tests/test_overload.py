"""Overload-armor chaos tests (ISSUE 2, docs/robustness.md): end-to-end
deadlines, admission control, per-peer circuit breakers, graceful drain,
and the failpoint registry that makes every failure path testable
without real partitions."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.server.admission import (AdmissionController,
                                         AdmissionRejected)
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.utils.deadline import (DeadlineExceeded, QueryContext,
                                       activate, check_current)
from pilosa_tpu.utils.faults import FAULTS, FaultInjected


@pytest.fixture(autouse=True)
def _clean_faults():
    """The registry is process-global: never leak an armed failpoint
    into the next test."""
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _req(port, method, path, data=None, timeout=30):
    body = None
    if data is not None:
        body = data.encode() if isinstance(data, str) else \
            json.dumps(data).encode()
    r = urllib.request.Request(
        f"http://localhost:{port}{path}", method=method, data=body)
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read())


def _status_of(port, path, data=None):
    """(status_code, body_dict) — errors don't raise."""
    try:
        return 200, _req(port, "POST", path, data)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:
            body = {}
        return e.code, body


def make_server(tmp_path, name="srv", **cfg):
    cfg.setdefault("anti_entropy_interval", 0)
    cfg.setdefault("bind", "localhost:0")
    s = Server(Config(data_dir=str(tmp_path / name), **cfg))
    s.open()
    return s


def _free_ports(n):
    socks = []
    for _ in range(n):
        sk = socket.socket()
        sk.bind(("localhost", 0))
        socks.append(sk)
    ports = [sk.getsockname()[1] for sk in socks]
    for sk in socks:
        sk.close()
    return ports


def make_cluster(tmp_path, n=2, replica_n=2, **cfg):
    ports = _free_ports(n)
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        servers.append(make_server(
            tmp_path, name=f"node{i}", bind=f"localhost:{p}",
            node_id=f"node{i}", cluster_hosts=hosts,
            replica_n=replica_n, **cfg))
    return servers


def _setup(port, index="ov", n_shards=4):
    _req(port, "POST", f"/index/{index}", {})
    _req(port, "POST", f"/index/{index}/field/f", {})
    # explicit generous timeout: setup must not flake under a server
    # configured with a tiny default query-timeout (cold JIT on the
    # first write can exceed it)
    _req(port, "POST", f"/index/{index}/query?timeout=120", " ".join(
        f"Set({s * SHARD_WIDTH + 3}, f=1)" for s in range(n_shards)))
    return index


# -- unit: failpoint registry ----------------------------------------------

def test_faults_registry_spec_and_times():
    # lint: allow(failpoint-names) — registry unit test arms synthetic
    # names on purpose; no trigger site should exist for them
    FAULTS.configure("a.b=error@key1#2; c.d=delay:0.01")
    # match filter: a miss doesn't trigger or consume
    FAULTS.hit("a.b", key="other")
    with pytest.raises(FaultInjected):
        FAULTS.hit("a.b", key="key1-and-more")
    with pytest.raises(FaultInjected):
        FAULTS.hit("a.b", key="key1")
    FAULTS.hit("a.b", key="key1")  # #2 exhausted -> disarmed
    t0 = time.perf_counter()
    FAULTS.hit("c.d")
    assert time.perf_counter() - t0 >= 0.01
    assert "c.d" in FAULTS.snapshot()
    # FaultInjected is an OSError so transport handling sees a real fault
    assert issubclass(FaultInjected, OSError)


def test_faults_bad_spec_rejected():
    with pytest.raises(ValueError):
        # lint: allow(failpoint-names) — malformed-spec rejection test
        FAULTS.configure("oops")
    with pytest.raises(ValueError):
        # lint: allow(failpoint-names) — unknown-mode rejection test
        FAULTS.arm("x", mode="explode")


# -- unit: deadline context -------------------------------------------------

def test_query_context_expiry_and_contextvar():
    ctx = QueryContext(0.02)
    ctx.check("early")  # not expired yet
    time.sleep(0.03)
    assert ctx.expired()
    with pytest.raises(DeadlineExceeded) as ei:
        ctx.check("late")
    assert "late" in str(ei.value)
    check_current("no ctx active")  # no-op outside activate
    with activate(QueryContext(None)):
        check_current("unlimited")  # unlimited budget never expires
    c2 = QueryContext(10)
    c2.cancel()
    with pytest.raises(DeadlineExceeded):
        c2.check()


# -- unit: admission controller --------------------------------------------

def test_admission_slots_queue_and_drain():
    adm = AdmissionController(max_slots=1, queue_timeout=0.05)
    adm.acquire()
    # slot busy + empty queue: second caller waits queue_timeout then 503
    t0 = time.perf_counter()
    with pytest.raises(AdmissionRejected) as ei:
        adm.acquire()
    assert time.perf_counter() - t0 >= 0.04
    assert ei.value.retry_after >= 1
    # queue overflow rejects IMMEDIATELY (no wait)
    blockers = [threading.Thread(
        target=lambda: _try_acquire(adm)) for _ in range(2)]
    for t in blockers:
        t.start()
    time.sleep(0.01)  # both waiting -> queue (max 2*1) full
    t0 = time.perf_counter()
    with pytest.raises(AdmissionRejected):
        adm.acquire()
    assert time.perf_counter() - t0 < 0.04
    for t in blockers:
        t.join()
    # drain: release the slot; wait_drained returns True; new acquires 503
    adm.begin_drain()
    adm.release()
    assert adm.wait_drained(1.0)
    with pytest.raises(AdmissionRejected):
        adm.acquire()
    snap = adm.snapshot()
    assert snap["draining"] and snap["inUse"] == 0
    assert snap["rejectedQueueFull"] >= 1 and snap["rejectedBusy"] >= 1


def _try_acquire(adm):
    try:
        adm.acquire()
        adm.release()
    except AdmissionRejected:
        pass


# -- deadline through the real server --------------------------------------

def test_deadline_expired_query_returns_504(tmp_path):
    srv = make_server(tmp_path)
    try:
        index = _setup(srv.port)
        # delay the shard-slice loop past the budget: the query must
        # abort between slices, not run to completion
        FAULTS.arm("mesh.slice", mode="delay", arg=0.2, match=index)
        t0 = time.perf_counter()
        code, body = _status_of(
            srv.port, f"/index/{index}/query?timeout=0.05",
            "Count(Row(f=1))")
        elapsed = time.perf_counter() - t0
        assert code == 504
        assert body["budgetS"] == 0.05
        assert body["elapsedS"] >= 0.05
        assert "deadline" in body["error"]
        assert elapsed < 2.0  # aborted, not run to completion
        FAULTS.disarm()
        # counters visible at /debug/vars; un-budgeted queries unaffected
        snap = _req(srv.port, "GET", "/debug/vars")
        assert snap["counts"]["query.deadline_abort"] >= 1
        assert snap["admission"]["public"]["admitted"] >= 1
        [cnt] = _req(srv.port, "POST", f"/index/{index}/query",
                     "Count(Row(f=1))")["results"]
        assert cnt == 4
    finally:
        srv.close()


def test_default_query_timeout_config(tmp_path):
    """query-timeout applies to public queries with no explicit
    ?timeout=, and an explicit one overrides it."""
    srv = make_server(tmp_path, query_timeout=0.05)
    try:
        index = _setup(srv.port)
        FAULTS.arm("mesh.slice", mode="delay", arg=0.2, match=index)
        code, _ = _status_of(srv.port, f"/index/{index}/query",
                             "Count(Row(f=1))")
        assert code == 504
        code, _ = _status_of(srv.port, f"/index/{index}/query?timeout=5",
                             "Count(Row(f=1))")
        assert code == 200
    finally:
        srv.close()


# -- admission through the real server -------------------------------------

def test_admission_overflow_returns_503_under_burst(tmp_path):
    srv = make_server(tmp_path, max_queries=1, queue_timeout=0.05)
    try:
        index = _setup(srv.port)
        FAULTS.arm("mesh.slice", mode="delay", arg=0.4, match=index)
        results = []

        def one():
            results.append(_status_of(
                srv.port, f"/index/{index}/query", "Count(Row(f=1))")[0])

        threads = [threading.Thread(target=one) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "hung handler thread"
        assert set(results) <= {200, 503}
        assert results.count(200) >= 1
        assert results.count(503) >= 1
        snap = _req(srv.port, "GET", "/debug/vars")
        pub = snap["admission"]["public"]
        assert pub["maxSlots"] == 1
        assert pub["rejectedBusy"] + pub["rejectedQueueFull"] >= 1
        assert snap["counts"]["admission.public.rejected"] >= 1
        # the Retry-After header rides the 503
        req = urllib.request.Request(
            f"http://localhost:{srv.port}/index/{index}/query",
            method="POST", data=b"Count(Row(f=1))")
        FAULTS.disarm()
        FAULTS.arm("mesh.slice", mode="delay", arg=0.4, match=index)
        slow = threading.Thread(target=one)
        slow.start()
        time.sleep(0.05)
        try:
            urllib.request.urlopen(req, timeout=10)
        except urllib.error.HTTPError as e:
            assert e.code == 503
            # computed + jittered backoff: fractional seconds, floored
            # at 1 (cli ingest parses floats)
            assert float(e.headers["Retry-After"]) >= 1
        slow.join(timeout=30)
    finally:
        srv.close()


# -- graceful drain ---------------------------------------------------------

def test_drain_completes_inflight_then_rejects(tmp_path):
    srv = make_server(tmp_path, max_queries=4, drain_seconds=5)
    try:
        index = _setup(srv.port)
        FAULTS.arm("mesh.slice", mode="delay", arg=0.3, match=index)
        inflight = []

        def one():
            inflight.append(_status_of(
                srv.port, f"/index/{index}/query", "Count(Row(f=1))")[0])

        t = threading.Thread(target=one)
        t.start()
        time.sleep(0.1)  # the query is inside its slice delay
        assert srv.drain() is True  # waited for the in-flight query
        t.join(timeout=10)
        assert inflight == [200]  # finished, not reset
        # post-drain: the socket is still up, new queries get 503
        code, body = _status_of(srv.port, f"/index/{index}/query",
                                "Count(Row(f=1))")
        assert code == 503 and "drain" in body["error"]
    finally:
        srv.close()


# -- circuit breaker + replica retry ----------------------------------------
# The multi-server chaos tests are slow-marked with the soak: each spins a
# fresh in-process cluster (seconds of XLA/server setup), and tier-1's
# wall-clock budget is tight.  The single-server deadline/admission/drain
# tests above stay tier-1.

@pytest.mark.slow
def test_breaker_opens_fails_fast_and_recovers(tmp_path):
    servers = make_cluster(tmp_path, n=2, replica_n=2,
                           breaker_threshold=2)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/cb", {})
        _req(p0, "POST", "/index/cb/field/f", {})
        _req(p0, "POST", "/index/cb/query", " ".join(
            f"Set({s * SHARD_WIDTH + 1}, f=1)" for s in range(6)))
        [want] = _req(p0, "POST", "/index/cb/query",
                      "Count(Row(f=1))")["results"]
        assert want == 6

        cl = servers[0].cluster
        peer_host = cl.by_id["node1"].host
        # every request to node1 transport-fails; threshold=2 opens
        FAULTS.arm("client.request", mode="error", match=peer_host)
        for _ in range(2):
            with pytest.raises(OSError):
                cl.client.status(peer_host, timeout=2)
        snap = cl.client.breaker_snapshot()
        assert snap[peer_host]["state"] == "open"
        FAULTS.disarm()  # node1 is healthy again, but the breaker is
        #                  still open (cooldown) -> the read router skips
        #                  it BEFORE dispatch (routing.breaker_skip) and
        #                  the replica answers instead of waiting out a
        #                  timeout
        t0 = time.perf_counter()
        [got] = _req(p0, "POST", "/index/cb/query",
                     "Count(Row(f=1))")["results"]
        assert time.perf_counter() - t0 < 5.0
        assert got == want
        assert cl.by_id["node1"].state == "DOWN"  # breaker agrees
        # breaker + routing state surface at /debug/vars
        dv = _req(p0, "GET", "/debug/vars")
        assert dv["breakers"][peer_host]["openedTotal"] >= 1
        assert dv["counts"].get("routing.breaker_skip", 0) >= 1
        assert dv["cluster"]["routing"]["breakerSkips"] >= 1
        # recovery: the health probe is ALWAYS admitted as the half-open
        # trial (no cooldown wait); success closes the breaker + READY
        cl.probe_peers()
        assert cl.client.breaker_snapshot()[peer_host]["state"] == "closed"
        assert cl.by_id["node1"].state == "READY"
        assert cl.state == "NORMAL"
    finally:
        for s in servers:
            s.close()


@pytest.mark.slow
def test_probe_soft_failures_need_threshold(tmp_path):
    """One transient probe miss must NOT flip the cluster DEGRADED;
    health-down-threshold consecutive misses must; recovery resets the
    streak.  Connection-refused (dead process) still flips at once."""
    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        cl = servers[0].cluster
        real_status = cl.client.status
        cl.client.status = lambda host, timeout=None: (
            (_ for _ in ()).throw(socket.timeout("probe timed out")))
        cl.probe_peers()
        assert cl.by_id["node1"].state == "READY"  # one soft miss
        assert cl.state == "NORMAL"
        cl.probe_peers()
        assert cl.by_id["node1"].state == "DOWN"   # second miss
        assert cl.state == "DEGRADED"
        cl.client.status = real_status
        cl.probe_peers()
        assert cl.by_id["node1"].state == "READY"
        assert cl.by_id["node1"].probe_fails == 0
        assert cl.state == "NORMAL"
        # refused = definite: one probe flips (the killed-node case)
        servers[1].close()
        cl.probe_peers()
        assert cl.by_id["node1"].state == "DOWN"
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


# -- deadline across the fan-out wire ---------------------------------------

@pytest.mark.slow
def test_deadline_mid_fanout_remote_inherits_budget(tmp_path):
    """A coordinator whose remote is failpoint-delayed must 504 within
    ~2x the budget (socket timeout clamped to the remaining budget), and
    the REMOTE must abort via the inherited header budget rather than
    running its delayed slice loop to completion."""
    servers = make_cluster(tmp_path, n=2, replica_n=1)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/fx", {})
        _req(p0, "POST", "/index/fx/field/f", {})
        cl = servers[0].cluster
        # a shard owned by node1 only (replica_n=1): the fan-out has no
        # local work and no replica to fall back to
        shard = next(s for s in range(64)
                     if cl.placement.shard_nodes("fx", s) == ["node1"])
        _req(p0, "POST", "/index/fx/query",
             f"Set({shard * SHARD_WIDTH + 7}, f=1)")
        FAULTS.arm("mesh.slice", mode="delay", arg=0.5, match="fx")
        t0 = time.perf_counter()
        code, body = _status_of(
            p0, f"/index/fx/query?timeout=0.05&shards={shard}",
            "Count(Row(f=1))")
        elapsed = time.perf_counter() - t0
        assert code == 504
        assert body["budgetS"] == 0.05
        # never waits out the remote's 0.5s slice delay, let alone the
        # 30s default socket timeout
        assert elapsed < 0.45, f"coordinator waited {elapsed:.3f}s"
        # the remote aborted by ITS deadline (inherited via the header):
        # its own 504 counter ticks once its delayed slice check runs
        deadline = time.monotonic() + 5
        aborted = 0
        while time.monotonic() < deadline:
            snap = _req(servers[1].port, "GET", "/debug/vars")
            aborted = snap["counts"].get("query.deadline_abort", 0)
            if aborted:
                break
            time.sleep(0.05)
        assert aborted >= 1, "remote never saw the shrunken budget"
    finally:
        for s in servers:
            s.close()


# -- durability + tracing satellites ----------------------------------------

def test_snapshot_fsyncs_file_and_directory(tmp_path, monkeypatch):
    import pilosa_tpu.utils.durable as durable
    synced = []
    real_fsync = durable.os.fsync
    monkeypatch.setattr(durable.os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd))[1])
    from pilosa_tpu.storage.fragment import Fragment
    frag = Fragment(str(tmp_path / "frag" / "0"), "i", "f", "standard", 0)
    frag.set_bit(1, 2)
    synced.clear()
    frag.snapshot()
    assert len(synced) >= 2  # temp file + directory
    frag.close()
    # attrs take the same durable path
    from pilosa_tpu.storage.attrs import AttrStore
    store = AttrStore(str(tmp_path / "attrs.json"))
    synced.clear()
    store.set_attrs(1, {"k": "v"})
    assert len(synced) >= 2


def test_snapshot_failpoint_surfaces_error(tmp_path):
    from pilosa_tpu.storage.fragment import Fragment
    frag = Fragment(str(tmp_path / "fp" / "0"), "i", "f", "standard", 0)
    try:
        FAULTS.arm("fragment.snapshot", mode="error")
        frag.set_bit(0, 1)
        with pytest.raises(OSError):
            frag.snapshot()
        FAULTS.disarm()
        frag.snapshot()  # recovers cleanly
    finally:
        FAULTS.disarm()
        frag.close()


def test_span_duration_immune_to_wall_clock_steps(monkeypatch):
    from pilosa_tpu.utils import tracing
    walls = iter([1000.0, 900.0, 900.0])  # wall clock steps BACKWARD
    monkeypatch.setattr(tracing.time, "time",
                        lambda: next(walls, 900.0))
    tracer = tracing.Tracer()
    with tracer.span("step") as s:
        time.sleep(0.01)
    d = s.to_dict()
    assert d["durationMS"] >= 10.0  # perf_counter pair, not wall delta


# -- soak: burst > slots against a 2-node cluster (CI, slow-marked) ---------

@pytest.mark.slow
def test_overload_soak_no_deadlock_bounded_p99(tmp_path):
    """Burst of 4x max-queries concurrent public queries against a
    2-node cluster: only 200s and 503s, every thread returns (no
    admission deadlock between public and internal planes), and the
    successful tail stays bounded."""
    servers = make_cluster(tmp_path, n=2, replica_n=2, max_queries=4,
                           queue_timeout=0.2)
    try:
        p0 = servers[0].port
        _req(p0, "POST", "/index/soak", {})
        _req(p0, "POST", "/index/soak/field/f", {})
        _req(p0, "POST", "/index/soak/query", " ".join(
            f"Set({s * SHARD_WIDTH + 2}, f=1)" for s in range(8)))
        FAULTS.arm("mesh.slice", mode="delay", arg=0.05, match="soak")
        codes, lats = [], []
        lock = threading.Lock()

        def one():
            for _ in range(3):
                t0 = time.perf_counter()
                code, _ = _status_of(p0, "/index/soak/query",
                                     "Count(Row(f=1))")
                dt = time.perf_counter() - t0
                with lock:
                    codes.append(code)
                    if code == 200:
                        lats.append(dt)

        threads = [threading.Thread(target=one) for _ in range(16)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "deadlocked thread"
        assert set(codes) <= {200, 503}, f"unexpected statuses {set(codes)}"
        assert codes.count(200) >= 1
        lats.sort()
        p99 = lats[int(len(lats) * 0.99) - 1] if len(lats) > 1 else lats[0]
        # bounded tail: slots cap concurrency, the queue is short, and
        # rejections are instant — nothing can queue for the whole burst
        assert p99 < 30.0, f"p99 {p99:.2f}s"
        assert time.perf_counter() - t0 < 120
    finally:
        for s in servers:
            s.close()
