"""SLO engine + flight recorder (docs/observability.md "SLOs &
alerting").

Covers: multi-window burn-rate math and the fire/resolve lifecycle over
a real TimeSeriesRing; every pathology rule against its synthetic
trigger; exact latency-good counting from the fixed histogram buckets;
rule selection (`alert-rules`); flight-recorder capture, rate limiting,
and LRU disk pruning; the `alert-names` analyzer rule on a synthetic
tree; and the real-socket acceptance story — a ChaosProxy straggler
fires the latency burn alert, a bundle lands on disk inside the budget,
the alert resolves after heal, and answers are byte-identical with
evaluation on vs off.
"""

import json
import os
import time
import urllib.request

import pytest

from pilosa_tpu.analysis.astlint import run as run_analysis
from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.utils import slo as slomod
from pilosa_tpu.utils.flightrec import FlightRecorder
from pilosa_tpu.utils.netchaos import ChaosProxy
from pilosa_tpu.utils.slo import RULES, EvalContext, SLOEngine
from pilosa_tpu.utils.stats import TIMING_BUCKETS, StatsClient
from pilosa_tpu.utils.timeseries import TimeSeriesRing

from test_cluster import _free_ports, _req, query


class _Log:
    def __init__(self):
        self.errors, self.infos = [], []

    def error(self, msg):
        self.errors.append(str(msg))

    def info(self, msg):
        self.infos.append(str(msg))


def _engine(ring=None, **kw):
    ring = ring or TimeSeriesRing(interval_s=1.0, window_s=40.0)
    kw.setdefault("logger", _Log())
    return SLOEngine(ring, StatsClient(), **kw), ring


def _push(ring, n, **cols):
    for _ in range(n):
        ring.sample(dict(cols), force=True)


# -- burn-rate math ---------------------------------------------------------


def test_burn_rate_and_window_sizing():
    eng, ring = _engine(target=0.999)
    # capacity 41 -> fast = max(2, 2) = 2, slow = max(6, 10) = 10
    assert eng.fast_n == 2 and eng.slow_n == 10
    _push(ring, 4, httpQueriesDelta=100, sloErrorsDelta=2)
    ctx = EvalContext(ring.last(eng.slow_n), eng)
    # 2% bad over a 0.1% budget = 20x in both windows
    assert ctx.burn("sloErrorsDelta", "httpQueriesDelta",
                    eng.fast_n) == pytest.approx(20.0)
    assert ctx.burn("sloErrorsDelta", "httpQueriesDelta",
                    eng.slow_n) == pytest.approx(20.0)


def test_no_traffic_burns_nothing():
    eng, ring = _engine()
    _push(ring, eng.slow_n, httpQueriesDelta=0, sloErrorsDelta=0)
    eng.evaluate()
    assert eng.active == {} and eng.fired_total == 0


def test_slow_window_guards_against_blips():
    """One bad fast-window interval must NOT fire: the slow window
    still averages healthy (the whole point of multi-window)."""
    eng, ring = _engine(target=0.999)
    _push(ring, eng.slow_n - 1, httpQueriesDelta=100, sloErrorsDelta=0)
    # the blip: 4 errors in the newest interval -> fast burn 20x (over
    # threshold) but slow burn only 4x (under) -> no page
    _push(ring, 1, httpQueriesDelta=100, sloErrorsDelta=4)
    eng.evaluate()
    assert "slo-availability-burn" not in eng.active


def test_fire_then_resolve_lifecycle():
    from pilosa_tpu.utils.events import EVENTS
    fired_hook = []
    eng, ring = _engine(target=0.999, on_fire=fired_hook.append)
    seq0 = EVENTS.last_seq()
    _push(ring, eng.slow_n, httpQueriesDelta=100, sloErrorsDelta=50)
    eng.evaluate()
    assert "slo-availability-burn" in eng.active
    assert eng.fired_total == 1
    assert fired_hook and fired_hook[0]["id"] == "slo-availability-burn"
    assert fired_hook[0]["severity"] == "page"
    # still firing: no double count, detail refreshed
    eng.evaluate()
    assert eng.fired_total == 1
    # heal: fast window drains first, resolve after clear_after=2
    # consecutive healthy evaluations
    _push(ring, eng.fast_n, httpQueriesDelta=100, sloErrorsDelta=0)
    eng.evaluate()
    assert "slo-availability-burn" in eng.active  # 1 quiet eval only
    eng.evaluate()
    assert "slo-availability-burn" not in eng.active
    assert eng.resolved_total == 1
    names = [e["event"] for e in EVENTS.since(seq0)]
    assert "alert.fire" in names and "alert.resolve" in names
    hist = [h["action"] for h in eng.snapshot()["history"]]
    assert hist == ["fire", "resolve"]


def test_rule_selection_and_unknown_id():
    log = _Log()
    eng, _ = _engine(rules="off")
    assert not eng.enabled
    eng2, _ = _engine(rules="quarantine,nope-nope", logger=log)
    assert set(eng2.rules) == {"quarantine"}
    assert any("nope-nope" in m for m in log.errors)
    eng3, _ = _engine(rules="all")
    assert set(eng3.rules) == set(RULES)


def test_broken_rule_is_logged_not_fatal(monkeypatch):
    log = _Log()
    eng, ring = _engine(logger=log)

    def boom(ctx):
        raise RuntimeError("rule bug")

    monkeypatch.setitem(
        eng.rules, "quarantine",
        slomod.AlertRule("quarantine", "ticket", "", boom))
    _push(ring, 2, httpQueriesDelta=1)
    eng.evaluate()  # must not raise
    assert any("quarantine" in m for m in log.errors)
    assert eng.evaluations == 1


# -- pathology rules --------------------------------------------------------


@pytest.mark.parametrize("col,threshold_attr,rule_id", [
    ("retracesDelta", "RETRACE_STORM", "retrace-storm"),
    ("evictionsDelta", "EVICTION_PRESSURE", "eviction-pressure"),
    ("ingestRejectedDelta", "INGEST_BACKPRESSURE", "ingest-backpressure"),
    ("breakerOpensDelta", "BREAKER_FLAPS", "breaker-flapping"),
])
def test_pathology_threshold_rules(col, threshold_attr, rule_id):
    thr = getattr(slomod, threshold_attr)
    eng, ring = _engine()
    _push(ring, 1, **{col: thr - 1})
    eng.evaluate()
    assert rule_id not in eng.active
    _push(ring, 1, **{col: thr})
    eng.evaluate()
    assert rule_id in eng.active


def test_hedge_storm_needs_fraction_and_floor():
    eng, ring = _engine()
    # plenty of hedges but a tiny fraction of queries: healthy
    _push(ring, 1, hedgesDelta=slomod.HEDGE_STORM_MIN,
          httpQueriesDelta=1000)
    eng.evaluate()
    assert "hedge-storm" not in eng.active
    # majority of queries hedged AND above the absolute floor (fresh
    # ring: the slow window must not still hold the healthy sample)
    eng2, ring2 = _engine()
    _push(ring2, 1, hedgesDelta=40, httpQueriesDelta=50)
    eng2.evaluate()
    assert "hedge-storm" in eng2.active


def test_quarantine_is_a_level_gauge_rule():
    eng, ring = _engine()
    _push(ring, 1, quarantinedFragments=0)
    eng.evaluate()
    assert "quarantine" not in eng.active
    _push(ring, 1, quarantinedFragments=2)
    eng.evaluate()
    assert "quarantine" in eng.active
    assert "2" in eng.active["quarantine"]["detail"]


def test_latency_burn_names_worst_tenant():
    class Reg:
        def snapshot(self):
            return {"polite": {"p99Ms": 10.0},
                    "noisy": {"p99Ms": 900.0},
                    "worse": {"p99Ms": 1200.0}}

    eng, ring = _engine(latency_ms=500.0, tenant_registry=Reg())
    _push(ring, eng.slow_n, httpQueriesDelta=10, sloSlowQueriesDelta=10)
    eng.evaluate()
    assert "worse" in eng.active["slo-latency-burn"]["detail"]


# -- exact good-count from the fixed histogram ------------------------------


def test_bucket_count_le_exact_at_edges():
    st = StatsClient()
    assert 0.05 in TIMING_BUCKETS and 0.5 in TIMING_BUCKETS
    for v in (0.01, 0.04, 0.2, 0.9):
        st.timing("http.query", v)
    assert st.bucket_count_le("http.query", 0.05) == 2
    assert st.bucket_count_le("http.query", 0.5) == 3
    # a non-edge bound snaps DOWN (conservative: never counts a bad
    # query as good) — 0.3 s sits in the (0.25, 0.5] bucket, so only
    # the <= 0.25 counts qualify
    assert st.bucket_count_le("http.query", 0.3) == \
        st.bucket_count_le("http.query", 0.25)
    assert st.bucket_count_le("never.recorded", 0.5) == 0


# -- flight recorder --------------------------------------------------------


def test_flightrec_capture_and_stamp(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"), budget_mb=4)
    path = rec.capture("alert-x y/z", lambda: {"k": 1})
    assert path is not None and os.path.isfile(path)
    assert "alert-x-y-z" in os.path.basename(path)  # sanitized reason
    data = json.loads(open(path).read())
    assert data["k"] == 1 and data["reason"] == "alert-x-y-z"
    assert rec.captures == 1
    assert rec.last["path"] == path and rec.last["bytes"] > 0


def test_flightrec_rate_limit_and_force(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"), budget_mb=4,
                         min_interval_s=3600.0)
    assert rec.capture("a", lambda: {}) is not None
    assert rec.capture("b", lambda: {}) is None  # inside the interval
    assert rec.rate_limited == 1
    assert rec.capture("c", lambda: {}, force=True) is not None


def test_flightrec_collect_failure_is_counted(tmp_path):
    log = _Log()
    rec = FlightRecorder(str(tmp_path / "fr"), budget_mb=4, logger=log)

    def boom():
        raise RuntimeError("collector bug")

    assert rec.capture("x", boom, force=True) is None
    assert rec.errors == 1 and log.errors
    assert rec.capture("y", lambda: {}, force=True) is not None


def test_flightrec_lru_prune_keeps_newest(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"), budget_mb=1,
                         min_interval_s=0.0)
    blob = "z" * (400 << 10)  # ~400 KiB per bundle, 1 MB budget
    paths = []
    for i in range(4):
        p = rec.capture(f"b{i}", lambda: {"blob": blob}, force=True)
        assert p is not None
        paths.append(p)
        # distinct mtimes so LRU order is deterministic
        os.utime(p, (time.monotonic(), 1_000_000 + i))
    rec.prune(keep=paths[-1])
    alive = [p for p in paths if os.path.exists(p)]
    assert paths[-1] in alive            # newest never pruned
    assert paths[0] not in alive         # oldest went first
    assert rec.disk_bytes() <= rec.budget_mb << 20
    assert rec.pruned >= 1


# -- the alert-names analyzer rule on a synthetic tree ----------------------


def _alert_tree(tmp_path, code, catalog):
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(code)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "<!-- alerts-catalog:begin -->\n"
        f"{catalog}\n"
        "<!-- alerts-catalog:end -->\n")
    (tmp_path / "tests").mkdir()
    return tmp_path


def test_alert_names_two_way_and_runbook(tmp_path):
    root = _alert_tree(
        tmp_path,
        '@alert_rule("covered")\n'
        'def a(ctx): pass\n'
        '@alert_rule("undocumented")\n'
        'def b(ctx): pass\n'
        '@alert_rule("no-runbook")\n'
        'def c(ctx): pass\n',
        "| `covered` | page | stuff | look at /debug/vars |\n"
        "| `no-runbook` | page | stuff | just vibes |\n"
        "| `dangling` | page | stuff | /debug/alerts |")
    msgs = " | ".join(
        f.message for f in run_analysis(root, ["alert-names"]))
    assert "undocumented" in msgs
    assert "dangling" in msgs
    assert "no-runbook" in msgs and "/debug" in msgs
    assert "'covered'" not in msgs


# -- real-socket acceptance -------------------------------------------------


def _get_raw(port, path):
    with urllib.request.urlopen(
            f"http://localhost:{port}{path}", timeout=30) as r:
        return r.read()


def _query_raw(port, index, pql):
    req = urllib.request.Request(
        f"http://localhost:{port}/index/{index}/query",
        method="POST", data=pql.encode())
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.read()


def test_answers_byte_identical_slo_on_off(tmp_path):
    """Evaluation must never change an answer: the same workload on an
    alerts-on and an alerts-off server produces byte-identical response
    bodies (the explain/profile exactness discipline)."""
    bodies = {}
    for mode in ("all", "off"):
        cfg = Config(data_dir=str(tmp_path / f"d-{mode}"),
                     bind="localhost:0", alert_rules=mode,
                     timeseries_interval=0.2, timeseries_window=10,
                     trace_sample_rate=0.0)
        s = Server(cfg)
        s.open()
        try:
            assert (s.slo is not None) == (mode == "all")
            _req(s.port, "POST", "/index/bi", {})
            _req(s.port, "POST", "/index/bi/field/f", {})
            cols = [i * 97 for i in range(300)]
            _req(s.port, "POST", "/index/bi/field/f/import",
                 {"rowIDs": [i % 7 for i in range(300)],
                  "columnIDs": cols})
            out = []
            for pql in ("Count(Row(f=1))", "Row(f=2)",
                        "TopN(f, n=3)",
                        "Count(Union(Row(f=0), Row(f=3)))"):
                out.append(_query_raw(s.port, "bi", pql))
            # a few evaluation passes while traffic flows, so the "on"
            # server actually exercises the engine mid-workload
            if s.slo is not None:
                s.sample_timeseries(force=True)
                s.slo.evaluate()
            out.append(_query_raw(s.port, "bi", "Count(Row(f=1))"))
            bodies[mode] = out
        finally:
            s.close()
    assert bodies["all"] == bodies["off"]


@pytest.fixture(scope="module")
def straggler_cluster(tmp_path_factory):
    """3 real servers, node1/node2 behind ChaosProxies, primary
    routing so a delayed proxy is a deterministic straggler."""
    tmp_path = tmp_path_factory.mktemp("slo")
    binds = _free_ports(3)
    proxies = {}
    hosts = [f"localhost:{binds[0]}"]
    for i in (1, 2):
        proxies[f"node{i}"] = ChaosProxy("localhost", binds[i])
        hosts.append(proxies[f"node{i}"].address)
    servers = []
    for i, p in enumerate(binds):
        srv = Server(Config(
            data_dir=str(tmp_path / f"node{i}"),
            bind=f"localhost:{p}", node_id=f"node{i}",
            cluster_hosts=hosts, replica_n=1,
            anti_entropy_interval=0, read_routing="primary",
            hedge_reads=False,
            # 250 ms objective: a TIMING_BUCKETS edge (exact good
            # counting), far above a healthy localhost fan-out
            # (~50-100 ms) and far below the proxy's 500 ms straggle
            slo_latency_ms=250.0, slo_target=0.999,
            flight_recorder_mb=4,
            # huge interval: the monitor thread stays quiet and the
            # test drives force-samples + evaluations deterministically
            timeseries_interval=60, timeseries_window=1200,
            trace_sample_rate=0.0))
        srv.open()
        servers.append(srv)
    yield servers, proxies
    for s in servers:
        try:
            s.close()
        except Exception:
            pass
    for pr in proxies.values():
        pr.close()


def test_straggler_fires_latency_alert_and_resolves(straggler_cluster):
    """The acceptance story: a proxied straggler pushes queries over
    the latency objective -> slo-latency-burn fires -> a bundle lands
    on disk inside the budget -> heal + healthy traffic -> resolve."""
    servers, proxies = straggler_cluster
    srv0 = servers[0]
    port = srv0.port
    n_shards = 6
    # an index where node0 does NOT own every shard, so the proxy
    # delay sits on the query path
    cl = srv0.cluster
    index = next(
        name for name in (f"sa{i}" for i in range(64))
        if any("node0" not in cl.placement.shard_nodes(name, s)
               for s in range(n_shards)))
    _req(port, "POST", f"/index/{index}", {})
    _req(port, "POST", f"/index/{index}/field/f", {})
    cols = [s * SHARD_WIDTH + 7 for s in range(n_shards)]
    _req(port, "POST", f"/index/{index}/field/f/import",
         {"rowIDs": [1] * len(cols), "columnIDs": cols})
    [baseline] = query(port, index, "Count(Row(f=1))")

    eng = srv0.slo
    assert eng is not None and eng.enabled

    def sample_and_evaluate():
        assert srv0.sample_timeseries(force=True)
        eng.evaluate()

    # prime: one healthy sample so deltas are per-interval
    sample_and_evaluate()
    assert "slo-latency-burn" not in eng.active

    for pr in proxies.values():
        pr.configure("down=latency:0.5")  # every remote read > 250 ms
    try:
        evals_before = eng.evaluations
        for _ in range(eng.fast_n + 1):
            for _ in range(3):
                assert query(port, index,
                             "Count(Row(f=1))") == [baseline]
            sample_and_evaluate()
            if "slo-latency-burn" in eng.active:
                break
        assert "slo-latency-burn" in eng.active, eng.snapshot()
        fired_at = eng.active["slo-latency-burn"]["firedAtEvaluation"]
        # fired within 2 evaluation passes of the first faulted sample
        assert fired_at - evals_before <= 2

        # the on-fire hook captured a bundle, on disk, within budget,
        # readable, and carrying the full debug plane
        rec = srv0.flightrec
        assert rec.captures >= 1
        bundle_path = rec.last["path"]
        assert os.path.isfile(bundle_path)
        assert rec.disk_bytes() <= rec.budget_mb << 20
        bundle = json.loads(open(bundle_path).read())
        assert bundle["reason"].startswith("alert-slo-latency-burn")
        assert "slo-latency-burn" in bundle["alerts"]["active"]
        assert bundle["timeseries"]["samples"]
        assert "vars" in bundle and "slowLog" in bundle

        # the debug surfaces agree
        alerts = json.loads(_get_raw(port, "/debug/alerts"))
        assert alerts["enabled"]
        assert "slo-latency-burn" in alerts["active"]
        v = json.loads(_get_raw(port, "/debug/vars"))
        assert "slo-latency-burn" in v["alerts"]["active"]
        # fleet rollup folds per-node alert state in (local node path)
        c = json.loads(_get_raw(port, "/debug/cluster"))
        assert c["nodes"]["node0"]["activeAlerts"] >= 1
        assert "slo-latency-burn" in c["nodes"]["node0"]["alertIds"]
    finally:
        for pr in proxies.values():
            pr.heal()

    # healthy traffic drains the fast window; resolve after 2 quiet
    # evaluation passes (extra iterations absorb a stray slow query on
    # a loaded CI box)
    for _ in range(8):
        for _ in range(3):
            assert query(port, index, "Count(Row(f=1))") == [baseline]
        sample_and_evaluate()
        if "slo-latency-burn" not in eng.active:
            break
    assert "slo-latency-burn" not in eng.active, eng.snapshot()
    assert eng.resolved_total >= 1


def test_on_demand_bundle_endpoint(straggler_cluster):
    servers, _ = straggler_cluster
    srv0 = servers[0]
    out = _req(srv0.port, "POST", "/debug/bundle",
               {"reason": "operator-drill"})
    assert os.path.isfile(out["path"])
    assert "operator-drill" in os.path.basename(out["path"])
    bundle = json.loads(open(out["path"]).read())
    assert bundle["node"] == "node0"
    # the stamp rides /debug/vars and the diagnostics payload
    v = _req(srv0.port, "GET", "/debug/vars")
    assert v["flightRecorder"]["last"]["path"] == out["path"]
    from pilosa_tpu.utils.diagnostics import DiagnosticsCollector
    diag = DiagnosticsCollector(srv0, endpoint="")
    payload = diag.payload()
    assert payload["lastBundle"]["path"] == out["path"]
    assert "activeAlerts" in payload
