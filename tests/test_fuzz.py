"""Fuzzing: malformed wire bytes and WAL records must raise typed errors,
never crash, hang, or silently mis-import (reference roaring/fuzzer.go:28-60
fuzzes unmarshal + op equivalence vs the naive oracle).

``unpack_roaring`` parses untrusted bytes off the network (anti-entropy
full-copy pulls, resize fetches, /import-roaring bodies), so it gets the
most attention: seeded random mutations of valid blobs, random garbage, and
a pack/unpack round-trip property check.
"""

import numpy as np
import pytest

from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.storage.fragment import _OP, _OP_SET, _OP_CLEAR, Fragment
from pilosa_tpu.storage.roaring_io import (
    RoaringFormatError, pack_roaring, unpack_roaring,
)

N_MUTATIONS = 10_000
ROW_CAP = 1 << 20  # generous cap: bounds allocations, not the fuzz space


def _valid_blobs(rng):
    """A few structurally distinct valid blobs (array, bitmap, multi-key)."""
    blobs = []
    # small array containers over two rows
    rows = np.array([0, 0, 1, 1, 1])
    cols = np.array([1, 5, 0, 70000, SHARD_WIDTH - 1])
    blobs.append(pack_roaring(rows, cols))
    # a dense bitmap container (> ARRAY_MAX_SIZE bits in one 2^16 block)
    cols_dense = rng.choice(60_000, size=5000, replace=False)
    blobs.append(pack_roaring(np.zeros(5000, dtype=np.int64), cols_dense))
    # empty
    blobs.append(pack_roaring(np.zeros(0, dtype=np.int64),
                              np.zeros(0, dtype=np.int64)))
    return blobs


def test_fuzz_unpack_roaring_mutations():
    rng = np.random.default_rng(1234)
    blobs = _valid_blobs(rng)
    crashes = 0
    for i in range(N_MUTATIONS):
        blob = bytearray(blobs[i % len(blobs)])
        # mutate 1-8 random bytes (or truncate/extend)
        action = rng.integers(0, 10)
        if action == 0 and len(blob) > 1:
            blob = blob[: rng.integers(0, len(blob))]
        elif action == 1:
            blob += bytes(rng.integers(0, 256, size=8, dtype=np.uint8))
        else:
            for _ in range(int(rng.integers(1, 9))):
                if not blob:
                    break
                blob[rng.integers(0, len(blob))] = int(rng.integers(0, 256))
        try:
            rows, cols = unpack_roaring(bytes(blob), ROW_CAP)
            # any accepted parse must satisfy the output contract
            assert (cols >= 0).all() and (cols < SHARD_WIDTH).all()
            assert (rows >= 0).all() and (rows <= ROW_CAP).all()
        except RoaringFormatError:
            pass  # the one allowed failure mode
        except Exception as e:  # pragma: no cover - fuzz failure reporting
            crashes += 1
            raise AssertionError(
                f"unpack_roaring crashed on mutation {i}: "
                f"{type(e).__name__}: {e}") from e
    assert crashes == 0


def test_fuzz_unpack_roaring_garbage():
    rng = np.random.default_rng(99)
    for i in range(2000):
        n = int(rng.integers(0, 400))
        data = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        try:
            unpack_roaring(data, ROW_CAP)
        except RoaringFormatError:
            pass


def test_roaring_roundtrip_property():
    rng = np.random.default_rng(7)
    for _ in range(50):
        n = int(rng.integers(0, 3000))
        rows = rng.integers(0, 64, size=n)
        cols = rng.integers(0, SHARD_WIDTH, size=n)
        blob = pack_roaring(rows, cols)
        r2, c2 = unpack_roaring(blob, ROW_CAP)
        want = np.unique(rows * SHARD_WIDTH + cols)
        got = r2 * SHARD_WIDTH + c2
        assert np.array_equal(np.sort(got), want)


def _wal_bytes(records):
    return b"".join(_OP.pack(op, r, c) for op, r, c in records)


def test_fuzz_wal_replay(tmp_path):
    """Mutated/truncated WAL buffers must either replay cleanly or raise
    ValueError — never crash or import out-of-range bits."""
    rng = np.random.default_rng(4321)
    valid = _wal_bytes([
        (_OP_SET, 1, 5), (_OP_SET, 2, 70000), (_OP_CLEAR, 1, 5),
        (_OP_SET, 0, SHARD_WIDTH - 1), (_OP_SET, 3, 12345),
    ])
    for i in range(2000):
        buf = bytearray(valid)
        action = rng.integers(0, 6)
        if action == 0:
            buf = buf[: rng.integers(0, len(buf))]
        else:
            for _ in range(int(rng.integers(1, 6))):
                buf[rng.integers(0, len(buf))] = int(rng.integers(0, 256))
        frag = Fragment(None, "i", "f", "standard", 0)
        try:
            frag._replay_wal(bytes(buf))
        except ValueError:
            continue
        rows, cols = frag.pairs()
        if rows.size:
            assert (rows >= 0).all()
            assert (cols >= 0).all() and (cols < SHARD_WIDTH).all()


def test_wal_torn_tail_dropped(tmp_path):
    """A crash mid-append leaves a partial trailing FRAME (the WAL is
    CRC-framed now — each append is one header+payload write, so a tear
    is a prefix of that): replay drops it and recovers everything before
    it (docs/robustness.md "Durability & recovery")."""
    from pilosa_tpu.storage.fragment import _WAL_FRAME
    from pilosa_tpu.utils.durable import checksum

    path = tmp_path / "frag"
    frag = Fragment(str(path), "i", "f", "standard", 0)
    frag.set_bit(1, 5)
    frag.set_bit(2, 6)
    frag.close()
    payload = _OP.pack(_OP_SET, 3, 7)
    torn = (_WAL_FRAME.pack(len(payload), checksum(payload)) + payload)[:12]
    with open(str(path) + ".wal", "ab") as f:
        f.write(torn)  # header + 4 payload bytes: torn mid-append
    frag2 = Fragment(str(path), "i", "f", "standard", 0)
    assert frag2.quarantined is None
    rows, cols = frag2.pairs()
    got = set(zip(rows.tolist(), cols.tolist()))
    assert got == {(1, 5), (2, 6)}
    frag2.close()
