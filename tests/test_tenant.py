"""Tenant isolation plane (ISSUE 17, docs/robustness.md "Tenant
isolation").

Covers: the tenant-token grammar fuzz contract (garbage/oversize/empty
-> TenantError, never anything else, and a clean 400 at the HTTP edge);
the contextvar identity spine (derived vs explicit, header forwarding
on internal hops); the per-tenant registry accounting + LRU churn armor;
deficit-round-robin slot grants converging to the weight ratio
(deterministic order test); tenant-first shedding (the most over-share
tenant's NEWEST waiter is evicted, the polite arrival is seated, the
shed is attributed to ITS tenant with a computed capped Retry-After);
the ``fair=False`` legacy single-FIFO differential; computed +
decorrelated-jitter Retry-After ranges; per-tenant byte quotas in the
result cache and the HBM residency budget (own-LRU-first eviction, the
just-filled entry never self-evicts, global pressure prefers over-quota
tenants); per-tenant hedge budgets (exhaustion degrades to unhedged
reads — counted, never an error); the degraded-result cache guard
regression (partial or quarantined-degraded answers are never memoized,
a complete fill-after-failover answer IS); and a hostile-flood chaos
test over real ChaosProxy sockets: the polite tenant stays admitted,
>= 95% of sheds are attributed to the hostile tenant, and answers stay
byte-identical to the unflooded baseline."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.cache.results import ResultCache
from pilosa_tpu.core import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.admission import (AdmissionController,
                                         AdmissionRejected,
                                         decorrelated_retry_after)
from pilosa_tpu.server.server import Config, Server
from pilosa_tpu.storage import Holder
from pilosa_tpu.storage.membudget import DeviceBudget
from pilosa_tpu.utils import degraded
from pilosa_tpu.utils import tenant as qtenant
from pilosa_tpu.utils.netchaos import ChaosProxy

from test_cluster import _free_ports, _req, query

N_SHARDS = 8


# -- token grammar + weights spec (fuzz contract) ---------------------------

def test_validate_token_accepts_metrics_safe_names():
    for tok in ("a", "acme", "tenant-7", "a.b_c-d", "X9", "a" * 64):
        assert qtenant.validate_token(tok) == tok


def test_validate_token_rejects_garbage_cleanly():
    bad = ["", "a" * 65, "-lead", ".lead", "_lead", "has space",
           "semi;colon", "tab\tchar", "new\nline", "nul\x00", "é",
           "a/b", "a:b", "{inject}", " ", None, 7, b"bytes"]
    for tok in bad:
        with pytest.raises(qtenant.TenantError):
            qtenant.validate_token(tok)


def test_validate_token_fuzz_never_raises_other_exceptions():
    rng = np.random.default_rng(171)
    for _ in range(500):
        n = int(rng.integers(0, 200))
        raw = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        tok = raw.decode("latin-1")
        try:
            out = qtenant.validate_token(tok)
            assert out == tok  # accepted means unchanged
        except qtenant.TenantError:
            pass  # the ONLY permitted failure


def test_derive_prefers_explicit_header_over_index():
    assert qtenant.derive("acme", "myindex") == ("acme", True)
    assert qtenant.derive(None, "myindex") == ("myindex", False)
    assert qtenant.derive(None, None) == (qtenant.DEFAULT_TENANT, False)
    with pytest.raises(qtenant.TenantError):
        qtenant.derive("bad token", "myindex")


def test_parse_weights_spec():
    assert qtenant.parse_weights("analytics:4,batch:1") == \
        {"analytics": 4.0, "batch": 1.0}
    assert qtenant.parse_weights("") == {}
    assert qtenant.parse_weights(" a:2 , b:0.5 ") == {"a": 2.0, "b": 0.5}
    for bad in ("noweight", "a:xyz", "bad name:2", ":3", "a:"):
        with pytest.raises(qtenant.TenantError):
            qtenant.parse_weights(bad)


# -- contextvar spine -------------------------------------------------------

def test_tenant_context_activate_and_forwarding():
    assert qtenant.current() == qtenant.DEFAULT_TENANT
    assert qtenant.current_or_none() is None
    assert qtenant.header_value() is None
    with qtenant.activate("idx-derived", explicit=False):
        assert qtenant.current() == "idx-derived"
        assert qtenant.current_or_none() == "idx-derived"
        # derived identities never forward: the peer re-derives
        assert qtenant.header_value() is None
        with qtenant.activate("acme", explicit=True):
            assert qtenant.current() == "acme"
            assert qtenant.header_value() == "acme"
        assert qtenant.current() == "idx-derived"
    assert qtenant.current_or_none() is None
    # None is a passthrough (the deadline.activate convention)
    with qtenant.activate(None):
        assert qtenant.current_or_none() is None


def test_registry_accounting_and_churn_cap():
    qtenant.REGISTRY.clear()
    qtenant.REGISTRY.note_request("t1", 0.010, 200)
    qtenant.REGISTRY.note_request("t1", 0.030, 500)
    qtenant.REGISTRY.note_shed("t1", "public")
    qtenant.REGISTRY.note_hedge_denied("t1")
    snap = qtenant.REGISTRY.snapshot()["t1"]
    assert snap["requests"] == 2 and snap["errors"] == 1
    assert snap["shed"] == 1 and snap["shedByPool"] == {"public": 1}
    assert snap["hedgeDenied"] == 1
    assert snap["p50Ms"] >= 10.0 and snap["p99Ms"] >= 29.0
    # hostile identifier churn cannot grow the table without bound
    for i in range(qtenant.MAX_TENANTS + 40):
        qtenant.REGISTRY.note_request(f"churn{i}", 0.001, 200)
    assert len(qtenant.REGISTRY.snapshot()) <= qtenant.MAX_TENANTS
    assert qtenant.REGISTRY.evicted >= 40
    qtenant.REGISTRY.clear()


def test_hedge_budget_token_bucket():
    hb = qtenant.HedgeBudget(rate=2.0)
    assert hb.try_take("t") and hb.try_take("t")
    assert not hb.try_take("t")           # bucket drained
    assert hb.denied == 1
    assert hb.try_take("other")           # per-tenant buckets
    assert hb.snapshot()["denied"] == 1
    # rate 0 disables the budget entirely
    free = qtenant.HedgeBudget(rate=0.0)
    assert all(free.try_take("t") for _ in range(50))
    assert free.denied == 0


# -- computed Retry-After ---------------------------------------------------

def test_decorrelated_retry_after_range_floor_cap():
    for _ in range(300):
        v = decorrelated_retry_after(2.0)
        assert 2.0 <= v <= 6.0
    # base below the floor clamps to [1, 3]
    assert all(1.0 <= decorrelated_retry_after(0.01) <= 3.0
               for _ in range(100))
    # base past the cap pins to the cap exactly
    assert decorrelated_retry_after(100.0) == 30.0
    # jitter actually spreads (not a constant)
    vals = {decorrelated_retry_after(2.0) for _ in range(100)}
    assert len(vals) > 5


# -- weighted-fair admission (DRR) ------------------------------------------

def test_drr_grant_order_follows_weights():
    """max_slots=1 with a held seed slot; 4 'a' then 2 'b' waiters with
    weights a:2,b:1 and burst=1 drain in EXACTLY the 2:1 pattern."""
    adm = AdmissionController(max_slots=1, queue_timeout=30.0,
                              max_queue=16, name="t-drr",
                              weights={"a": 2.0, "b": 1.0}, burst=1.0)
    assert adm.acquire(tenant="seed") == 0.0
    order, threads = [], []
    olock = threading.Lock()

    def worker(t):
        adm.acquire(tenant=t)
        with olock:
            order.append(t)
        adm.release()

    for t in ["a"] * 4 + ["b"] * 2:
        th = threading.Thread(target=worker, args=(t,), daemon=True)
        th.start()
        threads.append(th)
        deadline = time.monotonic() + 5
        while adm.waiting < len(threads) and time.monotonic() < deadline:
            time.sleep(0.002)
    assert adm.waiting == 6
    adm.release()           # seed frees the only slot: cascade drains
    for th in threads:
        th.join(timeout=10)
    assert order == ["a", "a", "b", "a", "a", "b"]
    snap = adm.snapshot()
    assert snap["inUse"] == 0 and snap["waiting"] == 0
    assert snap["tenants"]["a"]["admitted"] == 4
    assert snap["tenants"]["b"]["admitted"] == 2


def test_tenant_first_shedding_attributes_to_over_share_tenant():
    """Queue full of one tenant's flood: the polite arrival is seated by
    evicting the flooder's NEWEST waiter, the shed is attributed to the
    flooder, and its Retry-After is computed + capped."""
    qtenant.REGISTRY.clear()
    adm = AdmissionController(max_slots=1, queue_timeout=60.0,
                              max_queue=3, name="t-shed")
    adm.acquire(tenant="seed")
    rejected, done, threads = [], [], []

    def worker(t):
        try:
            adm.acquire(tenant=t)
            done.append(t)
            adm.release()
        except AdmissionRejected as e:
            rejected.append((t, e.retry_after))

    for _ in range(3):
        th = threading.Thread(target=worker, args=("hostile",),
                              daemon=True)
        th.start()
        threads.append(th)
    deadline = time.monotonic() + 5
    while adm.waiting < 3 and time.monotonic() < deadline:
        time.sleep(0.002)
    assert adm.waiting == 3                     # queue exactly full
    th = threading.Thread(target=worker, args=("polite",), daemon=True)
    th.start()
    threads.append(th)
    deadline = time.monotonic() + 5
    while not rejected and time.monotonic() < deadline:
        time.sleep(0.002)
    # exactly one shed, the flooder's, with the capped computed backoff
    assert rejected == [("hostile", 30.0)]
    adm.release()                               # cascade the rest
    for th in threads:
        th.join(timeout=10)
    assert sorted(done) == ["hostile", "hostile", "polite"]
    snap = adm.snapshot()
    assert snap["shedOverQuota"] == 1
    assert snap["tenants"]["hostile"]["shed"] == 1
    assert snap["tenants"]["polite"]["shed"] == 0
    reg = qtenant.REGISTRY.snapshot()
    assert reg["hostile"]["shed"] == 1
    assert reg["hostile"]["shedByPool"] == {"t-shed": 1}
    assert "polite" not in reg or reg["polite"]["shed"] == 0
    qtenant.REGISTRY.clear()


def test_fair_false_restores_legacy_fifo_shedding():
    """fair=False: one shared FIFO, queue overflow rejects the ARRIVAL
    (the pre-isolation behavior), and timeouts count rejected_busy."""
    adm = AdmissionController(max_slots=1, queue_timeout=0.15,
                              max_queue=1, name="t-legacy", fair=False)
    adm.acquire(tenant="seed")
    errs = []

    def waiter():
        try:
            adm.acquire(tenant="w1")
            adm.release()
        except AdmissionRejected as e:
            errs.append(("w1", e))

    th = threading.Thread(target=waiter, daemon=True)
    th.start()
    deadline = time.monotonic() + 5
    while adm.waiting < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    with pytest.raises(AdmissionRejected):      # arrival rejected
        adm.acquire(tenant="w2")
    assert adm.rejected_queue_full == 1
    th.join(timeout=10)                          # w1 times out
    assert [t for t, _ in errs] == ["w1"]
    assert adm.rejected_busy == 1
    assert adm.shed_over_quota == 0              # no fair-mode eviction
    assert adm.snapshot()["fair"] is False
    adm.release()


# -- per-tenant byte quotas (result cache + HBM residency) ------------------

def _fill(cache, i, tenant):
    # one plain-object result costs a fixed 128 estimated bytes
    cache.fill(("q", tenant, i), ("k", tenant, i), [object()],
               tenant=tenant)


def test_result_cache_tenant_quota_evicts_own_lru_first():
    qtenant.REGISTRY.clear()
    c = ResultCache(limit_bytes=1 << 20, tenant_quota_bytes=300)
    _fill(c, 0, "polite")
    for i in range(3):                 # 3 x 128 = 384 > 300 quota
        _fill(c, i, "hostile")
    snap = c.snapshot()
    assert snap["quotaEvicts"] == 1    # hostile's own OLDEST evicted
    assert snap["tenantBytes"]["hostile"] <= 300
    assert snap["tenantBytes"]["polite"] == 128   # neighbor untouched
    assert c.lookup(("k", "hostile", 0)) is None  # the LRU victim
    assert c.lookup(("k", "hostile", 2)) is not None
    assert c.lookup(("k", "polite", 0)) is not None
    reg = qtenant.REGISTRY.snapshot()
    assert reg["hostile"]["quotaEvicts"] == 1
    assert reg["hostile"]["quotaEvictBytes"] == 128
    qtenant.REGISTRY.clear()


def test_result_cache_quota_never_evicts_the_entry_being_filled():
    """A quota smaller than one answer still caches that answer — it
    rides transiently over; the NEXT fill pays instead."""
    c = ResultCache(limit_bytes=1 << 20, tenant_quota_bytes=100)
    _fill(c, 0, "t")
    assert c.snapshot()["entries"] == 1          # kept despite > quota
    _fill(c, 1, "t")
    snap = c.snapshot()
    assert snap["entries"] == 1                  # old one paid
    assert c.lookup(("k", "t", 1)) is not None


def test_result_cache_global_pressure_prefers_over_quota_tenant():
    """Global byte pressure lands on an over-quota tenant's entries
    before anyone else's, even when the filler is a polite tenant."""
    c = ResultCache(limit_bytes=550, tenant_quota_bytes=300)
    # one 320-byte entry: over quota, kept (lone-entry transient ride)
    c.fill(("q", "h"), ("k", "h"), [object()] * 4, tenant="hostile")
    _fill(c, 0, "polite")              # 448 resident
    c.lookup(("k", "h"))               # hostile is now MRU, polite LRU
    _fill(c, 1, "polite")              # 576 > 550: global eviction
    snap = c.snapshot()
    assert "hostile" not in snap["tenantBytes"]  # its entry paid
    assert snap["tenantBytes"]["polite"] == 256  # well under ITS quota
    assert c.lookup(("k", "h")) is None
    assert c.lookup(("k", "polite", 0)) is not None
    assert c.lookup(("k", "polite", 1)) is not None


def test_device_budget_tenant_quota_evicts_own_entries():
    qtenant.REGISTRY.clear()
    evicted = []
    b = DeviceBudget(limit_bytes=1000, tenant_quota_bytes=300)
    b.register(("p", 0), 150, lambda: evicted.append(("p", 0)),
               tenant="polite")
    for i in range(4):                 # 4 x 150 = 600 > 300 quota
        b.register(("h", i), 150,
                   (lambda k: lambda: evicted.append(("h", k)))(i),
                   tenant="hostile")
    st = b.stats()
    assert st["quotaEvictions"] == 2   # hostile's own oldest two
    assert st["tenantBytes"]["hostile"] == 300
    assert st["tenantBytes"]["polite"] == 150
    assert evicted == [("h", 0), ("h", 1)]
    assert qtenant.REGISTRY.snapshot()["hostile"]["quotaEvicts"] >= 1
    qtenant.REGISTRY.clear()


def test_device_budget_global_pressure_prefers_over_quota_tenant():
    evicted = []
    b = DeviceBudget(limit_bytes=550, tenant_quota_bytes=300)
    b.register(("h", 0), 320, lambda: evicted.append("h0"),
               tenant="hostile")      # over quota, kept (lone entry)
    b.register(("p", 0), 128, lambda: evicted.append("p0"),
               tenant="polite")
    b.touch(("h", 0))                 # hostile is now MRU, polite LRU
    # 128 more forces global pressure: the over-quota hostile entry
    # pays even though polite's is the colder LRU position otherwise
    b.register(("p", 1), 128, lambda: evicted.append("p1"),
               tenant="polite")
    assert evicted == ["h0"]
    assert b.stats()["tenantBytes"]["polite"] == 256


# -- degraded-result cache guard (regression pin) ---------------------------

def _one_shard_holder():
    h = Holder(None)
    idx = h.create_index("i", track_existence=False)
    idx.create_field("f")
    f = idx.field("f")
    f.import_bits(np.array([1, 1, 1]), np.array([0, 5, 9]))
    return h


def test_quarantined_degraded_answer_never_memoized():
    """The PR 17 bug pin: is_partial() alone would memoize a
    quarantined-degraded answer (empty rows standing in for poisoned
    fragments) and keep serving it after the fragments heal — the fill
    guard must check is_degraded(), i.e. quarantine counts too."""
    ex = Executor(_one_shard_holder())
    ex.result_cache.limit_bytes = 8 << 20
    with degraded.collect():
        degraded.note(1)               # a quarantined fragment touched
        assert degraded.is_degraded() and not degraded.is_partial()
        ex.execute("i", "Count(Row(f=1))")
    assert ex.result_cache.snapshot()["entries"] == 0
    # same query healthy: cached, then served from cache
    ex.execute("i", "Count(Row(f=1))")
    assert ex.result_cache.snapshot()["entries"] == 1
    ex.execute("i", "Count(Row(f=1))")
    assert ex.result_cache.hits == 1


def test_partial_answer_never_memoized_at_executor():
    ex = Executor(_one_shard_holder())
    ex.result_cache.limit_bytes = 8 << 20
    with degraded.collect(allow_partial=True):
        degraded.note_missing("i", [3], nodes=["node9"])
        assert degraded.is_partial()
        ex.execute("i", "Count(Row(f=1))")
    assert ex.result_cache.snapshot()["entries"] == 0


# -- HTTP edge + cluster plane (real servers, real sockets) -----------------

class _TenantCluster:
    """3 real servers with the isolation plane on; node1/node2 dialed
    through ChaosProxies (the test_churn.py harness) so floods and
    stragglers are real TCP behavior.  Tight slots (max_queries=2) +
    polite:4/hostile:1 weights make admission pressure testable."""

    def __init__(self, tmp_path):
        binds = _free_ports(3)
        self.servers = []
        self.proxies = {}
        hosts = [f"localhost:{binds[0]}"]
        for i in (1, 2):
            proxy = ChaosProxy("localhost", binds[i])
            self.proxies[f"node{i}"] = proxy
            hosts.append(proxy.address)
        for i, p in enumerate(binds):
            srv = Server(Config(
                data_dir=str(tmp_path / f"node{i}"),
                bind=f"localhost:{p}", node_id=f"node{i}",
                cluster_hosts=hosts, replica_n=2,
                anti_entropy_interval=0,
                read_routing="primary", hedge_delay_ms=40.0,
                max_queries=2, queue_timeout=0.25,
                tenant_weights="polite:4,hostile:1",
                result_cache_mb=8))
            srv.open()
            self.servers.append(srv)
        self.port = self.servers[0].port
        self.cl = self.servers[0].cluster
        self.index = next(
            name for name in (f"tn{i}" for i in range(64))
            if 0 < len(self._remote_owned(name)) < N_SHARDS)
        _req(self.port, "POST", f"/index/{self.index}", {})
        _req(self.port, "POST", f"/index/{self.index}/field/f", {})
        cols = [s * SHARD_WIDTH + (s % 5) for s in range(N_SHARDS)]
        _req(self.port, "POST", f"/index/{self.index}/field/f/import",
             {"rowIDs": [1] * len(cols), "columnIDs": cols})
        [self.count_all] = query(self.port, self.index,
                                 "Count(Row(f=1))")

    def _remote_owned(self, index):
        return [s for s in range(N_SHARDS)
                if "node0" not in
                self.cl.placement.shard_nodes(index, s)]

    def remote_owned(self):
        return self._remote_owned(self.index)

    def heal(self):
        for proxy in self.proxies.values():
            proxy.heal()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            self.cl.probe_peers()
            if all(n.state == "READY" for n in self.cl.nodes):
                return
            time.sleep(0.1)
        raise AssertionError(
            f"peers never recovered: "
            f"{[(n.id, n.state) for n in self.cl.nodes]}")

    def close(self):
        for s in self.servers:
            try:
                s.close()
            except Exception:
                pass
        for proxy in self.proxies.values():
            proxy.close()


@pytest.fixture(scope="module")
def tcluster(tmp_path_factory):
    c = _TenantCluster(tmp_path_factory.mktemp("tenant"))
    yield c
    c.close()


def _counts(port):
    return _req(port, "GET", "/debug/vars")["counts"]


def _tquery(port, index, pql, tenant=None, qs=""):
    r = urllib.request.Request(
        f"http://localhost:{port}/index/{index}/query{qs}",
        method="POST", data=pql.encode())
    if tenant is not None:
        r.add_header(qtenant.TENANT_HEADER, tenant)
    with urllib.request.urlopen(r, timeout=60) as resp:
        return json.loads(resp.read())


def test_http_bad_tenant_tokens_are_clean_400(tcluster):
    """The HTTP fuzz contract: malformed tokens are a 400 with an error
    body — never a 500, never a stack trace, never admitted."""
    for tok in ("has space", "a" * 65, "-lead", "bad!char", "a;b", ""):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _tquery(tcluster.port, tcluster.index, "Count(Row(f=1))",
                    tenant=tok)
        assert ei.value.code == 400
        body = json.loads(ei.value.read())
        assert "tenant" in body["error"].lower()
    # and the garbage never became a metrics label / registry row
    assert "a" * 65 not in qtenant.REGISTRY.snapshot()


def test_http_tenant_identity_derived_and_explicit(tcluster):
    """Identity lands in /debug/vars "tenants" and EXPLAIN's admission
    note; an explicit token forwards to peers' INTERNAL pools while a
    derived identity is re-derived from the index name."""
    # distinct PQL per sub-case: a result-cache hit would short-circuit
    # the fan-out whose internal-pool attribution this test asserts
    got = _tquery(tcluster.port, tcluster.index,
                  "Count(Intersect(Row(f=1)))", qs="?explain=true")
    assert got["results"] == [tcluster.count_all]
    [adm_note] = got["explain"]["admission"]
    assert adm_note["tenant"] == tcluster.index   # derived from index
    assert adm_note["pool"] == "public"
    assert adm_note["queuedMs"] >= 0.0
    got = _tquery(tcluster.port, tcluster.index,
                  "Count(Union(Row(f=1)))", tenant="acme",
                  qs="?explain=true")
    assert got["results"] == [tcluster.count_all]
    [adm_note] = got["explain"]["admission"]
    assert adm_note["tenant"] == "acme"
    # registry accounting lands in the handler's post-response finally —
    # poll briefly rather than racing the microseconds after _send
    deadline = time.monotonic() + 5.0
    while True:
        dv = _req(tcluster.port, "GET", "/debug/vars")
        rows = dv["tenants"]
        if tcluster.index in rows and "acme" in rows:
            break
        assert time.monotonic() < deadline, f"tenant rows: {rows}"
        time.sleep(0.02)
    assert dv["tenants"][tcluster.index]["requests"] >= 1
    assert dv["tenants"]["acme"]["requests"] >= 1
    # explicit token reached at least one peer's internal pool; the
    # derived identity was re-derived there from the index in the path
    peer_tenants = {}
    for srv in tcluster.servers[1:]:
        for t, row in srv.admission_internal.snapshot()[
                "tenants"].items():
            peer_tenants[t] = peer_tenants.get(t, 0) + row["admitted"]
    assert peer_tenants.get("acme", 0) >= 1
    assert peer_tenants.get(tcluster.index, 0) >= 1


def test_hedge_budget_exhaustion_degrades_to_unhedged(tcluster):
    """An exhausted hedge budget must deny the speculative duplicate —
    counted and named in EXPLAIN — while the query still answers
    correctly (slow, unhedged), never erroring."""
    cl = tcluster.cl
    shards = tcluster.remote_owned()
    assert shards, "placement gave node0 every shard replica?"
    s = shards[0]
    straggler = cl._ready_owner_order(tcluster.index, s)[0]
    before = _counts(tcluster.port)
    old_budget = cl.hedge_budget
    cl.hedge_budget = qtenant.HedgeBudget(rate=0.001)  # ~empty bucket
    tcluster.proxies[straggler].configure("down=latency:0.4")
    try:
        got = _tquery(tcluster.port, tcluster.index, "Count(Row(f=1))",
                      qs=f"?shards={s}&explain=true")
    finally:
        cl.hedge_budget = old_budget
        tcluster.heal()
    assert got["results"] == [1]                  # correct, unhedged
    assert "degraded" not in got
    denials = [h for h in got["explain"].get("hedges", [])
               if h.get("outcome") == "budget_denied"]
    assert denials and denials[0]["tenant"] == tcluster.index
    after = _counts(tcluster.port)
    assert after.get("cluster.hedge_budget_denied", 0) > \
        before.get("cluster.hedge_budget_denied", 0)
    assert after.get(f"tenant.{tcluster.index}.hedge_denied", 0) > \
        before.get(f"tenant.{tcluster.index}.hedge_denied", 0)
    assert qtenant.REGISTRY.snapshot()[
        tcluster.index]["hedgeDenied"] >= 1


def test_partial_answer_never_cached_complete_failover_is(tcluster):
    """The cluster-level fill guard: a partial answer (both remote
    nodes partitioned, ?partialResults=true) is never memoized — after
    healing, the same query answers COMPLETE, not the cached stub.  A
    complete answer served via mid-query failover (one node down) IS
    cached: the guard must not over-block."""
    rc = tcluster.servers[0].api.executor.result_cache
    pql = "Count(Union(Row(f=1), Row(f=1)))"   # unique to this test
    lost = tcluster.remote_owned()
    served = N_SHARDS - len(lost)
    for nid in ("node1", "node2"):
        tcluster.proxies[nid].configure("connect=partition")
        tcluster.proxies[nid].sever()
    try:
        got = _tquery(tcluster.port, tcluster.index, pql,
                      qs="?partialResults=true")
        assert got["results"] == [served]
        assert got["degraded"]["missingShards"] == \
            {tcluster.index: sorted(lost)}
        # repeat: STILL degraded and partial — not a cached complete lie
        again = _tquery(tcluster.port, tcluster.index, pql,
                        qs="?partialResults=true")
        assert again["results"] == [served] and "degraded" in again
    finally:
        tcluster.heal()
    # healed: the same query must answer complete — the partial answer
    # was never memoized under the (unchanged) generation key
    full = _tquery(tcluster.port, tcluster.index, pql)
    assert full["results"] == [tcluster.count_all]
    assert "degraded" not in full
    # fill-after-failover: ONE node partitioned, answer stays complete
    # via replica failover and THAT answer is cacheable
    hits0 = rc.snapshot()["hits"]
    tcluster.proxies["node1"].configure("connect=partition")
    tcluster.proxies["node1"].sever()
    try:
        got = _tquery(tcluster.port, tcluster.index, pql)
        assert got["results"] == [tcluster.count_all]
        assert "degraded" not in got
        again = _tquery(tcluster.port, tcluster.index, pql)
        assert again["results"] == [tcluster.count_all]
        assert rc.snapshot()["hits"] > hits0   # the repeat was served
    finally:
        tcluster.heal()


def test_hostile_flood_polite_tenant_stays_admitted(tcluster):
    """The tentpole end-to-end: 8 hostile threads flood through real
    sockets while a polite tenant runs sequential queries honoring
    Retry-After.  The polite tenant completes every query with
    byte-identical answers; >= 95% of sheds are attributed to the
    hostile tenant; hostile 503s carry computed fractional
    Retry-After."""
    qtenant.REGISTRY.clear()
    for proxy in tcluster.proxies.values():
        proxy.configure("down=latency:0.05")   # stretch fan-out RTT
    stop = threading.Event()
    hostile_unexpected, retry_afters = [], []

    def hostile_flood():
        n = 0
        while not stop.is_set() and n < 400:
            n += 1
            try:
                _tquery(tcluster.port, tcluster.index,
                        "Count(Row(f=1))", tenant="hostile")
            except urllib.error.HTTPError as e:
                e.read()
                if e.code != 503:
                    hostile_unexpected.append(e.code)
                else:
                    ra = e.headers.get("Retry-After")
                    if ra is not None:
                        retry_afters.append(float(ra))
            except OSError:
                pass

    threads = [threading.Thread(target=hostile_flood, daemon=True)
               for _ in range(8)]
    for th in threads:
        th.start()
    time.sleep(0.3)                     # let the flood saturate slots
    polite_ok = 0
    try:
        for _ in range(10):
            for _attempt in range(40):
                try:
                    got = _tquery(tcluster.port, tcluster.index,
                                  "Count(Row(f=1))", tenant="polite")
                    assert got["results"] == [tcluster.count_all]
                    polite_ok += 1
                    break
                except urllib.error.HTTPError as e:
                    e.read()
                    assert e.code == 503
                    ra = float(e.headers.get("Retry-After", "1"))
                    assert ra >= 1.0
                    time.sleep(min(ra, 0.2))   # bounded polite backoff
            else:
                raise AssertionError(
                    "polite tenant starved out by the flood")
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=30)
        tcluster.heal()
    assert polite_ok == 10
    assert not hostile_unexpected       # only 503s, never 5xx surprises
    reg = qtenant.REGISTRY.snapshot()
    hostile_shed = reg.get("hostile", {}).get("shed", 0)
    total_shed = sum(row.get("shed", 0) for row in reg.values())
    assert hostile_shed > 0, "the flood never hit admission pressure"
    assert hostile_shed / total_shed >= 0.95, \
        f"shed attribution leaked: {hostile_shed}/{total_shed}"
    # computed backoff: fractional, floored at 1, capped at 30
    assert retry_afters and all(1.0 <= ra <= 30.0
                                for ra in retry_afters)
    assert len({round(ra, 2) for ra in retry_afters}) > 1 \
        or len(retry_afters) < 5       # jitter spreads (unless tiny N)
    # the isolation columns surface at /debug/vars and the rollup
    dv = _req(tcluster.port, "GET", "/debug/vars")
    assert dv["tenants"]["hostile"]["shed"] == hostile_shed
    roll = _req(tcluster.port, "GET", "/debug/cluster?refresh=true")
    assert roll["tenants"]["hostile"]["shed"] >= hostile_shed
    qtenant.REGISTRY.clear()
