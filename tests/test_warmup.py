"""Warm-start subsystem (pilosa_tpu/warmup/, docs/warmup.md): the
CRC-framed signature corpus's crash safety (every-length truncation,
every-byte corruption — load never raises, never returns garbage),
recorder fold/seed/flush/compaction, the compile-cache LRU prune, the
coordinator's degrade-to-cold guarantees (corrupt/empty/stale corpus,
replay errors, expired budget all still reach READY), and a real
Server warm restart: prepared templates rebuilt, zero retraces during
replay, EXPLAIN flipping plan compile cold -> warm."""

import json
import os
import time

import pytest

from pilosa_tpu.warmup import (CorpusRecorder, SignatureCorpus, prune,
                               resolve_dir, top_n, WarmupCoordinator)
from pilosa_tpu.warmup.corpus import (CORPUS_MAGIC, SCHEMA_VERSION,
                                      _frame)
from pilosa_tpu.warmup.replayer import PHASE_READY, PHASE_WARMING

from test_observability import _req, make_server


def _rec(index="i", template="Count(Row(f=?))", query="Count(Row(f=1))",
         hits=1, **kw):
    rec = {"v": SCHEMA_VERSION, "index": index, "template": template,
           "query": query, "sig": "wholequery:abc", "fp": "fp1",
           "hits": hits, "lastUsed": 100.0, "compileS": 0.5}
    rec.update(kw)
    return rec


def _write_corpus(path, records):
    c = SignatureCorpus(str(path))
    c.open()
    c.append(records)
    c.close()


# -- corpus frame discipline -------------------------------------------------


def test_append_read_load_latest_wins(tmp_path):
    path = tmp_path / "signatures.log"
    recs = [_rec(hits=1), _rec(template="Row(g=?)", query="Row(g=2)",
                               hits=3),
            _rec(hits=7, query="Count(Row(f=9))")]  # same key as recs[0]
    _write_corpus(path, recs)
    assert SignatureCorpus.read(str(path)) == recs
    folded = SignatureCorpus.load(str(path))
    assert set(folded) == {("i", "Count(Row(f=?))"), ("i", "Row(g=?)")}
    # latest frame for a key wins (each frame is a full snapshot)
    assert folded[("i", "Count(Row(f=?))")]["hits"] == 7
    assert folded[("i", "Count(Row(f=?))")]["query"] == "Count(Row(f=9))"


def test_every_length_truncation_recovers(tmp_path):
    """Any kill -9 mid-write leaves a prefix; every prefix must load
    without raising and yield only records that were actually written."""
    path = tmp_path / "signatures.log"
    recs = [_rec(template=f"t{i}(?)", query=f"t{i}(1)", hits=i + 1)
            for i in range(3)]
    _write_corpus(path, recs)
    data = path.read_bytes()
    for cut in range(len(data) + 1):
        path.write_bytes(data[:cut])
        got = SignatureCorpus.read(str(path))
        assert got == recs[:len(got)]  # valid prefix, in order
        # and a fresh open() truncates the torn tail durably
        c = SignatureCorpus(str(path))
        c.open()
        c.close()
        assert SignatureCorpus.read(str(path)) == got
    path.write_bytes(data)
    assert len(SignatureCorpus.load(str(path))) == 3


def test_every_byte_corruption_recovers(tmp_path):
    """Flipping any single byte must never raise and must never invent
    a record: every loaded record equals one that was written."""
    path = tmp_path / "signatures.log"
    recs = [_rec(template=f"t{i}(?)", query=f"t{i}(1)", hits=i + 1)
            for i in range(3)]
    _write_corpus(path, recs)
    data = bytearray(path.read_bytes())
    for i in range(len(data)):
        corrupted = bytearray(data)
        corrupted[i] ^= 0xFF
        path.write_bytes(bytes(corrupted))
        for got in (SignatureCorpus.read(str(path)),
                    list(SignatureCorpus.load(str(path)).values())):
            for rec in got:
                assert rec in recs


def test_wrong_magic_resets_empty(tmp_path):
    path = tmp_path / "signatures.log"
    path.write_bytes(b"NOTMAGIC" + b"junk" * 10)
    c = SignatureCorpus(str(path))
    c.open()  # garbage prefix -> rewritten empty, not refused
    c.append([_rec()])
    c.close()
    assert len(SignatureCorpus.load(str(path))) == 1


def test_bad_records_dropped_not_fatal(tmp_path):
    path = tmp_path / "signatures.log"
    good = _rec()
    stale = _rec(template="old(?)")
    stale["v"] = SCHEMA_VERSION + 1          # stale schema version
    missing = {"v": SCHEMA_VERSION, "index": "i"}  # missing keys
    with open(path, "wb") as f:
        f.write(CORPUS_MAGIC)
        f.write(_frame(json.dumps(good).encode()))
        f.write(_frame(b"[1, 2, 3]"))         # CRC-valid, not a dict
        f.write(_frame(b"{not json"))         # CRC-valid, not JSON
        f.write(_frame(json.dumps(stale).encode()))
        f.write(_frame(json.dumps(missing).encode()))
    folded = SignatureCorpus.load(str(path))
    assert list(folded.values()) == [good]


def test_load_missing_and_empty_file(tmp_path):
    assert SignatureCorpus.load(str(tmp_path / "absent.log")) == {}
    (tmp_path / "empty.log").write_bytes(b"")
    assert SignatureCorpus.load(str(tmp_path / "empty.log")) == {}


def test_compact_rewrites_to_survivors(tmp_path):
    path = tmp_path / "signatures.log"
    c = SignatureCorpus(str(path))
    c.open()
    for i in range(40):
        c.append([_rec(template="hot(?)", query="hot(1)", hits=i)])
    big = path.stat().st_size
    c.compact([_rec(template="hot(?)", query="hot(1)", hits=39)])
    assert path.stat().st_size < big
    assert c.frames_appended == 1
    # the handle survives compaction: appends still land
    c.append([_rec(template="new(?)", query="new(2)")])
    c.close()
    assert set(SignatureCorpus.load(str(path))) == {
        ("i", "hot(?)"), ("i", "new(?)")}


def test_top_n_ranks_hits_then_recency():
    a = _rec(template="a(?)", hits=5, lastUsed=1.0)
    b = _rec(template="b(?)", hits=5, lastUsed=9.0)
    c = _rec(template="c(?)", hits=50, lastUsed=0.0)
    assert top_n([a, b, c], 2) == [c, b]
    assert top_n([a, b, c], 0) == []


# -- recorder ----------------------------------------------------------------


def test_recorder_note_flush_and_seed(tmp_path):
    path = tmp_path / "signatures.log"
    corpus = SignatureCorpus(str(path))
    corpus.open()
    rec = CorpusRecorder(keep_n=8)
    rec.note_sig("wholequery:deadbeef")
    rec.note("i", "Count(Row(f=1))")
    rec.note("i", "Count(Row(f=2))")  # same template, staged sig consumed
    rec.flush(corpus)
    corpus.close()
    folded = SignatureCorpus.load(str(path))
    (key, stored), = folded.items()
    assert key == ("i", "Count(Row(f=?))")
    assert stored["hits"] == 2
    assert stored["sig"] == "wholequery:deadbeef"
    assert stored["query"] == "Count(Row(f=2))"  # latest sample text

    # restart: seeding carries the hit count, new traffic adds to it
    rec2 = CorpusRecorder(keep_n=8)
    rec2.seed(folded)
    rec2.note("i", "Count(Row(f=3))")
    assert rec2.snapshot()["templates"] == 1
    corpus2 = SignatureCorpus(str(path))
    corpus2.open()
    rec2.flush(corpus2)
    corpus2.close()
    assert SignatureCorpus.load(str(path))[key]["hits"] == 3


def test_recorder_compacts_when_log_outgrows_bound(tmp_path):
    path = tmp_path / "signatures.log"
    corpus = SignatureCorpus(str(path))
    corpus.open()
    rec = CorpusRecorder(keep_n=2)
    for i in range(2 * rec.COMPACT_FACTOR + 3):
        rec.note(f"idx{i}", "Count(Row(f=1))")
        rec.flush(corpus)
    # the log was rewritten to the keep_n survivor set at least once
    assert corpus.frames_appended <= rec.keep_n * rec.COMPACT_FACTOR
    corpus.close()
    assert len(SignatureCorpus.read(str(path))) <= \
        rec.keep_n * rec.COMPACT_FACTOR + 1


# -- compile cache -----------------------------------------------------------


def test_resolve_dir_semantics(tmp_path):
    d = str(tmp_path)
    assert resolve_dir("off", d) is None
    assert resolve_dir("", d) == os.path.join(d, ".compile-cache")
    assert resolve_dir("/explicit/path", d) == "/explicit/path"
    assert resolve_dir("", None) is None


def test_prune_removes_oldest_first(tmp_path):
    files = []
    for i in range(4):
        p = tmp_path / f"entry{i}"
        p.write_bytes(b"x" * 1024 * 1024)  # 1 MB each
        os.utime(p, (100.0 + i, 100.0 + i))
        files.append(p)
    out = prune(str(tmp_path), 2)
    assert out["removed"] == 2 and out["files"] == 2
    assert not files[0].exists() and not files[1].exists()
    assert files[2].exists() and files[3].exists()
    # 0 = unbounded: nothing removed
    assert prune(str(tmp_path), 0)["removed"] == 0
    # missing dir never raises
    assert prune(str(tmp_path / "absent"), 1)["removed"] == 0


# -- coordinator (stub executor) ---------------------------------------------


class _StubExecutor:
    def __init__(self, fail_on=()):
        self.calls = []
        self.fail_on = set(fail_on)

    def execute(self, index, query):
        self.calls.append((index, query))
        if query in self.fail_on:
            raise RuntimeError("index dropped")
        return [0]


def _wait_ready(co, timeout=10.0):
    t0 = time.monotonic()
    while co.warming() and time.monotonic() - t0 < timeout:
        time.sleep(0.01)
    assert not co.warming()


def test_coordinator_cold_without_corpus(tmp_path):
    ex = _StubExecutor()
    co = WarmupCoordinator(ex, str(tmp_path / "signatures.log"))
    assert co.open() is False          # nothing to warm
    assert co.status()["phase"] == PHASE_READY
    co.start()
    co.close()
    assert ex.calls == []


def test_coordinator_disabled_by_top_n_zero(tmp_path):
    path = tmp_path / "signatures.log"
    _write_corpus(path, [_rec()])
    co = WarmupCoordinator(_StubExecutor(), str(path), top_n=0)
    assert co.open() is False
    co.close()


def test_coordinator_replays_top_n_then_ready(tmp_path):
    path = tmp_path / "signatures.log"
    _write_corpus(path, [_rec(template=f"t{i}(?)", query=f"t{i}(1)",
                              hits=10 - i) for i in range(5)])
    ex = _StubExecutor()
    co = WarmupCoordinator(ex, str(path), top_n=3, budget_s=30.0)
    flipped = []
    co.on_ready = lambda: flipped.append(True)
    assert co.open() is True
    assert co.status()["phase"] == PHASE_WARMING
    co.start()
    _wait_ready(co)
    st = co.status()
    assert st["planned"] == 3 and st["replayed"] == 3
    assert st["errors"] == 0 and st["skipped"] == 0
    # replay order is traffic rank: hottest first
    assert [q for _, q in ex.calls] == ["t0(1)", "t1(1)", "t2(1)"]
    assert flipped == [True]
    co.close()


def test_coordinator_replay_error_degrades_not_fails(tmp_path):
    path = tmp_path / "signatures.log"
    _write_corpus(path, [_rec(template="bad(?)", query="bad(1)", hits=9),
                         _rec(template="ok(?)", query="ok(1)", hits=1)])
    co = WarmupCoordinator(_StubExecutor(fail_on={"bad(1)"}), str(path))
    assert co.open() is True
    co.start()
    _wait_ready(co)
    st = co.status()
    assert st["errors"] == 1 and st["replayed"] == 1
    assert st["phase"] == PHASE_READY
    co.close()


def test_coordinator_budget_expiry_skips_remainder(tmp_path):
    path = tmp_path / "signatures.log"
    _write_corpus(path, [_rec(template=f"t{i}(?)", query=f"t{i}(1)")
                         for i in range(4)])
    co = WarmupCoordinator(_StubExecutor(), str(path), budget_s=0.0)
    assert co.open() is True
    co.start()
    _wait_ready(co)
    st = co.status()
    assert st["skipped"] == st["planned"] == 4
    assert st["replayed"] == 0 and st["phase"] == PHASE_READY
    co.close()


def test_coordinator_corrupt_corpus_cold_start(tmp_path):
    path = tmp_path / "signatures.log"
    path.write_bytes(os.urandom(512))  # garbage: wrong magic
    co = WarmupCoordinator(_StubExecutor(), str(path))
    assert co.open() is False          # cold start, never a crash
    assert co.status()["corpusEntries"] == 0
    co.start()
    co.close()
    # and the rewritten-empty log is usable going forward
    co.recorder.note("i", "Count(Row(f=1))")


# -- server end-to-end -------------------------------------------------------


@pytest.mark.slow
def test_server_warm_restart_end_to_end(tmp_path):
    """The full loop: serve -> corpus flushed on close -> restart enters
    warming -> replay through the real executor rebuilds prepared
    templates with zero retraces -> READY; EXPLAIN reports plan compile
    warm for post-restart traffic."""
    from pilosa_tpu.utils.devobs import COMPILES

    s = make_server(tmp_path, timeseries_interval=0,
                    metric_poll_interval=0)
    p = s.port
    _req(p, "POST", "/index/wi", {})
    _req(p, "POST", "/index/wi/field/f", {})
    _req(p, "POST", "/index/wi/query",
         "".join(f"Set({c}, f={r})" for r in range(3) for c in range(40)))
    for _ in range(3):
        out, _ = _req(p, "POST", "/index/wi/query", "Count(Row(f=1))")
        assert out["results"] == [40]
    st1, _ = _req(p, "GET", "/status")
    assert st1["phase"] == "ready" and st1["warming"] is False
    s.close()  # final flush writes the corpus

    s2 = make_server(tmp_path, timeseries_interval=0,
                     metric_poll_interval=0)
    try:
        assert s2.warmup.open.__self__ is s2.warmup  # sanity: wired
        t0 = time.monotonic()
        while s2.warmup.warming() and time.monotonic() - t0 < 60:
            time.sleep(0.02)
        st = s2.warmup.status()
        assert st["phase"] == "ready"
        assert st["replayed"] >= 1 and st["errors"] == 0
        assert st["retracesDuringWarm"] == 0
        # prepared template survived the restart (rebuilt by replay)
        prep = s2.api.executor.prepared
        assert prep is not None and len(prep._entries) >= 1
        # post-warm traffic does not compile: the replay already did
        before = COMPILES.totals()
        out, _ = _req(s2.port, "POST", "/index/wi/query?explain=true",
                      "Count(Row(f=1))")
        assert out["results"] == [40]
        after = COMPILES.totals()
        assert after["compiles"] == before["compiles"]
        plan = out["explain"]["plan"]
        assert plan and plan[0].get("compile") == "warm"
        # warmup surfaces at /debug/vars
        dv, _ = _req(s2.port, "GET", "/debug/vars")
        assert dv["warmup"]["phase"] == "ready"
        assert dv["warmup"]["replayed"] == st["replayed"]
    finally:
        s2.close()


def test_status_reports_warming_not_ready(tmp_path):
    """While the coordinator is warming, /status must say so (probes
    treat warming as not-READY) without ever claiming DOWN."""
    s = make_server(tmp_path, timeseries_interval=0,
                    metric_poll_interval=0)
    try:
        class _Stuck:
            def warming(self):
                return True

            def status(self):
                return {"phase": "warming"}

        s.api.warmup = _Stuck()
        st, _ = _req(s.port, "GET", "/status")
        assert st["warming"] is True and st["phase"] == "warming"
        assert st["nodes"][0]["state"] == "WARMING"
    finally:
        s.api.warmup = s.warmup
        s.close()


def test_cluster_local_warming_state(tmp_path):
    from pilosa_tpu.parallel.cluster import (Cluster, NODE_READY,
                                             NODE_WARMING)
    from pilosa_tpu.storage import Holder

    h = Holder(str(tmp_path / "h"))
    c = Cluster("node0", ["localhost:1", "localhost:2"], holder=h)
    c.set_local_warming(True)
    me = c.by_id["node0"]
    assert me.state == NODE_WARMING
    c.set_local_warming(False)
    assert me.state == NODE_READY
