#!/usr/bin/env bash
# Tier-1 verify, split into two legs (the PR 5/13/14 precedent, codified
# at PR 16): on a 1-core box the full suite no longer fits one 870 s
# timeout budget, so it runs as two halves with the SAME pytest flags as
# ROADMAP.md's single-command tier-1 line.  Each leg gets its own 870 s
# budget and prints its own DOTS_PASSED count.
#
#   scripts/tier1_split.sh        # both legs, exit non-zero if either fails
#   scripts/tier1_split.sh 1      # just leg 1 (core / single-node)
#   scripts/tier1_split.sh 2      # just leg 2 (cluster / distributed / bench)
#
# The leg partition is CHECKED: the analyzer's tier1-legs rule
# (pilosa_tpu/analysis/rules/tier1_legs.py, docs/static-analysis.md)
# fails if any tests/test_*.py on disk is missing from both lists below
# or a listed file no longer exists, and _check_partition here re-checks
# at run time — a new test file cannot silently fall outside tier-1.
set -uo pipefail
cd "$(dirname "$0")/.."

# Leg 1: core engine + storage + single-node serving.
LEG1="
tests/test_analysis.py
tests/test_batcher.py
tests/test_bitset.py
tests/test_bsi.py
tests/test_budget_stream.py
tests/test_cache.py
tests/test_cli.py
tests/test_containers.py
tests/test_crash.py
tests/test_device_obs.py
tests/test_differential.py
tests/test_durability.py
tests/test_events.py
tests/test_executor.py
tests/test_explain.py
tests/test_fuzz.py
tests/test_ingest.py
tests/test_kernels.py
tests/test_native.py
tests/test_observability.py
tests/test_pql.py
tests/test_prepared.py
tests/test_roaring_golden.py
tests/test_storage.py
tests/test_translate.py
tests/test_wholequery.py
"

# Leg 2: cluster plane (fan-out, chaos, routing, resize, wire) + server
# + bench smoke.
LEG2="
tests/test_bench_smoke.py
tests/test_churn.py
tests/test_cluster.py
tests/test_cluster_differential.py
tests/test_cluster_obs.py
tests/test_multihost.py
tests/test_overload.py
tests/test_parallel.py
tests/test_qwire.py
tests/test_routing.py
tests/test_server.py
tests/test_slo.py
tests/test_tenant.py
tests/test_topology.py
tests/test_warmup.py
"

_check_partition() {
    local missing=0
    for f in tests/test_*.py; do
        # no grep -q here: under pipefail, -q exits on first match and
        # can SIGPIPE the printf, failing the pipeline on a MATCH
        if ! printf '%s\n%s\n' "$LEG1" "$LEG2" | grep -x "$f" >/dev/null; then
            echo "tier1_split.sh: $f is in NEITHER leg — add it" >&2
            missing=1
        fi
    done
    for f in $LEG1 $LEG2; do
        if [ ! -f "$f" ]; then
            echo "tier1_split.sh: $f is listed but does not exist" >&2
            missing=1
        fi
    done
    return $missing
}

_run_leg() {
    local name="$1"; shift
    local log="/tmp/_t1_${name}.log"
    rm -f "$log"
    # shellcheck disable=SC2086  # word-splitting the file list is the point
    timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest $* -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$log"
    local rc=${PIPESTATUS[0]}
    echo "LEG${name}_DOTS_PASSED=$(grep -aE \
        '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$log" | tr -cd . | wc -c)"
    return $rc
}

_check_partition || exit 1

rc=0
case "${1:-all}" in
    1) _run_leg 1 $LEG1 || rc=$? ;;
    2) _run_leg 2 $LEG2 || rc=$? ;;
    all)
        _run_leg 1 $LEG1 || rc=$?
        _run_leg 2 $LEG2 || rc=$?
        ;;
    *) echo "usage: $0 [1|2]" >&2; exit 2 ;;
esac
exit $rc
