"""Profile host-side per-query overhead on the served path (CPU mesh).

Run:  python scripts/profile_query.py [--cprofile]
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pilosa_tpu.core import SHARD_WIDTH  # noqa: E402
from pilosa_tpu.storage import FieldOptions, Holder  # noqa: E402
from pilosa_tpu.executor import Executor  # noqa: E402

SEED = 7


def build():
    rng = np.random.default_rng(SEED)
    h = Holder(None)
    star = h.create_index("startrace", track_existence=False)
    stargazer = star.create_field("stargazer")
    n_rows, per_row = 64, 200_000
    stargazer.import_bits(
        np.repeat(np.arange(n_rows), per_row),
        rng.integers(0, SHARD_WIDTH, size=n_rows * per_row))
    return h, n_rows


def batch2(rng, n_rows, B):
    sets = rng.permuted(np.tile(np.arange(n_rows), (B, 1)), axis=1)[:, :8]
    return " ".join(
        "Count(Intersect(" + ", ".join(
            f"Row(stargazer={r})" for r in q) + "))" for q in sets)


def main():
    h, n_rows = build()
    ex = Executor(h, use_mesh=True)
    rng = np.random.default_rng(SEED + 1)
    B, iters = 128, 10

    # warm
    ex.execute("startrace", batch2(rng, n_rows, B))
    ex.execute("startrace", batch2(rng, n_rows, B))
    pc = ex.prepared
    print(f"prepared: hits={pc.hits} misses={pc.misses} "
          f"guard_misses={pc.guard_misses}", file=sys.stderr)

    if "--cprofile" in sys.argv:
        import cProfile
        import pstats
        pr = cProfile.Profile()
        pr.enable()
        for _ in range(iters):
            ex.execute("startrace", batch2(rng, n_rows, B))
        pr.disable()
        pstats.Stats(pr).sort_stats("cumulative").print_stats(30)
    else:
        t0 = time.perf_counter()
        for _ in range(iters):
            ex.execute("startrace", batch2(rng, n_rows, B))
        dt = time.perf_counter() - t0
        print(f"B={B} iters={iters}: {B*iters/dt:.0f} qps, "
              f"{dt/iters*1e3:.2f} ms/batch, "
              f"{dt/(B*iters)*1e6:.0f} us/call")
    print(f"prepared: hits={pc.hits} misses={pc.misses} "
          f"guard_misses={pc.guard_misses}", file=sys.stderr)


if __name__ == "__main__":
    main()
