#!/usr/bin/env bash
# Repo hygiene check: byte-compile everything, run the project invariant
# analyzer (pilosa_tpu/analysis — the AST lint suite that replaced the
# old grep-lints; docs/static-analysis.md has the rule catalog), and run
# the storage-durability fast suite.  Run locally or from CI
# (.github/workflows/ci.yml).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pilosa_tpu tests scripts bench.py

# Project invariant analyzer: traced-closure capture, wall-clock timing,
# bare/swallowed excepts, batcher bypass, cross-thread context
# discipline, metrics-docs catalog, failpoint-name catalog, event-names
# catalog, alert-names catalog (every alert rule id needs a runbook row
# naming a /debug surface — docs/observability.md).  Inline
# suppressions require a reason; the analyzer exits non-zero on any
# finding (run `pilosa-tpu analyze --list-rules` for the rule list).
python -m pilosa_tpu.analysis

# Storage durability fast suite (docs/robustness.md "Durability &
# recovery"): the byte-level corruption fuzz (truncate/flip at every
# offset of snapshot+WAL -> recover-or-quarantine, never a crash) and
# the short deterministic 2-cycle kill -9 crash harness.  The 20-cycle
# randomized soak is pytest -m slow.  Compressed-residency codec
# round-trip + compressed-vs-dense differential (docs/memory-budget.md
# "Compressed residency") ride along: a decode bug corrupts query
# results silently, so the differential is hygiene, not a nicety.
# Device-runtime observability (docs/observability.md "Device runtime")
# rides too: the retrace red flag is the alarm for that same decode-bug
# class, so its test is hygiene as well.  The streaming-ingest suite
# (docs/ingest.md) joins them: wire-codec corruption fuzz, the
# ingest-vs-bulk differential, group-commit counting, and the kill -9
# commit-window harness are all acked-durability guarantees.  The
# whole-query differential (docs/whole-query.md) rides for the same
# reason: the single-program path serves every read request by
# default, and a lowering bug corrupts answers silently — the
# three-leg byte-identity suite is hygiene, not a nicety.
# The elastic-serving suite (docs/cluster.md "Read routing &
# rebalancing") rides as well: the loaded-vs-primary differential is a
# byte-identity guarantee (a routing bug would serve wrong answers from
# a stale replica silently), and the balancer handoff test covers the
# overlay epoch protocol every node's ownership view depends on.
# The tail-tolerance suite (docs/robustness.md "Tail-tolerant fan-out")
# joins them: hedged reads and partial results both sit on exactness
# contracts — hedged answers must be byte-identical to unhedged ones,
# and degraded.missingShards must name EXACTLY the lost shards — and a
# bug in either silently corrupts or silently truncates answers.  The
# fast deterministic subset (real-socket ChaosProxy faults) runs here;
# the 20-cycle churn soak is pytest -m slow.
# The cluster-observability suite (docs/observability.md "Cluster
# plane") rides along: the event journal's framed-log torn-tail
# recovery is a durability contract, EXPLAIN answers must stay
# byte-identical to explain-off, and the fleet rollup must agree with
# per-node /debug/vars golden — silent drift in any of them turns the
# operable-cluster story into a lie.
# The internal-wire suite (docs/cluster.md "Internal query wire") is a
# correctness gate, not a perf test: the binary PTPUQRY1 framing must
# answer byte-identically to the JSON wire — including under
# mixed-version 415 downgrade — and reject every corrupted or truncated
# frame; a codec bug here silently corrupts every cluster read.
# The tenant-isolation suite (docs/robustness.md "Tenant isolation")
# rides for the same class of reason: weighted-fair admission and
# tenant-first shedding sit on an exactness contract (admitted answers
# are byte-identical with the plane on or off) plus an attribution
# contract (a hostile flood's sheds land on the hostile tenant) — and
# the degraded-result cache guard it pins prevents a partial answer
# from being memoized as the real one.
# The warm-start suite (docs/warmup.md) belongs with the durability
# gates: the signature corpus takes kill -9 mid-append by design, so
# its every-length truncation / every-byte corruption recovery — and
# the guarantee that NO corpus state can fail READY — is a crash-safety
# contract, not a perf test.
# The SLO/alerting suite (docs/observability.md "SLOs & alerting")
# rides for the exactness-contract reason too: alert evaluation must
# never change an answer (SLO-on vs SLO-off byte identity), a muted
# pager is a silent failure class of its own, and the flight recorder's
# disk budget is a bounded-resource guarantee.
# The container-kernel suite (docs/architecture.md "On native code and
# Pallas") rides with the decode differential above: the Pallas decode
# and fused-popcount kernels are a THIRD way to materialize every
# compressed answer, so the per-form goldens vs the unpack_packed
# oracle and the dense/jnp/pallas three-leg byte-identity run are the
# same silent-corruption gate as the PR 7 codec round-trip.
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' -p no:cacheprovider \
    tests/test_durability.py tests/test_crash.py tests/test_containers.py \
    tests/test_kernels.py \
    tests/test_device_obs.py tests/test_ingest.py tests/test_wholequery.py \
    tests/test_routing.py tests/test_churn.py \
    tests/test_events.py tests/test_explain.py tests/test_cluster_obs.py \
    tests/test_qwire.py tests/test_tenant.py tests/test_warmup.py \
    tests/test_slo.py

# committed bytecode/cache artifacts must never land in the tree (shell
# stays the right layer for a git-index check)
bad=$(git ls-files -- '*.pyc' '*__pycache__*' || true)
if [ -n "$bad" ]; then
    echo "FAIL: committed __pycache__/.pyc artifacts:"
    echo "$bad"
    exit 1
fi

echo "check.sh: OK"
