#!/usr/bin/env bash
# Repo hygiene check: byte-compile everything and grep-lint the two
# recurring review findings — wall-clock time.time() in span/duration
# timing (r2 verdict: durations must come from perf_counter pairs) and
# bare `except:` clauses (swallow KeyboardInterrupt/SystemExit).
# Run locally or from CI (.github/workflows/ci.yml).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pilosa_tpu tests scripts bench.py

# time.time() is allowed only at the annotated wall-clock sites:
# diagnostics uptime reporting, the tracing span's display-only start
# stamp (durations there come from a perf_counter pair), and the
# _wall_stamp helpers (anti-entropy last-error/last-success stamps, the
# launch ledger + time-series sample stamps — operator display, never
# subtracted; devobs/timeseries durations and interval pacing all come
# from perf_counter).
bad=$(grep -rn "time\.time()" pilosa_tpu bench.py \
    | grep -v "pilosa_tpu/utils/diagnostics.py" \
    | grep -v "self\.start = time\.time()" \
    | grep -v "_wall_stamp" || true)
if [ -n "$bad" ]; then
    echo "FAIL: wall-clock time.time() in timing code (use" \
         "time.perf_counter pairs; see utils/tracing.py):"
    echo "$bad"
    exit 1
fi

# bare `except:` swallows KeyboardInterrupt/SystemExit — name a type.
bad=$(grep -rnE --include="*.py" "except[[:space:]]*:" \
    pilosa_tpu tests scripts bench.py || true)
if [ -n "$bad" ]; then
    echo "FAIL: bare 'except:' clause (name an exception type):"
    echo "$bad"
    exit 1
fi

# Device dispatch must flow through the dispatch batcher (docs/batching.md):
# a direct shard_map-reducer call outside parallel/ bypasses cross-query
# fusion, the queued-deadline drop-out, and the dispatch stats.  Everything
# goes through DispatchBatcher's same-named wrappers (or its explicit
# disabled-mode fallback); only parallel/ touches the executables.
bad=$(grep -rnE --include="*.py" \
    "(mesh|mesh_exec)\.(count_async|count_batch_async|segments|segments_batch|row_counts|bsi_sum|bsi_min_max|group_counts)" \
    pilosa_tpu --exclude-dir=parallel || true)
if [ -n "$bad" ]; then
    echo "FAIL: direct mesh shard_map dispatch outside parallel/ (route" \
         "through executor.batcher — parallel/batcher.py):"
    echo "$bad"
    exit 1
fi

# Metrics-docs lint (docs/observability.md): every stats metric name in
# the tree must appear in the catalog, and every catalog row must match a
# call site — the catalog is the operator's contract, and a dangling row
# or an undocumented series are both drift.  Dynamic f-string segments
# in code and <...> placeholders in the docs both normalize to "*".
python - <<'PYEOF'
import fnmatch
import pathlib
import re
import sys

root = pathlib.Path("pilosa_tpu")
code: set[str] = set()
CALL = re.compile(
    r'[a-z_]*stats\.(?:count|gauge|timing|timer|histogram)\(\s*(f?)"([^"]+)"',
    re.S)
HELPER = re.compile(r"\b_count\(")  # dotted-name prefix helpers
NAME = re.compile(r'"([a-z0-9_]+(?:\.[a-z0-9_{}.]+)+)"')
for path in root.rglob("*.py"):
    text = path.read_text()
    for is_f, name in CALL.findall(text):
        if is_f:
            name = re.sub(r"\{[^}]*\}", "*", name)
        code.add(name)
    for m in HELPER.finditer(text):
        # capture every dotted literal near the helper call (covers
        # conditional-expression names like "a.hit" if ... else "a.miss")
        for name in NAME.findall(text[m.end():m.end() + 160]):
            code.add(re.sub(r"\{[^}]*\}", "*", name))

doc_text = pathlib.Path("docs/observability.md").read_text()
m = re.search(r"<!-- metrics-catalog:begin -->(.*?)"
              r"<!-- metrics-catalog:end -->", doc_text, re.S)
if not m:
    sys.exit("FAIL: docs/observability.md is missing the "
             "metrics-catalog markers")
docs = {re.sub(r"<[^>]*>", "*", n)
        for n in re.findall(r"^\| `([^`]+)`", m.group(1), re.M)}

undocumented = sorted(
    c for c in code if not any(fnmatch.fnmatch(c, d) for d in docs))
dangling = sorted(
    d for d in docs if not any(fnmatch.fnmatch(c, d) for c in code))
if undocumented:
    print("FAIL: metric names missing from the docs/observability.md "
          "catalog:")
    print("  " + "\n  ".join(undocumented))
if dangling:
    print("FAIL: docs/observability.md catalog rows matching no call "
          "site:")
    print("  " + "\n  ".join(dangling))
if undocumented or dangling:
    sys.exit(1)
PYEOF

# Storage durability fast suite (docs/robustness.md "Durability &
# recovery"): the byte-level corruption fuzz (truncate/flip at every
# offset of snapshot+WAL -> recover-or-quarantine, never a crash) and
# the short deterministic 2-cycle kill -9 crash harness.  The 20-cycle
# randomized soak is pytest -m slow.  Compressed-residency codec
# round-trip + compressed-vs-dense differential (docs/memory-budget.md
# "Compressed residency") ride along: a decode bug corrupts query
# results silently, so the differential is hygiene, not a nicety.
# Device-runtime observability (docs/observability.md "Device runtime")
# rides too: the retrace red flag is the alarm for that same decode-bug
# class, so its test is hygiene as well.  The streaming-ingest suite
# (docs/ingest.md) joins them: wire-codec corruption fuzz, the
# ingest-vs-bulk differential, group-commit counting, and the kill -9
# commit-window harness are all acked-durability guarantees.
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' -p no:cacheprovider \
    tests/test_durability.py tests/test_crash.py tests/test_containers.py \
    tests/test_device_obs.py tests/test_ingest.py

# committed bytecode/cache artifacts must never land in the tree
bad=$(git ls-files | grep -E "__pycache__|\.pyc$" || true)
if [ -n "$bad" ]; then
    echo "FAIL: committed __pycache__/.pyc artifacts:"
    echo "$bad"
    exit 1
fi

echo "check.sh: OK"
