#!/usr/bin/env bash
# Repo hygiene check: byte-compile everything and grep-lint the two
# recurring review findings — wall-clock time.time() in span/duration
# timing (r2 verdict: durations must come from perf_counter pairs) and
# bare `except:` clauses (swallow KeyboardInterrupt/SystemExit).
# Run locally or from CI (.github/workflows/ci.yml).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pilosa_tpu tests scripts bench.py

# time.time() is allowed only at the annotated wall-clock sites:
# diagnostics uptime reporting and the tracing span's display-only start
# stamp (durations there come from a perf_counter pair).
bad=$(grep -rn "time\.time()" pilosa_tpu bench.py \
    | grep -v "pilosa_tpu/utils/diagnostics.py" \
    | grep -v "self\.start = time\.time()" || true)
if [ -n "$bad" ]; then
    echo "FAIL: wall-clock time.time() in timing code (use" \
         "time.perf_counter pairs; see utils/tracing.py):"
    echo "$bad"
    exit 1
fi

# bare `except:` swallows KeyboardInterrupt/SystemExit — name a type.
bad=$(grep -rnE --include="*.py" "except[[:space:]]*:" \
    pilosa_tpu tests scripts bench.py || true)
if [ -n "$bad" ]; then
    echo "FAIL: bare 'except:' clause (name an exception type):"
    echo "$bad"
    exit 1
fi

# Device dispatch must flow through the dispatch batcher (docs/batching.md):
# a direct shard_map-reducer call outside parallel/ bypasses cross-query
# fusion, the queued-deadline drop-out, and the dispatch stats.  Everything
# goes through DispatchBatcher's same-named wrappers (or its explicit
# disabled-mode fallback); only parallel/ touches the executables.
bad=$(grep -rnE --include="*.py" \
    "(mesh|mesh_exec)\.(count_async|count_batch_async|segments|segments_batch|row_counts|bsi_sum|bsi_min_max|group_counts)" \
    pilosa_tpu --exclude-dir=parallel || true)
if [ -n "$bad" ]; then
    echo "FAIL: direct mesh shard_map dispatch outside parallel/ (route" \
         "through executor.batcher — parallel/batcher.py):"
    echo "$bad"
    exit 1
fi

# committed bytecode/cache artifacts must never land in the tree
bad=$(git ls-files | grep -E "__pycache__|\.pyc$" || true)
if [ -n "$bad" ]; then
    echo "FAIL: committed __pycache__/.pyc artifacts:"
    echo "$bad"
    exit 1
fi

echo "check.sh: OK"
