"""Core constants and position arithmetic for the TPU-native bitmap index.

The data model mirrors the reference engine exactly (see SURVEY.md §2 and the
reference's ``fragment.go:50-63``, ``shardwidth/20.go``): the column space of an
index is cut into fixed-width *shards* of ``2**20`` columns; a (field, view,
shard) triple is a *fragment*.  Inside a fragment a bit is addressed by
``pos = row_id * SHARD_WIDTH + (col % SHARD_WIDTH)``.

Where the reference stores a fragment as a 64-bit roaring bitmap (adaptive
array/bitmap/run containers, ``roaring/roaring.go:64-69``), this engine stores
it as a dense ``uint32[n_rows, SHARD_WORDS]`` bitset tensor: TPU VPUs operate
on 32-bit lanes natively and ``SHARD_WORDS = 32768 = 256*128`` keeps the minor
dimension a multiple of the 128-wide lane tiling so XLA never pads.
Container-level sparsity collapses to dense tiles in HBM — the round-trip and
branching cost of adaptive representations dwarfs the bandwidth saving on TPU.
"""

from __future__ import annotations

import re

# Shard geometry — compile-time constant, like the reference's build-tag
# selected exponent (shardwidth/20.go: Exponent = 20).
SHARD_WIDTH_EXP = 20
SHARD_WIDTH = 1 << SHARD_WIDTH_EXP

# Bitset word geometry.  The reference uses []uint64; TPU vector units are
# 32-bit, so we use uint32 words.
WORD_BITS = 32
WORD_BITS_EXP = 5
SHARD_WORDS = SHARD_WIDTH // WORD_BITS  # 32768 = 256 * 128

# A roaring "container" covers 2^16 bits (roaring/roaring.go:64); we keep the
# same granularity for block-level bookkeeping (checksums, sparsity masks).
CONTAINER_BITS = 1 << 16
CONTAINER_WORDS = CONTAINER_BITS // WORD_BITS  # 2048
CONTAINERS_PER_SHARD = SHARD_WIDTH // CONTAINER_BITS  # 16

# Anti-entropy block size in rows (fragment.go:81 HashBlockSize = 100).
HASH_BLOCK_SIZE = 100

# Default number of ops buffered in the write-ahead log before a snapshot
# rewrite (fragment.go:84 DefaultFragmentMaxOpN = 10000).
DEFAULT_FRAGMENT_MAX_OP_N = 10000

# Highest row id a fragment will accept (configurable via
# Config.max_row_id / PILOSA_TPU_MAX_ROW_ID).  The dense representation
# allocates n_rows*SHARD_WORDS*4 bytes per fragment, so an unbounded row id
# from a hostile import (rowIDs=[2**40]) would attempt a terabyte-scale
# allocation; the reference is sparse in row space and has no such hazard
# (roaring row keys are just u48 container keys).  2^20 rows caps a single
# fragment's dense worst case at 128 GiB logical — combined with doubling
# growth and sparse snapshots, real indexes stay far below it; raise the
# cap explicitly for wider row spaces.
DEFAULT_MAX_ROW_ID = (1 << 20) - 1

# Reserved existence-field name (index.go: existenceFieldName "_exists").
EXISTENCE_FIELD_NAME = "_exists"

# View name constants (view.go:37-41).
VIEW_STANDARD = "standard"
VIEW_BSI_GROUP_PREFIX = "bsig_"

# Cluster-level partitioning (cluster.go:44 defaultPartitionN).
DEFAULT_PARTITION_N = 256


# Process-wide schema generation counter.  Bumped on any DDL (index/field
# create or delete) and on BSI bit-depth growth; the prepared-statement cache
# (executor/prepared.py) keys its entries to it so a resolved plan is never
# replayed against a changed schema.  Over-invalidation (one counter for all
# holders) only costs a re-prepare.
_schema_epoch = 0


def bump_schema_epoch():
    global _schema_epoch
    _schema_epoch += 1


def schema_epoch() -> int:
    return _schema_epoch


# Process-wide attribute generation counter.  Row/column attributes ride
# query results (Row attrs, Options(columnAttrs)) but live outside the
# fragment stores, so their writes bump no fragment gen; the result cache
# (cache/results.py) keys entries to this counter instead so an attr write
# invalidates structurally like any other mutation.
_attr_epoch = 0


def bump_attr_epoch():
    global _attr_epoch
    _attr_epoch += 1


def attr_epoch() -> int:
    return _attr_epoch


_NAME_RE = re.compile(r"[a-z][a-z0-9_-]*")


def validate_name(name: str, kind: str = "name") -> str:
    """Index/field name rule (reference pilosa.go validateName:
    ^[a-z][a-z0-9_-]*$, max 64 chars)."""
    if not _NAME_RE.fullmatch(name) or len(name) > 64:
        raise ValueError(f"invalid {kind}: {name!r}")
    return name


def pos(row_id: int, col: int) -> int:
    """Bit position of (row, column) inside the column's shard
    (fragment.go:3087-3092)."""
    return (row_id << SHARD_WIDTH_EXP) + (col & (SHARD_WIDTH - 1))


def shard_of(col: int) -> int:
    """Which shard a column id falls in."""
    return col >> SHARD_WIDTH_EXP


def col_in_shard(col: int) -> int:
    """Column offset within its shard."""
    return col & (SHARD_WIDTH - 1)
