"""CLI: pilosa-tpu server|import|export|check|inspect|generate-config
(reference cmd/root.go + ctl/).

Run as `python -m pilosa_tpu <command>`.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import urllib.request


def _http(method: str, url: str, body: bytes | None = None,
          ctype: str = "application/json",
          ok_codes: tuple[int, ...] = ()) -> dict:
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", ctype)
    try:
        with urllib.request.urlopen(req) as resp:
            data = resp.read()
    except urllib.error.HTTPError as e:
        if e.code in ok_codes:
            return {}
        raise SystemExit(f"error: {e.code} {e.read().decode().strip()}")
    return json.loads(data) if data.strip() else {}


def _base_url(host: str) -> str:
    """--host may be bare (``node:10101``) or carry a scheme
    (``https://node:10101`` for TLS clusters); normalize to a base URL."""
    host = str(host)
    scheme, _, bare = host.rpartition("://")
    return f"{scheme or 'http'}://{bare}"


def cmd_server(args) -> int:
    """(ctl/server.go + server/server.go Command.Start)"""
    from .server.server import Config, Server

    overrides = dict(data_dir=args.data_dir, bind=args.bind,
                     replica_n=args.replicas, node_id=args.node_id)
    if args.cluster_hosts:
        overrides["cluster_hosts"] = args.cluster_hosts.split(",")
    if args.config:
        cfg = Config.from_toml(args.config, **overrides)
    else:
        cfg = Config.from_env(**overrides)
    srv = Server(cfg)
    srv.open()
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    srv.logger.info("shutting down")
    srv.close()
    return 0


def cmd_import(args) -> int:
    """CSV import: row,col[,timestamp] or col,value for -field-type=int
    (ctl/import.go:44-399)."""
    base = _base_url(args.host)
    if args.create:
        # 409 (already exists) is success for --create ("if missing")
        _http("POST", f"{base}/index/{args.index}",
              json.dumps({}).encode(), ok_codes=(409,))
        opts = {}
        if args.field_type == "int":
            opts = {"type": "int", "min": args.min, "max": args.max}
        elif args.field_type == "time":
            opts = {"type": "time", "timeQuantum": args.time_quantum}
        _http("POST", f"{base}/index/{args.index}/field/{args.field}",
              json.dumps({"options": opts}).encode(), ok_codes=(409,))

    url = f"{base}/index/{args.index}/field/{args.field}/import"
    total = 0
    rows, cols, vals, tss = [], [], [], []

    def flush():
        nonlocal rows, cols, vals, tss, total
        if not cols:
            return
        if args.field_type == "int":
            payload = {"columnIDs": cols, "values": vals}
            if args.clear:
                payload["clear"] = True
                payload.pop("values")
        else:
            payload = {"rowIDs": rows, "columnIDs": cols}
            if any(tss):
                payload["timestamps"] = tss
            if args.clear:
                payload["clear"] = True
        _http("POST", url, json.dumps(payload).encode())
        total += len(cols)
        rows, cols, vals, tss = [], [], [], []

    files = args.files or ["-"]
    for path in files:
        fh = sys.stdin if path == "-" else open(path)
        for line in fh:
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if args.field_type == "int":
                cols.append(int(parts[0]))
                vals.append(int(parts[1]))
            else:
                rows.append(int(parts[0]))
                cols.append(int(parts[1]))
                tss.append(int(parts[2]) if len(parts) > 2 else 0)
            if len(cols) >= args.batch_size:
                flush()
        if fh is not sys.stdin:
            fh.close()
    flush()
    print(f"imported {total} records into {args.index}/{args.field}")
    return 0


def cmd_ingest(args) -> int:
    """Stream a CSV/TSV file to the binary ingest endpoint
    (docs/ingest.md): lines of ``row,col[,ts]`` (or ``col,value`` with
    --field-type=int) are packed into length-prefixed CRC frames
    (ingest/wire.py) and POSTed in bounded batches.  503 responses honor
    Retry-After and resend the batch — frames are idempotent set
    bits/values, so a resend after a mid-stream failure is safe.  A
    progress line (records/s, MB/s, retries) goes to stderr."""
    import time as _time
    import urllib.error

    from .ingest import wire

    base = _base_url(args.host)
    if args.create:
        _http("POST", f"{base}/index/{args.index}",
              json.dumps({}).encode(), ok_codes=(409,))
        opts = {}
        if args.field_type == "int":
            opts = {"type": "int"}
        elif args.field_type == "time":
            opts = {"type": "time", "timeQuantum": args.time_quantum}
        _http("POST", f"{base}/index/{args.index}/field/{args.field}",
              json.dumps({"options": opts}).encode(), ok_codes=(409,))

    url = f"{base}/index/{args.index}/field/{args.field}/ingest"
    total = total_bytes = retries = 0
    t0 = _time.perf_counter()
    a_buf: list[int] = []
    b_buf: list[int] = []
    ts_buf: list[int] = []

    def progress(final=False):
        dt = max(_time.perf_counter() - t0, 1e-9)
        line = (f"\r{total} records  {total / dt:,.0f} rec/s  "
                f"{total_bytes / dt / 1e6:.1f} MB/s  retries {retries}")
        print(line + ("\n" if final else ""), end="", file=sys.stderr,
              flush=True)

    def send():
        nonlocal total, total_bytes, retries, a_buf, b_buf, ts_buf
        if not b_buf:
            return
        if args.field_type == "int":
            body = wire.encode_records(None, a_buf, values=b_buf)
        else:
            ts = ts_buf if any(ts_buf) else None
            body = wire.encode_records(a_buf, b_buf, ts=ts)
        for attempt in range(args.max_retries + 1):
            req = urllib.request.Request(url, data=body, method="POST")
            req.add_header("Content-Type", "application/octet-stream")
            if args.tenant:
                # explicit tenant token (docs/robustness.md "Tenant
                # isolation"): the stream rides that tenant's ingest
                # admission queue instead of the index-derived one
                req.add_header("X-Pilosa-Tpu-Tenant", args.tenant)
            try:
                with urllib.request.urlopen(req) as resp:
                    resp.read()
                break
            except urllib.error.HTTPError as e:
                e.read()
                if e.code != 503 or attempt >= args.max_retries:
                    raise SystemExit(
                        f"\ningest: {e.code} {e.reason}")
                retries += 1
                try:
                    wait = float(e.headers.get("Retry-After") or 1)
                except (TypeError, ValueError):
                    wait = 1.0
                _time.sleep(min(wait, 30.0))
            except (urllib.error.URLError, ConnectionError) as e:
                # a dropped connection mid-batch is retryable too: the
                # server only acks after its group commit, and frames
                # are idempotent — resending cannot double-apply
                if attempt >= args.max_retries:
                    raise SystemExit(f"\ningest: {e}")
                retries += 1
                _time.sleep(1.0)
        total += len(b_buf)
        total_bytes += len(body)
        a_buf, b_buf, ts_buf = [], [], []
        progress()

    files = args.files or ["-"]
    for path in files:
        fh = sys.stdin if path == "-" else open(path)
        sep = None  # sniffed per file: TSV if the first line has a tab
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if sep is None:
                sep = "\t" if "\t" in line else ","
            parts = line.split(sep)
            if args.field_type == "int":
                a_buf.append(int(parts[0]))   # col
                b_buf.append(int(parts[1]))   # value
            else:
                a_buf.append(int(parts[0]))   # row
                b_buf.append(int(parts[1]))   # col
                ts_buf.append(int(parts[2]) if len(parts) > 2 else 0)
            if len(b_buf) >= args.batch_size:
                send()
        if fh is not sys.stdin:
            fh.close()
    send()
    progress(final=True)
    print(f"ingested {total} records into {args.index}/{args.field}")
    return 0


def cmd_export(args) -> int:
    """(ctl/export.go:35-112).  Each shard is fetched from a node that
    OWNS it (ctl/export.go fragment-nodes routing) — a single-host fetch
    would silently miss shards placed on other cluster nodes."""
    base = _base_url(args.host)
    scheme = base.split("://", 1)[0]
    maxes = _http("GET", f"{base}/internal/shards/max")["standard"]
    max_shard = maxes.get(args.index, 0)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    for shard in range(max_shard + 1):
        nodes = _http("GET", f"{base}/internal/fragment/nodes"
                             f"?index={args.index}&shard={shard}")
        hosts = [n["uri"] for n in nodes if n.get("uri")] or [args.host]
        last_err = None
        for host in hosts:  # replica failover: any live owner serves
            # node URIs may already carry a scheme (TLS clusters); bare
            # hosts inherit the scheme used for args.host
            h_scheme, _, h_bare = str(host).rpartition("://")
            url = (f"{h_scheme or scheme}://{h_bare}"
                   f"/export?index={args.index}"
                   f"&field={args.field}&shard={shard}")
            try:
                with urllib.request.urlopen(
                        urllib.request.Request(url)) as resp:
                    out.write(resp.read().decode())
                last_err = None
                break
            except OSError as e:
                last_err = e
        if last_err is not None:
            print(f"export: shard {shard}: no reachable owner "
                  f"({last_err})", file=sys.stderr)
            if out is not sys.stdout:
                out.close()
            return 1
    if out is not sys.stdout:
        out.close()
    return 0


import contextlib


@contextlib.contextmanager
def _fail_stop_opens():
    """Offline check/inspect must REPORT corruption, not quarantine it:
    disable quarantine-on-corruption (and its sidecar-marker side
    effect) for the duration so a bad file raises like it always did."""
    from .storage import fragment as fragment_mod

    prev = fragment_mod.QUARANTINE_ON_CORRUPTION
    fragment_mod.QUARANTINE_ON_CORRUPTION = False
    try:
        yield
    finally:
        fragment_mod.QUARANTINE_ON_CORRUPTION = prev


def cmd_check(args) -> int:
    """Offline fragment file integrity check (ctl/check.go:28-135)."""
    import numpy as np

    from .core import SHARD_WORDS
    from .storage.fragment import Fragment

    ok = True
    with _fail_stop_opens():
        for path in args.files:
            if path.endswith(".wal"):
                continue
            try:
                frag = Fragment(path, "check", "check", "check", 0)
                n = int(np.unique(frag._idx // SHARD_WORDS).size)
                print(f"{path}: OK rows_with_data={n}")
                frag.close()
            except Exception as e:
                ok = False
                print(f"{path}: CORRUPT {e}")
    return 0 if ok else 1


def cmd_analyze(args) -> int:
    """Run the project invariant analyzer over a checkout
    (docs/static-analysis.md) — the same suite scripts/check.sh and CI
    run: AST lint rules plus the cross-file metric/failpoint catalogs.
    Exits non-zero on any finding."""
    from .analysis.astlint import main as analysis_main
    argv = ["--root", args.root]
    for r in args.rule or []:
        argv += ["--rule", r]
    if args.list_rules:
        argv.append("--list-rules")
    return analysis_main(argv)


def cmd_inspect(args) -> int:
    """Fragment stats (ctl/inspect.go:30-110)."""
    import numpy as np

    from .core import SHARD_WORDS
    from .storage.fragment import Fragment

    with _fail_stop_opens():
        for path in args.files:
            frag = Fragment(path, "inspect", "inspect", "inspect", 0)
            n_bits = int(np.bitwise_count(frag._val).sum())
            rows_used = int(np.unique(frag._idx // SHARD_WORDS).size)
            total_bits = frag.n_rows * SHARD_WORDS * 32
            density = n_bits / total_bits if total_bits else 0.0
            print(json.dumps({
                "path": path, "rows": frag.n_rows,
                "rowsWithData": rows_used,
                "bits": n_bits, "density": round(density, 6),
                "sizeBytes": frag.host_bytes(),
            }))
            frag.close()
    return 0


def _top_cluster(args) -> int:
    """``top --cluster``: poll /debug/cluster and render the fleet —
    per-node qps/p99/HBM/hedges with staleness flags plus the tail of
    the merged event timeline (docs/observability.md "Cluster
    plane")."""
    import time as _time

    base = _base_url(args.host)
    mb = 1 << 20
    polls = 0
    try:
        while True:
            c = _http("GET", f"{base}/debug/cluster")
            nodes = c.get("nodes") or {}
            print(f"-- pilosa-tpu fleet @ {args.host}  "
                  f"coordinator {c.get('coordinator')}  "
                  f"epoch {c.get('epoch')}  "
                  f"overlay {c.get('overlayEpoch')}")
            print(f"   {'node':<8} {'state':<8} {'qps':>7} {'p99ms':>8} "
                  f"{'hbmMB':>7} {'evict':>6} {'retrc':>6} "
                  f"{'hedges':>8} {'waves':>6} {'quar':>5} {'stale':>6}")
            for nid in sorted(nodes):
                n = nodes[nid]
                stale = "-" if not n.get("stale") else (
                    f"{n['staleS']:.0f}s" if n.get("staleS") is not None
                    else "?")
                p99 = n.get("p99Ms")
                print(f"   {nid:<8} {n.get('state', '?'):<8} "
                      f"{n.get('qps', 0):>7.1f} "
                      f"{p99 if p99 is not None else '-':>8} "
                      f"{n.get('hbmResidentBytes', 0) // mb:>7} "
                      f"{n.get('evictions', '-'):>6} "
                      f"{n.get('retraces', '-'):>6} "
                      f"{str(n.get('hedges', '-')) + '/' + str(n.get('hedgeWins', '-')):>8} "
                      f"{n.get('retryWaves', '-'):>6} "
                      f"{n.get('quarantinedFragments', '-'):>5} "
                      f"{stale:>6}")
            tail = (c.get("timeline") or [])[-args.events:] \
                if args.events > 0 else []
            if tail:
                print("   -- recent events")
                for e in tail:
                    extra = " ".join(
                        f"{k}={v}" for k, v in e.items()
                        if k not in ("event", "node", "wall", "seq"))
                    print(f"   {e.get('node', '?'):<8} "
                          f"{e.get('event')} {extra}")
            polls += 1
            if args.count and polls >= args.count:
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_top(args) -> int:
    """Live terminal summary of one node: poll /debug/timeseries +
    /debug/vars and render qps, p99, the HBM split, evictions/s, and
    compile/retrace counts — the operator loop for a box with no
    Prometheus attached (docs/observability.md "Device runtime").
    ``--cluster`` renders the whole fleet from /debug/cluster
    instead."""
    import time as _time

    if args.cluster:
        return _top_cluster(args)
    base = _base_url(args.host)
    mb = 1 << 20
    polls = 0
    prev_retraces = None
    try:
        while True:
            v = _http("GET", f"{base}/debug/vars")
            ts = _http("GET", f"{base}/debug/timeseries")
            samples = ts.get("samples") or []
            last = samples[-1] if samples else {}
            dt = ts.get("intervalS") or 1.0
            qps = last.get("httpQueriesDelta", 0) / dt
            evs = last.get("evictionsDelta", 0) / dt
            p99 = (v.get("timings", {}).get("http.query") or {}).get("p99")
            p99s = f"{p99 * 1e3:.1f}" if p99 is not None else "-"
            bud = v.get("deviceBudget", {})
            dev = v.get("device", {})
            comp = dev.get("compiles", {})
            lau = dev.get("launches", {})
            adm = (v.get("admission") or {}).get("public", {})
            bat = v.get("dispatchBatcher") or {}
            retr = comp.get("retraces", 0)
            flag = ""
            if prev_retraces is not None and retr > prev_retraces:
                # the PR-7-class red flag, front and center
                flag = f"  !! +{retr - prev_retraces} RETRACE"
            prev_retraces = retr
            print(f"-- pilosa-tpu top @ {args.host}  "
                  f"up {last.get('uptimeS', '-')}s  "
                  f"({len(samples)} samples x {dt}s)")
            print(f"   qps {qps:.1f}  p99 {p99s}ms  "
                  f"inflight {adm.get('inUse', 0)}  "
                  f"waiting {adm.get('waiting', 0)}  "
                  f"batcher queued {bat.get('queued', 0)}")
            print(f"   hbm {bud.get('residentBytes', 0) // mb}MB resident"
                  f" ({bud.get('compressedBytes', 0) // mb}MB compressed"
                  f" / {bud.get('denseBytes', 0) // mb}MB dense"
                  f" / {bud.get('pinnedBytes', 0) // mb}MB pinned)  "
                  f"evictions/s {evs:.2f}")
            # compile-s/interval: the ring's device.compile delta —
            # deploys are visibly cheap (warm) or visibly not (cold)
            comp_s = last.get("compileSDelta", 0.0)
            print(f"   device: compiles {comp.get('compiles', 0)}  "
                  f"retraces {retr}{flag}  "
                  f"compile-s/int {comp_s:.2f}  "
                  f"launches {lau.get('launches', 0)}  "
                  f"padding {100 * lau.get('paddingWasteRatio', 0):.1f}%  "
                  f"decode peak {lau.get('decodePeakBytes', 0) // mb}MB")
            # container-kernel plane: the resolved backend rides the
            # device.kernel_backend 0/1 gauge (1 = pallas)
            kb = (v.get("gauges") or {}).get("device.kernel_backend")
            print(f"   kernels: backend "
                  f"{'-' if kb is None else 'pallas' if kb else 'jnp'}  "
                  f"launches {lau.get('kernelLaunches', 0)}  "
                  f"tiles {lau.get('kernelTiles', 0)}")
            active = (v.get("alerts") or {}).get("active") or {}
            if active:
                print("   !! ALERTS: " + "  ".join(
                    f"{aid}[{a.get('severity')}]"
                    for aid, a in sorted(active.items())))
            warm = v.get("warmup") or {}
            if warm.get("phase") == "warming":
                print(f"   WARMING: {warm.get('replayed', 0)}"
                      f"/{warm.get('planned', 0)} replayed  "
                      f"errors {warm.get('errors', 0)}  "
                      f"budget {warm.get('budgetS', 0)}s")
            # per-peer routing load (docs/cluster.md "Read routing &
            # rebalancing"): EWMA RTT, in-flight depth, breaker state
            routing = (v.get("cluster") or {}).get("routing") or {}
            for nid, pr in sorted((routing.get("peers") or {}).items()):
                rtt = pr.get("ewmaRttMs")
                print(f"   peer {nid}: "
                      f"rtt {rtt if rtt is not None else '-'}ms  "
                      f"inflight {pr.get('inFlight', 0)}"
                      f"+{pr.get('reportedInFlight', 0)}  "
                      f"queued {pr.get('reportedQueued', 0)}  "
                      f"dispatches {pr.get('dispatches', 0)}"
                      f"{'  BREAKER-OPEN' if pr.get('breakerOpen') else ''}"
                      f"{'  DOWN' if pr.get('state') == 'DOWN' else ''}")
            polls += 1
            if args.count and polls >= args.count:
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_alerts(args) -> int:
    """Render /debug/alerts: objectives, burn-rate windows, the active
    alert table, and recent fire/resolve transitions
    (docs/observability.md "SLOs & alerting")."""
    base = _base_url(args.host)
    a = _http("GET", f"{base}/debug/alerts")
    if not a.get("enabled"):
        print("alert evaluation disabled (alert-rules = \"off\" "
              "or the time-series sampler is off)")
        return 0
    w = a.get("windows") or {}
    print(f"-- pilosa-tpu alerts @ {args.host}  "
          f"target {a.get('target')}  "
          f"latency-slo {a.get('latencyMs')}ms  "
          f"burn >{a.get('burnThreshold')}x  "
          f"windows {w.get('fastS')}s/{w.get('slowS')}s")
    print(f"   evaluations {a.get('evaluations', 0)}  "
          f"fired {a.get('firedTotal', 0)}  "
          f"resolved {a.get('resolvedTotal', 0)}")
    active = a.get("active") or {}
    if not active:
        print("   no active alerts")
    for aid, al in sorted(active.items()):
        print(f"   ACTIVE [{al.get('severity')}] {aid}  "
              f"for {al.get('durationS', 0):.0f}s  "
              f"{al.get('detail', '')}")
    hist = (a.get("history") or [])[-args.history:]
    if hist:
        import time as _time
        print("   -- recent transitions")
        for h in hist:
            when = _time.strftime("%H:%M:%S",
                                  _time.localtime(h.get("wall", 0)))
            extra = h.get("detail", "") \
                if h.get("action") == "fire" else ""
            print(f"   {when} {h.get('action'):<7} "
                  f"[{h.get('severity')}] {h.get('id')}  {extra}")
    rec = a.get("flightRecorder")
    if rec:
        last = rec.get("last") or {}
        print(f"   flight recorder: {rec.get('captures', 0)} bundles  "
              f"{rec.get('diskBytes', 0) >> 20}MB"
              f"/{rec.get('budgetMb', 0)}MB"
              + (f"  last {last.get('path')}" if last else ""))
    return 0


def cmd_bundle(args) -> int:
    """POST /debug/bundle: capture an on-demand flight-recorder
    diagnostic bundle and print where it landed."""
    base = _base_url(args.host)
    out = _http("POST", f"{base}/debug/bundle",
                json.dumps({"reason": args.reason}).encode())
    last = out.get("last") or {}
    print(f"bundle written: {out.get('path')} "
          f"({last.get('bytes', 0) >> 10} KiB)")
    return 0


DEFAULT_CONFIG = """\
# pilosa-tpu configuration
data-dir = "~/.pilosa_tpu"
bind = "localhost:10101"
max-op-n = 10000
# max-body-mb = 1024
# compressed residency (docs/memory-budget.md)
# compressed-resident = true   # sparse fragments stay HBM-resident as
#                              # packed container streams under a
#                              # device-budget limit
# compress-max-density = 0.5   # dense fallback: compress only below
#                              # this fraction of the dense footprint
# decode-workspace-mb = 1024   # per-launch dense decode ceiling
#                              # (bounds the jnp backend only)
# container-kernels = "auto"   # container decode backend: auto = fused
#                              # Pallas kernels on TPU, jnp elsewhere;
#                              # "jnp" is the kill switch
# cross-query dynamic batching (docs/batching.md)
# dispatch-batch = true         # fuse compatible in-flight queries
# dispatch-batch-max = 32       # queries per fused device launch
# dispatch-batch-window-us = 200  # max solo wait for batch company
# whole-query pjit programs (docs/whole-query.md)
# whole-query = true            # one compiled program per read request
# whole-query-fallback = "legacy"  # or "error": raise instead of
#                               # rerouting unsupported shapes
# streaming ingest (docs/ingest.md)
# ingest-flush-ms = 50     # group-commit window: one WAL frame + one gen
#                          # bump per fragment per flush
# ingest-delta-mb = 64     # device delta-overlay journal budget, 0 = off
# ingest-max-frame-mb = 32 # per-frame ceiling on the ingest wire
# query cache subsystem (docs/caching.md)
# result-cache-mb = 256    # generation-keyed result cache budget, 0 = off
# rank-rebuild-rows = 4096 # incremental rank-cache ceiling per batch
# overload armor (docs/robustness.md)
# query-timeout = 0        # default per-query deadline seconds, 0 = off
# max-queries = 64         # concurrent-query slots (public + internal)
# queue-timeout = 0.5      # seconds to wait for a slot before 503
# breaker-threshold = 5    # consecutive peer failures -> circuit open
# drain-seconds = 5        # graceful-drain budget on shutdown
# tail-tolerant reads (docs/robustness.md "Tail-tolerant fan-out")
# hedge-reads = true       # speculative duplicate of straggling read
#                          # RPCs; first answer wins, writes never hedge
# hedge-delay-ms = 0       # 0 = derive from the router's EWMA RTT
# partial-results = false  # server default for ?partialResults: serve
#                          # reads with unservable shards, naming the
#                          # missing shards in the degraded object
# internal-wire = "bin1"   # /internal/query transport: PTPUQRY1 framed
#                          # binary (roaring-packed segments), per-peer
#                          # negotiated; "json" restores the JSON
#                          # envelope exactly (docs/cluster.md)
# durability & recovery (docs/robustness.md)
# wal-crc = true           # CRC-frame new WAL files (torn-tail recovery)
# quarantine-on-corruption = true  # corrupt fragment -> quarantine +
#                          # replica repair instead of failing startup
# repair-interval = 60     # seconds between quarantine-repair sweeps
# observability (docs/observability.md)
# slow-query-threshold = 1 # seconds before a query lands in /debug/slow
# slow-log-size = 128      # slow-query ring-buffer entries
# slow-log-text-max = 512  # query-text chars stored per slow entry
#                          # (over-ceiling entries marked textTruncated)
# profile-default = false  # profile tree on every response, not just
#                          # ?profile=true
# trace-sample-rate = 1.0  # fraction of traces recorded (cluster-wide)
# timeseries-interval = 5  # seconds between /debug/timeseries samples,
#                          # 0 = sampler off
# timeseries-window = 600  # seconds of history the time-series ring keeps
# launch-ledger-size = 256 # /debug/launches ring entries
# event-journal-size = 512 # /debug/events ring entries (breaker/node/
#                          # quarantine/overlay/resize transitions)
# event-log = false        # persist the journal to <data-dir>/events.log
#                          # (length+CRC framed JSON records)
# batch-temp-mb = 4096     # per-launch batch-temp workspace for fused
#                          # [B, rows, W] row_counts/TopN device temps
# SLOs & alerting (docs/observability.md "SLOs & alerting")
# slo-latency-ms = 500     # latency objective: queries over this are
#                          # SLO-bad for the burn-rate evaluator
# slo-target = 0.999       # good-fraction objective for availability
#                          # and latency SLOs
# alert-rules = "all"      # "all", "off", or a comma list of rule ids
#                          # (catalog in docs/observability.md)
# flight-recorder-mb = 64  # on-alert diagnostic bundle disk budget
#                          # under <data-dir>/flightrec, 0 = off
# warm start (docs/warmup.md)
# compile-cache-dir = ""   # persistent XLA compile cache; "" =
#                          # <data-dir>/.compile-cache, "off" disables
# compile-cache-mb = 256   # cache size bound, LRU-pruned; 0 = unbounded
# warmup-top-n = 32        # corpus signatures replayed before READY,
#                          # 0 = no warmup replay
# warmup-budget-s = 30     # wall-clock budget for the warmup replay

# elastic serving (docs/cluster.md "Read routing & rebalancing")
# read-routing = "loaded"  # or "primary" (pin to jump-hash primary),
#                          # "round-robin"
# residency-routing = true # prefer the replica holding the shard
#                          # HBM-resident / host-staged
# balancer = false         # hot-shard handoffs (coordinator-driven,
#                          # epoch-gated placement overlay)
# balancer-interval = 30   # seconds between balancer ticks
# hot-shard-threshold = 4  # hot = this multiple of the mean shard load

[cluster]
# hosts = ["localhost:10101", "localhost:10102"]
replicas = 1

[anti-entropy]
interval = 600
"""


def cmd_generate_config(args) -> int:
    print(DEFAULT_CONFIG, end="")
    return 0


def cmd_config(args) -> int:
    """Print the RESOLVED configuration after the TOML < env < flag
    cascade (reference `pilosa config`, cmd/config.go)."""
    from .server.server import Config

    cfg = Config.from_toml(args.config) if args.config else \
        Config.from_env()
    q = json.dumps  # JSON string syntax is valid TOML basic-string syntax
    print(f"data-dir = {q(cfg.data_dir)}")
    print(f"bind = {q(cfg.bind)}")
    print(f"max-op-n = {cfg.max_op_n}")
    print(f"max-row-id = {cfg.max_row_id}")
    print(f"use-mesh = {str(cfg.use_mesh).lower()}")
    print(f"dispatch-batch = {str(cfg.dispatch_batch).lower()}")
    print(f"dispatch-batch-max = {cfg.dispatch_batch_max}")
    print(f"dispatch-batch-window-us = {cfg.dispatch_batch_window_us}")
    print(f"whole-query = {str(cfg.whole_query).lower()}")
    print(f"whole-query-fallback = {q(cfg.whole_query_fallback)}")
    print(f"device-budget-mb = {cfg.device_budget_mb}")
    print(f"compressed-resident = {str(cfg.compressed_resident).lower()}")
    print(f"compress-max-density = {cfg.compress_max_density}")
    print(f"decode-workspace-mb = {cfg.decode_workspace_mb}")
    print(f"container-kernels = {q(cfg.container_kernels)}")
    print(f"ingest-flush-ms = {cfg.ingest_flush_ms}")
    print(f"ingest-delta-mb = {cfg.ingest_delta_mb}")
    print(f"ingest-max-frame-mb = {cfg.ingest_max_frame_mb}")
    print(f"max-body-mb = {cfg.max_body_mb}")
    print(f"result-cache-mb = {cfg.result_cache_mb}")
    print(f"rank-rebuild-rows = {cfg.rank_rebuild_rows}")
    print(f"query-timeout = {cfg.query_timeout}")
    print(f"max-queries = {cfg.max_queries}")
    print(f"queue-timeout = {cfg.queue_timeout}")
    print(f"breaker-threshold = {cfg.breaker_threshold}")
    print(f"drain-seconds = {cfg.drain_seconds}")
    print(f"health-down-threshold = {cfg.health_down_threshold}")
    print(f"hedge-reads = {str(cfg.hedge_reads).lower()}")
    print(f"hedge-delay-ms = {cfg.hedge_delay_ms}")
    print(f"partial-results = {str(cfg.partial_results).lower()}")
    print(f"internal-wire = {q(cfg.internal_wire)}")
    print(f"read-routing = {q(cfg.read_routing)}")
    print(f"residency-routing = {str(cfg.residency_routing).lower()}")
    print(f"balancer = {str(cfg.balancer).lower()}")
    print(f"balancer-interval = {cfg.balancer_interval}")
    print(f"hot-shard-threshold = {cfg.hot_shard_threshold}")
    print(f"wal-crc = {str(cfg.wal_crc).lower()}")
    print(f"quarantine-on-corruption = "
          f"{str(cfg.quarantine_on_corruption).lower()}")
    print(f"repair-interval = {cfg.repair_interval}")
    print(f"slow-query-threshold = {cfg.slow_query_threshold}")
    print(f"slow-log-size = {cfg.slow_log_size}")
    print(f"slow-log-text-max = {cfg.slow_log_text_max}")
    print(f"profile-default = {str(cfg.profile_default).lower()}")
    print(f"trace-sample-rate = {cfg.trace_sample_rate}")
    print(f"timeseries-interval = {cfg.timeseries_interval}")
    print(f"timeseries-window = {cfg.timeseries_window}")
    print(f"launch-ledger-size = {cfg.launch_ledger_size}")
    print(f"event-journal-size = {cfg.event_journal_size}")
    print(f"event-log = {str(cfg.event_log).lower()}")
    print(f"batch-temp-mb = {cfg.batch_temp_mb}")
    print(f"slo-latency-ms = {cfg.slo_latency_ms}")
    print(f"slo-target = {cfg.slo_target}")
    print(f"alert-rules = {q(cfg.alert_rules)}")
    print(f"flight-recorder-mb = {cfg.flight_recorder_mb}")
    print()
    print("[cluster]")
    print(f"hosts = [{', '.join(q(h) for h in cfg.cluster_hosts)}]")
    print(f"replicas = {cfg.replica_n}")
    print()
    print("[anti-entropy]")
    print(f"interval = {cfg.anti_entropy_interval}")
    if cfg.tls_certificate:
        print()
        print("[tls]")
        print(f"certificate = {q(cfg.tls_certificate)}")
        print(f"key = {q(cfg.tls_key)}")
        if cfg.tls_ca_certificate:
            print(f"ca-certificate = {q(cfg.tls_ca_certificate)}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pilosa-tpu",
        description="TPU-native distributed bitmap index")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("server", help="run a server node")
    sp.add_argument("-c", "--config", help="TOML config file")
    sp.add_argument("-d", "--data-dir", default=None)
    sp.add_argument("-b", "--bind", default=None)
    sp.add_argument("--cluster-hosts", default=None,
                    help="comma-separated host:port list (multi-node)")
    sp.add_argument("--node-id", default=None)
    sp.add_argument("--replicas", type=int, default=None)
    sp.set_defaults(fn=cmd_server)

    sp = sub.add_parser("import", help="bulk-import CSV")
    sp.add_argument("-host", default="localhost:10101")
    sp.add_argument("-i", "--index", required=True)
    sp.add_argument("-f", "--field", required=True)
    sp.add_argument("--create", action="store_true",
                    help="create index/field if missing")
    sp.add_argument("--field-type", default="set",
                    choices=["set", "int", "time"])
    sp.add_argument("--min", type=int, default=0)
    sp.add_argument("--max", type=int, default=2 ** 32)
    sp.add_argument("--time-quantum", default="YMD")
    sp.add_argument("--clear", action="store_true")
    sp.add_argument("--batch-size", type=int, default=100_000,
                    help="records per import request (ctl/import.go "
                         "importBufferSize)")
    sp.add_argument("files", nargs="*")
    sp.set_defaults(fn=cmd_import)

    sp = sub.add_parser("ingest",
                        help="stream CSV/TSV to the binary ingest "
                             "endpoint")
    sp.add_argument("-host", default="localhost:10101")
    sp.add_argument("-i", "--index", required=True)
    sp.add_argument("-f", "--field", required=True)
    sp.add_argument("--create", action="store_true",
                    help="create index/field if missing")
    sp.add_argument("--field-type", default="set",
                    choices=["set", "int", "time"])
    sp.add_argument("--time-quantum", default="YMD")
    sp.add_argument("--batch-size", type=int, default=200_000,
                    help="records per POST (each POST is one framed "
                         "stream; 503s resend the whole batch)")
    sp.add_argument("--max-retries", type=int, default=8,
                    help="503 retries per batch before giving up")
    sp.add_argument("--tenant", default="",
                    help="explicit tenant token sent as "
                         "X-Pilosa-Tpu-Tenant (default: the server "
                         "derives the tenant from the index name)")
    sp.add_argument("files", nargs="*")
    sp.set_defaults(fn=cmd_ingest)

    sp = sub.add_parser("export", help="export a field as CSV")
    sp.add_argument("-host", default="localhost:10101")
    sp.add_argument("-i", "--index", required=True)
    sp.add_argument("-f", "--field", required=True)
    sp.add_argument("-o", "--output", default="-")
    sp.set_defaults(fn=cmd_export)

    sp = sub.add_parser("check", help="check fragment file integrity")
    sp.add_argument("files", nargs="+")
    sp.set_defaults(fn=cmd_check)

    sp = sub.add_parser("inspect", help="inspect fragment file stats")
    sp.add_argument("files", nargs="+")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("analyze",
                        help="run the project invariant analyzer "
                             "(AST lint suite) over a checkout")
    sp.add_argument("--root", default=".",
                    help="repo checkout to analyze (default: cwd)")
    sp.add_argument("--rule", action="append", default=None,
                    help="run only this rule id (repeatable)")
    sp.add_argument("--list-rules", action="store_true")
    sp.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser("top", help="live terminal summary of a node")
    sp.add_argument("-host", default="localhost:10101")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="seconds between polls")
    sp.add_argument("--count", type=int, default=0,
                    help="polls before exiting (0 = forever)")
    sp.add_argument("--cluster", action="store_true",
                    help="render the fleet rollup (/debug/cluster): "
                         "per-node summaries + merged event timeline")
    sp.add_argument("--events", type=int, default=8,
                    help="timeline entries shown per --cluster poll")
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("alerts",
                        help="show the SLO engine's alert state "
                             "(/debug/alerts)")
    sp.add_argument("-host", default="localhost:10101")
    sp.add_argument("--history", type=int, default=16,
                    help="recent fire/resolve transitions shown")
    sp.set_defaults(fn=cmd_alerts)

    sp = sub.add_parser("bundle",
                        help="capture an on-demand flight-recorder "
                             "diagnostic bundle (POST /debug/bundle)")
    sp.add_argument("-host", default="localhost:10101")
    sp.add_argument("--reason", default="manual",
                    help="reason tag embedded in the bundle filename")
    sp.set_defaults(fn=cmd_bundle)

    sp = sub.add_parser("generate-config", help="print default config")
    sp.set_defaults(fn=cmd_generate_config)

    sp = sub.add_parser("config",
                        help="print the resolved configuration")
    sp.add_argument("-c", "--config", help="TOML config file")
    sp.set_defaults(fn=cmd_config)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # stdout piped into a closed reader (e.g. `| head`): standard
        # CLI behavior is to exit quietly
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
