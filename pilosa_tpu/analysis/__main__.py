import sys

from .astlint import main

sys.exit(main())
