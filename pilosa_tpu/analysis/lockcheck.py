"""Runtime lock-order race detector (docs/static-analysis.md).

The PR 9 committer race — two inline flushers acking a WAL sequence
another thread was still writing — was an ORDERING bug no unit test saw
until interleavings lined up.  Go's engine leans on ``-race``; this is
the ordering half of that idea for our 25 lock-using modules: every
lock the project takes is created through ``utils/locks.py`` with a
lock-CLASS name (``fragment``, ``holder``, ``budget``,
``committer-flush``, ...), and when ``PILOSA_TPU_LOCKCHECK`` is set the
factories hand out instrumented primitives that

* keep the per-thread held-lock stack,
* record every acquisition edge (class held -> class acquired) into a
  process-global order graph with the first sample site per edge,
* flag same-class nesting on distinct objects immediately (unless the
  class is declared self-nesting-safe below), plus same-thread
  re-acquire of a non-reentrant lock (guaranteed self-deadlock),
* detect order-inversion cycles over the class graph at report time.

Reports surface at process exit (stderr) and at ``/debug/locks``; with
``PILOSA_TPU_LOCKCHECK=strict`` a dirty report hard-fails the process,
which is how CI turns the chaos/overload/ingest suites' interleavings
into race coverage.  Unarmed processes pay nothing: the factories
return plain ``threading`` primitives and this module is never
imported.
"""

from __future__ import annotations

import os
import threading
import traceback

# Lock classes that may legitimately nest instances of themselves.
# Keep this list justified (docs/static-analysis.md hierarchy table):
#   stats      — StatsClient._share_with hands one shared lock to every
#                child client, so "nesting" is the same object via two
#                names; distinct-instance nesting (server stats inside a
#                private bench instance) is scoped and acyclic.
#   budget     — DeviceBudget instances (device / host-stage / ingest-
#                delta) are independent leaf registries; eviction
#                callbacks run OUTSIDE the lock by design, so nested
#                instances cannot form a cycle.
SELF_NESTING_OK = {"stats", "budget"}

_MODE = os.environ.get("PILOSA_TPU_LOCKCHECK", "").strip().lower()


def mode() -> str:
    return _MODE


def armed() -> bool:
    return _MODE not in ("", "0", "off")


def strict() -> bool:
    return _MODE in ("strict", "fail")


class _Graph:
    """Process-global acquisition-order graph + violation log.  Guarded
    by a RAW lock — the checker must never recurse into itself."""

    def __init__(self):
        self._mu = threading.Lock()
        # (held_cls, acquired_cls) -> first sample site
        self.edges: dict[tuple[str, str], str] = {}
        # kind -> {dedupe_key: description}
        self.violations: dict[str, dict[str, str]] = {}

    def _site(self, skip: int = 3) -> str:
        # nearest non-lockcheck frame: the acquisition site itself
        for frame in reversed(traceback.extract_stack()[:-skip]):
            if "lockcheck" not in frame.filename \
                    and "threading" not in frame.filename:
                return f"{frame.filename}:{frame.lineno} in {frame.name}"
        return "?"

    def note_edge(self, held: str, acquired: str):
        key = (held, acquired)
        if key in self.edges:          # cheap unlocked membership probe
            return
        site = self._site()
        with self._mu:
            self.edges.setdefault(key, site)

    def note_violation(self, kind: str, dedupe: str, desc: str):
        with self._mu:
            self.violations.setdefault(kind, {}).setdefault(dedupe, desc)

    def cycles(self) -> list[list[str]]:
        """Elementary order-inversion cycles over the class graph
        (self-edges are the same-class-nesting check's business)."""
        with self._mu:
            adj: dict[str, set[str]] = {}
            for a, b in self.edges:
                if a != b:
                    adj.setdefault(a, set()).add(b)
        out: list[list[str]] = []
        seen_cycles: set[frozenset] = set()

        def dfs(start: str, node: str, path: list[str],
                on_path: set[str]):
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(path + [start])
                elif nxt not in on_path and nxt > start:
                    # only expand nodes ordered after start: each cycle
                    # is found exactly once, from its smallest node
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out

    def report(self) -> dict:
        cycles = self.cycles()
        with self._mu:
            edges = [{"from": a, "to": b, "site": s}
                     for (a, b), s in sorted(self.edges.items())]
            violations = [
                {"kind": kind, "detail": desc}
                for kind, entries in sorted(self.violations.items())
                for desc in entries.values()
            ]
        for cyc in cycles:
            edge_sites = {f"{a}->{b}": self.edges.get((a, b), "?")
                          for a, b in zip(cyc, cyc[1:])}
            violations.append({
                "kind": "order-inversion",
                "detail": f"lock classes acquired in conflicting orders: "
                          f"{' -> '.join(cyc)} (sites: {edge_sites})"})
        return {"mode": _MODE or "off", "armed": armed(),
                "lockClasses": sorted({c for e in self.edges for c in e}),
                "edges": edges, "violations": violations}

    def reset(self):
        with self._mu:
            self.edges.clear()
            self.violations.clear()


GRAPH = _Graph()
_TLS = threading.local()


def _held() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _note_acquiring(lock: "_CheckedBase"):
    # Reentrancy guard: a gc callback (utils/gcnotify.py) can fire inside
    # the checker's own bookkeeping (note_edge allocates) and acquire an
    # instrumented lock — re-entering the graph lock on the same thread
    # would self-deadlock the detector.  Held-stack pushes still happen;
    # only graph/violation recording is skipped for the nested acquire.
    if getattr(_TLS, "busy", False):
        return
    _TLS.busy = True
    try:
        _note_acquiring_inner(lock)
    finally:
        _TLS.busy = False


def _note_acquiring_inner(lock: "_CheckedBase"):
    held = _held()
    # Lazily prune hand-offs: threading.Lock legally releases on a
    # thread other than the acquirer, which pops nothing from the
    # acquirer's stack.  An entry whose lock is no longer held by THIS
    # thread is stale — without the prune it would fabricate edges (and
    # phantom strict-mode inversions) forever after.
    me = threading.get_ident()
    if any(h._holder_tid != me for h in held):
        held[:] = [h for h in held if h._holder_tid == me]
    for h in held:
        if h is lock and not lock._reentrant:
            GRAPH.note_violation(
                "self-deadlock",
                f"{lock._cls}:{id(lock)}",
                f"thread {threading.current_thread().name} re-acquired "
                f"non-reentrant '{lock._cls}' lock it already holds at "
                f"{GRAPH._site(skip=4)}")
        elif h._cls == lock._cls and h is not lock \
                and lock._cls not in SELF_NESTING_OK:
            GRAPH.note_violation(
                "same-class-nesting",
                f"{lock._cls}@{GRAPH._site(skip=4)}",
                f"two distinct '{lock._cls}' locks nested without a "
                f"declared hierarchy at {GRAPH._site(skip=4)} "
                f"(thread {threading.current_thread().name})")
    if held:
        GRAPH.note_edge(held[-1]._cls, lock._cls)


class _CheckedBase:
    _reentrant = False

    def __init__(self, cls_name: str):
        self._cls = cls_name
        self._holder_tid: int | None = None

    # -- bookkeeping around the inner primitive ----------------------------

    def _pre(self):
        _note_acquiring(self)

    def _pushed(self):
        self._holder_tid = threading.get_ident()
        _held().append(self)

    def _popped(self):
        # clear ownership FIRST: a cross-thread release (lock handoff)
        # finds nothing in this thread's stack, and the acquirer's stale
        # entry is pruned lazily in _note_acquiring_inner
        self._holder_tid = None
        held = _held()
        # release order need not be LIFO; remove the newest entry for
        # this object
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break


class CheckedLock(_CheckedBase):
    """Instrumented non-reentrant lock; full threading.Lock surface so
    Condition can wrap it."""

    def __init__(self, cls_name: str):
        super().__init__(cls_name)
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._pre()  # record BEFORE blocking: a deadlock still logs
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if not blocking:
                self._pre()
            self._pushed()
        return ok

    def release(self):
        self._popped()
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()


class CheckedRLock(_CheckedBase):
    """Instrumented reentrant lock; exposes the private Condition hooks
    (_is_owned/_release_save/_acquire_restore) so Condition.wait keeps
    the held-stack honest across the release/re-acquire."""

    _reentrant = True

    def __init__(self, cls_name: str):
        super().__init__(cls_name)
        self._inner = threading.RLock()
        self._owner: int | None = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        first = self._owner != me
        if first and blocking:
            self._pre()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if first and not blocking:
                self._pre()
            self._owner = me
            self._count += 1
            if first:
                self._pushed()
        return ok

    def release(self):
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        last = self._count == 0
        if last:
            self._owner = None
            self._popped()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    # -- Condition protocol ------------------------------------------------

    def _is_owned(self):
        return self._owner == threading.get_ident()

    def _release_save(self):
        count = self._count
        self._count = 0
        self._owner = None
        self._popped()
        return self._inner._release_save(), count

    def _acquire_restore(self, state):
        inner_state, count = state
        self._inner._acquire_restore(inner_state)
        self._owner = threading.get_ident()
        self._count = count
        self._pushed()


def checked_condition(cls_name: str, rlock: bool = False):
    lock = CheckedRLock(cls_name) if rlock else CheckedLock(cls_name)
    return threading.Condition(lock)


# -- reporting --------------------------------------------------------------


def report() -> dict:
    return GRAPH.report()


def reset():
    GRAPH.reset()


def _exit_report():
    rep = GRAPH.report()
    if not rep["violations"]:
        return
    import sys
    print(f"lockcheck: {len(rep['violations'])} violation(s) "
          f"(PILOSA_TPU_LOCKCHECK={_MODE}):", file=sys.stderr)
    for v in rep["violations"]:
        print(f"  [{v['kind']}] {v['detail']}", file=sys.stderr)
    if strict():
        # atexit cannot change the interpreter's exit status any other
        # way; a dirty strict run must fail CI.  Flush BOTH streams:
        # os._exit discards buffered pipe output, and losing the pytest
        # tail would hide which test drove the interleaving.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(70)


if armed():
    import atexit
    atexit.register(_exit_report)
