"""Project invariant analyzer (docs/static-analysis.md).

Two halves:

* ``astlint`` — a scope-aware AST lint suite encoding the invariants
  this repo used to enforce with review conventions and check.sh greps
  (traced-closure capture, wall-clock timing, exception swallows,
  batcher bypass, cross-thread context discipline, metrics/failpoint
  catalogs).  Run as ``python -m pilosa_tpu.analysis`` from the repo
  root, or ``pilosa-tpu analyze``; exits non-zero on any finding.

* ``lockcheck`` — a runtime lock-order race detector: instrumented
  Lock/RLock/Condition (adopted tree-wide via utils/locks.py) that
  records per-thread held-lock stacks, builds the global acquisition-
  order graph over named lock classes, and reports order-inversion
  cycles and undeclared same-class nesting at process exit and at
  /debug/locks.  Armed with ``PILOSA_TPU_LOCKCHECK=1`` (``=strict``
  additionally fails the process on violations).

This package deliberately imports nothing heavyweight at package level:
``utils/locks.py`` pulls ``lockcheck`` on every armed process start, and
the lint suite must stay runnable on a box without jax.
"""
