"""AST lint framework: scope-aware rules over the project tree.

Each rule encodes an invariant a real bug taught us (docs/static-
analysis.md has the catalog with the motivating PR per rule).  Rules are
AST passes, not greps: they see aliased imports, nested scopes, and call
shapes the old check.sh regexes missed.

Suppressions are inline comments on the finding line (or the line
directly above, for lines with no room):

    # lint: allow(<rule>[, <rule>...]) — <reason>

and every suppression MUST carry a reason — a reasonless allow is itself
a finding (``suppression-reason``), and an allow that no longer matches
any finding is too (``suppression-unused``), so the allow list can only
shrink as bugs are fixed.

Two rule kinds register here:

* per-module rules (``@rule``) — run once per parsed file, scoped to
  ``src`` (pilosa_tpu/, scripts/, bench.py) or ``all`` (src + tests/);
* project rules (``@project_rule``) — run once over the whole tree
  (cross-file catalogs: metrics docs, failpoint names).

``run()`` is the ``python -m pilosa_tpu.analysis`` entry; ``lint_source``
lints a source string for the golden-fixture tests.
"""

from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass
from pathlib import Path

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*([a-z0-9_*,\- ]+?)\s*\)\s*(?:[—–:-]+\s*)?(.*)")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Suppressions:
    """Inline ``# lint: allow(rule) — reason`` comments of one file.
    Parsed from real COMMENT tokens — text inside a docstring that
    merely looks like a suppression (this framework's own docs, say)
    suppresses nothing."""

    def __init__(self, source: str):
        import io
        import tokenize
        self.by_line: dict[int, set[str]] = {}
        self.missing_reason: list[tuple[int, set[str]]] = []
        self.comment_lines: set[int] = set()
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, SyntaxError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            # only comment-ONLY lines extend a suppression block upward;
            # a trailing comment on a code line must not leak its allow
            # onto the next line's findings
            if tok.line.lstrip().startswith("#"):
                self.comment_lines.add(tok.start[0])
            m = SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            i = tok.start[0]
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            self.by_line[i] = rules
            if not m.group(2).strip():
                self.missing_reason.append((i, rules))
        self._used: set[tuple[int, str]] = set()

    def _match(self, rule_id: str, ln: int) -> bool:
        rules = self.by_line.get(ln)
        if rules and (rule_id in rules or "*" in rules):
            self._used.add((ln, rule_id if rule_id in rules else "*"))
            return True
        return False

    def allows(self, rule_id: str, line: int) -> bool:
        # the comment rides the finding line itself, or anywhere in the
        # contiguous comment block directly above it (reasons wrap)
        if self._match(rule_id, line):
            return True
        ln = line - 1
        while ln in self.comment_lines:
            if self._match(rule_id, ln):
                return True
            ln -= 1
        return False

    def unused(self, active_rules: set[str]):
        """(line, rule) allows that matched no finding — stale allows
        must be deleted, not accumulate.  Only rules that actually ran
        count (a partial run must not read scoped-out allows as stale)."""
        for ln, rules in self.by_line.items():
            for r in rules:
                if r == "*" or r not in active_rules:
                    continue
                if (ln, r) not in self._used:
                    yield ln, r


# -- scope analysis ---------------------------------------------------------


class Scope:
    """One lexical scope: bindings, loads, and which bindings are
    loop-carried or reassigned — the closure-capture rule's raw data."""

    __slots__ = ("node", "kind", "parent", "children", "bound",
                 "bind_count", "loop_bound", "globals_", "loads", "funcs")

    def __init__(self, node, kind: str, parent: "Scope | None"):
        self.node = node
        self.kind = kind            # "module" | "function" | "class"
        self.parent = parent
        self.children: list[Scope] = []
        self.bound: set[str] = set()
        self.bind_count: dict[str, int] = {}
        self.loop_bound: set[str] = set()
        self.globals_: set[str] = set()
        self.loads: list[tuple[str, int]] = []
        self.funcs: dict[str, Scope] = {}   # name -> immediate child def
        if parent is not None:
            parent.children.append(self)

    def bind(self, name: str, loop: bool = False, n: int = 1):
        self.bound.add(name)
        self.bind_count[name] = self.bind_count.get(name, 0) + n
        if loop:
            self.loop_bound.add(name)

    def free_reads(self):
        """(name, line) loads not satisfied by this scope, including
        nested scopes' unsatisfied loads (class bodies execute in the
        enclosing trace, so they count too)."""
        out = []
        for name, ln in self.loads:
            if name not in self.bound and name not in self.globals_:
                out.append((name, ln))
        for child in self.children:
            for name, ln in child.free_reads():
                if name not in self.bound and name not in self.globals_:
                    out.append((name, ln))
        return out

    def lookup_func(self, name: str) -> "Scope | None":
        """Resolve ``name`` to a function scope visible from here (the
        Name-passed-to-wrapper case)."""
        s: Scope | None = self
        while s is not None:
            if name in s.funcs:
                return s.funcs[name]
            s = s.parent
        return None

    def enclosing_function(self) -> "Scope | None":
        s = self.parent
        while s is not None and s.kind == "class":  # classes don't close
            s = s.parent
        return s if s is not None and s.kind == "function" else None


class _ScopeBuilder(ast.NodeVisitor):
    def __init__(self, tree):
        self.root = Scope(tree, "module", None)
        self._cur = self.root
        self._loop = 0
        self.generic_visit_scope(tree)

    # every visited node gets a backlink to its scope so rules can map a
    # call site to its lexical context
    def visit(self, node):
        node._ptpu_scope = self._cur
        super().visit(node)

    def generic_visit_scope(self, node):
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _enter(self, node, kind: str):
        prev, prev_loop = self._cur, self._loop
        self._cur = Scope(node, kind, prev)
        self._loop = 0
        return prev, prev_loop

    def _exit(self, saved):
        self._cur, self._loop = saved

    def _bind_args(self, args: ast.arguments):
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            self._cur.bind(a.arg)
        if args.vararg:
            self._cur.bind(args.vararg.arg)
        if args.kwarg:
            self._cur.bind(args.kwarg.arg)

    def _visit_funclike(self, node, name: str | None):
        # decorators/defaults/annotations evaluate in the DEFINING scope
        for dec in getattr(node, "decorator_list", []):
            self.visit(dec)
        for d in node.args.defaults + [d for d in node.args.kw_defaults
                                       if d is not None]:
            self.visit(d)
        if name is not None:
            self._cur.bind(name, loop=self._loop > 0)
        saved = self._enter(node, "function")
        if name is not None:
            saved[0].funcs[name] = self._cur
        node._ptpu_scope = saved[0]          # the def site's scope
        node._ptpu_fscope = self._cur        # the function's own scope
        self._bind_args(node.args)
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            self.visit(stmt)
        self._exit(saved)

    def visit_FunctionDef(self, node):
        self._visit_funclike(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._visit_funclike(node, None)

    def visit_ClassDef(self, node):
        for dec in node.decorator_list:
            self.visit(dec)
        for base in node.bases + node.keywords:
            self.visit(base)
        self._cur.bind(node.name, loop=self._loop > 0)
        saved = self._enter(node, "class")
        node._ptpu_scope = saved[0]
        for stmt in node.body:
            self.visit(stmt)
        self._exit(saved)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self._cur.loads.append((node.id, node.lineno))
        else:
            self._cur.bind(node.id, loop=self._loop > 0)

    def visit_AugAssign(self, node):
        # x += ... both reads and REBINDS x: count it twice so a single
        # aug-assigned local registers as reassigned
        if isinstance(node.target, ast.Name):
            self._cur.loads.append((node.target.id, node.lineno))
            self._cur.bind(node.target.id, loop=self._loop > 0, n=2)
        else:
            self.visit(node.target)
        self.visit(node.value)

    def _visit_loop(self, node, target=None):
        if target is not None:
            self.visit(getattr(node, "iter"))
        self._loop += 1
        if target is not None:
            self.visit(target)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._loop -= 1

    def visit_For(self, node):
        self._visit_loop(node, node.target)

    visit_AsyncFor = visit_For

    def visit_While(self, node):
        self.visit(node.test)
        self._visit_loop(node)

    def visit_Import(self, node):
        for alias in node.names:
            self._cur.bind(alias.asname or alias.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name != "*":
                self._cur.bind(alias.asname or alias.name)

    def visit_Global(self, node):
        self._cur.globals_.update(node.names)

    def visit_Nonlocal(self, node):
        # conservative: a nonlocal write targets an outer binding the
        # outer scope already counts; don't double-book it here
        self._cur.globals_.update(node.names)

    def visit_ExceptHandler(self, node):
        if node.type is not None:
            self.visit(node.type)
        if node.name:
            self._cur.bind(node.name)
        for stmt in node.body:
            self.visit(stmt)

    def generic_visit(self, node):
        self.generic_visit_scope(node)


# -- parsed module ----------------------------------------------------------


class Module:
    def __init__(self, rel: str, source: str, is_test: bool = False):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.is_test = is_test
        self.tree = ast.parse(source, filename=rel)
        self.suppressions = Suppressions(source)
        self._scopes: Scope | None = None

    @property
    def scopes(self) -> Scope:
        if self._scopes is None:
            self._scopes = _ScopeBuilder(self.tree).root
        return self._scopes


# -- registry ---------------------------------------------------------------


@dataclass
class Rule:
    id: str
    scope: str          # "src" | "all"
    fn: object
    doc: str


RULES: dict[str, Rule] = {}
PROJECT_RULES: dict[str, Rule] = {}


def rule(rule_id: str, scope: str = "src", doc: str = ""):
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, scope, fn, doc or fn.__doc__ or "")
        fn.rule_id = rule_id
        return fn
    return deco


def project_rule(rule_id: str, doc: str = ""):
    def deco(fn):
        PROJECT_RULES[rule_id] = Rule(rule_id, "all", fn,
                                      doc or fn.__doc__ or "")
        fn.rule_id = rule_id
        return fn
    return deco


def _load_rules():
    from . import rules  # noqa: F401  (registers on import)


# -- runner -----------------------------------------------------------------

SRC_DIRS = ("pilosa_tpu", "scripts")
SRC_FILES = ("bench.py",)


def iter_modules(root: Path):
    """Yield (rel, path, is_test) for every lintable python file."""
    seen = []
    for d in SRC_DIRS:
        base = root / d
        if base.is_dir():
            seen += [(p, False) for p in sorted(base.rglob("*.py"))]
    for f in SRC_FILES:
        p = root / f
        if p.is_file():
            seen.append((p, False))
    tests = root / "tests"
    if tests.is_dir():
        seen += [(p, True) for p in sorted(tests.rglob("*.py"))]
    for path, is_test in seen:
        yield str(path.relative_to(root)), path, is_test


def _run_module_rules(mod: Module, rule_ids) -> list[Finding]:
    out = []
    for r in (RULES[i] for i in rule_ids):
        if r.scope == "src" and mod.is_test:
            continue
        for line, msg in r.fn(mod):
            if not mod.suppressions.allows(r.id, line):
                out.append(Finding(r.id, mod.rel, line, msg))
    return out


def run(root: Path, rule_ids: list[str] | None = None) -> list[Finding]:
    """Lint the whole tree; returns every unsuppressed finding.
    Unknown rule ids raise — a typo'd ``--rule`` must not silently
    analyze nothing and report success (the failpoint-names bug class,
    turned on ourselves)."""
    _load_rules()
    if rule_ids is not None:
        unknown = [i for i in rule_ids
                   if i not in RULES and i not in PROJECT_RULES]
        if unknown:
            known = ", ".join(sorted({**RULES, **PROJECT_RULES}))
            raise ValueError(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(known: {known})")
    mod_ids = [i for i in (rule_ids or RULES) if i in RULES]
    proj_ids = [i for i in (rule_ids or PROJECT_RULES) if i in PROJECT_RULES]
    modules: dict[str, Module] = {}
    findings: list[Finding] = []
    for rel, path, is_test in iter_modules(root):
        try:
            modules[rel] = Module(rel, path.read_text(), is_test)
        except SyntaxError as e:
            findings.append(Finding("syntax", rel, e.lineno or 0, str(e)))
    for mod in modules.values():
        findings += _run_module_rules(mod, mod_ids)
    for r in (PROJECT_RULES[i] for i in proj_ids):
        for f in r.fn(modules, root):
            mod = modules.get(f.path)
            if mod is None or not mod.suppressions.allows(r.id, f.line):
                findings.append(f)
    # suppression hygiene runs only on a FULL-rule pass: a scoped run
    # hasn't exercised the other rules' allows
    if rule_ids is None:
        active = set(RULES) | set(PROJECT_RULES)
        for mod in modules.values():
            for ln, rules_ in mod.suppressions.missing_reason:
                findings.append(Finding(
                    "suppression-reason", mod.rel, ln,
                    f"allow({', '.join(sorted(rules_))}) carries no "
                    f"reason — every suppression must say why"))
            for ln, rid in mod.suppressions.unused(active):
                findings.append(Finding(
                    "suppression-unused", mod.rel, ln,
                    f"allow({rid}) matches no finding — delete the "
                    f"stale suppression"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_source(source: str, rule_ids: list[str] | None = None,
                rel: str = "snippet.py",
                is_test: bool = False) -> list[Finding]:
    """Lint one source string (the golden-fixture test entry)."""
    _load_rules()
    mod = Module(rel, source, is_test)
    ids = [i for i in (rule_ids or RULES) if i in RULES]
    return sorted(_run_module_rules(mod, ids),
                  key=lambda f: (f.line, f.rule))


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m pilosa_tpu.analysis",
        description="project invariant analyzer (docs/static-analysis.md)")
    p.add_argument("--root", default=".",
                   help="repo checkout to analyze (default: cwd)")
    p.add_argument("--rule", action="append", dest="rules", default=None,
                   help="run only this rule id (repeatable)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)
    _load_rules()
    if args.list_rules:
        for r in sorted({**RULES, **PROJECT_RULES}.values(),
                        key=lambda r: r.id):
            first = (r.doc or "").strip().splitlines()
            print(f"{r.id:24s} {first[0] if first else ''}")
        return 0
    root = Path(args.root).resolve()
    if not (root / "pilosa_tpu").is_dir():
        print(f"analysis: no pilosa_tpu/ package under {root}",
              file=sys.stderr)
        return 2
    try:
        findings = run(root, args.rules)
    except ValueError as e:
        print(f"analysis: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f)
    n_rules = len(RULES) + len(PROJECT_RULES)
    if findings:
        print(f"analysis: FAIL — {len(findings)} finding(s) "
              f"across {n_rules} rules")
        return 1
    print(f"analysis: OK ({n_rules} rules)")
    return 0
