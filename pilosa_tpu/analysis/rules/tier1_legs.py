"""tier1-legs: the split tier-1 runner's leg partition covers tests/.

scripts/tier1_split.sh runs the tier-1 suite as two explicitly-listed
legs (the suite stopped fitting one timeout budget on a 1-core box).
An explicit list rots: a new test file that lands in NEITHER leg simply
never runs in split-mode tier-1, and nothing would say so.  This rule
makes the partition load-bearing — every ``tests/test_*.py`` on disk
must appear in the script, and every listed file must still exist.
"""

from __future__ import annotations

import re

from ..astlint import Finding, project_rule

LISTED = re.compile(r"\btests/test_\w+\.py\b")


@project_rule("tier1-legs")
def check(modules, root):
    """Test files outside both tier-1 legs / stale leg entries."""
    script_path = root / "scripts" / "tier1_split.sh"
    script_rel = "scripts/tier1_split.sh"
    if not script_path.is_file():
        yield Finding("tier1-legs", script_rel, 1,
                      "scripts/tier1_split.sh is missing")
        return
    text = script_path.read_text()
    listed = set(LISTED.findall(text))
    on_disk = {f"tests/{p.name}"
               for p in (root / "tests").glob("test_*.py")}
    for f in sorted(on_disk - listed):
        yield Finding("tier1-legs", f, 1,
                      f"{f} is in neither leg of scripts/tier1_split.sh "
                      f"— it never runs in split-mode tier-1; add it to "
                      f"a leg list")
    for f in sorted(listed - on_disk):
        line = text[:text.index(f)].count("\n") + 1
        yield Finding("tier1-legs", script_rel, line,
                      f"leg entry {f} does not exist on disk")
