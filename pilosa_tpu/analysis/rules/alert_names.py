"""alert-names: two-way alert_rule <-> docs/observability.md catalog.

Alert-rule ids are the paging contract: an operator woken by
``ALERT fire [page] slo-latency-burn`` must find a catalog row that
says what the alert means and — crucially — WHERE TO LOOK, so every
row's runbook line must name a ``/debug`` surface.  The lint is
two-way like event-names: a registered rule with no catalog row is an
unexplained page; a catalog row matching no ``alert_rule("...")``
registration documents an alert that can never fire.
"""

from __future__ import annotations

import ast
import re

from ..astlint import Finding, project_rule

CATALOG = re.compile(r"<!-- alerts-catalog:begin -->(.*?)"
                     r"<!-- alerts-catalog:end -->", re.S)


def _rule_sites(mod):
    """(id, line) for every literal ``alert_rule("...")`` decorator or
    call in a module (utils/slo.py today, but the lint is site-agnostic
    so subsystem-local rules stay covered)."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else None
        if name != "alert_rule":
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            yield node.args[0].value, node.lineno


@project_rule("alert-names")
def check(modules, root):
    """alert_rule id missing a catalog row / row with no registration /
    row whose runbook names no /debug surface."""
    code: dict[str, tuple[str, int]] = {}
    for rel, mod in modules.items():
        if not rel.startswith("pilosa_tpu"):
            continue
        if rel.startswith("pilosa_tpu/analysis/"):
            continue  # the analyzer's own docs show ids on purpose
        for rid, line in _rule_sites(mod):
            code.setdefault(rid, (rel, line))
    if not code:
        return  # SLO engine absent: nothing to check against

    doc_path = root / "docs" / "observability.md"
    doc_rel = "docs/observability.md"
    if not doc_path.is_file():
        yield Finding("alert-names", doc_rel, 1,
                      "docs/observability.md is missing")
        return
    doc_text = doc_path.read_text()
    m = CATALOG.search(doc_text)
    if m is None:
        yield Finding("alert-names", doc_rel, 1,
                      "missing the alerts-catalog markers")
        return
    cat_line = doc_text.count("\n", 0, m.start()) + 1
    rows: dict[str, str] = {}
    for row in re.finditer(r"^\| `([^`]+)`(.*)$", m.group(1), re.M):
        rows[row.group(1)] = row.group(2)

    for rid in sorted(code):
        if rid not in rows:
            rel, line = code[rid]
            yield Finding("alert-names", rel, line,
                          f"alert rule '{rid}' is registered but missing "
                          f"from the docs/observability.md alerts catalog")
    for rid in sorted(rows):
        if rid not in code:
            yield Finding("alert-names", doc_rel, cat_line,
                          f"alerts-catalog row '{rid}' matches no "
                          f"alert_rule registration")
        elif "/debug" not in rows[rid]:
            yield Finding("alert-names", doc_rel, cat_line,
                          f"alerts-catalog row '{rid}' has no runbook "
                          f"surface — the row must name a /debug "
                          f"endpoint to look at")
