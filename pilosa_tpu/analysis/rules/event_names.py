"""event-names: two-way events.emit <-> docs/observability.md catalog.

The event catalog (the ``events-catalog`` markers) is the operator's
contract for the /debug/events journal and the /debug/cluster fleet
timeline, exactly like the metrics catalog is for /metrics: an
uncataloged ``events.emit("...")`` site produces timeline entries no
runbook explains, and a dangling catalog row documents an event that
can never fire (the failpoint-names lesson — a name nothing emits reads
as "this never happened" when it actually CAN'T happen).
"""

from __future__ import annotations

import ast
import re

from ..astlint import Finding, project_rule

CATALOG = re.compile(r"<!-- events-catalog:begin -->(.*?)"
                     r"<!-- events-catalog:end -->", re.S)


def _recv(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _emit_sites(mod):
    """(name, line) for every literal ``events.emit("...")`` /
    ``EVENTS.emit("...")`` call in a module."""
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"):
            continue
        recv = _recv(node.func.value)
        if not (recv.endswith("events") or recv.endswith("EVENTS")
                or recv.endswith("self")):
            continue
        # self.emit(...) only counts inside utils/events.py itself
        if recv.endswith("self") and not mod.rel.endswith(
                "utils/events.py"):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            yield node.args[0].value, node.lineno


@project_rule("event-names")
def check(modules, root):
    """events.emit name missing from the catalog / row no site emits."""
    code: dict[str, tuple[str, int]] = {}
    for rel, mod in modules.items():
        if not rel.startswith("pilosa_tpu"):
            continue
        if rel.startswith("pilosa_tpu/analysis/"):
            continue  # the analyzer's own docs show names on purpose
        for name, line in _emit_sites(mod):
            code.setdefault(name, (rel, line))
    if not code:
        return  # journal absent: nothing to check against

    doc_path = root / "docs" / "observability.md"
    doc_rel = "docs/observability.md"
    if not doc_path.is_file():
        yield Finding("event-names", doc_rel, 1,
                      "docs/observability.md is missing")
        return
    doc_text = doc_path.read_text()
    m = CATALOG.search(doc_text)
    if m is None:
        yield Finding("event-names", doc_rel, 1,
                      "missing the events-catalog markers")
        return
    cat_line = doc_text.count("\n", 0, m.start()) + 1
    docs = set(re.findall(r"^\| `([^`]+)`", m.group(1), re.M))

    for name in sorted(code):
        if name not in docs:
            rel, line = code[name]
            yield Finding("event-names", rel, line,
                          f"event '{name}' is emitted but missing from "
                          f"the docs/observability.md events catalog")
    for d in sorted(docs):
        if d not in code:
            yield Finding("event-names", doc_rel, cat_line,
                          f"events-catalog row '{d}' matches no "
                          f"events.emit site")
