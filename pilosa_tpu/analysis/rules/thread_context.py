"""thread-context: contextvar reads across an unprotected thread hop
(the PR 5 orphan-span/profile bug class).

The tracing and profile contexts ride contextvars (utils/tracing.py,
utils/profile.py).  A function handed to ``pool.submit`` or
``Thread(target=...)`` runs with EMPTY contextvars: spans parent as
orphan roots and profile events vanish, silently — exactly what PR 5
fixed by threading ``capture()``/``attach()``/``task()`` through every
pool boundary (cluster fan-out, dispatch batcher, mesh prefetch).

The rule flags a submit/Thread callsite whose resolvable target touches
tracing/profile context (``qprof.stage``, ``tracer.span``,
``GLOBAL_TRACER``...) without re-attaching a captured context (no
``attach``/``task``/``activate`` in its body).  Background monitors that
intentionally start fresh root traces carry an inline allow.
"""

from __future__ import annotations

import ast

from ..astlint import rule

_CTX_ATTRS = {"stage", "event", "span", "current", "capture", "inject",
              "current_trace_id"}
_CTX_FRAGMENTS = ("prof", "trac")
_REATTACH = {"attach", "task", "activate"}


def _chain(node) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _touches_context(fn_node) -> tuple[bool, bool]:
    """(touches tracing/profile contextvars, re-attaches a context)."""
    touches = reattaches = False
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        name = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name) else None)
        if name in _REATTACH:
            reattaches = True
        if name in _CTX_ATTRS and isinstance(node.func, ast.Attribute):
            recv = "".join(_chain(node.func.value)).lower()
            if any(f in recv for f in _CTX_FRAGMENTS):
                touches = True
    return touches, reattaches


def _resolve_target(arg, call_scope):
    """The submitted callable's function scope, when statically
    resolvable: a local def/lambda by name, or a self-method."""
    if isinstance(arg, ast.Lambda):
        return arg._ptpu_fscope
    if isinstance(arg, ast.Name):
        return call_scope.lookup_func(arg.id)
    if isinstance(arg, ast.Attribute) and \
            isinstance(arg.value, ast.Name) and arg.value.id == "self":
        s = call_scope
        while s is not None and s.kind != "class":
            s = s.parent
        if s is not None:
            return s.funcs.get(arg.attr)
    return None


@rule("thread-context", scope="src")
def check(mod):
    """submit/Thread target touches tracing/profile contextvars without
    re-attaching captured context."""
    mod.scopes  # annotate nodes with their scopes
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        target = None
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name) else None)
        if fname == "submit" and node.args:
            target = node.args[0]
        elif fname == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        if target is None:
            continue
        fscope = _resolve_target(target, node._ptpu_scope)
        if fscope is None:
            continue  # wrapped (tracer.task(fn)) or non-local: fine
        touches, reattaches = _touches_context(fscope.node)
        if touches and not reattaches:
            yield node.lineno, (
                "thread-hop target touches tracing/profile contextvars "
                "without re-attaching captured context — wrap it with "
                "tracer.task()/attach() (or profile.activate) so spans "
                "and profile events land in the submitting request")
