"""bare-except / swallowed-exception: handlers that eat errors silently
(the PR 6 anti-entropy swallow class).

PR 6's worst finding was a broad ``except`` turning a failed shard poll
into a clean-looking pass — the node reported healthy anti-entropy while
never syncing.  Two rules:

* ``bare-except`` — a bare ``except:`` catches KeyboardInterrupt and
  SystemExit; always name a type.  (Scope ``all``: tests included, as
  the old grep did.)
* ``swallowed-exception`` — an ``except Exception``/``BaseException``
  whose body neither re-raises, uses the bound exception (returning or
  recording it counts), logs (``Logger.event``/``error``/...), counts a
  stat, nor calls an error-accounting helper (``_note_ae_error``,
  ``_mark_down``, ...).  Such a handler makes failure indistinguishable
  from success; make the error observable or carry an inline allow with
  the reason it truly is noise.
"""

from __future__ import annotations

import ast

from ..astlint import rule

BROAD = {"Exception", "BaseException"}

# logging calls make a handler observably handle the error whatever the
# receiver is (log.event, self.logger.error, traceback.print_exc, ...)
_LOG_CALLS = {
    "event", "error", "exception", "warning", "warn", "info", "debug",
    "print_exc", "format_exc",
}
# stat-recording verbs count only on a stats-looking receiver — a bare
# list.count(x) or deque-ish observe() must not read as error accounting
_STAT_CALLS = {
    "count", "incr", "increment", "timing", "gauge", "histogram",
    "observe", "set_value",
}
_STAT_RECEIVERS = ("stat", "hist", "metric")
# snake_case word stems marking error-accounting helpers/slots; matched
# per component (mark_down, _note_ae_error, evict_errors) so unrelated
# words merely CONTAINING a stem (shutdown, discount) don't qualify
_HANDLED_STEMS = ("error", "fail", "down", "quarantine", "reject",
                  "note", "abort")


def _is_broad(type_node) -> bool:
    if type_node is None:
        return False
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    for n in nodes:
        name = n.id if isinstance(n, ast.Name) else (
            n.attr if isinstance(n, ast.Attribute) else None)
        if name in BROAD:
            return True
    return False


def _stemmed(name: str) -> bool:
    comps = name.lower().split("_")
    return any(c.startswith(stem) for c in comps for stem in _HANDLED_STEMS)


def _receiver(node) -> str:
    parts = []
    n = node.func.value if isinstance(node.func, ast.Attribute) else None
    while isinstance(n, ast.Attribute):
        parts.append(n.attr)
        n = n.value
    if isinstance(n, ast.Name):
        parts.append(n.id)
    return ".".join(reversed(parts)).lower()


def _handles(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if bound and isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Load) and node.id == bound:
            return True  # the exception is returned/recorded/re-wrapped
        if isinstance(node, ast.Call):
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else None)
            if name is None:
                continue
            if name in _LOG_CALLS or _stemmed(name):
                return True
            if name in _STAT_CALLS and any(
                    r in _receiver(node) for r in _STAT_RECEIVERS):
                return True
        if isinstance(node, (ast.Attribute, ast.Name)) \
                and not isinstance(node.ctx, ast.Load):
            # a store into an error-accounting slot counts
            # (self.evict_errors += 1, last_error = ...)
            target = node.attr if isinstance(node, ast.Attribute) \
                else node.id
            if _stemmed(target):
                return True
    return False


@rule("bare-except", scope="all")
def check_bare(mod):
    """Bare ``except:`` swallows KeyboardInterrupt/SystemExit."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield node.lineno, ("bare 'except:' catches KeyboardInterrupt/"
                               "SystemExit — name an exception type")


@rule("swallowed-exception", scope="src")
def check_swallow(mod):
    """``except Exception`` body that hides the error entirely."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node.type) \
                and not _handles(node):
            yield node.lineno, (
                "except Exception swallows the error invisibly — "
                "re-raise, log (Logger.event/error), count a stat, or "
                "carry an inline allow saying why silence is correct")
