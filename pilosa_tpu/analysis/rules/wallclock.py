"""wall-clock: ``time.time()`` in timing code (the r2 verdict class).

Durations must come from ``time.perf_counter()`` pairs — wall clock
steps (NTP slew, manual set) mid-measurement produce negative/garbage
durations in traces, histograms, and pacing loops.  Wall-clock reads
are legitimate only as display-only stamps, and those sites annotate
themselves: a ``_wall_stamp`` helper, or an inline
``# lint: allow(wall-clock) — <reason>``.

This replaces the check.sh grep, which missed ``from time import time``
and ``import time as t`` aliases entirely — this pass tracks the import
bindings, so every spelling of a wall-clock read is caught.
"""

from __future__ import annotations

import ast

from ..astlint import rule


@rule("wall-clock", scope="src")
def check(mod):
    """time.time() outside an annotated _wall_stamp/display-only site."""
    mod_aliases: set[str] = set()    # names bound to the time MODULE
    fn_aliases: set[str] = set()     # names bound to the time FUNCTION
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mod_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        fn_aliases.add(a.asname or "time")
    if not mod_aliases and not fn_aliases:
        return
    mod.scopes  # ensure every node carries its _ptpu_scope backlink
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Attribute) and f.attr == "time"
               and isinstance(f.value, ast.Name)
               and f.value.id in mod_aliases) \
            or (isinstance(f, ast.Name) and f.id in fn_aliases)
        if not hit:
            continue
        # annotated wall-stamp helpers are the sanctioned sites
        s = node._ptpu_scope
        allowed = False
        while s is not None:
            fn = s.node
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "_wall_stamp":
                allowed = True
                break
            s = s.parent
        if not allowed:
            yield node.lineno, (
                "wall-clock time.time() in timing code — durations come "
                "from perf_counter pairs; display-only stamps go through "
                "a _wall_stamp helper or carry an inline allow")
