"""tenant-attribution: admission acquires and cache fills must carry a
tenant label.

The tenant isolation plane (docs/robustness.md "Tenant isolation") only
works when every enforcement point knows WHO the work belongs to: an
``admission.acquire()`` without a tenant admits under the shared default
bucket (weighted fairness degrades to FIFO for that caller), and a
result-cache ``fill()`` without one charges the bytes to nobody (the
per-tenant quota cannot see them, so a flood refills past its cap).
This rule keeps new call sites honest: every acquire on an admission
pool and every fill on a result cache must pass an explicit ``tenant=``
keyword — even when the value is ``qtenant.current_or_none()``, the
explicitness is the point (a reviewer sees the attribution decision).
``tenant.*`` journal events must name their tenant the same way.

Scope: src, excluding the isolation plane's own modules (the admission
controller, the caches, and utils/ implement the mechanism; they are
the ones being attributed TO) — and tests, which exercise bare pools
deliberately.
"""

from __future__ import annotations

import ast

from ..astlint import rule

# the mechanism itself: these define/own the tenant plumbing
EXEMPT_PREFIXES = (
    "pilosa_tpu/server/admission.py",
    "pilosa_tpu/cache/",
    "pilosa_tpu/storage/membudget.py",
    "pilosa_tpu/utils/",
    "pilosa_tpu/analysis/",
)

# receiver-name fragments that identify an admission pool or a result
# cache at a call site (adm.acquire(...), self.admission.acquire(...),
# cache.fill(...), self.result_cache.fill(...))
ADMISSION_RECV = ("admission", "adm")
CACHE_RECV = ("cache",)


def _recv_name(func: ast.Attribute) -> str:
    """Dotted receiver of an attribute call, e.g.
    ``self.result_cache.fill`` -> "self.result_cache"."""
    parts = []
    node = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords) \
        or any(kw.arg is None for kw in call.keywords)  # **kwargs


@rule("tenant-attribution", scope="src")
def check(mod):
    """Admission acquire / cache fill sites must pass tenant=."""
    rel = mod.rel.replace("\\", "/")
    if rel.startswith(EXEMPT_PREFIXES):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # tenant.* journal events must carry tenant=
        if isinstance(func, ast.Attribute) and func.attr == "emit" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and node.args[0].value.startswith("tenant.") \
                and not _has_kw(node, "tenant"):
            yield node.lineno, (
                f"journal event {node.args[0].value!r} emitted without "
                f"a tenant= field — a tenant-plane event that cannot "
                f"say whose it is defeats shed/quota attribution")
            continue
        if not isinstance(func, ast.Attribute):
            continue
        recv = _recv_name(func).lower()
        last = recv.rsplit(".", 1)[-1]
        if func.attr == "acquire" \
                and (last in ADMISSION_RECV
                     or any(f in last for f in ADMISSION_RECV)) \
                and not _has_kw(node, "tenant"):
            yield node.lineno, (
                f"admission acquire on '{_recv_name(func)}' without "
                f"tenant= — untagged admission rides the shared "
                f"default bucket, so weighted fairness and "
                f"tenant-first shedding cannot see this caller "
                f"(pass tenant=qtenant.current() or an explicit name)")
        elif func.attr == "fill" \
                and any(f in last for f in CACHE_RECV) \
                and not _has_kw(node, "tenant"):
            yield node.lineno, (
                f"result-cache fill on '{_recv_name(func)}' without "
                f"tenant= — unattributed bytes are invisible to the "
                f"per-tenant quota (pass "
                f"tenant=qtenant.current_or_none())")
