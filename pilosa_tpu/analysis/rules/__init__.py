"""Rule modules — importing this package registers every rule with the
astlint registry (one module per rule, docs/static-analysis.md)."""

from . import (  # noqa: F401
    alert_names,
    batcher_bypass,
    event_names,
    except_swallow,
    failpoints,
    metrics_docs,
    router_bypass,
    tenant_attribution,
    thread_context,
    tier1_legs,
    traced_closure,
    wallclock,
)
