"""batcher-bypass: direct mesh reducer dispatch outside parallel/.

Device dispatch must flow through the dispatch batcher
(docs/batching.md): a direct shard_map-reducer call bypasses cross-query
fusion, the queued-deadline drop-out, and the dispatch stats.  Only
``parallel/`` touches the executables; everything else goes through
``executor.batcher``'s same-named wrappers (or its explicit
disabled-mode fallback).

Replaces the check.sh grep with a receiver-aware pass: besides literal
``mesh.segments(...)`` shapes it tracks simple local aliases
(``m = self.executor.mesh; m.segments(...)`` and
``m = MeshExecutor(...)``), which the grep could never see.
"""

from __future__ import annotations

import ast

from ..astlint import rule

REDUCERS = {
    "count_async", "count_batch_async", "segments", "segments_batch",
    "row_counts", "bsi_sum", "bsi_min_max", "group_counts",
}


def _chain_names(node) -> list[str]:
    """Attribute chain as name parts: self.executor.mesh -> [self,
    executor, mesh]."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _is_mesh_expr(node, aliases: set[str]) -> bool:
    if isinstance(node, ast.Call):  # m = MeshExecutor(...)
        inner = _chain_names(node.func)
        return bool(inner) and inner[-1] == "MeshExecutor"
    parts = _chain_names(node)
    if not parts:
        return False
    if parts[0] in aliases:
        return True
    return any("mesh" in p for p in parts)


@rule("batcher-bypass", scope="src")
def check(mod):
    """Mesh reducer call outside parallel/ (route through the batcher)."""
    rel = mod.rel.replace("\\", "/")
    if rel.startswith(("pilosa_tpu/parallel/", "pilosa_tpu/analysis/")):
        return
    # one linear pass per function body keeps alias tracking simple:
    # a Name assigned from a mesh-looking expression taints that name
    # for the rest of the module (over-approximate, which is the safe
    # direction for a bypass check)
    aliases: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_mesh_expr(node.value, aliases):
            aliases.add(node.targets[0].id)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in REDUCERS:
            continue
        if _is_mesh_expr(node.func.value, aliases):
            yield node.lineno, (
                f"direct mesh dispatch '{node.func.attr}' outside "
                f"parallel/ — route through executor.batcher "
                f"(parallel/batcher.py) so fusion, deadline drop-out, "
                f"and dispatch stats apply")
