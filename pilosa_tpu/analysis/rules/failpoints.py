"""failpoint-names: armed failpoints must name a real trigger site.

``FAULTS.hit("fragment.wal")`` callsites define the failpoint namespace;
tests and game-day specs arm names from it.  A typo'd arm
(``fragment.waal=kill:25``) silently never fires — the crash harness
soaks against NOTHING and reports green.  This rule collects every
literal ``FAULTS.hit`` name in pilosa_tpu/ and checks every armed
reference against it: ``FAULTS.arm("...")`` first arguments, literal
``FAULTS.configure`` specs, and any ``name=error|delay|kill`` spec
string literal (env specs, crash-harness specs, f-string prefixes).
"""

from __future__ import annotations

import ast
import re

from ..astlint import Finding, project_rule

SPEC_NAME = re.compile(r"([a-z0-9_.]+)=(?:error|delay|kill)\b")


def _recv(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _str_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_parts(node):
    """String content visible in a Constant or an f-string's constant
    segments (the crash harness builds specs like
    f"fragment.wal=kill:{n}")."""
    s = _str_const(node)
    if s is not None:
        yield s
    elif isinstance(node, ast.JoinedStr):
        for part in node.values:
            s = _str_const(part)
            if s is not None:
                yield s


@project_rule("failpoint-names")
def check(modules, root):
    """Armed failpoint name with no FAULTS.hit trigger site."""
    hits: set[str] = set()
    for rel, mod in modules.items():
        if not rel.startswith("pilosa_tpu"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "hit" \
                    and _recv(node.func.value).endswith("FAULTS") \
                    and node.args:
                name = _str_const(node.args[0])
                if name:
                    hits.add(name)
    if not hits:
        return  # registry absent: nothing to check against

    def armed_names(mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and "FAULTS" in _recv(node.func.value):
                if node.func.attr == "arm" and node.args:
                    name = _str_const(node.args[0])
                    if name:
                        yield name, node.lineno
                    continue
                if node.func.attr == "configure" and node.args:
                    spec = _str_const(node.args[0])
                    for part in (spec or "").split(";"):
                        name = part.strip().partition("=")[0]
                        if name:
                            yield name, node.lineno
                    continue
            # bare spec literals: env specs, crash-harness kill specs
            for text in _literal_parts(node):
                for name in SPEC_NAME.findall(text):
                    yield name, node.lineno

    for rel, mod in modules.items():
        if rel.startswith("pilosa_tpu/analysis/"):
            continue  # the analyzer's own docs show BAD specs on purpose
        seen: set[tuple[str, int]] = set()
        for name, line in armed_names(mod):
            if name in hits or (name, line) in seen:
                continue
            seen.add((name, line))
            yield Finding(
                "failpoint-names", rel, line,
                f"failpoint '{name}' is armed but has no FAULTS.hit "
                f"trigger site — a typo'd arm never fires and the "
                f"harness soaks against nothing")
