"""router-bypass: read fan-out grouping outside the routing layer.

The read fan-out's shard->node decision belongs to the read router
(parallel/routing.py): it owns replica scoring, residency preference,
breaker pre-skip, and the placement-overlay view.  A call site that
groups shards by jump-hash primary itself — ``shards_by_node`` (the
primary-pinned grouping helper) or the cluster's internal grouping
methods — dispatches reads the router never saw: no load spreading, no
breaker skip, no overlay consistency, and the per-shard balancer
counters go blind.

Scope: everything outside ``pilosa_tpu/parallel/`` (the routing layer
itself and the cluster module that delegates to it).  Placement's
``shards_by_node`` stays available for unit tests of the hash ring.
"""

from __future__ import annotations

import ast

from ..astlint import rule

GROUPERS = {"shards_by_node", "_group_shards", "_ready_owner_order"}


@rule("router-bypass", scope="src")
def check(mod):
    """Read fan-out grouping outside parallel/ (route through
    cluster.router / ReadRouter.group_shards)."""
    rel = mod.rel.replace("\\", "/")
    if rel.startswith(("pilosa_tpu/parallel/", "pilosa_tpu/analysis/")):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in GROUPERS:
            continue
        yield node.lineno, (
            f"read fan-out grouping '{node.func.attr}' outside "
            f"parallel/ — route through the read router "
            f"(parallel/routing.py group_shards) so replica scoring, "
            f"breaker pre-skip, the placement overlay, and the "
            f"hot-shard counters all apply")
