"""traced-closure: loop-carried/reassigned locals read inside traced
closures (the PR 7 silent-retrace bug class).

jax executes a traced function's python body at TRACE time only.  A
cached executable that later re-traces (new shape bucket, new stacked
group size) re-reads its closure CELLS — which a later loop iteration
may have rebound to another group's values.  The PR 7 bug was exactly
this: a re-traced segments executable read ``layout`` rebound to the
NEXT group's container buckets and silently dropped every run container
(guarded until now only by the comment at parallel/mesh_exec.py:979).

The rule: inside any function decorated by / passed to ``jax.jit``,
``vmap``, ``pmap``, ``shard_map`` (or this repo's ``_jit_shard_map`` /
``_InstrumentedExec`` wrappers), a read of an enclosing FUNCTION scope
name that is loop-carried or reassigned must instead be frozen as a
keyword default (``_layout=layout``).  Single-assignment enclosing
locals and module globals are safe — the cell can never change under a
re-trace.
"""

from __future__ import annotations

import ast

from ..astlint import rule

TRACE_WRAPPERS = {
    "jit", "vmap", "pmap", "shard_map", "_shard_map", "_jit_shard_map",
    "_InstrumentedExec", "eval_shape", "make_jaxpr",
    # Pallas kernel bodies (ops/kernels.py) are traced exactly like jit
    # bodies — a pallas_call re-trace re-reads closure cells the same way
    "pallas_call",
}


def _callable_name(node) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _mentions_wrapper(node) -> bool:
    return any(_callable_name(n) in TRACE_WRAPPERS
               for n in ast.walk(node)
               if isinstance(n, (ast.Name, ast.Attribute)))


def _traced_scopes(mod):
    """Function scopes whose bodies jax traces: decorated defs plus
    functions/lambdas passed (directly or by name) to a wrapper call."""
    scopes = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_mentions_wrapper(d) for d in node.decorator_list):
                scopes.add(node._ptpu_fscope)
        elif isinstance(node, ast.Call):
            if _callable_name(node.func) not in TRACE_WRAPPERS:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for a in args:
                if isinstance(a, ast.Lambda):
                    scopes.add(a._ptpu_fscope)
                elif isinstance(a, ast.Name):
                    target = node._ptpu_scope.lookup_func(a.id)
                    if target is not None:
                        scopes.add(target)
    return scopes


@rule("traced-closure", scope="src")
def check(mod):
    """Traced closure reads an enclosing loop-carried/reassigned local
    (freeze it as a keyword default)."""
    mod.scopes  # annotate nodes with scope backlinks before walking
    seen = set()
    for fscope in _traced_scopes(mod):
        for name, line in fscope.free_reads():
            if (name, line) in seen:
                continue
            anc = fscope.enclosing_function()
            while anc is not None:
                if name in anc.globals_:
                    break
                if name in anc.bound:
                    loopy = name in anc.loop_bound
                    if loopy or anc.bind_count.get(name, 0) >= 2:
                        seen.add((name, line))
                        how = "loop-carried" if loopy else "reassigned"
                        yield line, (
                            f"traced closure reads {how} enclosing local "
                            f"'{name}'; a re-trace reads the rebound cell "
                            f"— freeze it as a keyword default "
                            f"(_{name}={name})")
                    break
                anc = anc.enclosing_function()
