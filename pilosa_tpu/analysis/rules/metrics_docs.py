"""metrics-docs: two-way stats-name <-> docs/observability.md catalog.

The metrics catalog is the operator's contract: every stats series the
code can emit must have a catalog row, and every row must still match a
call site.  An undocumented series is invisible to dashboards; a
dangling row documents a lie.  Dynamic f-string segments in code and
``<...>`` placeholders in docs both normalize to ``*`` and match by
glob, exactly as the retired check.sh python block did.
"""

from __future__ import annotations

import fnmatch
import re

from ..astlint import Finding, project_rule

CALL = re.compile(
    r'[a-z_]*stats\.(?:count|gauge|timing|timer|histogram)\(\s*(f?)"([^"]+)"',
    re.S)
HELPER = re.compile(r"\b_count\(")  # dotted-name prefix helpers
NAME = re.compile(r'"([a-z0-9_]+(?:\.[a-z0-9_{}.]+)+)"')
CATALOG = re.compile(r"<!-- metrics-catalog:begin -->(.*?)"
                     r"<!-- metrics-catalog:end -->", re.S)


@project_rule("metrics-docs")
def check(modules, root):
    """Stats series missing from the catalog / rows matching no site."""
    code: dict[str, tuple[str, int]] = {}  # name -> first (rel, line)
    for rel, mod in modules.items():
        if not rel.startswith("pilosa_tpu"):
            continue
        for m in CALL.finditer(mod.source):
            is_f, name = m.groups()
            if is_f:
                name = re.sub(r"\{[^}]*\}", "*", name)
            code.setdefault(name,
                            (rel, mod.source.count("\n", 0, m.start()) + 1))
        for m in HELPER.finditer(mod.source):
            # every dotted literal near the helper call (covers
            # conditional names like "a.hit" if ... else "a.miss")
            line = mod.source.count("\n", 0, m.start()) + 1
            for name in NAME.findall(mod.source[m.end():m.end() + 160]):
                code.setdefault(re.sub(r"\{[^}]*\}", "*", name),
                                (rel, line))

    doc_path = root / "docs" / "observability.md"
    doc_rel = "docs/observability.md"
    if not doc_path.is_file():
        yield Finding("metrics-docs", doc_rel, 1,
                      "docs/observability.md is missing")
        return
    doc_text = doc_path.read_text()
    m = CATALOG.search(doc_text)
    if m is None:
        yield Finding("metrics-docs", doc_rel, 1,
                      "missing the metrics-catalog markers")
        return
    cat_line = doc_text.count("\n", 0, m.start()) + 1
    docs = {re.sub(r"<[^>]*>", "*", n)
            for n in re.findall(r"^\| `([^`]+)`", m.group(1), re.M)}

    for name in sorted(code):
        if not any(fnmatch.fnmatch(name, d) for d in docs):
            rel, line = code[name]
            yield Finding("metrics-docs", rel, line,
                          f"stats series '{name}' missing from the "
                          f"docs/observability.md catalog")
    for d in sorted(docs):
        if not any(fnmatch.fnmatch(c, d) for c in code):
            yield Finding("metrics-docs", doc_rel, cat_line,
                          f"catalog row '{d}' matches no stats call site")
