"""Query cache subsystem (reference cache.go RankCache / lru.Cache).

Two cooperating layers:

* ``rank``   — per-fragment rank/LRU caches of the hottest rows, honoring
  the field's ``cacheType``/``cacheSize`` (cache.go:40 rankCache,
  consulted by fragment.go:1570 top).  Unlike the reference, TopN answers
  derived from these caches stay EXACT: the cache only prunes the
  candidate set, and pruning is used only when it can prove coverage.
* ``results`` — a generation-keyed result cache memoizing finished query
  results; invalidation is structural (fragment ``gen`` stamps bumped by
  every mutation key the entries), never TTL-based.
"""

from .rank import RankCache, iter_rank_caches, topn_from_rank
from .results import ResultCache, gen_summary, gen_vector

__all__ = ["RankCache", "iter_rank_caches", "topn_from_rank",
           "ResultCache", "gen_summary", "gen_vector"]
