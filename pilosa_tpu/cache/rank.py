"""Per-fragment rank cache (reference cache.go:40 rankCache / :299
lruCache, consulted by fragment.go:1570 top).

Each fragment of a ``cacheType: ranked|lru`` field keeps an in-memory map
of its hottest rows' EXACT per-fragment bit counts, maintained
incrementally on the write paths (set_bit / clear_bit / bulk_import
recompute just the touched rows from the sparse store) and rebuilt lazily
after bulk mutations that touch more than ``RANK_REBUILD_ROWS`` distinct
rows (or whole-row stores, mutex imports, BSI imports).

Exactness — where the reference diverges from a full scan, we do not.
The reference answers TopN straight from the cache, so a row whose count
decayed below the cache floor silently vanishes from results.  Here the
cache is only a CANDIDATE PRUNER: every cache tracks ``bound``, an upper
bound on the count any row OUTSIDE the cache can have (the best excluded
count at build time, ratcheted up by evictions and rejected admissions).
``topn_from_rank`` unions the cached rows across shards, computes exact
global counts for that candidate set (cached counts are exact; uncached
rows of an incomplete cache are recounted from the host sparse store),
and serves the answer only when the n-th candidate's count strictly
exceeds the summed bounds — i.e. when no pruned row can possibly reach
the top n, ties included.  Otherwise it reports a candidate fallback and
the executor runs the full scan.
"""

from __future__ import annotations

import numpy as np

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"

# Distinct rows a single batched write may touch before incremental
# maintenance gives up and marks the cache for a lazy full rebuild
# (config knob ``rank-rebuild-rows``; the process-wide value follows the
# most recent Server's config, like the device-budget globals).
RANK_REBUILD_ROWS = 4096


class RankCache:
    """Row -> exact per-fragment count for up to ``size`` rows.

    ``ranked`` evicts the lowest-count row on overflow; ``lru`` evicts the
    least-recently-written one (dict insertion order is the recency
    order).  ``complete`` means every row with any set bit is present —
    the cache then IS the fragment's exact count vector.  ``bound`` is
    the pruning invariant described in the module docstring; it only
    ratchets up between rebuilds, so a cache degraded by churn falls back
    (and is marked for rebuild) rather than ever returning a wrong
    answer."""

    __slots__ = ("cache_type", "size", "rows", "complete", "bound",
                 "built_bound", "dirty", "builds")

    def __init__(self, cache_type: str, size: int):
        self.cache_type = cache_type
        self.size = max(int(size), 0)
        self.rows: dict[int, int] = {}
        self.complete = False
        self.bound = 0
        self.built_bound = 0
        self.dirty = True
        self.builds = 0

    # -- build (cache.go Recalculate / fragment.go RecalculateCache) -------

    def build(self, frag):
        """Full rebuild from the fragment's host sparse store: O(nnz)."""
        rids, counts = frag.row_counts_all_host()
        if rids.size <= self.size:
            self.rows = {int(r): int(c) for r, c in zip(rids, counts)}
            self.complete = True
            self.bound = self.built_bound = 0
        else:
            # keep the top ``size`` by (-count, row) — the TopN ordering
            order = np.lexsort((rids, -counts))
            kept = order[: self.size]
            self.rows = {int(rids[i]): int(counts[i]) for i in kept}
            self.complete = False
            # best excluded count bounds every row we do not track
            self.bound = self.built_bound = int(counts[order[self.size]])
        self.dirty = False
        self.builds += 1

    def ensure(self, frag) -> bool:
        """Rebuild if dirty; returns True when a rebuild ran."""
        if not self.dirty:
            return False
        self.build(frag)
        return True

    def invalidate(self):
        self.dirty = True

    # -- incremental maintenance (cache.go Add/BulkAdd) --------------------

    def note_write(self, frag, rows):
        """Called under the fragment lock after a successful mutation with
        the (possibly repeated) row ids it touched."""
        if self.dirty:
            return
        rows = np.unique(np.asarray(rows, dtype=np.int64))
        if rows.size > RANK_REBUILD_ROWS:
            self.dirty = True  # bulk mutation: rebuild lazily
            return
        counts = frag.row_counts_host(rows)
        for row, c in zip(rows.tolist(), counts.tolist()):
            self._update(int(row), int(c))

    def _update(self, row: int, count: int):
        if count <= 0:
            # an emptied row leaves the cache; completeness is preserved
            # (we still know every nonzero row) and the bound stays — a
            # pruned row's count never rises from someone else's clear
            self.rows.pop(row, None)
            return
        if row in self.rows:
            self.rows[row] = count
            if self.cache_type == CACHE_TYPE_LRU:
                # refresh recency (dict order = insertion order)
                self.rows[row] = self.rows.pop(row)
            return
        if len(self.rows) < self.size:
            self.rows[row] = count
            return
        # cache full: admit-and-evict, ratcheting the bound so pruning
        # stays sound for whatever leaves (or never enters) the cache
        if self.size == 0:
            self.complete = False
            self.bound = max(self.bound, count)
            return
        if self.cache_type == CACHE_TYPE_LRU:
            evict_row = next(iter(self.rows))
        else:
            evict_row, _ = min(self.rows.items(),
                               key=lambda kv: (kv[1], -kv[0]))
            if self.rows[evict_row] >= count:
                # the newcomer ranks below everything cached: reject it
                self.complete = False
                self.bound = max(self.bound, count)
                return
        evicted = self.rows.pop(evict_row)
        self.rows[row] = count
        self.complete = False
        self.bound = max(self.bound, evicted)

    def degraded(self) -> bool:
        """The bound has ratcheted past its built value — pruning power is
        decaying and a rebuild would restore it."""
        return self.bound > self.built_bound


def iter_rank_caches(holder):
    """Every (fragment, rank cache) pair in the holder — the one walk
    behind the /internal/cache/clear route, recalculate-caches, and the
    bench's cold-path flush."""
    for idx in list(holder.indexes.values()):
        for f in list(idx.fields.values()):
            for v in list(f.views.values()):
                for frag in list(v.fragments.values()):
                    if frag.rank_cache is not None:
                        yield frag, frag.rank_cache


def topn_from_rank(field, shards, n: int, stats=None):
    """Exact unfiltered TopN from the field's per-fragment rank caches, or
    None when coverage can't be proven (the caller falls back to the full
    scan).  Byte-identical to the device path: identical counts ranked by
    the same (-count, ascending id) order (results.rank_counts).

    ``n == 0`` means unlimited, which needs every nonzero row — served
    only when every cache is complete."""
    from ..core import VIEW_STANDARD
    from ..executor.results import Pair

    v = field.view(VIEW_STANDARD)
    entries = []  # (frag, rc, rows-snapshot, complete, bound) per shard
    if v is not None:
        for shard in shards:
            frag = v.fragment(shard)
            if frag is None:
                continue
            rc = frag.rank_cache
            if rc is None:
                return None  # cache disabled mid-flight: full scan
            # snapshot under the fragment lock: concurrent writers mutate
            # rc.rows in place, and iterating a live dict would race
            with frag._lock:
                if rc.ensure(frag) and stats is not None:
                    stats.count("rankcache.build")
                entries.append((frag, rc, dict(rc.rows), rc.complete,
                                rc.bound))
    candidates: set[int] = set()
    bound = 0
    for _frag, _rc, rows, complete, rc_bound in entries:
        candidates.update(rows)
        if not complete:
            bound += rc_bound
    # exact global counts for the candidate set: cached counts are exact;
    # a candidate missing from an INCOMPLETE cache is recounted from that
    # fragment's host sparse store (complete caches prove absence = 0)
    totals: dict[int, int] = dict.fromkeys(candidates, 0)
    for frag, _rc, rows, complete, _b in entries:
        missing = [] if complete else \
            [r for r in candidates if r not in rows]
        if missing:
            marr = np.asarray(sorted(missing), dtype=np.int64)
            for r, c in zip(marr.tolist(),
                            frag.row_counts_host(marr).tolist()):
                totals[r] += int(c)
        for r, c in rows.items():
            totals[r] += c
    pairs = sorted(
        (Pair(r, c) for r, c in totals.items() if c > 0),
        key=lambda p: (-p.count, p.id))
    from ..utils import explain as qexplain
    if bound == 0:
        if stats is not None:
            stats.count("rankcache.hit")
        qexplain.note("caches", {"cache": "rank", "outcome": "prune",
                                 "candidates": len(candidates),
                                 "bound": 0})
        return pairs[:n] if n else pairs
    if n and len(pairs) >= n and pairs[n - 1].count > bound:
        if stats is not None:
            stats.count("rankcache.hit")
        qexplain.note("caches", {"cache": "rank", "outcome": "prune",
                                 "candidates": len(candidates),
                                 "bound": bound})
        return pairs[:n]
    # coverage unproven: full scan, and mark churn-degraded caches so the
    # next query rebuilds them instead of falling back forever
    for _frag, rc, _rows, _complete, _b in entries:
        if rc.degraded():
            rc.invalidate()
    if stats is not None:
        stats.count("rankcache.fallback")
    qexplain.note("caches", {"cache": "rank", "outcome": "fallback",
                             "candidates": len(candidates),
                             "bound": bound})
    return None
