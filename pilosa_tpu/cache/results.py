"""Generation-keyed result cache.

Memoizes finished read-query results keyed by (scope, index, normalized
query repr, shard set) PLUS everything the answer is a pure function of:
the index's fragment GENERATION VECTOR (every fragment stamps a unique,
monotonically increasing ``gen`` on mutation — storage/fragment.py:132),
the schema epoch (DDL / BSI depth growth), and the attr epoch (row/column
attribute writes).  Invalidation is therefore STRUCTURAL, never TTL-based:
a mutation changes a gen, the current key stops matching, and the stale
entry simply ages out of the LRU.  Local writes, remote imports received
on ``/internal/import/*``, and anti-entropy block repairs all go through
the same fragment mutators, so they all bump gens and thereby invalidate
exactly the affected entries.

The cluster layer adds a remote component to coordinator-scope keys: gen
summaries piggybacked on ``/internal/query`` responses and ``/status``
probes, plus a per-(index, peer) write version bumped whenever this node
forwards a write/import/repair to that peer (parallel/cluster.py).

Entries are LRU-bounded by bytes (``result-cache-mb``; 0 disables).  A
fill that supersedes an older entry for the same (scope, index, query,
shards) under different generations counts as an INVALIDATION and evicts
the stale entry eagerly, so churned queries don't pool garbage.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..utils import tenant as qtenant
from ..utils.locks import make_lock


# -- generation vectors ------------------------------------------------------

def gen_vector(holder, index: str, shards=None) -> tuple:
    """Precise per-fragment generation vector of ``index`` (optionally
    restricted to a shard set) — the local component of a cache key.
    Fragment creation/deletion changes the tuple shape, so appearing and
    vanishing fragments invalidate too."""
    idx = holder.index(index)
    if idx is None:
        return ()
    parts = []
    for fname, f in sorted(idx.fields.items()):
        for vname, v in sorted(f.views.items()):
            for shard, frag in sorted(v.fragments.items()):
                if shards is None or shard in shards:
                    parts.append((fname, vname, shard, frag.gen))
    return tuple(parts)


def gen_summary(holder, index: str) -> tuple[int, int, int]:
    """Compact (count, max, sum) of the index's fragment gens for wire
    piggybacking.  Gens come from one strictly increasing process counter,
    so ``max`` strictly increases on ANY mutation and ``count`` moves on
    fragment create/GC — the triple changes whenever the data does."""
    idx = holder.index(index)
    if idx is None:
        return (0, 0, 0)
    n = mx = total = 0
    for f in list(idx.fields.values()):
        for v in list(f.views.values()):
            for frag in list(v.fragments.values()):
                g = frag.gen
                n += 1
                total += g
                if g > mx:
                    mx = g
    return (n, mx, total)


def query_is_readonly(query) -> bool:
    """True when no call in the tree mutates state (Options can wrap
    writes, so the check is recursive)."""
    from ..pql.ast import WRITE_CALLS

    def walk(c):
        if c.name in WRITE_CALLS:
            return False
        return all(walk(ch) for ch in c.children)

    return all(walk(c) for c in query.calls)


def _result_bytes(results) -> int:
    """Conservative host-byte estimate of a results list (for the LRU
    byte budget)."""
    total = 64
    for r in results:
        total += 64
        segments = getattr(r, "segments", None)
        if segments is not None:
            for seg in segments.values():
                total += np.asarray(seg).nbytes
        elif isinstance(r, list):
            total += 64 * len(r)
        rows = getattr(r, "rows", None)
        if isinstance(rows, list):
            total += 8 * len(rows)
    return total


def _host_results(results):
    """Pull RowResult segments to host numpy IN PLACE: cached entries must
    not pin device (HBM) buffers, and every consumer already accepts
    numpy segments (the non-mesh path returns them natively)."""
    for r in results:
        segments = getattr(r, "segments", None)
        if segments is not None:
            r.segments = {s: np.asarray(seg) for s, seg in segments.items()}
    return results


class ResultCache:
    """(scope…, gens…) -> results list; thread-safe, LRU by bytes.

    ``limit_bytes == 0`` disables lookups and fills entirely (the bare-
    Executor default; the server wires ``result-cache-mb`` through).

    ``tenant_quota_bytes`` (``tenant-cache-quota-mb``; 0 = no per-tenant
    cap) bounds any ONE tenant's resident bytes: a fill that pushes its
    tenant over quota evicts that tenant's own oldest entries first, and
    global byte pressure also lands on over-quota tenants' entries before
    anyone else's — one tenant's churn cannot flush its neighbors
    (docs/robustness.md "Tenant isolation")."""

    def __init__(self, limit_bytes: int = 0, stats=None,
                 tenant_quota_bytes: int = 0):
        self.limit_bytes = limit_bytes
        self.tenant_quota_bytes = tenant_quota_bytes
        self.stats = stats
        self._lock = make_lock("result-cache")
        # key -> (results, nbytes, tenant)
        self._entries: OrderedDict = OrderedDict()
        self._by_query: dict = {}  # qkey -> full key (stale-entry sweep)
        self._tenant_bytes: dict[str, int] = {}
        self.resident_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evicts = 0
        self.invalidates = 0
        self.quota_evicts = 0

    def _count(self, name: str):
        if self.stats is not None:
            self.stats.count(name)

    def lookup(self, key):
        """Cached results list (shallow copy) or None."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        self._count("resultcache.hit" if entry is not None
                    else "resultcache.miss")
        return list(entry[0]) if entry is not None else None

    def _unlink(self, key) -> int:
        """Pop ``key`` and keep the byte ledgers consistent; returns the
        freed bytes (0 when absent).  Caller holds the lock."""
        entry = self._entries.pop(key, None)
        if entry is None:
            return 0
        _r, nb, t = entry
        self.resident_bytes -= nb
        if t is not None:
            left = self._tenant_bytes.get(t, 0) - nb
            if left > 0:
                self._tenant_bytes[t] = left
            else:
                self._tenant_bytes.pop(t, None)
        return nb

    def _evict_tenant_lru(self, tenant, keep) -> bool:
        """Evict ``tenant``'s least-recently-used entry (quota
        pressure), never ``keep`` — the entry being filled; a lone
        over-quota entry rides transiently over, so a quota smaller
        than one answer still caches that answer.  Caller holds the
        lock."""
        for k, entry in self._entries.items():  # LRU order
            if entry[2] == tenant and k != keep:
                self._unlink(k)
                self.evicts += 1
                self.quota_evicts += 1
                self._count("resultcache.evict")
                if self.stats is not None:
                    self.stats.count(f"tenant.{tenant}.quota_evict")
                qtenant.REGISTRY.note_quota_evict(tenant, entry[1])
                return True
        return False

    def _global_victim(self):
        """Global-pressure victim key: the oldest entry of any
        OVER-QUOTA tenant if one exists, else the plain LRU head.
        Caller holds the lock."""
        if self.tenant_quota_bytes > 0:
            over = {t for t, b in self._tenant_bytes.items()
                    if b > self.tenant_quota_bytes}
            if over:
                for k, entry in self._entries.items():
                    if entry[2] in over:
                        return k
        return next(iter(self._entries))

    def fill(self, qkey, key, results, tenant=None):
        """Insert under ``key``; ``qkey`` is the generation-free prefix
        used to eagerly drop a superseded (stale-gen) entry.  ``tenant``
        charges the entry's bytes to that tenant's quota (None falls
        back to the ambient request tenant)."""
        nbytes = _result_bytes(results)
        if nbytes > self.limit_bytes:
            return  # larger than the whole budget: never admit
        if tenant is None:
            tenant = qtenant.current_or_none()
        results = _host_results(results)
        with self._lock:
            old_key = self._by_query.get(qkey)
            if old_key is not None and old_key != key:
                if self._unlink(old_key):
                    self.invalidates += 1
                    self._count("resultcache.invalidate")
            self._by_query[qkey] = key
            self._unlink(key)
            self._entries[key] = (results, nbytes, tenant)
            self.resident_bytes += nbytes
            if tenant is not None:
                self._tenant_bytes[tenant] = \
                    self._tenant_bytes.get(tenant, 0) + nbytes
                # per-tenant quota: the filling tenant's own LRU pays
                while self.tenant_quota_bytes > 0 \
                        and self._tenant_bytes.get(tenant, 0) \
                        > self.tenant_quota_bytes \
                        and self._evict_tenant_lru(tenant, key):
                    pass
            while self.resident_bytes > self.limit_bytes and self._entries:
                self._unlink(self._global_victim())
                self.evicts += 1
                self._count("resultcache.evict")
            # _by_query is bookkeeping only; prune dangling pointers so it
            # cannot outgrow the entry table
            if len(self._by_query) > 2 * len(self._entries) + 64:
                live = set(self._entries)
                self._by_query = {q: k for q, k in self._by_query.items()
                                  if k in live}

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._by_query.clear()
            self._tenant_bytes.clear()
            self.resident_bytes = 0
        return n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.resident_bytes,
                "limitBytes": self.limit_bytes,
                "tenantQuotaBytes": self.tenant_quota_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evicts": self.evicts,
                "invalidates": self.invalidates,
                "quotaEvicts": self.quota_evicts,
                "tenantBytes": dict(self._tenant_bytes),
            }
