"""Server: composition root (reference server.go:46 Server,
server/server.go:137 Command.Start).

Builds holder -> API -> HTTP handler and runs background monitors.  Config
cascades TOML file < PILOSA_TPU_* env < explicit kwargs (reference
cmd/root.go:60 setAllConfig).
"""

from __future__ import annotations

import dataclasses
import os
import threading

from ..api import API
from ..storage import Holder
from ..utils.logger import Logger
from .handler import make_http_server


@dataclasses.dataclass
class Config:
    """(reference server/config.go:36 Config)"""
    data_dir: str = "~/.pilosa_tpu"
    bind: str = "localhost:10101"
    max_op_n: int = 10000
    # Highest row id accepted by any fragment (core.DEFAULT_MAX_ROW_ID).
    max_row_id: int = 0  # 0 = keep default
    # cluster
    node_id: str = "node0"
    cluster_hosts: list = dataclasses.field(default_factory=list)
    replica_n: int = 1
    # execution: serve queries through the device-mesh executor (stacked
    # shard batches + ICI reductions); off = per-shard host dispatch
    use_mesh: bool = True
    # -- cross-query dynamic batching (docs/batching.md) -------------------
    # Coalesce compatible concurrent queries into one fused device launch
    # (vmapped over a query axis) instead of one shard_map launch each
    # behind the collective-launch lock.  Off = every dispatch goes
    # straight to its own executable (the pre-batching behavior).
    dispatch_batch: bool = True
    # Queries per fused launch before the dispatcher fires early.
    dispatch_batch_max: int = 32
    # Microseconds the oldest queued ticket may wait for company before
    # the batch launches anyway (the solo-query latency tax ceiling).
    dispatch_batch_window_us: float = 200.0
    # -- whole-query pjit programs (docs/whole-query.md) -------------------
    # Compile each read request into ONE pjit program over the mesh
    # (every call, every shape group, reductions in-program) instead of
    # one executable per reducer stage.  Off restores the legacy
    # per-stage dispatch exactly (the kill switch).
    whole_query: bool = True
    # Fallback policy for shapes the program can't express: "legacy"
    # reroutes to the per-stage path (counted `wholequery.fallback` +
    # structured log event); "error" raises instead — a debugging mode
    # that makes every silent slow path loud.
    whole_query_fallback: str = "legacy"
    # HBM budget for device-resident fragment mirrors + stacked shard
    # blocks (storage/membudget.py DeviceBudget — the syswrap map-cap
    # analog, syswrap/mmap.go:46).  0 = unlimited (accounting only).
    device_budget_mb: int = 0
    # Host-side dense staging cache ceiling (docs/memory-budget.md):
    # expanded fragment blocks kept on host so re-uploads after HBM
    # eviction skip the sparse->dense expansion.  0 disables the cache,
    # -1 = unbounded.
    host_stage_mb: int = 4096
    # -- compressed residency (docs/memory-budget.md) ----------------------
    # Keep sparse fragments HBM-resident as packed array/bitmap/run
    # container streams (ops/containers.py), decoded to dense tiles on
    # device at op time; engages only under a device-budget limit.
    compressed_resident: bool = True
    # Density fallback: a fragment compresses only when its estimated
    # packed bytes are at most this fraction of its dense footprint
    # (dense corpora stay dense — no decode cost, no ~1x "compression").
    compress_max_density: float = 0.5
    # Per-launch dense decode workspace ceiling (MB): shard slices are
    # cut so one launch never decodes more dense tile bytes than this.
    decode_workspace_mb: int = 1024
    # Container decode backend (ops/kernels.py): "auto" picks the fused
    # Pallas kernels on TPU and the jnp decode elsewhere; "pallas"
    # forces the kernels (interpreted off-TPU — the differential-test
    # mode); "jnp" is the kill switch restoring the pure-XLA decode.
    container_kernels: str = "auto"
    # -- streaming ingest (docs/ingest.md) ---------------------------------
    # Group-commit window: milliseconds the committer lets submissions
    # coalesce before flushing (one WAL frame + one gen bump + one
    # rank-cache touch per fragment per flush).  <= 0 flushes inline.
    ingest_flush_ms: float = 50.0
    # Process-wide budget for ingest delta-overlay journals — the bits
    # OR'd into resident device state between folds.  Over it (or per
    # fragment over an eighth of it) journals fold and device forms
    # rebuild from the sparse store.  0 disables overlays entirely.
    ingest_delta_mb: int = 64
    # Per-frame ceiling on the ingest wire (a frame buffers whole for
    # its CRC, so this bounds per-connection memory).
    ingest_max_frame_mb: int = 32
    # monitors / metrics (reference server/config.go metric section)
    anti_entropy_interval: float = 600.0
    metric_poll_interval: float = 60.0
    metric_service: str = "expvar"  # expvar | statsd | none
    metric_host: str = "localhost:8125"
    # Diagnostics (reference diagnostics.go, default-off here): when an
    # endpoint is set, POST an anonymized runtime/schema summary there on
    # the given interval — for the OPERATOR's fleet monitoring.
    diagnostics_endpoint: str = ""
    diagnostics_interval: float = 3600.0
    # TLS (reference server/tlsconfig.go): serve HTTPS when certificate +
    # key are set; a CA certificate additionally enforces MUTUAL TLS.
    # Cluster peers must then be listed as https://host:port.
    tls_certificate: str = ""
    tls_key: str = ""
    tls_ca_certificate: str = ""
    tls_skip_verify: bool = False  # client side: don't verify peer certs
    # HTTP request-body ceiling (MB); 413 above it, 0 = unlimited.
    # Generous default: bulk imports of a dense shard legitimately run
    # to hundreds of MB.
    max_body_mb: int = 1024
    # Opt-in higher ceiling for the node-to-node /internal/ plane
    # (roaring import fan-out, resize fragment copies); 0 (default) =
    # same as max_body_mb.  Raise only behind mutual TLS — the path
    # prefix is not authentication.
    max_body_internal_mb: int = 0
    # -- overload armor (docs/robustness.md) -------------------------------
    # Default end-to-end deadline (seconds) for public queries without an
    # explicit ?timeout=; expired queries abort between shard slices and
    # return 504.  0 = unlimited.
    query_timeout: float = 0.0
    # Concurrent-query slot pool size (public and internal pools are
    # SEPARATE instances of this size so coordinator fan-out can never
    # self-deadlock behind public traffic).  0 = unlimited.
    max_queries: int = 64
    # Seconds an over-slot query may wait for a slot before 503 +
    # Retry-After; the wait queue holds at most 2*max_queries.
    queue_timeout: float = 0.5
    # Consecutive node-to-node TRANSPORT failures that open a peer's
    # circuit breaker (fail-fast ClusterError; half-open probe on the
    # health cadence).  0 disables breaking.
    breaker_threshold: int = 5
    # Graceful-drain budget: close() stops admitting new queries, lets
    # in-flight ones finish for up to this many seconds, then closes.
    drain_seconds: float = 5.0
    # Consecutive SOFT probe failures (timeouts/resets — refused
    # connections flip immediately) before NODE_DOWN.
    health_down_threshold: int = 2
    # -- tail-tolerant reads (docs/robustness.md "Tail-tolerant fan-out")
    # Hedged reads: a read fan-out RPC still unanswered after its hedge
    # delay speculatively duplicates to the next-best replica; the first
    # answer wins, the loser is ignored.  Internal read calls are
    # idempotent, so hedging never changes answers; writes are never
    # hedged.  Off disables speculation entirely.
    hedge_reads: bool = True
    # Milliseconds before an in-flight read RPC is hedged.  0 (default)
    # derives the delay per dispatch from the router's EWMA RTT (a
    # multiple of the cheapest known peer RTT — see parallel/routing.py);
    # a cold cluster with no RTT history then hedges nothing.
    hedge_delay_ms: float = 0.0
    # Server default for ?partialResults: when true, a read whose shards
    # are truly unservable (every replica dead/partitioned/exhausted)
    # answers with what it has, and the response's degraded object names
    # exactly the missing shards/nodes.  Off = such reads fail loudly.
    partial_results: bool = False
    # Internal query wire (docs/cluster.md "Internal query wire"):
    # "bin1" (default) speaks the PTPUQRY1 CRC-framed binary transport
    # on /internal/query — roaring-packed row segments, packed numpy
    # scalar arrays — negotiating per peer via the /status `wire`
    # capability list and downgrading to JSON on refusal; "json"
    # restores the pre-binary JSON envelope exactly, both served and
    # spoken.
    internal_wire: str = "bin1"
    # -- tenant isolation (docs/robustness.md "Tenant isolation") ----------
    # Weighted-fair per-tenant admission queues + tenant-first shedding.
    # Off collapses the wait queues back to the single pre-isolation
    # FIFO (reject-the-arrival shedding) for differential benches.
    tenant_isolation: bool = True
    # Relative admission weights, "name:weight,...": e.g.
    # "analytics:4,batch:1" gives analytics 4 slot grants per batch
    # grant under contention.  Unlisted tenants weigh 1.
    tenant_weights: str = ""
    # Burst allowance: an idle tenant banks up to weight*burst slot
    # credits, so a short burst rides through un-queued-on before
    # deficit round-robin paces it.
    tenant_burst: float = 8.0
    # Per-tenant byte cap (MB) inside the result cache AND the HBM
    # residency budget: a tenant filling past it evicts its OWN entries
    # first, and global pressure prefers over-quota tenants.  0 = no
    # per-tenant cap (the global budgets still apply).
    tenant_cache_quota_mb: int = 0
    # Per-tenant hedge token budget (tokens/second, equal burst): each
    # speculative read draws one token from the requesting tenant's
    # bucket; an exhausted bucket reads unhedged (counted, never an
    # error).  0 = unlimited hedging.
    tenant_hedge_budget: float = 32.0
    # -- elastic serving (docs/cluster.md "Read routing & rebalancing") ----
    # Read fan-out replica policy: "primary" pins reads to the jump-hash
    # primary (the pre-routing behavior, byte-for-byte), "round-robin"
    # rotates among READY owners, "loaded" scores replicas by EWMA RTT x
    # queue pressure with a residency discount (parallel/routing.py).
    read_routing: str = "loaded"
    # Prefer the replica that holds the queried shards HBM-resident or
    # host-staged (residency tiers piggybacked on /status probes); off =
    # pure load scores.
    residency_routing: bool = True
    # Hot-shard balancer (parallel/balancer.py): the coordinator widens a
    # sustained-hot shard's replica set by one underloaded node (resize-
    # fetch copy + epoch-gated placement-overlay broadcast).  Off
    # (default) keeps placement exactly static jump-hash.
    balancer: bool = False
    # Seconds between balancer ticks (also the shard-load counter
    # window).
    balancer_interval: float = 30.0
    # A shard is "hot" when its dispatch count over the window exceeds
    # this multiple of the mean across active shards (plus an absolute
    # floor; balancer.HOT_MIN_COUNT).
    hot_shard_threshold: float = 4.0
    # Failpoint spec armed at startup (utils/faults.py syntax); empty =
    # nothing armed.  For chaos tests and game-days only.
    failpoints: str = ""
    # -- durability & recovery (docs/robustness.md) ------------------------
    # Frame new WAL files with length+CRC records so torn tails are
    # detected and truncated at a record boundary on replay.  Off writes
    # the legacy bare record stream (old-reader compatibility /
    # differential testing); existing files always keep THEIR format
    # until the next snapshot truncation.
    wal_crc: bool = True
    # A corrupt snapshot/WAL quarantines the fragment — empty reads with
    # a degraded flag, writes refused with a retryable 503, replica
    # repair heals it — instead of raising out of startup.  Off restores
    # fail-stop opens (debugging / single-node forensics).
    quarantine_on_corruption: bool = True
    # Seconds between dedicated quarantine-repair sweeps (re-fetch
    # quarantined fragments wholesale from a healthy replica).  The
    # anti-entropy pass also repairs on its own cadence; this knob keeps
    # the time-to-heal well under anti-entropy-interval.  0 disables the
    # dedicated sweep.
    repair_interval: float = 60.0
    # -- query cache subsystem (docs/caching.md) ---------------------------
    # Host-byte budget for the generation-keyed result cache (LRU); 0
    # disables it.  Off by default so chaos/overload exercises hit the
    # real execution path; production serving wants it on (e.g. 256).
    result_cache_mb: int = 0
    # Distinct rows a batched write may touch before a fragment's rank
    # cache stops updating incrementally and rebuilds lazily instead.
    rank_rebuild_rows: int = 4096
    # -- observability (docs/observability.md) -----------------------------
    # Queries slower than this (seconds) land in the slow-query log ring
    # (/debug/slow) with their trace id + profile tree, and are emitted
    # as structured log lines.  0 disables the log.
    slow_query_threshold: float = 1.0
    # Entries kept in the slow-query ring buffer.
    slow_log_size: int = 128
    # Return the per-query profile tree on EVERY query response, not just
    # those with ?profile=true (an always-on EXPLAIN ANALYZE).
    profile_default: bool = False
    # Fraction of trace ROOTS recorded to the span ring buffer; the
    # decision propagates to children and across the wire, so a trace is
    # recorded everywhere or nowhere.  1.0 = always-on (Dapper-style).
    trace_sample_rate: float = 1.0
    # -- device-runtime observability (docs/observability.md) --------------
    # Seconds between in-process time-series samples of the runtime
    # gauges (HBM split, admission depth, compile/retrace counts, edge
    # histogram deltas) served at /debug/timeseries and rendered by
    # /debug/dashboard.  0 disables the sampler.
    timeseries_interval: float = 5.0
    # Seconds of history the time-series ring retains — the "what
    # happened in the last N minutes" horizon; memory is one flat dict
    # per window/interval samples.
    timeseries_window: float = 600.0
    # Entries kept in the device launch-ledger ring (/debug/launches).
    launch_ledger_size: int = 256
    # -- cluster observability plane (docs/observability.md) ---------------
    # Entries kept in the structured event-journal ring (/debug/events):
    # breaker/node/quarantine/overlay/resize/backpressure transitions.
    event_journal_size: int = 512
    # Persist the event journal to <data-dir>/events.log as length+CRC
    # framed JSON records (torn tails truncate at a frame boundary on
    # reopen).  Off keeps the journal in-memory only.
    event_log: bool = False
    # Characters of query text stored per slow-log entry.  Raise it when
    # harvesting a recorded workload for replay (bench.py): entries
    # still over the ceiling are marked textTruncated and skipped by the
    # replay harvester.
    slow_log_text_max: int = 512
    # -- SLOs & alerting (docs/observability.md "SLOs & alerting") ---------
    # Latency objective: the SLO counts an http.query over this many
    # milliseconds as bad (snapped down to the nearest latency-histogram
    # bucket edge so the count is exact).
    slo_latency_ms: float = 500.0
    # Objective target for BOTH SLOs: the good fraction of http.query
    # (non-5xx for availability, under slo-latency-ms for latency) the
    # burn-rate windows are judged against.
    slo_target: float = 0.999
    # Alert rules the SLO engine evaluates each time-series interval:
    # "all", "off", or a comma-separated list of rule ids (the
    # docs/observability.md alerts catalog).  Evaluation also requires
    # the time-series ring (timeseries-interval > 0).
    alert_rules: str = "all"
    # Disk budget (MB) for flight-recorder diagnostic bundles under
    # <data-dir>/flightrec, LRU-pruned by file mtime (the compile-cache
    # discipline).  0 disables the recorder (alerts still fire).
    flight_recorder_mb: int = 64
    # Per-launch batch-temp workspace ceiling (MB) for fused/batched
    # [B, rows, W] device temps (row_counts/TopN batches): the batch
    # axis chunks when a launch would exceed it (counted
    # query.batch_temp_splits), and the cross-query batcher stops
    # fusing past it.  The decode-workspace-mb pattern, on the batch
    # axis.
    batch_temp_mb: int = 4096
    # -- warm start (docs/warmup.md) ---------------------------------------
    # Directory for jax's persistent XLA compilation cache, so a
    # restarted process reuses executables instead of recompiling.
    # "" = <data-dir>/.compile-cache; "off" disables the on-disk cache
    # (the signature corpus + warmup replay still run).
    compile_cache_dir: str = ""
    # Size bound (MB) for the compile-cache directory, LRU-pruned by
    # file mtime at startup and clean shutdown.  0 = unbounded.
    compile_cache_mb: int = 256
    # Corpus signatures the AOT warmup replayer replays at startup (the
    # top-N by traffic) before this node reports READY.  0 disables the
    # replay (corpus recording still runs for the next restart).
    warmup_top_n: int = 32
    # Wall-clock budget (seconds) for the warmup replay: entries beyond
    # it are skipped (counted) and the node goes READY anyway — warmup
    # may make READY later, never absent.
    warmup_budget_s: float = 30.0
    verbose: bool = False

    @classmethod
    def from_env(cls, **overrides) -> "Config":
        cfg = cls()
        cls._apply_env(cfg)
        cls._apply_overrides(cfg, overrides)
        return cfg

    @staticmethod
    def _apply_env(cfg):
        env_map = {
            "PILOSA_TPU_DATA_DIR": ("data_dir", str),
            "PILOSA_TPU_BIND": ("bind", str),
            "PILOSA_TPU_NODE_ID": ("node_id", str),
            "PILOSA_TPU_REPLICA_N": ("replica_n", int),
            "PILOSA_TPU_CLUSTER_HOSTS": (
                "cluster_hosts", lambda s: s.split(",") if s else []),
            "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": (
                "anti_entropy_interval", float),
            "PILOSA_TPU_VERBOSE": ("verbose", lambda s: s == "true"),
            "PILOSA_TPU_MAX_ROW_ID": ("max_row_id", int),
            "PILOSA_TPU_USE_MESH": ("use_mesh", lambda s: s != "false"),
            "PILOSA_TPU_DISPATCH_BATCH": (
                "dispatch_batch", lambda s: s != "false"),
            "PILOSA_TPU_DISPATCH_BATCH_MAX": ("dispatch_batch_max", int),
            "PILOSA_TPU_DISPATCH_BATCH_WINDOW_US": (
                "dispatch_batch_window_us", float),
            "PILOSA_TPU_WHOLE_QUERY": (
                "whole_query", lambda s: s != "false"),
            "PILOSA_TPU_WHOLE_QUERY_FALLBACK": ("whole_query_fallback",
                                                str),
            "PILOSA_TPU_DEVICE_BUDGET_MB": ("device_budget_mb", int),
            "PILOSA_TPU_HOST_STAGE_MB": ("host_stage_mb", int),
            "PILOSA_TPU_COMPRESSED_RESIDENT": (
                "compressed_resident", lambda s: s != "false"),
            "PILOSA_TPU_COMPRESS_MAX_DENSITY": ("compress_max_density",
                                                float),
            "PILOSA_TPU_DECODE_WORKSPACE_MB": ("decode_workspace_mb",
                                               int),
            "PILOSA_TPU_CONTAINER_KERNELS": ("container_kernels", str),
            "PILOSA_TPU_INGEST_FLUSH_MS": ("ingest_flush_ms", float),
            "PILOSA_TPU_INGEST_DELTA_MB": ("ingest_delta_mb", int),
            "PILOSA_TPU_INGEST_MAX_FRAME_MB": ("ingest_max_frame_mb",
                                               int),
            "PILOSA_TPU_METRIC_SERVICE": ("metric_service", str),
            "PILOSA_TPU_METRIC_HOST": ("metric_host", str),
            "PILOSA_TPU_DIAGNOSTICS_ENDPOINT": ("diagnostics_endpoint",
                                                str),
            "PILOSA_TPU_DIAGNOSTICS_INTERVAL": ("diagnostics_interval",
                                                float),
            "PILOSA_TPU_TLS_CERTIFICATE": ("tls_certificate", str),
            "PILOSA_TPU_TLS_KEY": ("tls_key", str),
            "PILOSA_TPU_TLS_CA_CERTIFICATE": ("tls_ca_certificate", str),
            "PILOSA_TPU_TLS_SKIP_VERIFY": (
                "tls_skip_verify", lambda s: s == "true"),
            "PILOSA_TPU_MAX_BODY_MB": ("max_body_mb", int),
            "PILOSA_TPU_MAX_BODY_INTERNAL_MB": ("max_body_internal_mb",
                                                int),
            "PILOSA_TPU_QUERY_TIMEOUT": ("query_timeout", float),
            "PILOSA_TPU_MAX_QUERIES": ("max_queries", int),
            "PILOSA_TPU_QUEUE_TIMEOUT": ("queue_timeout", float),
            "PILOSA_TPU_BREAKER_THRESHOLD": ("breaker_threshold", int),
            "PILOSA_TPU_DRAIN_SECONDS": ("drain_seconds", float),
            "PILOSA_TPU_HEALTH_DOWN_THRESHOLD": ("health_down_threshold",
                                                 int),
            "PILOSA_TPU_HEDGE_READS": (
                "hedge_reads", lambda s: s != "false"),
            "PILOSA_TPU_HEDGE_DELAY_MS": ("hedge_delay_ms", float),
            "PILOSA_TPU_PARTIAL_RESULTS": (
                "partial_results", lambda s: s == "true"),
            "PILOSA_TPU_INTERNAL_WIRE": ("internal_wire", str),
            "PILOSA_TPU_TENANT_ISOLATION": (
                "tenant_isolation", lambda s: s != "false"),
            "PILOSA_TPU_TENANT_WEIGHTS": ("tenant_weights", str),
            "PILOSA_TPU_TENANT_BURST": ("tenant_burst", float),
            "PILOSA_TPU_TENANT_CACHE_QUOTA_MB": (
                "tenant_cache_quota_mb", int),
            "PILOSA_TPU_TENANT_HEDGE_BUDGET": (
                "tenant_hedge_budget", float),
            "PILOSA_TPU_READ_ROUTING": ("read_routing", str),
            "PILOSA_TPU_RESIDENCY_ROUTING": (
                "residency_routing", lambda s: s != "false"),
            "PILOSA_TPU_BALANCER": ("balancer", lambda s: s == "true"),
            "PILOSA_TPU_BALANCER_INTERVAL": ("balancer_interval", float),
            "PILOSA_TPU_HOT_SHARD_THRESHOLD": ("hot_shard_threshold",
                                               float),
            "PILOSA_TPU_FAILPOINTS": ("failpoints", str),
            "PILOSA_TPU_WAL_CRC": ("wal_crc", lambda s: s != "false"),
            "PILOSA_TPU_QUARANTINE_ON_CORRUPTION": (
                "quarantine_on_corruption", lambda s: s != "false"),
            "PILOSA_TPU_REPAIR_INTERVAL": ("repair_interval", float),
            "PILOSA_TPU_RESULT_CACHE_MB": ("result_cache_mb", int),
            "PILOSA_TPU_RANK_REBUILD_ROWS": ("rank_rebuild_rows", int),
            "PILOSA_TPU_SLOW_QUERY_THRESHOLD": ("slow_query_threshold",
                                                float),
            "PILOSA_TPU_SLOW_LOG_SIZE": ("slow_log_size", int),
            "PILOSA_TPU_PROFILE_DEFAULT": (
                "profile_default", lambda s: s == "true"),
            "PILOSA_TPU_TRACE_SAMPLE_RATE": ("trace_sample_rate", float),
            "PILOSA_TPU_TIMESERIES_INTERVAL": ("timeseries_interval",
                                               float),
            "PILOSA_TPU_TIMESERIES_WINDOW": ("timeseries_window", float),
            "PILOSA_TPU_LAUNCH_LEDGER_SIZE": ("launch_ledger_size", int),
            "PILOSA_TPU_EVENT_JOURNAL_SIZE": ("event_journal_size", int),
            "PILOSA_TPU_EVENT_LOG": ("event_log", lambda s: s == "true"),
            "PILOSA_TPU_SLOW_LOG_TEXT_MAX": ("slow_log_text_max", int),
            "PILOSA_TPU_SLO_LATENCY_MS": ("slo_latency_ms", float),
            "PILOSA_TPU_SLO_TARGET": ("slo_target", float),
            "PILOSA_TPU_ALERT_RULES": ("alert_rules", str),
            "PILOSA_TPU_FLIGHT_RECORDER_MB": ("flight_recorder_mb", int),
            "PILOSA_TPU_BATCH_TEMP_MB": ("batch_temp_mb", int),
            "PILOSA_TPU_COMPILE_CACHE_DIR": ("compile_cache_dir", str),
            "PILOSA_TPU_COMPILE_CACHE_MB": ("compile_cache_mb", int),
            "PILOSA_TPU_WARMUP_TOP_N": ("warmup_top_n", int),
            "PILOSA_TPU_WARMUP_BUDGET_S": ("warmup_budget_s", float),
        }
        for env, (attr, conv) in env_map.items():
            if env in os.environ:
                setattr(cfg, attr, conv(os.environ[env]))

    @staticmethod
    def _apply_overrides(cfg, overrides):
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)

    @classmethod
    def from_toml(cls, path: str, **overrides) -> "Config":
        """Precedence: TOML file < PILOSA_TPU_* env < explicit kwargs
        (reference cmd/root.go:60 setAllConfig)."""
        from ..utils import toml
        with open(path, "rb") as f:
            doc = toml.load(f)
        cfg = cls()
        mapping = {
            "data-dir": "data_dir", "bind": "bind", "max-op-n": "max_op_n",
            "max-row-id": "max_row_id", "use-mesh": "use_mesh",
            "dispatch-batch": "dispatch_batch",
            "dispatch-batch-max": "dispatch_batch_max",
            "dispatch-batch-window-us": "dispatch_batch_window_us",
            "whole-query": "whole_query",
            "whole-query-fallback": "whole_query_fallback",
            "device-budget-mb": "device_budget_mb",
            "host-stage-mb": "host_stage_mb",
            "compressed-resident": "compressed_resident",
            "compress-max-density": "compress_max_density",
            "decode-workspace-mb": "decode_workspace_mb",
            "container-kernels": "container_kernels",
            "ingest-flush-ms": "ingest_flush_ms",
            "ingest-delta-mb": "ingest_delta_mb",
            "ingest-max-frame-mb": "ingest_max_frame_mb",
            "max-body-mb": "max_body_mb",
            "max-body-internal-mb": "max_body_internal_mb",
            "query-timeout": "query_timeout",
            "max-queries": "max_queries",
            "queue-timeout": "queue_timeout",
            "breaker-threshold": "breaker_threshold",
            "drain-seconds": "drain_seconds",
            "health-down-threshold": "health_down_threshold",
            "hedge-reads": "hedge_reads",
            "hedge-delay-ms": "hedge_delay_ms",
            "partial-results": "partial_results",
            "internal-wire": "internal_wire",
            "tenant-isolation": "tenant_isolation",
            "tenant-weights": "tenant_weights",
            "tenant-burst": "tenant_burst",
            "tenant-cache-quota-mb": "tenant_cache_quota_mb",
            "tenant-hedge-budget": "tenant_hedge_budget",
            "read-routing": "read_routing",
            "residency-routing": "residency_routing",
            "balancer": "balancer",
            "balancer-interval": "balancer_interval",
            "hot-shard-threshold": "hot_shard_threshold",
            "failpoints": "failpoints",
            "wal-crc": "wal_crc",
            "quarantine-on-corruption": "quarantine_on_corruption",
            "repair-interval": "repair_interval",
            "result-cache-mb": "result_cache_mb",
            "rank-rebuild-rows": "rank_rebuild_rows",
            "slow-query-threshold": "slow_query_threshold",
            "slow-log-size": "slow_log_size",
            "profile-default": "profile_default",
            "trace-sample-rate": "trace_sample_rate",
            "timeseries-interval": "timeseries_interval",
            "timeseries-window": "timeseries_window",
            "launch-ledger-size": "launch_ledger_size",
            "event-journal-size": "event_journal_size",
            "event-log": "event_log",
            "slow-log-text-max": "slow_log_text_max",
            "slo-latency-ms": "slo_latency_ms",
            "slo-target": "slo_target",
            "alert-rules": "alert_rules",
            "flight-recorder-mb": "flight_recorder_mb",
            "batch-temp-mb": "batch_temp_mb",
            "compile-cache-dir": "compile_cache_dir",
            "compile-cache-mb": "compile_cache_mb",
            "warmup-top-n": "warmup_top_n",
            "warmup-budget-s": "warmup_budget_s",
        }
        for key, attr in mapping.items():
            if key in doc:
                setattr(cfg, attr, doc[key])
        cluster = doc.get("cluster", {})
        if "hosts" in cluster:
            cfg.cluster_hosts = cluster["hosts"]
        if "replicas" in cluster:
            cfg.replica_n = cluster["replicas"]
        if "anti-entropy" in doc and "interval" in doc["anti-entropy"]:
            cfg.anti_entropy_interval = float(doc["anti-entropy"]["interval"])
        tls = doc.get("tls", {})
        for key, attr in (("certificate", "tls_certificate"),
                          ("key", "tls_key"),
                          ("ca-certificate", "tls_ca_certificate"),
                          ("skip-verify", "tls_skip_verify")):
            if key in tls:
                setattr(cfg, attr, tls[key])
        cls._apply_env(cfg)
        cls._apply_overrides(cfg, overrides)
        return cfg


class Server:
    def __init__(self, config: Config | None = None):
        self.config = config or Config()
        self.logger = Logger(verbose=self.config.verbose)
        from ..utils.stats import make_stats_client
        self.stats = make_stats_client(self.config.metric_service,
                                       self.config.metric_host)
        # The budget is process-wide; the most recent Server's config wins
        # (0 restores unlimited — a stale limit from an earlier instance in
        # the same process must not outlive its config).
        from ..storage.membudget import DEFAULT_BUDGET, HOST_STAGE_BUDGET
        DEFAULT_BUDGET.limit_bytes = (
            self.config.device_budget_mb * (1 << 20)
            if self.config.device_budget_mb > 0 else None)
        HOST_STAGE_BUDGET.limit_bytes = (
            self.config.host_stage_mb * (1 << 20)
            if self.config.host_stage_mb > 0
            else (0 if self.config.host_stage_mb == 0 else None))
        HOST_STAGE_BUDGET.shrink_to_limit()
        # tenant isolation (docs/robustness.md "Tenant isolation"):
        # per-tenant residency quota on the HBM tier, same process-wide
        # most-recent-Server-wins convention as the limits above
        DEFAULT_BUDGET.tenant_quota_bytes = \
            max(self.config.tenant_cache_quota_mb, 0) << 20
        # Durability knobs are process-wide module flags on the fragment
        # codec (same most-recent-Server-wins convention as the budgets):
        # they govern file OPENS, which happen under holder.open() below.
        from ..storage import fragment as _fragment
        _fragment.WAL_CRC = bool(self.config.wal_crc)
        _fragment.QUARANTINE_ON_CORRUPTION = bool(
            self.config.quarantine_on_corruption)
        # compressed residency (docs/memory-budget.md): process-wide
        # module knobs on the fragment codec and the mesh slice planner,
        # same most-recent-Server-wins convention as the budgets
        _fragment.COMPRESSED_RESIDENT = bool(self.config.compressed_resident)
        _fragment.COMPRESS_MAX_DENSITY = max(
            float(self.config.compress_max_density), 0.0)
        from ..parallel import mesh_exec as _mesh_exec
        _mesh_exec.DECODE_WORKSPACE_BYTES = \
            max(self.config.decode_workspace_mb, 1) << 20
        # container-kernels backend selector (ops/kernels.py); the
        # resolved backend rides compressed device signatures, so a
        # change rebuilds stacks/executables rather than retracing
        from ..ops import kernels as _kernels
        _kernels.CONTAINER_KERNELS = str(self.config.container_kernels)
        # batch-temp workspace (docs/observability.md satellite of the
        # decode-workspace pattern): bounds fused/batched [B, rows, W]
        # device temps; process-wide, most recent Server wins
        from ..executor import executor as _executor_mod
        _executor_mod.BATCH_TEMP_BYTES = \
            max(self.config.batch_temp_mb, 1) << 20
        # streaming ingest (docs/ingest.md): the delta-overlay budget is
        # process-wide like the others (most recent Server wins)
        from ..storage import membudget as _membudget
        _membudget.INGEST_DELTA_LIMIT_BYTES = \
            max(self.config.ingest_delta_mb, 0) << 20
        data_dir = os.path.expanduser(self.config.data_dir)
        self.holder = Holder(
            data_dir, max_op_n=self.config.max_op_n,
            max_row_id=(self.config.max_row_id
                        if self.config.max_row_id > 0 else None))
        # failpoints (utils/faults.py): config/env-armed chaos injection;
        # the registry is process-global and a no-op when the spec is
        # empty (the production default)
        if self.config.failpoints:
            from ..utils.faults import FAULTS
            FAULTS.configure(self.config.failpoints)
        self.cluster = None
        if self.config.cluster_hosts:
            from ..parallel.cluster import Cluster
            self.cluster = Cluster(
                node_id=self.config.node_id,
                hosts=self.config.cluster_hosts,
                replica_n=self.config.replica_n,
                holder=self.holder,
                health_down_threshold=self.config.health_down_threshold,
                breaker_threshold=self.config.breaker_threshold,
                stats=self.stats,
                read_routing=self.config.read_routing,
                residency_routing=self.config.residency_routing,
                balancer=self.config.balancer,
                balancer_interval=self.config.balancer_interval,
                hot_shard_threshold=self.config.hot_shard_threshold,
                hedge_reads=self.config.hedge_reads,
                hedge_delay_ms=self.config.hedge_delay_ms,
                internal_wire=self.config.internal_wire,
                tenant_hedge_budget=(
                    self.config.tenant_hedge_budget
                    if self.config.tenant_isolation else 0.0),
            )
            # fan-out failure events (cluster.fanout_failed) land in the
            # server log like the whole-query fallbacks
            self.cluster.logger = self.logger
            if not self.cluster.is_coordinator:
                # key translation lives on the coordinator; replicas route
                # to it with a read-through cache
                self.holder.translate_factory = \
                    self.cluster.remote_translate_factory
        self.api = API(
            self.holder, cluster=self.cluster, stats=self.stats,
            use_mesh=self.config.use_mesh,
            dispatch_batch=self.config.dispatch_batch,
            dispatch_batch_max=self.config.dispatch_batch_max,
            dispatch_batch_window_us=self.config.dispatch_batch_window_us,
            whole_query=self.config.whole_query,
            whole_query_fallback=self.config.whole_query_fallback)
        # wholequery.fallback events land in the server log (the
        # executor stays silent standalone, like the compile registry)
        self.api.executor.logger = self.logger
        # query cache subsystem (docs/caching.md): byte budget for the
        # result cache; the rank-rebuild threshold is process-wide like
        # the memory budgets (most recent Server's config wins)
        self.api.executor.result_cache.limit_bytes = \
            max(self.config.result_cache_mb, 0) << 20
        self.api.executor.result_cache.tenant_quota_bytes = \
            (max(self.config.tenant_cache_quota_mb, 0) << 20) \
            if self.config.tenant_isolation else 0
        from .. import cache as _cache_pkg
        _cache_pkg.rank.RANK_REBUILD_ROWS = max(
            self.config.rank_rebuild_rows, 0)
        host, port = self._parse_bind(self.config.bind)
        tls = None
        if self.config.tls_certificate and self.config.tls_key:
            tls = (self.config.tls_certificate, self.config.tls_key,
                   self.config.tls_ca_certificate or None)
            if self.cluster is not None:
                self.cluster.client.configure_tls(
                    self.config.tls_certificate, self.config.tls_key,
                    self.config.tls_ca_certificate or None,
                    self.config.tls_skip_verify)
        # Admission control (server/admission.py): separate public and
        # internal slot pools of the same size — the split, not the
        # sizing, is what prevents coordinator fan-out from deadlocking
        # behind public traffic.
        from .admission import AdmissionController
        from ..utils.tenant import parse_weights
        tenant_weights = parse_weights(self.config.tenant_weights)
        tenant_kw = dict(weights=tenant_weights,
                         burst=self.config.tenant_burst,
                         fair=self.config.tenant_isolation)
        self.admission = AdmissionController(
            self.config.max_queries, self.config.queue_timeout,
            stats=self.stats, name="public", **tenant_kw)
        self.admission_internal = AdmissionController(
            self.config.max_queries, self.config.queue_timeout,
            stats=self.stats, name="internal", **tenant_kw)
        # Third pool for streaming ingest (docs/ingest.md): sustained
        # writes must not occupy read slots, and forwarded-ingest
        # handling on a peer must not queue behind ITS public writes
        # either (forwards never re-forward, so depth-1 sharing is
        # deadlock-free).
        self.admission_ingest = AdmissionController(
            self.config.max_queries, self.config.queue_timeout,
            stats=self.stats, name="ingest", **tenant_kw)
        # Group committer: the write path's flush/merge engine.
        from ..ingest import GroupCommitter
        self.committer = GroupCommitter(
            self.holder, flush_ms=self.config.ingest_flush_ms,
            stats=self.stats)
        # Observability (docs/observability.md): the slow-query ring +
        # the trace-sampling decision.  The tracer is process-wide like
        # the memory budgets — the most recent Server's config wins.
        from ..utils.slowlog import SlowQueryLog
        from ..utils.tracing import GLOBAL_TRACER
        GLOBAL_TRACER.sample_rate = min(
            max(self.config.trace_sample_rate, 0.0), 1.0)
        self.slowlog = SlowQueryLog(
            threshold_s=self.config.slow_query_threshold,
            size=self.config.slow_log_size,
            logger=self.logger, stats=self.stats,
            text_max=self.config.slow_log_text_max)
        # Event journal (docs/observability.md "Cluster plane"):
        # process-wide like the tracer — the most recent Server's config
        # sizes the ring, stamps the node id, and (opt-in) attaches the
        # framed on-disk log under the data dir.
        from ..utils.events import EVENTS
        EVENTS.resize(self.config.event_journal_size)
        EVENTS.node_id = self.config.node_id
        if self.config.event_log:
            # the holder creates data_dir at open(); the journal
            # attaches earlier, so ensure the directory here
            os.makedirs(data_dir, exist_ok=True)
            EVENTS.open_log(os.path.join(data_dir, "events.log"))
        # Device-runtime observability (docs/observability.md "Device
        # runtime"): the process-wide compile registry logs retraces
        # through THIS server's logger (most recent Server wins, like
        # the budgets), the launch ledger resizes to the configured
        # ring, and the time-series ring samples the runtime gauges on
        # its own monitor thread.
        from ..utils import devobs
        devobs.COMPILES.logger = self.logger
        devobs.LEDGER.resize(self.config.launch_ledger_size)
        from ..utils.timeseries import TimeSeriesRing
        self.timeseries = None
        self._ts_prev: dict = {}
        if self.config.timeseries_interval > 0:
            self.timeseries = TimeSeriesRing(
                interval_s=self.config.timeseries_interval,
                window_s=self.config.timeseries_window)
        # SLO engine + flight recorder (docs/observability.md "SLOs &
        # alerting"): burn-rate evaluation rides the time-series monitor
        # thread (one pass per accepted sample — never a query or scrape
        # path), and a fire transition triggers a rate-limited
        # diagnostic-bundle capture before the rings rotate the
        # evidence out.
        from ..utils.flightrec import FlightRecorder
        self.flightrec = None
        if self.config.flight_recorder_mb > 0:
            self.flightrec = FlightRecorder(
                os.path.join(data_dir, "flightrec"),
                budget_mb=self.config.flight_recorder_mb,
                logger=self.logger, stats=self.stats)
        from ..utils import tenant as _tenant
        from ..utils.slo import SLOEngine
        self.slo = None
        if self.timeseries is not None:
            slo = SLOEngine(
                self.timeseries, self.stats,
                latency_ms=self.config.slo_latency_ms,
                target=self.config.slo_target,
                rules=self.config.alert_rules,
                logger=self.logger, on_fire=self._on_alert_fire,
                tenant_registry=_tenant.REGISTRY)
            if slo.enabled:
                self.slo = slo
        # Warm-start subsystem (docs/warmup.md): persistent XLA compile
        # cache under the data dir, durable signature corpus, and the
        # AOT warmup coordinator that replays the corpus before READY.
        # The compile cache is configured HERE (before any executable
        # compiles) so even the first queries of a fresh process land
        # their compilations on disk for the next restart.
        from .. import warmup as _warmup
        self._compile_cache_dir = _warmup.resolve_dir(
            self.config.compile_cache_dir, data_dir)
        cache_on = False
        if self._compile_cache_dir is not None:
            cache_on = _warmup.configure(self._compile_cache_dir)
            if cache_on:
                _warmup.prune(self._compile_cache_dir,
                              self.config.compile_cache_mb)
        self.warmup = _warmup.WarmupCoordinator(
            self.api.executor,
            os.path.join(data_dir, "signatures.log"),
            top_n=self.config.warmup_top_n,
            budget_s=self.config.warmup_budget_s,
            logger=self.logger, stats=self.stats)
        self.warmup.cache_enabled = cache_on
        self.api.warmup = self.warmup
        # the executor feeds the corpus recorder on its success paths
        # (the logger-injection pattern)
        self.api.executor.warm_recorder = self.warmup.recorder
        self.httpd = make_http_server(
            self.api, host, port, server=self, tls=tls,
            max_body_bytes=self.config.max_body_mb << 20,
            max_body_bytes_internal=self.config.max_body_internal_mb << 20,
            admission=self.admission,
            admission_internal=self.admission_internal,
            admission_ingest=self.admission_ingest,
            ingest_max_frame_bytes=max(
                self.config.ingest_max_frame_mb, 1) << 20,
            default_query_timeout=self.config.query_timeout,
            partial_results=self.config.partial_results,
            slowlog=self.slowlog,
            profile_default=self.config.profile_default)
        # Fleet rollup (docs/observability.md "Cluster plane"): any
        # clustered node can aggregate its peers' /debug/vars + event
        # journals into /debug/cluster and the pilosa_tpu_cluster_*
        # family; the local node's summary is built from the SAME
        # build_debug_vars body peers serve over the wire.
        self.rollup = None
        if self.cluster is not None:
            from ..parallel.rollup import FleetRollup
            from .handler import build_debug_vars
            self.rollup = FleetRollup(
                self.cluster,
                local_vars_fn=lambda: build_debug_vars(self.api, self),
                stats=self.stats)
        from ..utils.diagnostics import DiagnosticsCollector
        self.diagnostics = DiagnosticsCollector(
            self, self.config.diagnostics_endpoint,
            self.config.diagnostics_interval)
        self._threads: list[threading.Thread] = []
        self._closing = threading.Event()

    @staticmethod
    def _parse_bind(bind: str) -> tuple[str, int]:
        bind = bind.removeprefix("https://").removeprefix("http://")
        host, _, port = bind.rpartition(":")
        return host or "localhost", int(port)

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def register_internal_routes(self, router):
        if self.cluster is not None:
            self.cluster.register_routes(router, server=self)

    def open(self):
        """(reference server.go:417 Open)"""
        self.holder.open()
        # Warm start (docs/warmup.md): load the corpus and decide the
        # phase AFTER local WAL replay has made the holder queryable
        # and BEFORE the listener serves /status — a probing peer never
        # sees a cold node as READY.  The replay itself runs on the
        # coordinator's own thread, concurrent with the rest of startup
        # (cluster join, serve loop, monitors).
        warming = self.warmup.open()
        if self.cluster is not None:
            self.cluster.open(self.api)
        if warming:
            if self.cluster is not None:
                self.cluster.set_local_warming(True)
            self.warmup.on_ready = self._warmup_ready
        self.warmup.start()
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        self.logger.info(
            f"pilosa-tpu listening on http://{self.config.bind}")
        if self.cluster is not None and self.config.anti_entropy_interval > 0:
            t = threading.Thread(target=self._monitor_anti_entropy,
                                 daemon=True)
            t.start()
            self._threads.append(t)
        if self.cluster is not None and self.config.repair_interval > 0:
            t = threading.Thread(target=self._monitor_repair, daemon=True)
            t.start()
            self._threads.append(t)
        if self.config.metric_poll_interval > 0:
            t = threading.Thread(target=self._monitor_runtime, daemon=True)
            t.start()
            self._threads.append(t)
        if self.timeseries is not None:
            t = threading.Thread(target=self._monitor_timeseries,
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self.diagnostics.open()  # no-op unless an endpoint is configured

    def _warmup_ready(self):
        """Warmup-replay completion hook: flip the local node's
        advertised state to READY (peers' probe folds catch up within
        one health interval)."""
        if self.cluster is not None:
            self.cluster.set_local_warming(False)

    def collect_runtime_stats(self):
        """Process-level gauges (server.go:813 monitorRuntime + gopsutil;
        /proc in place of gopsutil, gc module in place of MemStats)."""
        import gc as _gc
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        self.stats.gauge("runtime.rss_bytes",
                                         int(line.split()[1]) * 1024)
                        break
        except OSError:
            pass
        try:
            self.stats.gauge("runtime.open_fds",
                             len(os.listdir("/proc/self/fd")))
        except OSError:
            pass
        self.stats.gauge("runtime.threads", threading.active_count())
        g0, g1, g2 = _gc.get_count()
        self.stats.gauge("runtime.gc_gen0", g0)
        from ..utils.gcnotify import global_notifier
        snap = global_notifier().snapshot()
        for gen in range(3):
            self.stats.gauge(f"runtime.gc_collections_gen{gen}",
                             snap["collections"][gen])
            self.stats.gauge(f"runtime.gc_pause_ms_gen{gen}",
                             round(snap["pause_s"][gen] * 1e3, 3))
        self.stats.gauge("runtime.gc_collected", snap["collected"])
        from ..storage.membudget import DEFAULT_BUDGET, HOST_STAGE_BUDGET
        self.stats.gauge("runtime.hbm_resident_bytes",
                         DEFAULT_BUDGET.resident_bytes)
        # streaming-pipeline counters (docs/memory-budget.md): upload
        # volume, prefetch effectiveness, pin pressure, host staging
        b = DEFAULT_BUDGET.stats()
        self.stats.gauge("runtime.hbm_upload_bytes", b["uploadBytes"])
        self.stats.gauge("runtime.hbm_evictions", b["evictions"])
        self.stats.gauge("runtime.hbm_prefetch_hits", b["prefetchHits"])
        self.stats.gauge("runtime.hbm_prefetch_misses",
                         b["prefetchMisses"])
        self.stats.gauge("runtime.hbm_pinned_bytes", b["pinnedBytes"])
        self.stats.gauge("runtime.host_stage_bytes",
                         HOST_STAGE_BUDGET.resident_bytes)
        self.update_storage_gauges()
        # admission slot/queue occupancy (counters live in stats counts)
        for pool in (self.admission, self.admission_internal):
            s = pool.snapshot()
            self.stats.gauge(f"admission.{pool.name}.in_use", s["inUse"])
            self.stats.gauge(f"admission.{pool.name}.waiting",
                             s["waiting"])

    def _monitor_runtime(self):
        while not self._closing.wait(self.config.metric_poll_interval):
            try:
                self.collect_runtime_stats()
            except Exception as e:
                # a monitor that dies silently leaves gauges frozen at
                # their last values — indistinguishable from a healthy
                # quiet server (the PR 6 swallow class)
                self.logger.error(f"runtime stats poll failed: {e}")

    def sample_timeseries(self, force: bool = False) -> bool:
        """One time-series sample (docs/observability.md "Device
        runtime"): level gauges (HBM split, host stage, admission and
        batcher occupancy, decode high-watermark, instantaneous p99) plus
        per-interval DELTAS of the monotone counters (edge histogram
        count/sum, evictions, uploads, compiles/retraces, launches,
        padding) so the ring answers "what changed in that interval"
        directly.  The previous counter snapshot only advances when the
        ring accepts the sample, so deltas always span exactly one
        retained interval."""
        if self.timeseries is None:
            return False
        from ..parallel import mesh_exec as _mesh_exec
        from ..storage.membudget import DEFAULT_BUDGET, HOST_STAGE_BUDGET
        from ..utils import devobs
        from ..utils import events as _events_mod
        b = DEFAULT_BUDGET.stats()
        req_count, _ = self.stats.timing_totals("http.request")
        q_count, q_sum = self.stats.timing_totals("http.query")
        comp = devobs.COMPILES.totals()
        led = devobs.LEDGER.aggregates()
        adm = self.admission.snapshot()
        counters = {
            "httpRequests": req_count,
            "httpQueries": q_count,
            "httpQueryS": q_sum,
            "evictions": b["evictions"],
            "evictedBytes": b["evictedBytes"],
            "uploadBytes": b["uploadBytes"],
            "compiles": comp["compiles"],
            "retraces": comp["retraces"],
            "compileS": comp["compileSecondsTotal"],
            "launches": led["launches"],
            "rowsActual": led["rowsActual"],
            "rowsPadded": led["rowsPadded"],
            # PR 19 fused container kernels: launches/tiles were on the
            # ledger aggregates but never sampled into the ring
            "kernelLaunches": led["kernelLaunches"],
            "kernelTiles": led["kernelTiles"],
        }
        # SLO counters (docs/observability.md "SLOs & alerting"): bad
        # http.query counts — 5xx responses and queries over the
        # latency objective (exact from the fixed histogram buckets) —
        # whose ring deltas feed the burn-rate windows
        q_good = self.stats.bucket_count_le(
            "http.query", self.config.slo_latency_ms / 1e3)
        counters.update({
            "sloErrors": self.stats.count_value("http.query_5xx"),
            "sloSlowQueries": max(q_count - q_good, 0),
        })
        # cluster-health motion (docs/observability.md "Cluster plane"):
        # per-interval deltas of the PR 13/14 cluster counters so the
        # dashboard timeline shows routing/hedging/partial churn, not
        # just device churn.  Zero-valued on single-node servers.
        counters.update({
            "hedges": self.stats.count_value("cluster.hedges"),
            "hedgeWins": self.stats.count_value("cluster.hedge_wins"),
            "retryWaves": self.stats.count_value("cluster.retry_waves"),
            "partialResults": self.stats.count_value(
                "cluster.partial_results"),
            "routingFallbacks": self.stats.count_value(
                "routing.fallback"),
            "breakerSkips": self.stats.count_value(
                "routing.breaker_skip"),
            "balancerHandoffs": self.cluster.balancer.handoffs
            if self.cluster is not None else 0,
            "fleetEvents": _events_mod.EVENTS.last_seq(),
            # breaker OPEN transitions and ingest-backpressure 503s:
            # the flapping/backpressure pathology rules read these
            "breakerOpens": self.stats.count_value("breaker.opened"),
            "ingestRejected": self.stats.count_value("ingest.rejected"),
        })
        # PR 17 tenant plane: total sheds across tenants (the per-tenant
        # split stays on /debug/vars "tenants"; the ring answers "did
        # isolation shed anything in that interval")
        from ..utils import tenant as _tenant
        counters["tenantSheds"] = sum(
            t["shed"] for t in _tenant.REGISTRY.snapshot().values())
        # The counter sources are process-wide singletons that predate
        # this Server: the first sample has no previous snapshot, and
        # reporting lifetime totals as "this interval's delta" would
        # spike every dashboard sparkline — its deltas are zero instead.
        prev = self._ts_prev or counters
        values = {k + "Delta": round(v - prev.get(k, 0), 6)
                  for k, v in counters.items()}
        p99 = self.stats.percentile("http.query", 0.99)
        batcher = self.api.executor.batcher
        values.update({
            "hbmResidentBytes": b["residentBytes"],
            "hbmCompressedBytes": b["compressedBytes"],
            "hbmDenseBytes": b["denseBytes"],
            "hbmPinnedBytes": b["pinnedBytes"],
            "hostStageBytes": HOST_STAGE_BUDGET.resident_bytes,
            "admissionInUse": adm["inUse"],
            "admissionWaiting": adm["waiting"],
            "batcherQueued": batcher.pending() if batcher is not None
            else 0,
            "decodePeakBytes": led["decodePeakBytes"],
            "decodeWorkspaceBytes": _mesh_exec.DECODE_WORKSPACE_BYTES,
            "httpQueryP99Ms": round(p99 * 1e3, 3) if p99 else 0.0,
            # level gauge for the quarantine alert rule: fragments
            # currently refused by corruption checks
            "quarantinedFragments": len(
                self.holder.quarantined_fragments()),
        })
        accepted = self.timeseries.sample(values, force=force)
        if accepted:
            self._ts_prev = counters
        return accepted

    def _monitor_timeseries(self):
        while not self._closing.wait(self.config.timeseries_interval):
            try:
                accepted = self.sample_timeseries()
                # SLO evaluation rides the sampler cadence (one pass
                # per accepted sample) so burn-rate windows and ring
                # intervals stay the same clock — and stays OFF the
                # query and scrape paths entirely
                if accepted and self.slo is not None:
                    self.slo.evaluate()
            except Exception as e:
                # a silently dead sampler shows a flat-lined
                # /debug/timeseries that reads as "idle", not "broken"
                self.logger.error(f"time-series sample failed: {e}")

    def _on_alert_fire(self, alert: dict):
        """Fire-transition hook (utils/slo.py): capture a diagnostic
        bundle while the rings still hold the incident's evidence.
        Rate-limited inside the recorder; runs on the monitor thread."""
        if self.flightrec is None:
            return
        self.flightrec.capture("alert-" + alert["id"], self.build_bundle)

    def build_bundle(self) -> dict:
        """The flight-recorder payload (docs/observability.md "SLOs &
        alerting"): every bounded debug surface, snapshotted into one
        JSON document so post-incident forensics survive ring
        rotation."""
        from ..utils import devobs
        from ..utils.events import EVENTS
        from .handler import build_debug_vars
        return {
            "node": self.config.node_id,
            "bind": self.config.bind,
            "vars": build_debug_vars(self.api, self),
            "timeseries": self.timeseries.snapshot()
            if self.timeseries is not None else None,
            "events": EVENTS.snapshot(),
            "slowLog": self.slowlog.snapshot(),
            "compiles": devobs.COMPILES.snapshot(),
            "launches": devobs.LEDGER.snapshot(),
            "alerts": self.slo.snapshot() if self.slo is not None
            else None,
        }

    def capture_bundle(self, reason: str, force: bool = False
                       ) -> str | None:
        """On-demand bundle capture (POST /debug/bundle, `pilosa-tpu
        bundle`); returns the bundle path or None when rate-limited."""
        if self.flightrec is None:
            return None
        return self.flightrec.capture(reason, self.build_bundle,
                                      force=force)

    def _monitor_anti_entropy(self):
        """(server.go:514 monitorAntiEntropy)"""
        while not self._closing.wait(self.config.anti_entropy_interval):
            try:
                self.cluster.sync_holder()
            except Exception as e:
                self.logger.error(f"anti-entropy sync failed: {e}")

    def _monitor_repair(self):
        """Dedicated quarantine-repair sweep (docs/robustness.md): a
        corrupt fragment heals on the repair-interval cadence instead of
        waiting out the (much longer) anti-entropy interval.  Cheap when
        healthy — one holder scan finding nothing."""
        while not self._closing.wait(self.config.repair_interval):
            try:
                if self.holder.quarantined_fragments():
                    n = self.cluster.repair_quarantined()
                    if n:
                        self.logger.info(
                            f"repaired {n} quarantined fragment(s) "
                            f"from replicas")
            except Exception as e:
                self.logger.error(f"quarantine repair failed: {e}")

    def update_storage_gauges(self, container_stats=None):
        """Durability counters -> stats gauges (referenced from the
        fragment codec's module docs): called on the metric poll AND from
        the /metrics and /debug/vars handlers so scrapes see current
        values, not poll-stale ones.  ``container_stats`` lets a caller
        that already computed Holder.container_stats() (the /debug/vars
        handler) pass it in instead of re-walking every fragment."""
        from ..storage.fragment import storage_events
        ev = storage_events()
        self.stats.gauge("storage.quarantine_events", ev["quarantine"])
        self.stats.gauge("storage.torn_wal_recoveries",
                         ev["torn_tail_recovered"])
        self.stats.gauge("storage.repairs", ev["repair"])
        self.stats.gauge("storage.quarantined_fragments",
                         len(self.holder.quarantined_fragments()))
        # compressed residency (docs/memory-budget.md): resident split +
        # container-type histogram of the packed streams
        from ..storage.membudget import DEFAULT_BUDGET
        b = DEFAULT_BUDGET.stats()
        self.stats.gauge("runtime.hbm_compressed_bytes",
                         b["compressedBytes"])
        self.stats.gauge("runtime.hbm_dense_bytes", b["denseBytes"])
        cs = container_stats if container_stats is not None \
            else self.holder.container_stats()
        self.stats.gauge("storage.containers_array", cs["array"])
        self.stats.gauge("storage.containers_bitmap", cs["bitmap"])
        self.stats.gauge("storage.containers_run", cs["run"])
        self.stats.gauge("storage.compressed_fragments",
                         cs["compressedFragments"])
        # streaming ingest (docs/ingest.md): overlay-journal residency,
        # unflushed backlog, and fold count — refreshed at scrape time
        from ..storage.membudget import INGEST_DELTA_BUDGET
        self.stats.gauge("ingest.delta_bytes",
                         INGEST_DELTA_BUDGET.resident_bytes)
        ing = self.committer.snapshot()
        self.stats.gauge("ingest.delta_fragments",
                         ing["journalFragments"])
        self.stats.gauge("ingest.merge_backlog", ing["pendingBytes"])
        self.stats.gauge("ingest.folds", ing["folds"])
        self.update_device_gauges()
        self.update_routing_gauges()

    def update_routing_gauges(self):
        """Per-peer routing-state gauges (docs/cluster.md "Read routing
        & rebalancing"), refreshed at scrape time like the storage
        gauges: the operator's answer to "why is this replica not taking
        reads" must reflect now, not the last metric poll."""
        if self.cluster is None:
            return
        for nid, g in self.cluster.router.peer_states():
            self.stats.gauge(f"cluster.peer.{nid}.ewma_rtt_ms",
                             g["ewma_rtt_ms"])
            self.stats.gauge(f"cluster.peer.{nid}.inflight",
                             g["inflight"])
            self.stats.gauge(f"cluster.peer.{nid}.queued", g["queued"])
            self.stats.gauge(f"cluster.peer.{nid}.residency_age_s",
                             g["residency_age_s"])
            self.stats.gauge(f"cluster.peer.{nid}.breaker_open",
                             g["breaker_open"])
            self.stats.gauge(f"cluster.peer.{nid}.dispatches",
                             g["dispatches"])
        snap = self.cluster.overlay_snapshot()
        self.stats.gauge("cluster.overlay_entries", len(snap["entries"]))
        self.stats.gauge("cluster.overlay_epoch", snap["epoch"])
        self.stats.gauge("cluster.balancer_handoffs",
                         self.cluster.balancer.handoffs)

    def update_device_gauges(self):
        """Compile-registry + launch-ledger gauges (docs/observability.md
        "Device runtime"), refreshed at scrape time like the storage
        gauges so /metrics and /debug/vars see current values — a
        retrace burst between metric polls must not be invisible."""
        from ..parallel import mesh_exec as _mesh_exec
        from ..utils import devobs
        c = devobs.COMPILES.totals()
        self.stats.gauge("device.compiles_total", c["compiles"])
        self.stats.gauge("device.retraces_total", c["retraces"])
        self.stats.gauge("device.compile_seconds_total",
                         c["compileSecondsTotal"])
        led = devobs.LEDGER.aggregates()
        self.stats.gauge("device.launches_total", led["launches"])
        self.stats.gauge("device.launch_rows", led["rowsActual"])
        self.stats.gauge("device.padded_rows", led["rowsPadded"])
        self.stats.gauge("device.padding_waste_ratio",
                         led["paddingWasteRatio"])
        self.stats.gauge("device.decode_workspace_peak_bytes",
                         led["decodePeakBytes"])
        self.stats.gauge("device.decode_workspace_limit_bytes",
                         _mesh_exec.DECODE_WORKSPACE_BYTES)
        self.stats.gauge("device.kernel_launches", led["kernelLaunches"])
        from ..ops import kernels as _kernels
        # resolved backend as a 0/1 flag gauge (1 = pallas kernels
        # active), the scrape-friendly encoding of a string state
        self.stats.gauge("device.kernel_backend",
                         1 if _kernels.resolve() == "pallas" else 0)

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful drain: stop ADMITTING public queries (new ones get
        503 + Retry-After while the socket stays up, so clients fail over
        cleanly) and wait for in-flight ones to finish.  Returns True if
        everything drained inside the deadline.  Idempotent; close()
        calls it first."""
        if timeout is None:
            timeout = self.config.drain_seconds
        from ..utils import events
        events.emit("server.drain", budgetS=round(max(timeout, 0.0), 3))
        self.admission.begin_drain()
        drained = self.admission.wait_drained(max(timeout, 0.0))
        if not drained:
            self.logger.error(
                f"drain deadline ({timeout:.3g}s) passed with "
                f"{self.admission.snapshot()['inUse']} queries in flight; "
                f"closing anyway")
        return drained

    def close(self):
        # drain BEFORE severing sockets: in-flight queries finish under
        # the drain deadline instead of seeing a connection reset
        self.drain()
        self._closing.set()
        self.diagnostics.close()
        self.httpd.shutdown()
        # sever live keep-alive connections: their handler threads would
        # otherwise keep serving THIS closed server's holder — and after
        # a same-port restart, peers' pooled connections would write
        # into the dead object graph (r5 cluster-fuzz finding)
        if hasattr(self.httpd, "close_connections"):
            self.httpd.close_connections()
        self.httpd.server_close()
        # final group-commit flush AFTER the listener is gone (no new
        # submissions) and BEFORE the holder closes the WAL files
        self.committer.close()
        if self.rollup is not None:
            self.rollup.close()
        if self.cluster is not None:
            self.cluster.close()
        self.api.executor.close()
        # warm start (docs/warmup.md): stop the flush thread, take the
        # final corpus flush while the compile registry still holds this
        # run's entries, and LRU-prune the compile cache to its bound
        self.warmup.close()
        if self._compile_cache_dir is not None:
            from .. import warmup as _warmup
            _warmup.prune(self._compile_cache_dir,
                          self.config.compile_cache_mb)
        # release this server's on-disk event log handle (the journal
        # itself is process-wide and keeps its ring)
        from ..utils.events import EVENTS
        if self.config.event_log:
            EVENTS.close_log()
        self.holder.close()
