"""HTTP server layer (reference http/ + server/)."""

from .server import Config, Server  # noqa: F401
